"""The bench kernel-smoke gate itself: every check passes in interpret mode,
and a seeded perturbation of ANY kernel's result trips the gate loudly
(VERDICT r2 item 3 — the gate must be proven able to fail)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

_NAMES = []


def _names():
    # one full suite execution, shared by every parametrized case (each
    # yield of _kernel_checks computes real kernels — it is not free)
    if not _NAMES:
        _NAMES.extend(n for n, _, _ in bench._kernel_checks())
    return _NAMES


def test_all_checks_pass_clean():
    seen = []
    for name, err, tol in bench._kernel_checks():
        assert err < tol, f"{name}: {err} >= {tol}"
        seen.append(name)
    if not _NAMES:  # reuse this run for the parametrized cases below
        _NAMES.extend(seen)


@pytest.mark.parametrize("name", [
    "flash_fwd_causal1", "flash_bwd_dq_causal0", "flash_bwd_dkv_alias",
    "layer_norm", "rms_norm", "group_norm", "group_norm_bwd_dx",
    "ring_step_loss", "ring_bwd_dq", "fused_ce_loss", "fused_ce_dweight",
])
def test_gate_trips_on_perturbation(name):
    if name == "flash_bwd_dkv_alias":
        name = "flash_bwd_dk_causal1"
    names = _names()
    assert name in names, f"{name} not in gate: {names}"
    with pytest.raises(AssertionError, match=name):
        bench.kernel_smoke(perturb=name)


def test_gate_covers_backward_paths():
    names = _names()
    for required in ("flash_bwd_dq_causal0", "flash_bwd_dv_causal1",
                     "group_norm_bwd_dw", "ring_bwd_dk", "fused_ce_dhidden"):
        assert required in names
