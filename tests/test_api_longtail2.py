"""Round-2 API long tail: root ops, losses, unpool, nn.utils, beam search
(verdict-style probe list driven to zero — each op checked numerically)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestRootOps:
    def test_special_functions(self):
        from scipy import special as sp

        x = np.linspace(0.1, 3.0, 7).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(paddle.i0e(t).numpy(), sp.i0e(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.i1(t).numpy(), sp.i1(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.i1e(t).numpy(), sp.i1e(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.polygamma(t, 1).numpy(),
                                   sp.polygamma(1, x).astype(np.float32),
                                   rtol=1e-4)

    def test_gamma_family(self):
        from scipy import special as sp

        x = np.linspace(0.2, 4.0, 9).astype(np.float32)
        a = np.linspace(0.5, 3.0, 9).astype(np.float32)
        tx, ta = paddle.to_tensor(x), paddle.to_tensor(a)
        np.testing.assert_allclose(paddle.gammaln(tx).numpy(),
                                   sp.gammaln(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.gammainc(ta, tx).numpy(),
                                   sp.gammainc(a, x), rtol=1e-5)
        np.testing.assert_allclose(paddle.gammaincc(ta, tx).numpy(),
                                   sp.gammaincc(a, x), rtol=1e-5)
        # P + Q = 1, tensor-method form, and a grad through gammainc (d/dx
        # of P(a, x) is the gamma pdf)
        np.testing.assert_allclose(
            (ta.gammainc(tx) + ta.gammaincc(tx)).numpy(),
            np.ones_like(x), rtol=1e-6)
        tx2 = paddle.to_tensor(x)
        tx2.stop_gradient = False
        paddle.gammainc(ta, tx2).sum().backward()
        pdf = np.exp(-x) * x ** (a - 1) / sp.gamma(a)
        np.testing.assert_allclose(tx2.grad.numpy(), pdf, rtol=1e-4)

    def test_logit_signbit_positive(self):
        p = np.array([0.1, 0.5, 0.9], np.float32)
        np.testing.assert_allclose(paddle.logit(paddle.to_tensor(p)).numpy(),
                                   np.log(p / (1 - p)), rtol=1e-5)
        s = paddle.signbit(paddle.to_tensor(np.array([-1.0, 0.0, 2.0]))).numpy()
        np.testing.assert_array_equal(s, [True, False, False])
        x = paddle.to_tensor([1.0, -2.0])
        np.testing.assert_array_equal(paddle.positive(x).numpy(), x.numpy())

    def test_dist_and_inverse(self):
        a = np.random.RandomState(0).randn(3, 3).astype(np.float32)
        b = np.random.RandomState(1).randn(3, 3).astype(np.float32)
        np.testing.assert_allclose(
            float(paddle.dist(paddle.to_tensor(a), paddle.to_tensor(b), p=2)),
            np.linalg.norm((a - b).ravel()), rtol=1e-5)
        m = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        np.testing.assert_allclose(
            paddle.inverse(paddle.to_tensor(m)).numpy(), np.linalg.inv(m),
            rtol=1e-4, atol=1e-5)

    def test_combinations(self):
        import itertools

        x = np.array([3.0, 1.0, 2.0, 5.0], np.float32)
        out = paddle.combinations(paddle.to_tensor(x), r=2).numpy()
        ref = np.asarray(list(itertools.combinations(x, 2)), np.float32)
        np.testing.assert_allclose(out, ref)

    def test_splits_and_stacks(self):
        x = np.arange(24, dtype=np.float32).reshape(4, 6)
        t = paddle.to_tensor(x)
        outs = paddle.tensor_split(t, 3, axis=1)
        np.testing.assert_allclose(np.concatenate([o.numpy() for o in outs], 1), x)
        hs = paddle.hsplit(t, 2)
        assert hs[0].shape == [4, 3]
        vs = paddle.vsplit(t, 2)
        assert vs[0].shape == [2, 6]
        np.testing.assert_allclose(
            paddle.hstack([t, t]).numpy(), np.hstack([x, x]))
        np.testing.assert_allclose(
            paddle.vstack([t, t]).numpy(), np.vstack([x, x]))
        v = paddle.to_tensor(np.arange(4, dtype=np.float32))
        np.testing.assert_allclose(paddle.column_stack([v, v]).numpy(),
                                   np.column_stack([v.numpy(), v.numpy()]))
        np.testing.assert_allclose(paddle.fliplr(t).numpy(), np.fliplr(x))
        np.testing.assert_allclose(paddle.flipud(t).numpy(), np.flipud(x))

    def test_unflatten_index_fill_misc(self):
        x = np.arange(24, dtype=np.float32).reshape(4, 6)
        t = paddle.to_tensor(x)
        assert paddle.unflatten(t, 1, [2, 3]).shape == [4, 2, 3]
        out = paddle.index_fill(t, paddle.to_tensor(np.array([0, 2])), 0, -1.0)
        assert (out.numpy()[[0, 2]] == -1).all()
        assert (out.numpy()[1] == x[1]).all()
        assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
        assert paddle.tolist(t) == x.tolist()
        np.testing.assert_array_equal(paddle.shape(t).numpy(), [4, 6])
        np.testing.assert_array_equal(
            paddle.tril_indices(3, 3).numpy(),
            np.stack(np.tril_indices(3)))
        np.testing.assert_array_equal(
            paddle.triu_indices(3, 3, 1).numpy(),
            np.stack(np.triu_indices(3, 1)))

    def test_inplace_methods(self):
        t = paddle.to_tensor([4.0, 9.0])
        t.sqrt_()
        np.testing.assert_allclose(t.numpy(), [2.0, 3.0])
        t.unsqueeze_(0)
        assert t.shape == [1, 2]
        t.squeeze_(0)
        assert t.shape == [2]
        t2 = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        t2.flatten_()
        assert t2.shape == [4]
        t3 = paddle.to_tensor([0.5])
        t3.reciprocal_()
        np.testing.assert_allclose(t3.numpy(), [2.0])


class TestNewLosses:
    def test_gaussian_nll(self):
        mu = paddle.to_tensor([0.0, 1.0])
        y = paddle.to_tensor([0.5, 0.5])
        var = paddle.to_tensor([1.0, 4.0])
        out = float(F.gaussian_nll_loss(mu, y, var))
        ref = np.mean([0.5 * (np.log(1.0) + 0.25), 0.5 * (np.log(4.0) + 0.25 / 4)])
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_poisson_nll(self):
        x = paddle.to_tensor([0.5, 1.0])
        y = paddle.to_tensor([1.0, 2.0])
        out = float(F.poisson_nll_loss(x, y))
        ref = np.mean(np.exp([0.5, 1.0]) - np.array([1.0, 2.0]) * np.array([0.5, 1.0]))
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_soft_margin_and_multilabel(self):
        x = paddle.to_tensor([[0.5, -1.0]])
        y = paddle.to_tensor([[1.0, -1.0]])
        out = float(F.soft_margin_loss(x, y))
        ref = np.mean(np.log1p(np.exp(-np.array([0.5, 1.0]))))
        np.testing.assert_allclose(out, ref, rtol=1e-5)
        lbl = paddle.to_tensor([[1.0, 0.0]])
        out2 = float(F.multi_label_soft_margin_loss(x, lbl))
        assert out2 > 0

    def test_multi_margin(self):
        x = paddle.to_tensor([[0.1, 0.9, 0.3]])
        y = paddle.to_tensor(np.array([1], np.int64))
        out = float(F.multi_margin_loss(x, y))
        ref = (max(0, 1 - 0.9 + 0.1) + max(0, 1 - 0.9 + 0.3)) / 3
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_dice_npair_layers(self):
        probs = paddle.to_tensor(np.random.RandomState(0).dirichlet(
            np.ones(3), size=4).astype(np.float32))
        lbl = paddle.to_tensor(np.random.RandomState(1).randint(
            0, 3, (4, 1)).astype(np.int64))
        d = float(F.dice_loss(probs, lbl))
        assert 0 <= d <= 1
        a = paddle.to_tensor(np.random.RandomState(2).randn(4, 8).astype(np.float32))
        p = paddle.to_tensor(np.random.RandomState(3).randn(4, 8).astype(np.float32))
        yl = paddle.to_tensor(np.array([0, 1, 0, 1], np.int64))
        assert float(F.npair_loss(a, p, yl)) > 0
        # layer wrappers construct + run
        nn.GaussianNLLLoss()(probs, probs, probs + 1.0)
        nn.PoissonNLLLoss()(probs, probs)
        nn.SoftMarginLoss()(a, paddle.sign(p))
        nn.MultiMarginLoss()(probs, lbl.squeeze(-1))

    def test_hsigmoid(self):
        paddle.seed(0)
        layer = nn.HSigmoidLoss(feature_size=8, num_classes=6)
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(np.array([0, 1, 4, 5], np.int64))
        loss = layer(x, y)
        assert float(loss) > 0
        loss.backward()
        assert layer.weight.grad is not None


class TestUnpool:
    def test_max_pool_mask_and_unpool2d_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        t = paddle.to_tensor(x)
        out, mask = F.max_pool2d(t, 2, stride=2, return_mask=True)
        assert out.shape == [2, 3, 4, 4]
        # mask indices point at the max within each window
        ref = x.reshape(2, 3, 4, 2, 4, 2).transpose(0, 1, 2, 4, 3, 5).reshape(2, 3, 4, 4, 4)
        np.testing.assert_allclose(out.numpy(), ref.max(-1))
        un = F.max_unpool2d(out, mask, 2, stride=2)
        assert un.shape == [2, 3, 8, 8]
        # unpooled keeps exactly the max values at their original spots
        np.testing.assert_allclose(un.numpy().max(axis=(2, 3)),
                                   x.max(axis=(2, 3)))
        count_nonzero = (un.numpy() != 0).sum()
        assert count_nonzero <= 2 * 3 * 16

    def test_unpool_layers(self):
        x = paddle.to_tensor(np.random.RandomState(1).randn(1, 2, 8).astype(np.float32))
        out, mask = F.max_pool1d(x, 2, return_mask=True)
        un = nn.MaxUnPool1D(2)(out, mask)
        assert un.shape == [1, 2, 8]
        x3 = paddle.to_tensor(np.random.RandomState(2).randn(1, 2, 4, 4, 4).astype(np.float32))
        out3, mask3 = F.max_pool3d(x3, 2, return_mask=True)
        un3 = nn.MaxUnPool3D(2)(out3, mask3)
        assert un3.shape == [1, 2, 4, 4, 4]


class TestNnUtils:
    def test_weight_norm_roundtrip(self):
        from paddle_tpu.nn.utils import weight_norm, remove_weight_norm

        paddle.seed(0)
        lin = nn.Linear(4, 3)
        w0 = lin.weight.numpy().copy()
        weight_norm(lin, dim=0)
        names = [n for n, _ in lin.named_parameters()]
        assert "weight_g" in names and "weight_v" in names
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype(np.float32))
        y1 = lin(x).numpy()
        # reconstructed weight equals original at init
        remove_weight_norm(lin)
        np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(lin(x).numpy(), y1, rtol=1e-5, atol=1e-6)

    def test_weight_norm_dim_none_scalar_g(self):
        # dim=None: one norm over EVERY axis (scalar g), not per-row
        from paddle_tpu.nn.utils import weight_norm

        lin = nn.Linear(4, 3)
        w0 = np.asarray(lin.weight._data).copy()
        weight_norm(lin, dim=None)
        g = np.asarray(lin.weight_g._data)
        assert g.size == 1
        np.testing.assert_allclose(float(g.reshape(())),
                                   np.linalg.norm(w0), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(lin.weight._data), w0,
                                   rtol=1e-5)

    def test_weight_norm_grads(self):
        from paddle_tpu.nn.utils import weight_norm

        paddle.seed(0)
        lin = weight_norm(nn.Linear(4, 3))
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype(np.float32))
        loss = lin(x).sum()
        loss.backward()
        assert lin.weight_g.grad is not None
        assert lin.weight_v.grad is not None

    def test_spectral_norm_contracts(self):
        from paddle_tpu.nn.utils import spectral_norm

        paddle.seed(0)
        lin = spectral_norm(nn.Linear(6, 6), n_power_iterations=5)
        x = paddle.to_tensor(np.eye(6, dtype=np.float32))
        w_eff = lin(x).numpy() - lin.bias.numpy()
        s = np.linalg.svd(w_eff, compute_uv=False)
        assert s[0] < 1.5  # spectral radius ~<= 1 after normalization

    def test_vector_roundtrip_and_clip(self):
        from paddle_tpu.nn.utils import (clip_grad_norm_, clip_grad_value_,
                                         parameters_to_vector,
                                         vector_to_parameters)

        lin = nn.Linear(3, 2)
        vec = parameters_to_vector(lin.parameters())
        assert vec.shape == [3 * 2 + 2]
        vector_to_parameters(vec * 0 + 1.0, lin.parameters())
        np.testing.assert_allclose(lin.weight.numpy(), np.ones((3, 2)))
        p = paddle.Parameter(np.ones(4, np.float32))
        p.grad = paddle.to_tensor(np.full(4, 10.0, np.float32))
        total = clip_grad_norm_([p], max_norm=1.0)
        assert float(total) == pytest.approx(20.0)
        np.testing.assert_allclose(np.linalg.norm(p.grad.numpy()), 1.0,
                                   rtol=1e-4)
        p.grad = paddle.to_tensor(np.full(4, 10.0, np.float32))
        clip_grad_value_([p], 0.5)
        np.testing.assert_allclose(p.grad.numpy(), 0.5)


class TestBeamSearch:
    def test_greedy_path_recovered(self):
        """Deterministic cell: logits independent of state, so beam search
        must recover the argmax sequence in beam 0."""
        vocab, hidden = 5, 4
        logits_seq = np.full((vocab,), -5.0, np.float32)

        class Cell(nn.Layer):
            def forward(self, inputs, states):
                # favor token (last+1) % vocab, end at token 4 -> end_token
                ids = inputs.numpy().astype(int).reshape(-1)
                out = np.full((len(ids), vocab), -5.0, np.float32)
                for i, t in enumerate(ids):
                    out[i, (t + 1) % vocab] = 5.0
                return paddle.to_tensor(out), states

        from paddle_tpu.nn import BeamSearchDecoder, dynamic_decode

        cell = Cell()
        dec = BeamSearchDecoder(cell, start_token=0, end_token=4, beam_size=2)
        init = paddle.to_tensor(np.zeros((2, hidden), np.float32))
        out, _ = dynamic_decode(dec, inits=init, max_step_num=10)
        ids = out.numpy()[:, :, 0]  # best beam
        # path 0 -> 1 -> 2 -> 3 -> 4(end)
        np.testing.assert_array_equal(ids[0][:4], [1, 2, 3, 4])

    def test_gather_tree(self):
        ids = paddle.to_tensor(np.array(
            [[[2, 5]], [[6, 3]], [[1, 9]]], np.int32))   # [T=3, B=1, beam=2]
        parents = paddle.to_tensor(np.array(
            [[[0, 0]], [[1, 0]], [[0, 1]]], np.int32))
        out = F.gather_tree(ids, parents).numpy()
        assert out.shape == (3, 1, 2)
        # final beam 0 traces parents: t2 beam0 <- parent0 (t1 beam0 <- parent1)
        np.testing.assert_array_equal(out[:, 0, 0], [5, 6, 1])


class TestPoolCeilMode:
    def test_ceil_mode_matches_torch_semantics(self):
        # 8x8, k3 s2: floor -> 3x3, ceil -> 4x4 with the partial edge
        # window (validated against torch.nn.MaxPool2d/AvgPool2d)
        x = paddle.to_tensor(np.random.RandomState(0).randn(1, 1, 8, 8)
                             .astype(np.float32))
        assert nn.MaxPool2D(3, stride=2)(x).shape == [1, 1, 3, 3]
        out = nn.MaxPool2D(3, stride=2, ceil_mode=True)(x)
        assert out.shape == [1, 1, 4, 4]
        np.testing.assert_allclose(out.numpy()[0, 0, 3, 3],
                                   x.numpy()[0, 0, 6:, 6:].max())
        outa = nn.AvgPool2D(3, stride=2, ceil_mode=True)(x)
        assert outa.shape == [1, 1, 4, 4]
        # exclusive counts: edge window averages only real cells
        np.testing.assert_allclose(outa.numpy()[0, 0, 3, 3],
                                   x.numpy()[0, 0, 6:, 6:].mean(), rtol=1e-6)
        om, mask = F.max_pool2d(x, 3, stride=2, ceil_mode=True,
                                return_mask=True)
        np.testing.assert_allclose(om.numpy(), out.numpy())

    def test_ceil_mode_no_window_in_right_padding(self):
        # torch/reference rule: decrement the ceil output size whenever the
        # last window would start entirely inside the right padding.
        # k2 s2 p1 on 5x5: naive ceil gives 4x4 (with a -inf / 0-count
        # window); the reference answer is 3x3.
        import torch
        import torch.nn.functional as TF

        rng = np.random.RandomState(1)
        for L, k, s, p in [(5, 2, 2, 1), (5, 3, 2, 1), (6, 4, 3, 2),
                           (5, 2, 3, 1), (9, 5, 4, 2)]:
            x = rng.randn(2, 3, L, L).astype(np.float32)
            tm = TF.max_pool2d(torch.tensor(x), k, s, p,
                               ceil_mode=True).numpy()
            om = F.max_pool2d(paddle.to_tensor(x), k, s, p,
                              ceil_mode=True).numpy()
            np.testing.assert_allclose(om, tm, err_msg=f"{(L, k, s, p)}")
            ta = TF.avg_pool2d(torch.tensor(x), k, s, p, ceil_mode=True,
                               count_include_pad=False).numpy()
            oa = F.avg_pool2d(paddle.to_tensor(x), k, s, p,
                              ceil_mode=True, exclusive=True).numpy()
            np.testing.assert_allclose(oa, ta, rtol=1e-6,
                                       err_msg=f"{(L, k, s, p)}")
            tm2, ti = TF.max_pool2d(torch.tensor(x), k, s, p,
                                    ceil_mode=True, return_indices=True)
            om2, oi = F.max_pool2d(paddle.to_tensor(x), k, s, p,
                                   ceil_mode=True, return_mask=True)
            np.testing.assert_allclose(om2.numpy(), tm2.numpy())
            np.testing.assert_array_equal(oi.numpy(), ti.numpy())
