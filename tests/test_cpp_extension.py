"""Custom C++ op path (P29): compile a host op with the system toolchain and
run it through jax.pure_callback inside eager and jitted code."""

import shutil
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_custom_host_op_roundtrip(tmp_path):
    src = tmp_path / "myop.cc"
    src.write_text(textwrap.dedent("""
        extern "C" void double_plus_one(const float* in, float* out,
                                        long n) {
            for (long i = 0; i < n; ++i) out[i] = 2.0f * in[i] + 1.0f;
        }
    """))
    lib = cpp_extension.load("myop", [str(src)],
                             build_directory=str(tmp_path))
    op = cpp_extension.host_op(lib, "double_plus_one", lambda s: s)

    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    out = op(x)
    np.testing.assert_allclose(out.numpy(), 2 * x.numpy() + 1)

    # works under jit too (pure_callback stages a host call)
    import jax

    got = jax.jit(lambda a: op(paddle.Tensor(a))._data)(x._data)
    np.testing.assert_allclose(np.asarray(got), 2 * x.numpy() + 1)
