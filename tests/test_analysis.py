"""paddle_tpu.analysis: trace-safety linter + graph doctor. Every PTA rule
code gets one positive (fires on a minimal repro) and one negative (silent
on the corrected version) case; the dy2static "Deliberately NOT converted"
docstring constructs are each machine-checked; the converter's runtime
error and to_static(check=True) share the same diagnostics."""

import os
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import (check, lint_source, lint_file,
                                 diagnose_jaxpr, diagnose_program,
                                 doctor, RULES, ERROR, TraceSafetyWarning,
                                 check_balance, check_census,
                                 diagnose_donation, serving_check)
from paddle_tpu.analysis import donation_doctor, serving_lint
from paddle_tpu.analysis.diagnostics import scan_statement

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "analysis")


def _load_fixture(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        f"_analysis_fixture_{name}", os.path.join(FIXTURES, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _t(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


_CFG = {"scale": 2.0}        # mutable global the check=True test reads


def codes_of(src, mode="trace"):
    return {d.code for d in lint_source(src, filename="t.py", mode=mode)}


class TestConverterContractRules:
    """PTA0xx: the 'Deliberately NOT converted' docstring as rules."""

    def test_pta001_del_in_body(self):
        pos = """
def f(x):
    if x > 0:
        del x
    return 1
"""
        neg = """
def f(x):
    y = x * 2
    del x
    return y
"""
        assert "PTA001" in codes_of(pos)
        assert "PTA001" not in codes_of(neg)

    def test_pta002_global_nonlocal_in_body(self):
        pos = """
def f(x):
    if x > 0:
        global G
        G = 1
    return x
"""
        pos_nonlocal = """
def outer():
    n = 0
    def f(x):
        while x > 0:
            nonlocal n
            n = n + 1
            x = x - 1
        return x
    return f
"""
        neg = """
def f(x):
    global G
    G = 1
    return x
"""
        assert "PTA002" in codes_of(pos)
        assert "PTA002" in codes_of(pos_nonlocal)
        assert "PTA002" not in codes_of(neg)

    def test_pta003_loop_else(self):
        pos = """
def f(x):
    while x > 0:
        x = x - 1
    else:
        x = x + 1
    return x
"""
        pos_for = """
def f(x, items):
    for i in items:
        x = x + i
    else:
        x = x + 1
    return x
"""
        neg = """
def f(x):
    while x > 0:
        x = x - 1
    return x
"""
        assert "PTA003" in codes_of(pos)
        assert "PTA003" in codes_of(pos_for)
        assert "PTA003" not in codes_of(neg)

    def test_pta004_exit_inside_with_try(self):
        pos_with = """
def f(x):
    with open("/dev/null") as fh:
        if x > 0:
            return x
    return x + 1
"""
        pos_try = """
def f(x):
    while x > 0:
        try:
            x = x - 1
            break
        except ValueError:
            pass
    return x
"""
        neg = """
def f(x):
    with open("/dev/null") as fh:
        y = x + 1
    if x > 0:
        return y
    return x
"""
        assert "PTA004" in codes_of(pos_with)
        assert "PTA004" in codes_of(pos_try)
        assert "PTA004" not in codes_of(neg)

    def test_pta005_generator_coroutine(self):
        pos = """
def f(xs):
    for x in xs:
        yield x
"""
        pos_async = """
async def f(x):
    return x
"""
        neg = """
def f(xs):
    return [x for x in xs]
"""
        assert "PTA005" in codes_of(pos)
        assert "PTA005" in codes_of(pos_async)
        assert "PTA005" not in codes_of(neg)
        assert RULES["PTA005"].severity == ERROR

    def test_pta006_return_in_non_range_for(self):
        pos = """
def f(x, items):
    for it in items:
        if it > 0:
            return it
    return x
"""
        neg = """
def f(x):
    for i in range(10):
        if i > 5:
            return i
    return x
"""
        assert "PTA006" in codes_of(pos)
        assert "PTA006" not in codes_of(neg)

    def test_pta007_unreachable_exit_via_scanner(self):
        # PTA007 is the converter-side form: a plain exit that SURVIVED
        # the early-exit rewrite (include_plain_exits=True)
        import ast

        tree = ast.parse("while x > 0:\n    x = x - 1\n    break\n")
        node = tree.body[0]
        codes = {c for c, _ in scan_statement(node,
                                              include_plain_exits=True)}
        assert codes == {"PTA007"}
        assert not scan_statement(node)       # linter form: exits stage

    def test_scanner_covers_docstring_contract(self):
        """Every construct in the dy2static 'Deliberately NOT converted'
        list classifies to its code."""
        import ast

        cases = [
            ("if x:\n    del y\n", "PTA001"),
            ("if x:\n    global g\n", "PTA002"),
            ("if x:\n    nonlocal g\n", "PTA002"),
            ("while x:\n    x = 1\nelse:\n    x = 2\n", "PTA003"),
            ("for i in it:\n    x = 1\nelse:\n    x = 2\n", "PTA003"),
            ("if x:\n    with c:\n        return 1\n", "PTA004"),
            ("if x:\n    try:\n        break\n    finally:\n"
             "        pass\n", "PTA004"),
            ("if x:\n    for i in items:\n        return i\n", "PTA006"),
        ]
        for src, want in cases:
            node = ast.parse(src).body[0]
            got = {c for c, _ in scan_statement(node)}
            assert want in got, (src, want, got)


class TestConcretizationRules:
    def test_pta101_host_read(self):
        pos = "def f(x):\n    return x.numpy()\n"
        pos_item = "def f(x):\n    return x.mean().item()\n"
        neg = "def f(x):\n    return x + 1\n"
        assert "PTA101" in codes_of(pos)
        assert "PTA101" in codes_of(pos_item)
        assert "PTA101" not in codes_of(neg)

    def test_pta102_scalar_coercion(self):
        pos = "def f(x):\n    n = int(x)\n    return n\n"
        neg = "def f(x):\n    n = int(3.7)\n    return x + n\n"
        assert "PTA102" in codes_of(pos)
        assert "PTA102" not in codes_of(neg)

    def test_pta103_traced_branch_in_unconvertible_scope(self):
        pos = """
def f(x):
    if x > 0:
        del x
        return 1
    return 0
"""
        neg = """
def f(x):
    if x > 0:
        y = x * 2
    else:
        y = x - 1
    return y
"""
        assert "PTA103" in codes_of(pos)
        assert "PTA103" not in codes_of(neg)
        assert RULES["PTA103"].severity == ERROR


class TestRetraceRules:
    def test_pta201_mutable_global_read(self):
        pos = """
CACHE = {}

def f(x):
    y = CACHE.get("k", 0)
    return x + y
"""
        neg = """
SCALE = 2.5

def f(x):
    return x * SCALE
"""
        assert "PTA201" in codes_of(pos)
        assert "PTA201" not in codes_of(neg)

    def test_pta202_python_rng(self):
        pos = """
import random

def f(x):
    return x * random.random()
"""
        pos_np = """
import numpy as np

def f(x):
    return x + np.random.rand()
"""
        neg = """
def f(x):
    return x * 2.0
"""
        assert "PTA202" in codes_of(pos)
        assert "PTA202" in codes_of(pos_np)
        assert "PTA202" not in codes_of(neg)

    def test_pta203_shape_dependent_branch(self):
        pos = """
def f(x):
    if x.shape[0] > 1:
        return x * 2
    return x
"""
        neg = """
def f(x):
    if x.sum() > 1:
        y = x * 2
    else:
        y = x
    return y
"""
        assert "PTA203" in codes_of(pos)
        assert "PTA203" not in codes_of(neg)


class TestSideEffectRules:
    def test_pta301_module_state_mutation(self):
        pos = """
class L:
    def forward(self, x):
        self.last_input = x
        return x * 2
"""
        neg = """
class L:
    def forward(self, x):
        y = x * 2
        return y
"""
        assert "PTA301" in codes_of(pos)
        assert "PTA301" not in codes_of(neg)

    def test_pta302_outer_container_mutation(self):
        pos = """
RESULTS = []

def f(x):
    RESULTS.append(x)
    return x
"""
        neg = """
def f(x):
    results = []
    results.append(x)
    return results
"""
        assert "PTA302" in codes_of(pos)
        assert "PTA302" not in codes_of(neg)


class TestSelfLintRules:
    def test_pta401_module_level_jit(self):
        pos = """
import jax

def _impl(x, n):
    return x * n

f = jax.jit(_impl)
"""
        pos_dec = """
import jax

@jax.jit
def f(x):
    return x * 2
"""
        neg = """
import jax

def _impl(x, n):
    return x * n

f = jax.jit(_impl, static_argnums=1)
"""
        assert "PTA401" in codes_of(pos, mode="package")
        assert "PTA401" in codes_of(pos_dec, mode="package")
        assert "PTA401" not in codes_of(neg, mode="package")

    def test_pta402_tracer_leaking_cache(self):
        pos = """
_CACHE = {}

def f(key, x):
    _CACHE[key] = x
    return x
"""
        neg = """
_CACHE = {}

def f(key, x):
    _CACHE[key] = x  # noqa: PTA402
    return x
"""
        neg_slot = """
_CONFIG = [None]

def configure(cfg):
    _CONFIG[0] = cfg
"""
        assert "PTA402" in codes_of(pos, mode="package")
        assert "PTA402" not in codes_of(neg, mode="package")
        assert "PTA402" not in codes_of(neg_slot, mode="package")

    def test_package_mode_scopes_trace_rules_to_to_static(self):
        src = """
def helper(x):
    return x.numpy()

@to_static
def traced(x):
    return x.numpy()
"""
        diags = lint_source(src, filename="t.py", mode="package")
        lines = [d.line for d in diags if d.code == "PTA101"]
        assert lines == [7]       # only the decorated function flags


class TestNoqaAndFormatting:
    def test_bare_noqa_suppresses_everything(self):
        src = "def f(x):\n    return x.numpy()  # noqa\n"
        assert codes_of(src) == set()

    def test_listed_noqa_is_code_specific(self):
        src = "def f(x):\n    return x.numpy()  # noqa: PTA102\n"
        assert "PTA101" in codes_of(src)

    def test_diagnostic_format_and_registry(self):
        d = lint_source("def f(x):\n    return x.numpy()\n",
                        filename="m.py")[0]
        s = d.format()
        assert s.startswith("m.py:2: PTA101 warning:")
        assert "hint:" in s
        assert set(d.code for d in []) == set()
        for code, rule in RULES.items():
            assert rule.code == code and rule.hint and rule.title


class TestCheckApi:
    def test_check_reports_real_file_and_line(self):
        def leaky(x):
            y = x.numpy()
            return y

        diags = check(leaky)
        assert any(d.code == "PTA101" for d in diags)
        d = next(d for d in diags if d.code == "PTA101")
        assert d.file.endswith("test_analysis.py")
        src_line = open(__file__).read().splitlines()[d.line - 1]
        assert ".numpy()" in src_line

    def test_check_unwraps_to_static(self):
        @paddle.jit.to_static
        def g(x):
            return x.numpy()

        assert any(d.code == "PTA101" for d in check(g))

    def test_check_clean_function(self):
        def clean(x):
            return x * 2 + 1

        assert check(clean) == []

    def test_check_rejects_non_callables(self):
        with pytest.raises(TypeError):
            check(42)

    def test_to_static_check_kwarg_warns(self):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")

            @paddle.jit.to_static(check=True)
            def h(x):
                return x * _CFG["scale"]

        msgs = [str(x.message) for x in w
                if issubclass(x.category, TraceSafetyWarning)]
        assert any("PTA201" in m for m in msgs)
        # a retrace hazard WARNS but the function still compiles and runs
        np.testing.assert_allclose(h(_t([1.0, 2.0])).numpy(), [2.0, 4.0])


class TestConverterRuntimeError:
    def test_traced_predicate_cites_diagnostic(self):
        from paddle_tpu.jit.dy2static import UnconvertibleControlFlowError

        @paddle.jit.to_static
        def f(x):
            if paddle.sum(x) > 0:
                del x
                return _t(0.0)
            return x

        with pytest.raises(UnconvertibleControlFlowError) as ei:
            f(_t([1.0, 2.0]))
        msg = str(ei.value)
        assert "PTA001" in msg
        assert "hint:" in msg
        assert "test_analysis.py" in msg

    def test_concrete_predicate_keeps_python_semantics(self):
        from paddle_tpu.jit.dy2static import convert_to_static

        def f(x, flag):
            if flag:
                del flag
                return x + 1
            return x

        conv = convert_to_static(f)
        np.testing.assert_allclose(conv(_t(1.0), True).numpy(), 2.0)
        np.testing.assert_allclose(conv(_t(1.0), False).numpy(), 1.0)


class TestGraphDoctorJaxpr:
    def test_pta501_dead_compute(self):
        import jax
        import jax.numpy as jnp

        def f(a):
            dead = a + 5.0      # never used
            return a * 2.0

        j = jax.make_jaxpr(f)(jnp.ones(3))
        assert any(d.code == "PTA501" for d in diagnose_jaxpr(j))

        def g(a):
            return a * 2.0

        j2 = jax.make_jaxpr(g)(jnp.ones(3))
        assert not any(d.code == "PTA501" for d in diagnose_jaxpr(j2))

    def test_pta502_unused_input(self):
        import jax
        import jax.numpy as jnp

        j = jax.make_jaxpr(lambda a, b: a * 2.0)(jnp.ones(3), jnp.ones(3))
        assert any(d.code == "PTA502" for d in diagnose_jaxpr(j))
        j2 = jax.make_jaxpr(lambda a, b: a * b)(jnp.ones(3), jnp.ones(3))
        assert not any(d.code == "PTA502" for d in diagnose_jaxpr(j2))

    def test_pta503_silent_widening(self):
        import jax
        import jax.numpy as jnp

        def f(x):
            return jnp.asarray(x, jnp.float32) + 1.0

        j = jax.make_jaxpr(f)(jnp.ones(3, jnp.bfloat16))
        assert any(d.code == "PTA503" for d in diagnose_jaxpr(j))

        def g(x):                # stays bf16 throughout
            return x + jnp.ones(3, jnp.bfloat16)

        j2 = jax.make_jaxpr(g)(jnp.ones(3, jnp.bfloat16))
        assert not any(d.code == "PTA503" for d in diagnose_jaxpr(j2))

    def test_pta504_host_callback(self):
        import jax
        import jax.numpy as jnp

        def f(x):
            y = jax.pure_callback(
                lambda a: np.asarray(a) * 2.0,
                jax.ShapeDtypeStruct((3,), jnp.float32), x)
            return y + 1.0

        j = jax.make_jaxpr(f)(jnp.ones(3, jnp.float32))
        assert any(d.code == "PTA504" for d in diagnose_jaxpr(j))
        j2 = jax.make_jaxpr(lambda x: x * 2.0 + 1.0)(jnp.ones(3))
        assert not any(d.code == "PTA504" for d in diagnose_jaxpr(j2))

    def test_pta505_unbound_collective_axis(self):
        import jax
        import jax.numpy as jnp

        j = jax.make_jaxpr(lambda x: jax.lax.psum(x, "tp"),
                           axis_env=[("tp", 2)])(jnp.ones(3))
        diags = diagnose_jaxpr(j, mesh_axes=("dp", "mp"))
        assert any(d.code == "PTA505" for d in diags)
        ok = diagnose_jaxpr(j, mesh_axes=("tp", "dp"))
        assert not any(d.code == "PTA505" for d in ok)
        # no mesh given -> axis check is skipped, not spuriously failed
        assert not any(d.code == "PTA505" for d in diagnose_jaxpr(j))

    def test_doctor_traces_paddle_functions(self):
        def f(x):
            return x * 2.0 + 1.0

        diags = doctor(f, _t([1.0, 2.0, 3.0]))
        assert not any(d.severity == ERROR for d in diags)


class TestGraphDoctorProgram:
    def test_dead_node_and_unused_feed(self):
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                x = paddle.static.data("x", [4], "float32")
                paddle.static.data("unused", [4], "float32")
                y = x * 2.0
                dead = x + 5.0
                diags = diagnose_program([y], program=main)
                codes = {d.code for d in diags}
                assert "PTA501" in codes
                assert "PTA502" in codes
                # fetching everything clears PTA501; wiring the feed
                # clears PTA502
                all_fetched = diagnose_program([y, dead], program=main)
                assert not any(d.code == "PTA501" for d in all_fetched)
        finally:
            paddle.disable_static()

    def test_clean_program_is_clean(self):
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                x = paddle.static.data("x", [4], "float32")
                y = x * 2.0 + 1.0
                diags = diagnose_program([y], program=main)
                assert diags == []
        finally:
            paddle.disable_static()


class TestCli:
    def test_cli_flags_errors_and_exits_nonzero(self, tmp_path):
        from paddle_tpu.analysis.cli import main

        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\n\n"
            "def _impl(x, n):\n    return x * n\n\n"
            "f = jax.jit(_impl)\n")
        assert main([str(bad)]) == 1

    def test_cli_clean_file_exits_zero(self, tmp_path, capsys):
        from paddle_tpu.analysis.cli import main

        good = tmp_path / "good.py"
        good.write_text("def f(x):\n    return x + 1\n")
        assert main([str(good)]) == 0
        out = capsys.readouterr().out
        assert "0 error(s)" in out

    def test_cli_missing_path(self):
        from paddle_tpu.analysis.cli import main

        assert main(["/nonexistent/nowhere.py"]) == 2

    def test_cli_syntax_error_reports_pta000(self, tmp_path):
        broken = tmp_path / "broken.py"
        broken.write_text("def f(:\n")
        diags = lint_file(str(broken))
        assert len(diags) == 1 and diags[0].code == "PTA000"
        assert diags[0].severity == ERROR


def _jx():
    import jax.numpy as jnp

    return jnp


class TestServingLintRules:
    """PTA51x: thread-ownership & lock-discipline doctrine as code."""

    def _codes(self, src):
        return [d.code for d in serving_lint.lint_source(src, "t.py")]

    def test_pta510_engine_mutation_outside_worker(self):
        src = """
class Supervisor:
    def kill(self, worker):
        worker.engine.close()
"""
        assert self._codes(src) == ["PTA510"]

    def test_pta510_worker_owned_methods_are_clean(self):
        src = """
import threading

class Worker:
    def __init__(self):
        self.t = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        self._step()

    def _step(self):
        self.engine.step()
"""
        assert self._codes(src) == []

    def test_pta510_alias_is_tracked(self):
        src = """
class Supervisor:
    def reap(self):
        eng = self.engine
        eng.abort(1)
"""
        assert self._codes(src) == ["PTA510"]

    def test_pta511_handle_mutation_needs_lock(self):
        src = """
class Router:
    def mark(self, handle):
        handle.failing_over = True
"""
        assert self._codes(src) == ["PTA511"]
        locked = """
class Router:
    def mark(self, handle):
        with handle.lock:
            handle.failing_over = True
"""
        assert self._codes(locked) == []

    def test_pta512_blocking_under_lock(self):
        src = """
class W:
    def pump(self):
        with self.lock:
            item = self.q.get()
"""
        assert self._codes(src) == ["PTA512"]
        # dict.get(key, default) is a lookup, not a blocking wait
        lookup = """
class W:
    def pump(self):
        with self.lock:
            n = self.ordinals.get(("a", "b"), 0)
"""
        assert self._codes(lookup) == []

    def test_pta513_wallclock_in_fault_scope(self):
        src = """
import time

class FaultPlan:
    def schedule(self):
        return time.monotonic()
"""
        assert self._codes(src) == ["PTA513"]
        # failover paths are not fault-injection paths
        other = """
import time

class FailoverPolicy:
    def delay(self):
        return time.monotonic()
"""
        assert self._codes(other) == []

    def test_pta514_undisciplined_thread(self):
        src = """
import threading

class P:
    def start(self):
        self.t = threading.Thread(target=self._run)

    def _run(self):
        pass
"""
        assert self._codes(src) == ["PTA514"]
        joined = src.replace("def _run", """def stop(self):
        self.t.join()

    def _run""")
        assert self._codes(joined) == []

    @pytest.mark.parametrize("code", ["510", "511", "512", "513", "514"])
    def test_fixture_fires_exactly_once_and_noqa_suppresses(self, code):
        path = os.path.join(FIXTURES, f"pta{code}.py")
        diags = serving_lint.lint_file(path)
        assert [d.code for d in diags] == [f"PTA{code}"]
        d = diags[0]
        assert d.file == path and d.line > 0
        # the fixture's noqa'd counterpart was suppressed: the same
        # construct appears >= twice in the source
        with open(path) as fh:
            assert fh.read().count(f"noqa: PTA{code}") == 1

    def test_serving_check_maps_to_real_source(self):
        class Rogue:
            def kill(self, worker):
                worker.engine.close()

        diags = serving_check(Rogue)
        assert [d.code for d in diags] == ["PTA510"]
        assert diags[0].file.endswith("test_analysis.py")
        assert diags[0].line > 0


class TestDonationDoctor:
    """PTA60x: donation discipline, AST and jaxpr level."""

    def _codes(self, src):
        return [d.code for d in donation_doctor.lint_source(src, "t.py")]

    def test_pta601_use_after_donate(self):
        src = """
class E:
    def dispatch(self, step):
        fn = CompiledFn(step, donate_argnums=(0,))
        out = fn(self.buf)
        return self.buf.sum()
"""
        assert self._codes(src) == ["PTA601"]
        rebound = """
class E:
    def dispatch(self, step):
        fn = CompiledFn(step, donate_argnums=(0,))
        out = fn(self.buf)
        self.buf = out
        return self.buf.sum()
"""
        assert self._codes(rebound) == []

    def test_pta602_double_donation(self):
        src = """
class E:
    def dispatch(self, step):
        fn = CompiledFn(step, donate_argnums=(0, 1))
        out = fn(self.buf, self.buf)
        self.buf = out
        return out
"""
        assert self._codes(src) == ["PTA602"]

    def test_pta603_unrebound_engine_state(self):
        src = """
class E:
    def dispatch(self, step):
        fn = CompiledFn(step, donate_argnums=(0,))
        out = fn(self.pool.k)
        return out
"""
        assert self._codes(src) == ["PTA603"]
        rebound = """
class E:
    def dispatch(self, step):
        fn = CompiledFn(step, donate_argnums=(0,))
        out = fn(self.pool.k)
        self.pool.rebind(out)
        return out
"""
        assert self._codes(rebound) == []

    def test_donate_spec_resolves_ifexp_and_augassign(self):
        # the real engine shape: accumulated literal + conditional spec
        src = """
class E:
    def build(self, donate, quant):
        spec = (1, 2)
        if quant:
            spec += (3, 4)
        fn = CompiledFn(step, donate_argnums=spec if donate else ())
        out = fn(x, self.a, self.b, self.c, self.d)
        self.a, self.b = out[:2]
        self.c, self.d = out[2:]
        return out
"""
        assert self._codes(src) == []

    @pytest.mark.parametrize("code", ["601", "602", "603"])
    def test_fixture_fires_exactly_once_and_noqa_suppresses(self, code):
        path = os.path.join(FIXTURES, f"pta{code}.py")
        diags = donation_doctor.lint_file(path)
        assert [d.code for d in diags] == [f"PTA{code}"]
        assert diags[0].file == path and diags[0].line > 0

    def test_pta604_unfulfillable_donation_jaxpr(self):
        jnp = _jx()
        a = jnp.ones((4, 4))
        mod = _load_fixture("pta604")
        diags = diagnose_donation(mod.unfulfillable, a, a,
                                  donate_argnums=(0,))
        assert [d.code for d in diags] == ["PTA604"]
        assert diags[0].file.endswith("pta604.py") and diags[0].line > 0
        assert diagnose_donation(mod.unfulfillable_suppressed, a, a,
                                 donate_argnums=(0,)) == []
        assert diagnose_donation(mod.fulfillable, a, a,
                                 donate_argnums=(0,)) == []

    def test_pta602_out_of_range_and_duplicate_argnums(self):
        jnp = _jx()

        def f(a):
            return a

        diags = diagnose_donation(f, jnp.ones(3), donate_argnums=(0, 0))
        assert "PTA602" in {d.code for d in diags}
        diags = diagnose_donation(f, jnp.ones(3), donate_argnums=(5,))
        assert [d.code for d in diags] == ["PTA602"]

    def test_diagnose_donation_accepts_compiled_fn(self):
        from paddle_tpu.serving.engine import CompiledFn

        jnp = _jx()

        def step(a, b):
            return (a + b).sum()   # scalar out: donation unfulfillable

        fn = CompiledFn(step, donate_argnums=(0,))
        diags = diagnose_donation(fn, jnp.ones((4, 4)), jnp.ones((4, 4)))
        assert [d.code for d in diags] == ["PTA604"]


class TestCollectiveBalance:
    """PTA70x: static balance + census verification, no execution."""

    def test_pta701_unbalanced_cond(self):
        jnp = _jx()
        mod = _load_fixture("pta701")
        x = jnp.ones(4)
        diags = check_balance(mod.lopsided, x, True, axis_sizes={"dp": 2})
        assert [d.code for d in diags] == ["PTA701"]
        assert diags[0].file.endswith("pta701.py") and diags[0].line > 0
        assert check_balance(mod.lopsided_suppressed, x, True,
                             axis_sizes={"dp": 2}) == []
        assert check_balance(mod.balanced, x, True,
                             axis_sizes={"dp": 2}) == []

    def test_pta702_collective_in_while(self):
        jnp = _jx()
        mod = _load_fixture("pta702")
        x = jnp.ones(4)
        diags = check_balance(mod.chatty_loop, x, axis_sizes={"dp": 2})
        assert [d.code for d in diags] == ["PTA702"]
        assert check_balance(mod.chatty_loop_suppressed, x,
                             axis_sizes={"dp": 2}) == []
        assert check_balance(mod.quiet_loop, x, axis_sizes={"dp": 2}) == []

    def test_pta703_unbound_axis(self):
        jnp = _jx()
        mod = _load_fixture("pta703")
        x = jnp.ones(4)
        diags = check_balance(mod.stray_axis, x,
                              axis_env=[("mystery", 2)])
        assert [d.code for d in diags] == ["PTA703"]
        # declaring the axis (axis_sizes) binds it
        assert check_balance(mod.stray_axis, x,
                             axis_sizes={"mystery": 2}) == []
        assert check_balance(mod.stray_axis_suppressed, x,
                             axis_env=[("mystery", 2)]) == []

    def test_pta704_census_drift(self):
        jnp = _jx()
        mod = _load_fixture("pta704")
        x = jnp.ones(4)
        expected = {("psum", "dp"): 1}
        diags = check_census(mod.census_drifter, (x,), expected=expected,
                             axis_sizes={"dp": 2})
        assert [d.code for d in diags] == ["PTA704"]
        assert diags[0].file.endswith("pta704.py") and diags[0].line > 0
        assert check_census(mod.census_drifter_suppressed, (x,),
                            expected=expected, axis_sizes={"dp": 2}) == []
        assert check_census(mod.census_exact, (x,), expected=expected,
                            axis_sizes={"dp": 2}) == []

    def test_census_registry_formulas(self):
        from paddle_tpu.analysis import register_expected_census

        jnp = _jx()
        register_expected_census(
            "test-psum-linear", lambda n: {("psum", "dp"): n})

        def f(x):
            from jax import lax

            return lax.psum(x, "dp")

        assert check_census(f, (jnp.ones(4),), name="test-psum-linear",
                            formula_kwargs={"n": 1},
                            axis_sizes={"dp": 2}) == []
        drift = check_census(f, (jnp.ones(4),), name="test-psum-linear",
                             formula_kwargs={"n": 3},
                             axis_sizes={"dp": 2})
        assert [d.code for d in drift] == ["PTA704"]
        with pytest.raises(ValueError, match="registered formula"):
            check_census(f, (jnp.ones(4),), name="no-such-formula")

    def test_multichip_decode_census_reproduced_statically(self):
        """The acceptance gate: the balance checker reproduces the
        committed MULTICHIP decode census (psum=L*h,
        all_gather=(3L+1)*h) from the REAL compiled decode program
        without executing it, and finds the program balanced."""
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving import EngineConfig, MeshEngine

        cfg = GPTConfig(vocab_size=128, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4,
                        max_position_embeddings=64)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        m.eval()
        eng = MeshEngine(m, EngineConfig(num_slots=2, max_seq_len=32,
                                         max_horizon=4,
                                         prefix_block_size=4,
                                         prefix_cache_bytes=0),
                         tp=2, register_profiler=False)
        try:
            L, h = 2, 4
            fn, args = eng.decode_census_program(horizon=h)
            expected = eng.expected_decode_census(horizon=h)
            assert expected == {("psum", "tp"): L * h,
                                ("all_gather", "tp"): (3 * L + 1) * h}
            assert check_census(fn, args, expected=expected) == []
            # and a deliberately-wrong formula is caught
            bad = dict(expected)
            bad[("psum", "tp")] += 1
            assert [d.code for d in
                    check_census(fn, args, expected=bad)] == ["PTA704"]
            # balance: shard_map binds "tp" even under lax.scan
            assert check_balance(fn, *args) == []
        finally:
            eng.close()


class TestGraphDoctorShardMapScan:
    def test_pta505_respects_shard_map_bound_axes_under_scan(self):
        """Regression: shard_map under lax.scan (the MeshEngine decode
        shape) binds its mesh axes for the body — PTA505 must not
        fire, and truly-unbound axes still must."""
        import jax
        from jax import lax
        from jax.sharding import Mesh
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        jnp = _jx()
        mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("tp",))

        def body(x):
            return x + lax.psum(x, "tp")

        smapped = shard_map(body, mesh=mesh, in_specs=P("tp"),
                            out_specs=P("tp"))

        def scanned(x):
            def step(carry, _):
                return smapped(carry), None

            out, _ = lax.scan(step, x, None, length=3)
            return out

        closed = jax.make_jaxpr(scanned)(jnp.ones(2))
        diags = diagnose_jaxpr(closed, mesh_axes=set())
        assert not any(d.code == "PTA505" for d in diags)
        # the doctor and the balance checker agree (no double report)
        assert not any(d.code == "PTA703"
                       for d in check_balance(scanned, jnp.ones(2)))


class TestServingCli:
    def test_serving_flag_runs_phase2_analyzers(self, capsys):
        from paddle_tpu.analysis.cli import main

        path = os.path.join(FIXTURES, "pta510.py")
        assert main([path]) == 0          # phase 1 alone: clean
        assert main(["--serving", path]) == 1
        out = capsys.readouterr().out
        assert "PTA510" in out

    def test_json_mode_and_exit_contract(self, capsys):
        import json

        from paddle_tpu.analysis.cli import main

        path = os.path.join(FIXTURES, "pta511.py")
        assert main(["--serving", "--json", path]) == 1
        report = json.loads(capsys.readouterr().out)
        assert report["files"] == 1 and report["errors"] == 1
        [diag] = report["diagnostics"]
        assert diag["code"] == "PTA511" and diag["file"] == path
        assert diag["line"] > 0 and "lock" in diag["hint"]

    def test_json_clean_run(self, tmp_path, capsys):
        import json

        from paddle_tpu.analysis.cli import main

        good = tmp_path / "good.py"
        good.write_text("def f(x):\n    return x + 1\n")
        assert main(["--serving", "--json", str(good)]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report == {"files": 1, "errors": 0, "warnings": 0,
                          "diagnostics": []}

    def test_overlapping_paths_deduped(self, capsys):
        import json

        from paddle_tpu.analysis.cli import main

        path = os.path.join(FIXTURES, "pta511.py")
        assert main(["--serving", "--json", path, FIXTURES, path]) == 1
        report = json.loads(capsys.readouterr().out)
        n511 = [d["code"] for d in report["diagnostics"]].count("PTA511")
        assert n511 == 1

    def test_missing_path_and_internal_error_exit_two(self, capsys):
        from paddle_tpu.analysis.cli import main

        assert main(["/nonexistent/nowhere.py"]) == 2

    def test_repo_serving_gate_is_clean(self):
        """The acceptance gate CI runs: zero unsuppressed findings over
        the serving stack, strict (warnings fail too)."""
        from paddle_tpu.analysis.cli import main

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        pkg = os.path.join(root, "paddle_tpu")
        paths = [os.path.join(pkg, "serving"),
                 os.path.join(pkg, "serving", "gateway"),
                 os.path.join(pkg, "serving", "sharded"),
                 os.path.join(pkg, "observability")]
        assert main(["--serving", "--strict", "--json"] + paths) == 0


def test_rule_code_count_meets_acceptance():
    """The issue requires >= 8 distinct demonstrated rule codes; keep the
    registry honest about what this suite demonstrates."""
    demonstrated = {
        "PTA001", "PTA002", "PTA003", "PTA004", "PTA005", "PTA006",
        "PTA007", "PTA101", "PTA102", "PTA103", "PTA201", "PTA202",
        "PTA203", "PTA301", "PTA302", "PTA401", "PTA402",
        "PTA501", "PTA502", "PTA503", "PTA504", "PTA505",
        # phase 2: serving-stack verifiers
        "PTA510", "PTA511", "PTA512", "PTA513", "PTA514",
        "PTA601", "PTA602", "PTA603", "PTA604",
        "PTA701", "PTA702", "PTA703", "PTA704",
    }
    assert demonstrated <= (set(RULES) | {"PTA000"})
    assert len(demonstrated) >= 8
