"""paddle_tpu.analysis: trace-safety linter + graph doctor. Every PTA rule
code gets one positive (fires on a minimal repro) and one negative (silent
on the corrected version) case; the dy2static "Deliberately NOT converted"
docstring constructs are each machine-checked; the converter's runtime
error and to_static(check=True) share the same diagnostics."""

import os
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import (check, lint_source, lint_file,
                                 diagnose_jaxpr, diagnose_program,
                                 doctor, RULES, ERROR, TraceSafetyWarning)
from paddle_tpu.analysis.diagnostics import scan_statement


def _t(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


_CFG = {"scale": 2.0}        # mutable global the check=True test reads


def codes_of(src, mode="trace"):
    return {d.code for d in lint_source(src, filename="t.py", mode=mode)}


class TestConverterContractRules:
    """PTA0xx: the 'Deliberately NOT converted' docstring as rules."""

    def test_pta001_del_in_body(self):
        pos = """
def f(x):
    if x > 0:
        del x
    return 1
"""
        neg = """
def f(x):
    y = x * 2
    del x
    return y
"""
        assert "PTA001" in codes_of(pos)
        assert "PTA001" not in codes_of(neg)

    def test_pta002_global_nonlocal_in_body(self):
        pos = """
def f(x):
    if x > 0:
        global G
        G = 1
    return x
"""
        pos_nonlocal = """
def outer():
    n = 0
    def f(x):
        while x > 0:
            nonlocal n
            n = n + 1
            x = x - 1
        return x
    return f
"""
        neg = """
def f(x):
    global G
    G = 1
    return x
"""
        assert "PTA002" in codes_of(pos)
        assert "PTA002" in codes_of(pos_nonlocal)
        assert "PTA002" not in codes_of(neg)

    def test_pta003_loop_else(self):
        pos = """
def f(x):
    while x > 0:
        x = x - 1
    else:
        x = x + 1
    return x
"""
        pos_for = """
def f(x, items):
    for i in items:
        x = x + i
    else:
        x = x + 1
    return x
"""
        neg = """
def f(x):
    while x > 0:
        x = x - 1
    return x
"""
        assert "PTA003" in codes_of(pos)
        assert "PTA003" in codes_of(pos_for)
        assert "PTA003" not in codes_of(neg)

    def test_pta004_exit_inside_with_try(self):
        pos_with = """
def f(x):
    with open("/dev/null") as fh:
        if x > 0:
            return x
    return x + 1
"""
        pos_try = """
def f(x):
    while x > 0:
        try:
            x = x - 1
            break
        except ValueError:
            pass
    return x
"""
        neg = """
def f(x):
    with open("/dev/null") as fh:
        y = x + 1
    if x > 0:
        return y
    return x
"""
        assert "PTA004" in codes_of(pos_with)
        assert "PTA004" in codes_of(pos_try)
        assert "PTA004" not in codes_of(neg)

    def test_pta005_generator_coroutine(self):
        pos = """
def f(xs):
    for x in xs:
        yield x
"""
        pos_async = """
async def f(x):
    return x
"""
        neg = """
def f(xs):
    return [x for x in xs]
"""
        assert "PTA005" in codes_of(pos)
        assert "PTA005" in codes_of(pos_async)
        assert "PTA005" not in codes_of(neg)
        assert RULES["PTA005"].severity == ERROR

    def test_pta006_return_in_non_range_for(self):
        pos = """
def f(x, items):
    for it in items:
        if it > 0:
            return it
    return x
"""
        neg = """
def f(x):
    for i in range(10):
        if i > 5:
            return i
    return x
"""
        assert "PTA006" in codes_of(pos)
        assert "PTA006" not in codes_of(neg)

    def test_pta007_unreachable_exit_via_scanner(self):
        # PTA007 is the converter-side form: a plain exit that SURVIVED
        # the early-exit rewrite (include_plain_exits=True)
        import ast

        tree = ast.parse("while x > 0:\n    x = x - 1\n    break\n")
        node = tree.body[0]
        codes = {c for c, _ in scan_statement(node,
                                              include_plain_exits=True)}
        assert codes == {"PTA007"}
        assert not scan_statement(node)       # linter form: exits stage

    def test_scanner_covers_docstring_contract(self):
        """Every construct in the dy2static 'Deliberately NOT converted'
        list classifies to its code."""
        import ast

        cases = [
            ("if x:\n    del y\n", "PTA001"),
            ("if x:\n    global g\n", "PTA002"),
            ("if x:\n    nonlocal g\n", "PTA002"),
            ("while x:\n    x = 1\nelse:\n    x = 2\n", "PTA003"),
            ("for i in it:\n    x = 1\nelse:\n    x = 2\n", "PTA003"),
            ("if x:\n    with c:\n        return 1\n", "PTA004"),
            ("if x:\n    try:\n        break\n    finally:\n"
             "        pass\n", "PTA004"),
            ("if x:\n    for i in items:\n        return i\n", "PTA006"),
        ]
        for src, want in cases:
            node = ast.parse(src).body[0]
            got = {c for c, _ in scan_statement(node)}
            assert want in got, (src, want, got)


class TestConcretizationRules:
    def test_pta101_host_read(self):
        pos = "def f(x):\n    return x.numpy()\n"
        pos_item = "def f(x):\n    return x.mean().item()\n"
        neg = "def f(x):\n    return x + 1\n"
        assert "PTA101" in codes_of(pos)
        assert "PTA101" in codes_of(pos_item)
        assert "PTA101" not in codes_of(neg)

    def test_pta102_scalar_coercion(self):
        pos = "def f(x):\n    n = int(x)\n    return n\n"
        neg = "def f(x):\n    n = int(3.7)\n    return x + n\n"
        assert "PTA102" in codes_of(pos)
        assert "PTA102" not in codes_of(neg)

    def test_pta103_traced_branch_in_unconvertible_scope(self):
        pos = """
def f(x):
    if x > 0:
        del x
        return 1
    return 0
"""
        neg = """
def f(x):
    if x > 0:
        y = x * 2
    else:
        y = x - 1
    return y
"""
        assert "PTA103" in codes_of(pos)
        assert "PTA103" not in codes_of(neg)
        assert RULES["PTA103"].severity == ERROR


class TestRetraceRules:
    def test_pta201_mutable_global_read(self):
        pos = """
CACHE = {}

def f(x):
    y = CACHE.get("k", 0)
    return x + y
"""
        neg = """
SCALE = 2.5

def f(x):
    return x * SCALE
"""
        assert "PTA201" in codes_of(pos)
        assert "PTA201" not in codes_of(neg)

    def test_pta202_python_rng(self):
        pos = """
import random

def f(x):
    return x * random.random()
"""
        pos_np = """
import numpy as np

def f(x):
    return x + np.random.rand()
"""
        neg = """
def f(x):
    return x * 2.0
"""
        assert "PTA202" in codes_of(pos)
        assert "PTA202" in codes_of(pos_np)
        assert "PTA202" not in codes_of(neg)

    def test_pta203_shape_dependent_branch(self):
        pos = """
def f(x):
    if x.shape[0] > 1:
        return x * 2
    return x
"""
        neg = """
def f(x):
    if x.sum() > 1:
        y = x * 2
    else:
        y = x
    return y
"""
        assert "PTA203" in codes_of(pos)
        assert "PTA203" not in codes_of(neg)


class TestSideEffectRules:
    def test_pta301_module_state_mutation(self):
        pos = """
class L:
    def forward(self, x):
        self.last_input = x
        return x * 2
"""
        neg = """
class L:
    def forward(self, x):
        y = x * 2
        return y
"""
        assert "PTA301" in codes_of(pos)
        assert "PTA301" not in codes_of(neg)

    def test_pta302_outer_container_mutation(self):
        pos = """
RESULTS = []

def f(x):
    RESULTS.append(x)
    return x
"""
        neg = """
def f(x):
    results = []
    results.append(x)
    return results
"""
        assert "PTA302" in codes_of(pos)
        assert "PTA302" not in codes_of(neg)


class TestSelfLintRules:
    def test_pta401_module_level_jit(self):
        pos = """
import jax

def _impl(x, n):
    return x * n

f = jax.jit(_impl)
"""
        pos_dec = """
import jax

@jax.jit
def f(x):
    return x * 2
"""
        neg = """
import jax

def _impl(x, n):
    return x * n

f = jax.jit(_impl, static_argnums=1)
"""
        assert "PTA401" in codes_of(pos, mode="package")
        assert "PTA401" in codes_of(pos_dec, mode="package")
        assert "PTA401" not in codes_of(neg, mode="package")

    def test_pta402_tracer_leaking_cache(self):
        pos = """
_CACHE = {}

def f(key, x):
    _CACHE[key] = x
    return x
"""
        neg = """
_CACHE = {}

def f(key, x):
    _CACHE[key] = x  # noqa: PTA402
    return x
"""
        neg_slot = """
_CONFIG = [None]

def configure(cfg):
    _CONFIG[0] = cfg
"""
        assert "PTA402" in codes_of(pos, mode="package")
        assert "PTA402" not in codes_of(neg, mode="package")
        assert "PTA402" not in codes_of(neg_slot, mode="package")

    def test_package_mode_scopes_trace_rules_to_to_static(self):
        src = """
def helper(x):
    return x.numpy()

@to_static
def traced(x):
    return x.numpy()
"""
        diags = lint_source(src, filename="t.py", mode="package")
        lines = [d.line for d in diags if d.code == "PTA101"]
        assert lines == [7]       # only the decorated function flags


class TestNoqaAndFormatting:
    def test_bare_noqa_suppresses_everything(self):
        src = "def f(x):\n    return x.numpy()  # noqa\n"
        assert codes_of(src) == set()

    def test_listed_noqa_is_code_specific(self):
        src = "def f(x):\n    return x.numpy()  # noqa: PTA102\n"
        assert "PTA101" in codes_of(src)

    def test_diagnostic_format_and_registry(self):
        d = lint_source("def f(x):\n    return x.numpy()\n",
                        filename="m.py")[0]
        s = d.format()
        assert s.startswith("m.py:2: PTA101 warning:")
        assert "hint:" in s
        assert set(d.code for d in []) == set()
        for code, rule in RULES.items():
            assert rule.code == code and rule.hint and rule.title


class TestCheckApi:
    def test_check_reports_real_file_and_line(self):
        def leaky(x):
            y = x.numpy()
            return y

        diags = check(leaky)
        assert any(d.code == "PTA101" for d in diags)
        d = next(d for d in diags if d.code == "PTA101")
        assert d.file.endswith("test_analysis.py")
        src_line = open(__file__).read().splitlines()[d.line - 1]
        assert ".numpy()" in src_line

    def test_check_unwraps_to_static(self):
        @paddle.jit.to_static
        def g(x):
            return x.numpy()

        assert any(d.code == "PTA101" for d in check(g))

    def test_check_clean_function(self):
        def clean(x):
            return x * 2 + 1

        assert check(clean) == []

    def test_check_rejects_non_callables(self):
        with pytest.raises(TypeError):
            check(42)

    def test_to_static_check_kwarg_warns(self):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")

            @paddle.jit.to_static(check=True)
            def h(x):
                return x * _CFG["scale"]

        msgs = [str(x.message) for x in w
                if issubclass(x.category, TraceSafetyWarning)]
        assert any("PTA201" in m for m in msgs)
        # a retrace hazard WARNS but the function still compiles and runs
        np.testing.assert_allclose(h(_t([1.0, 2.0])).numpy(), [2.0, 4.0])


class TestConverterRuntimeError:
    def test_traced_predicate_cites_diagnostic(self):
        from paddle_tpu.jit.dy2static import UnconvertibleControlFlowError

        @paddle.jit.to_static
        def f(x):
            if paddle.sum(x) > 0:
                del x
                return _t(0.0)
            return x

        with pytest.raises(UnconvertibleControlFlowError) as ei:
            f(_t([1.0, 2.0]))
        msg = str(ei.value)
        assert "PTA001" in msg
        assert "hint:" in msg
        assert "test_analysis.py" in msg

    def test_concrete_predicate_keeps_python_semantics(self):
        from paddle_tpu.jit.dy2static import convert_to_static

        def f(x, flag):
            if flag:
                del flag
                return x + 1
            return x

        conv = convert_to_static(f)
        np.testing.assert_allclose(conv(_t(1.0), True).numpy(), 2.0)
        np.testing.assert_allclose(conv(_t(1.0), False).numpy(), 1.0)


class TestGraphDoctorJaxpr:
    def test_pta501_dead_compute(self):
        import jax
        import jax.numpy as jnp

        def f(a):
            dead = a + 5.0      # never used
            return a * 2.0

        j = jax.make_jaxpr(f)(jnp.ones(3))
        assert any(d.code == "PTA501" for d in diagnose_jaxpr(j))

        def g(a):
            return a * 2.0

        j2 = jax.make_jaxpr(g)(jnp.ones(3))
        assert not any(d.code == "PTA501" for d in diagnose_jaxpr(j2))

    def test_pta502_unused_input(self):
        import jax
        import jax.numpy as jnp

        j = jax.make_jaxpr(lambda a, b: a * 2.0)(jnp.ones(3), jnp.ones(3))
        assert any(d.code == "PTA502" for d in diagnose_jaxpr(j))
        j2 = jax.make_jaxpr(lambda a, b: a * b)(jnp.ones(3), jnp.ones(3))
        assert not any(d.code == "PTA502" for d in diagnose_jaxpr(j2))

    def test_pta503_silent_widening(self):
        import jax
        import jax.numpy as jnp

        def f(x):
            return jnp.asarray(x, jnp.float32) + 1.0

        j = jax.make_jaxpr(f)(jnp.ones(3, jnp.bfloat16))
        assert any(d.code == "PTA503" for d in diagnose_jaxpr(j))

        def g(x):                # stays bf16 throughout
            return x + jnp.ones(3, jnp.bfloat16)

        j2 = jax.make_jaxpr(g)(jnp.ones(3, jnp.bfloat16))
        assert not any(d.code == "PTA503" for d in diagnose_jaxpr(j2))

    def test_pta504_host_callback(self):
        import jax
        import jax.numpy as jnp

        def f(x):
            y = jax.pure_callback(
                lambda a: np.asarray(a) * 2.0,
                jax.ShapeDtypeStruct((3,), jnp.float32), x)
            return y + 1.0

        j = jax.make_jaxpr(f)(jnp.ones(3, jnp.float32))
        assert any(d.code == "PTA504" for d in diagnose_jaxpr(j))
        j2 = jax.make_jaxpr(lambda x: x * 2.0 + 1.0)(jnp.ones(3))
        assert not any(d.code == "PTA504" for d in diagnose_jaxpr(j2))

    def test_pta505_unbound_collective_axis(self):
        import jax
        import jax.numpy as jnp

        j = jax.make_jaxpr(lambda x: jax.lax.psum(x, "tp"),
                           axis_env=[("tp", 2)])(jnp.ones(3))
        diags = diagnose_jaxpr(j, mesh_axes=("dp", "mp"))
        assert any(d.code == "PTA505" for d in diags)
        ok = diagnose_jaxpr(j, mesh_axes=("tp", "dp"))
        assert not any(d.code == "PTA505" for d in ok)
        # no mesh given -> axis check is skipped, not spuriously failed
        assert not any(d.code == "PTA505" for d in diagnose_jaxpr(j))

    def test_doctor_traces_paddle_functions(self):
        def f(x):
            return x * 2.0 + 1.0

        diags = doctor(f, _t([1.0, 2.0, 3.0]))
        assert not any(d.severity == ERROR for d in diags)


class TestGraphDoctorProgram:
    def test_dead_node_and_unused_feed(self):
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                x = paddle.static.data("x", [4], "float32")
                paddle.static.data("unused", [4], "float32")
                y = x * 2.0
                dead = x + 5.0
                diags = diagnose_program([y], program=main)
                codes = {d.code for d in diags}
                assert "PTA501" in codes
                assert "PTA502" in codes
                # fetching everything clears PTA501; wiring the feed
                # clears PTA502
                all_fetched = diagnose_program([y, dead], program=main)
                assert not any(d.code == "PTA501" for d in all_fetched)
        finally:
            paddle.disable_static()

    def test_clean_program_is_clean(self):
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                x = paddle.static.data("x", [4], "float32")
                y = x * 2.0 + 1.0
                diags = diagnose_program([y], program=main)
                assert diags == []
        finally:
            paddle.disable_static()


class TestCli:
    def test_cli_flags_errors_and_exits_nonzero(self, tmp_path):
        from paddle_tpu.analysis.cli import main

        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\n\n"
            "def _impl(x, n):\n    return x * n\n\n"
            "f = jax.jit(_impl)\n")
        assert main([str(bad)]) == 1

    def test_cli_clean_file_exits_zero(self, tmp_path, capsys):
        from paddle_tpu.analysis.cli import main

        good = tmp_path / "good.py"
        good.write_text("def f(x):\n    return x + 1\n")
        assert main([str(good)]) == 0
        out = capsys.readouterr().out
        assert "0 error(s)" in out

    def test_cli_missing_path(self):
        from paddle_tpu.analysis.cli import main

        assert main(["/nonexistent/nowhere.py"]) == 2

    def test_cli_syntax_error_reports_pta000(self, tmp_path):
        broken = tmp_path / "broken.py"
        broken.write_text("def f(:\n")
        diags = lint_file(str(broken))
        assert len(diags) == 1 and diags[0].code == "PTA000"
        assert diags[0].severity == ERROR


def test_rule_code_count_meets_acceptance():
    """The issue requires >= 8 distinct demonstrated rule codes; keep the
    registry honest about what this suite demonstrates."""
    demonstrated = {
        "PTA001", "PTA002", "PTA003", "PTA004", "PTA005", "PTA006",
        "PTA007", "PTA101", "PTA102", "PTA103", "PTA201", "PTA202",
        "PTA203", "PTA301", "PTA302", "PTA401", "PTA402",
        "PTA501", "PTA502", "PTA503", "PTA504", "PTA505",
    }
    assert demonstrated <= (set(RULES) | {"PTA000"})
    assert len(demonstrated) >= 8
