"""Pipeline parallelism tests: the compiled ppermute schedule must match the
serial model numerically (SURVEY.md §4 — the reference asserts
hybrid-parallel losses equal the single-process run; same invariant here, on
the 8-device CPU mesh)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
    SharedLayerDesc,
)
from paddle_tpu.jit.train_step import TrainStep

H = 16


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(H, H)

    def forward(self, x):
        return paddle.tanh(self.fc(x))


class Head(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(H, 4)

    def forward(self, x):
        return self.fc(x)


def _mse(logits, labels):
    return nn.functional.mse_loss(logits, labels)


def _descs():
    return ([LayerDesc(nn.Linear, 8, H)] +
            [LayerDesc(Block) for _ in range(6)] +
            [LayerDesc(Head)])


def _batch(B=16):
    rng = np.random.RandomState(0)
    return (rng.randn(B, 8).astype(np.float32),
            rng.randn(B, 4).astype(np.float32))


def _serial_losses(pp_model, n_steps=3, lr=0.05, n_micro=4):
    """Reference: same PipelineLayer trained serially, microbatch-averaged
    loss (grad accumulation == microbatching)."""
    opt = paddle.optimizer.Momentum(learning_rate=lr,
                                    parameters=pp_model.parameters())

    def loss_fn(model, x, y):
        xs, ys = x._data, y._data
        n = n_micro
        mb = xs.shape[0] // n
        total = None
        for i in range(n):
            out = model(paddle.Tensor(xs[i * mb:(i + 1) * mb]))
            l = _mse(out, paddle.Tensor(ys[i * mb:(i + 1) * mb]))
            total = l if total is None else total + l
        return total / n

    step = TrainStep(pp_model, loss_fn, opt)
    x, y = _batch()
    return [float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
            for _ in range(n_steps)]


class TestSegmentation:
    def test_uniform(self):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=4)
        pl = PipelineLayer(_descs(), loss_fn=_mse)
        assert pl.num_stages == 4
        assert pl.segment_parts[0] == 0 and pl.segment_parts[-1] == 8
        sizes = [pl.segment_parts[i + 1] - pl.segment_parts[i] for i in range(4)]
        assert sum(sizes) == 8 and max(sizes) - min(sizes) <= 1

    def test_layer_seg_method(self):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=2)
        pl = PipelineLayer(_descs(), loss_fn=_mse, seg_method="layer:Block")
        # prefix (input Linear) joins stage 0; blocks split 3/3
        assert pl.segment_parts == [0, 4, 8]

    def test_stage_param_names(self):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=4)
        pl = PipelineLayer(_descs(), loss_fn=_mse)
        all_names = set(pl.state_dict())
        per_stage = [set(pl.stage_param_names(k)) for k in range(4)]
        assert set().union(*per_stage) == all_names
        for a in range(4):
            for b in range(a + 1, 4):
                assert not (per_stage[a] & per_stage[b])


class TestPipelineParity:
    @pytest.mark.parametrize("pp,n_micro", [(2, 4), (4, 8), (1, 4)])
    def test_train_batch_matches_serial(self, pp, n_micro):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=pp)
        paddle.seed(7)
        model = PipelineLayer(_descs(), loss_fn=_mse)
        ref = _serial_losses(model, n_micro=n_micro)

        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(pp=pp)
        paddle.seed(7)
        model2 = PipelineLayer(_descs(), loss_fn=_mse)
        runner = PipelineParallel(model2, hcg,
                                  {"accumulate_steps": n_micro})
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=model2.parameters())
        x, y = _batch()
        losses = [float(runner.train_batch(
            (paddle.to_tensor(x), paddle.to_tensor(y)), opt))
            for _ in range(3)]
        np.testing.assert_allclose(losses, ref, rtol=2e-5, atol=1e-6)

    def test_dp_pp_composition(self):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=2)
        paddle.seed(9)
        model = PipelineLayer(_descs(), loss_fn=_mse)
        ref = _serial_losses(model, n_micro=2)

        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(dp=4, pp=2)
        paddle.seed(9)
        model2 = PipelineLayer(_descs(), loss_fn=_mse)
        runner = PipelineParallel(model2, hcg, {"accumulate_steps": 2})
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=model2.parameters())
        x, y = _batch()
        losses = [float(runner.train_batch(
            (paddle.to_tensor(x), paddle.to_tensor(y)), opt))
            for _ in range(3)]
        np.testing.assert_allclose(losses, ref, rtol=2e-5, atol=1e-6)

    def test_recompute_matches(self):
        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(pp=2)
        paddle.seed(11)
        model = PipelineLayer(_descs(), loss_fn=_mse)
        runner = PipelineParallel(model, hcg, {"accumulate_steps": 2})
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=model.parameters())
        x, y = _batch()
        base = float(runner.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt))

        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(pp=2)
        paddle.seed(11)
        model_r = PipelineLayer(_descs(), loss_fn=_mse, recompute_interval=1)
        runner_r = PipelineParallel(model_r, hcg, {"accumulate_steps": 2})
        opt_r = paddle.optimizer.Momentum(learning_rate=0.05,
                                          parameters=model_r.parameters())
        remat = float(runner_r.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt_r))
        np.testing.assert_allclose(remat, base, rtol=1e-6)

    def test_eval_batch(self):
        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(pp=2)
        model = PipelineLayer(_descs(), loss_fn=_mse)
        runner = PipelineParallel(model, hcg, {"accumulate_steps": 2})
        x, y = _batch()
        loss = runner.eval_batch((paddle.to_tensor(x), paddle.to_tensor(y)))
        assert np.isfinite(float(loss))


class TestSharedLayerDesc:
    def test_tied_weights_single_instance(self):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=2)
        descs = ([SharedLayerDesc("emb", nn.Linear, 8, H)] +
                 [LayerDesc(Block) for _ in range(2)] +
                 [SharedLayerDesc("emb", nn.Linear, 8, H,
                                  forward_func=lambda l, x: l(x))])
        pl = PipelineLayer(descs, loss_fn=_mse)
        names = [n for n, _ in pl.named_parameters()]
        # tied layer contributes its params exactly once
        assert len(names) == len(set(names))
        n_linear_params = sum(1 for n in names if n.startswith(("0.", "3.")))
        assert n_linear_params == 2  # weight+bias of the ONE shared instance


class MPBlock(nn.Layer):
    """Megatron block: column-parallel → gelu → row-parallel."""

    def __init__(self):
        super().__init__()
        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers import (
            ColumnParallelLinear, RowParallelLinear,
        )

        self.col = ColumnParallelLinear(H, 2 * H, gather_output=False,
                                        has_bias=True)
        self.row = RowParallelLinear(2 * H, H, input_is_parallel=True,
                                     has_bias=True)

    def forward(self, x):
        return x + self.row(nn.functional.gelu(self.col(x)))


class TestPipelineTensorParallel:
    """pp×mp(×dp) composition: mp-layer params enter shard_map sharded over
    'mp' and issue explicit Megatron collectives inside each stage."""

    @pytest.mark.parametrize("dp,pp,mp", [(1, 2, 2), (2, 2, 2)])
    def test_pp_mp_matches_serial(self, dp, pp, mp):
        def mp_descs():
            return ([LayerDesc(nn.Linear, 8, H)] +
                    [LayerDesc(MPBlock) for _ in range(4)] +
                    [LayerDesc(Head)])

        n_micro = 4
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=1, mp=1)
        paddle.seed(11)
        serial_model = PipelineLayer(mp_descs(), loss_fn=_mse)
        ref = _serial_losses(serial_model, n_micro=n_micro)

        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(dp=dp, pp=pp, mp=mp)
        paddle.seed(11)
        model = PipelineLayer(mp_descs(), loss_fn=_mse)
        ppm = PipelineParallel(model, hcg=hcg,
                               strategy={"accumulate_steps": n_micro})
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=ppm.parameters())
        x, y = _batch()
        losses = [float(ppm.train_batch(
            (paddle.to_tensor(x), paddle.to_tensor(y)), opt))
            for _ in range(3)]
        np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=1e-5)


class TestInterleavedVPP:
    """num_virtual_pipeline_stages > 1: chunks interleave round-robin over
    ranks; losses must still match the serial model exactly."""

    @pytest.mark.parametrize("pp,v,n_micro", [(2, 2, 4), (4, 2, 8)])
    def test_vpp_matches_serial(self, pp, v, n_micro):
        def vdescs():
            return ([LayerDesc(nn.Linear, 8, H)] +
                    [LayerDesc(Block) for _ in range(2 * pp * v - 2)] +
                    [LayerDesc(Head)])

        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=1)
        paddle.seed(21)
        serial_model = PipelineLayer(vdescs(), loss_fn=_mse)
        ref = _serial_losses(serial_model, n_micro=n_micro)

        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(pp=pp)
        paddle.seed(21)
        model = PipelineLayer(vdescs(), loss_fn=_mse,
                              num_virtual_pipeline_stages=v)
        assert len(model.segment_parts) == pp * v + 1
        ppm = PipelineParallel(model, hcg=hcg,
                               strategy={"accumulate_steps": n_micro})
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=ppm.parameters())
        x, y = _batch()
        losses = [float(ppm.train_batch(
            (paddle.to_tensor(x), paddle.to_tensor(y)), opt))
            for _ in range(3)]
        np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=1e-5)

    def test_vpp_chunk_ownership(self):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=2)
        pl = PipelineLayer(_descs(), loss_fn=_mse,
                           num_virtual_pipeline_stages=2)
        # 8 items over 4 chunks; rank r owns chunks r and r+2
        all_names = set(pl.state_dict())
        s0 = set(pl.stage_param_names(0))
        s1 = set(pl.stage_param_names(1))
        assert s0 | s1 == all_names
        assert not (s0 & s1)


class TestPipelineMemory:
    """Measured memory semantics of the compiled schedule (VERDICT r1 item 3):
    activation residuals grow O(accumulate_steps), but under recompute the
    per-microbatch growth is only the tick's boundary tensors (x_mb + hidden
    + y_mb), not the stages' internal activations."""

    def _temp_bytes(self, n_micro, remat, mb=8, h=256, schedule="gpipe"):
        import jax
        import jax.numpy as jnp

        class WideBlock(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(h, h)

            def forward(self, x):
                return paddle.tanh(self.fc(x))

        hcg = dist.create_hybrid_communicate_group(pp=4)
        descs = [LayerDesc(nn.Linear, 32, h)] + \
            [LayerDesc(WideBlock) for _ in range(7)]
        pl = PipelineLayer(descs, loss_fn=_mse,
                           recompute_interval=1 if remat else 0)
        pp = PipelineParallel(pl, hcg, {"accumulate_steps": n_micro,
                                        "schedule": schedule})
        pure, names = pp._pipeline_pure_fn(n_micro)
        sd = pl.state_dict()
        params = [sd[n]._data for n in names]
        x = jnp.zeros((n_micro, mb, 32), jnp.float32)
        y = jnp.zeros((n_micro, mb, h), jnp.float32)
        key = jax.random.key(0)
        grad_fn = jax.jit(jax.grad(lambda ps, xx, yy, k: pure(xx, yy, k, *ps)))
        comp = grad_fn.lower(params, x, y, key).compile()
        return comp.memory_analysis().temp_size_in_bytes

    def test_remat_growth_is_boundary_sized(self):
        mb, h = 8, 256
        per_micro_remat = (self._temp_bytes(32, True) -
                           self._temp_bytes(4, True)) / 28
        per_micro_plain = (self._temp_bytes(32, False) -
                           self._temp_bytes(4, False)) / 28
        # boundary tensors per tick: x_mb [8,32] + hid [8,256] + y_mb [8,256]
        boundary = mb * 32 * 4 + 2 * mb * h * 4
        # remat growth ~= boundary (allow 2x for XLA padding/layout slack)
        assert per_micro_remat < 2 * boundary, (per_micro_remat, boundary)
        # and clearly smaller than the no-remat full-activation growth
        assert per_micro_remat < 0.5 * per_micro_plain, (
            per_micro_remat, per_micro_plain)


class TestPipeline1F1B:
    """Literal 1F1B schedule (VERDICT r2 item 4): hand-interleaved
    per-microbatch fwd/bwd with residuals in a depth-bounded ring buffer —
    parity with serial, composes with dp, and in-flight activations are
    O(pp_depth), not O(accumulate_steps)."""

    @pytest.mark.parametrize("pp,n_micro", [(2, 4), (4, 8), (4, 16)])
    def test_matches_serial(self, pp, n_micro):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=pp)
        paddle.seed(7)
        model = PipelineLayer(_descs(), loss_fn=_mse)
        ref = _serial_losses(model, n_micro=n_micro)

        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(pp=pp)
        paddle.seed(7)
        model2 = PipelineLayer(_descs(), loss_fn=_mse)
        runner = PipelineParallel(model2, hcg,
                                  {"accumulate_steps": n_micro,
                                   "schedule": "1f1b"})
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=model2.parameters())
        x, y = _batch()
        losses = [float(runner.train_batch(
            (paddle.to_tensor(x), paddle.to_tensor(y)), opt))
            for _ in range(3)]
        np.testing.assert_allclose(losses, ref, rtol=2e-5, atol=1e-6)

    def test_dp_pp_composition(self):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=2)
        paddle.seed(9)
        model = PipelineLayer(_descs(), loss_fn=_mse)
        ref = _serial_losses(model, n_micro=4)

        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(dp=2, pp=2)
        paddle.seed(9)
        model2 = PipelineLayer(_descs(), loss_fn=_mse)
        runner = PipelineParallel(model2, hcg,
                                  {"accumulate_steps": 4,
                                   "schedule": "1f1b"})
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=model2.parameters())
        x, y = _batch()
        losses = [float(runner.train_batch(
            (paddle.to_tensor(x), paddle.to_tensor(y)), opt))
            for _ in range(3)]
        np.testing.assert_allclose(losses, ref, rtol=2e-5, atol=1e-6)

    def test_in_flight_activations_depth_bounded(self):
        """The VERDICT r2 requirement verbatim: at accumulate_steps=32 with
        no recompute, 1F1B's in-flight activation memory must be bounded by
        pipeline depth — measured growth per extra microbatch ~0 — while
        the jax.grad GPipe schedule grows O(accumulate_steps)."""
        mem = TestPipelineMemory()
        g32 = mem._temp_bytes(32, False, schedule="1f1b")
        g4 = mem._temp_bytes(4, False, schedule="1f1b")
        p32 = mem._temp_bytes(32, False, schedule="gpipe")
        p4 = mem._temp_bytes(4, False, schedule="gpipe")
        gpipe_growth = (p32 - p4) / 28
        onef_growth = (g32 - g4) / 28
        # GPipe no-remat grows by roughly a full stage-residual per extra
        # microbatch; 1F1B's ring buffer is sized by depth, so growth per
        # microbatch must be a small fraction of GPipe's
        assert gpipe_growth > 0
        assert onef_growth < 0.2 * gpipe_growth, (onef_growth, gpipe_growth)
        # and absolute temp memory at M=32 must be well under GPipe's
        assert g32 < 0.7 * p32, (g32, p32)

    def test_mp_pp_composition_matches_gpipe(self):
        """pp2 x mp2 (+ the hand grad psum rules: replicated params psum
        over mp, sharded params not): 1F1B must reproduce the gpipe
        schedule (itself serial-parity-tested) step for step."""
        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers import (
            ColumnParallelLinear, RowParallelLinear,
        )

        H2 = 32

        class MPBlock(nn.Layer):
            def __init__(self):
                super().__init__()
                self.col = ColumnParallelLinear(H2, 2 * H2,
                                                gather_output=False)
                self.row = RowParallelLinear(2 * H2, H2,
                                             input_is_parallel=True)

            def forward(self, x):
                return x + self.row(nn.functional.gelu(self.col(x)))

        def run(schedule):
            dist.set_hybrid_communicate_group(None)
            hcg = dist.create_hybrid_communicate_group(pp=2, mp=2)
            paddle.seed(11)
            pl = PipelineLayer(
                [LayerDesc(nn.Linear, 16, H2)] +
                [LayerDesc(MPBlock) for _ in range(4)] +
                [LayerDesc(nn.Linear, H2, 8)],
                loss_fn=_mse)
            runner = PipelineParallel(pl, hcg,
                                      {"accumulate_steps": 4,
                                       "schedule": schedule})
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=pl.parameters())
            rng = np.random.RandomState(3)
            x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
            y = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
            return [float(runner.train_batch((x, y), opt))
                    for _ in range(3)]

        ref = run("gpipe")
        got = run("1f1b")
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)

    def test_pp1_falls_back_to_serial_builder(self):
        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(pp=1)
        paddle.seed(5)
        model = PipelineLayer(_descs(), loss_fn=_mse)
        runner = PipelineParallel(model, hcg, {"accumulate_steps": 4,
                                               "schedule": "1f1b"})
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=model.parameters())
        x, y = _batch()
        loss = float(runner.train_batch(
            (paddle.to_tensor(x), paddle.to_tensor(y)), opt))
        assert np.isfinite(loss)

    @staticmethod
    def _tied_descs():
        """GPT-style tying: the embedding Linear(8,H) on stage 0 is reused
        as the output head (x @ W.T: H->8) on the LAST stage."""
        return ([SharedLayerDesc("emb", nn.Linear, 8, H)] +
                [LayerDesc(Block) for _ in range(4)] +
                [SharedLayerDesc(
                    "emb", nn.Linear, 8, H,
                    forward_func=lambda lyr, x: paddle.matmul(
                        x, lyr.weight, transpose_y=True))])

    def _run_tied(self, schedule, v=1, n_micro=4, pp=2):
        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(pp=pp)
        paddle.seed(5)
        model = PipelineLayer(self._tied_descs(), loss_fn=_mse,
                              num_virtual_pipeline_stages=v)
        runner = PipelineParallel(model, hcg, {"accumulate_steps": n_micro,
                                               "schedule": schedule})
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=model.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
        return [float(runner.train_batch((x, y), opt)) for _ in range(3)]

    def test_tied_weights_match_gpipe(self):
        """VERDICT r3 item 2: tie_word_embeddings-style models train under
        schedule='1f1b' with loss parity vs gpipe (whose whole-graph
        autodiff handles tying natively and is serial-parity-tested). If
        the non-owning stage's tied-weight grad contribution were dropped,
        the trajectories would diverge from step 2 on."""
        ref = self._run_tied("gpipe")
        got = self._run_tied("1f1b")
        assert ref[0] != ref[1]  # training actually moves
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)

    @pytest.mark.parametrize("pp,v,n_micro", [(2, 2, 4), (2, 2, 8)])
    def test_vpp_1f1b_matches_serial(self, pp, v, n_micro):
        """VERDICT r3 item 2: the 1f1b clock extends to virtual stages
        (Megatron interleaved layout) — parity with the serial model."""
        def vdescs():
            return ([LayerDesc(nn.Linear, 8, H)] +
                    [LayerDesc(Block) for _ in range(2 * pp * v - 2)] +
                    [LayerDesc(Head)])

        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(pp=1)
        paddle.seed(21)
        serial_model = PipelineLayer(vdescs(), loss_fn=_mse)
        ref = _serial_losses(serial_model, n_micro=n_micro)

        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(pp=pp)
        paddle.seed(21)
        model = PipelineLayer(vdescs(), loss_fn=_mse,
                              num_virtual_pipeline_stages=v)
        runner = PipelineParallel(model, hcg, {"accumulate_steps": n_micro,
                                               "schedule": "1f1b"})
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=model.parameters())
        x, y = _batch()
        losses = [float(runner.train_batch(
            (paddle.to_tensor(x), paddle.to_tensor(y)), opt))
            for _ in range(3)]
        np.testing.assert_allclose(losses, ref, rtol=2e-5, atol=1e-6)

    def test_vpp_1f1b_tied_weights(self):
        """1f1b x VPP x tying all at once: chunk 0 (rank 0) and the last
        chunk (rank 1, virtual slot 1) share the embedding."""
        ref = self._run_tied("gpipe", v=2, n_micro=4)
        got = self._run_tied("1f1b", v=2, n_micro=4)
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)

    def test_residual_structure_drift_fails_loudly(self):
        """VERDICT r3 item 9: a layer whose traced structure DIFFERS
        between the probe trace and the schedule trace must raise the
        trace-time layout diagnostic, not silently corrupt the ring."""

        class Shifty(nn.Layer):
            # structure changes on the 3rd trace: eval_shape (1), probe
            # (2), then the forward branch (3) sees an extra residual
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(H, H)
                self.traces = 0

            def forward(self, x):
                self.traces += 1
                out = paddle.tanh(self.fc(x))
                if self.traces >= 3:
                    out = out + paddle.exp(x * 0.001) * 0.01
                return out

        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(pp=2)
        paddle.seed(5)
        model = PipelineLayer(
            [LayerDesc(nn.Linear, 8, H), LayerDesc(Shifty)] +
            [LayerDesc(Block) for _ in range(2)] + [LayerDesc(Head)],
            loss_fn=_mse)
        runner = PipelineParallel(model, hcg, {"accumulate_steps": 4,
                                               "schedule": "1f1b"})
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=model.parameters())
        x, y = _batch()
        with pytest.raises(Exception, match="drifted between traces"):
            runner.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)),
                               opt)
