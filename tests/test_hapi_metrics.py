"""Model.fit metric parity: the jit (TrainStep) path must report the same
per-epoch metrics as eager (VERDICT r1 item 7; ref Model.fit always updates
metrics on train outputs)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _data(n=64, d=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    y = rng.randint(0, classes, (n, 1)).astype(np.int64)
    return [(x[i:i + 8], y[i:i + 8]) for i in range(0, n, 8)]


def _run_epoch(jit):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = paddle.Model(net)
    acc = paddle.metric.Accuracy()
    model.prepare(paddle.optimizer.SGD(learning_rate=0.0,
                                       parameters=net.parameters()),
                  nn.CrossEntropyLoss(), metrics=acc, jit=jit)
    for xb, yb in _data():
        model.train_batch([paddle.to_tensor(xb)], [paddle.to_tensor(yb)])
    return acc.accumulate()


class TestFitMetricsParity:
    def test_jit_matches_eager_accuracy(self):
        # lr=0 so both paths see identical weights on every batch
        a_eager = _run_epoch(jit=False)
        a_jit = _run_epoch(jit=True)
        assert a_eager == a_jit, (a_eager, a_jit)
        assert 0.0 <= a_jit <= 1.0

    def test_fit_logs_contain_metric(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        model = paddle.Model(net)
        acc = paddle.metric.Accuracy()
        model.prepare(paddle.optimizer.SGD(learning_rate=0.01,
                                           parameters=net.parameters()),
                      nn.CrossEntropyLoss(), metrics=acc, jit=True)
        seen = {}

        from paddle_tpu.hapi.callbacks import Callback

        class Grab(Callback):
            def on_epoch_end(self, epoch, logs=None):
                seen.update(logs or {})

        rng = np.random.RandomState(0)
        x = rng.randn(32, 8).astype(np.float32)
        y = rng.randint(0, 4, (32, 1)).astype(np.int64)
        model.fit([(x[i:i + 8], y[i:i + 8]) for i in range(0, 32, 8)],
                  epochs=1, verbose=0, callbacks=[Grab()])
        assert "acc" in seen, seen


class TestTupleComputeMetrics:
    def test_precision_metric_in_train_batch(self):
        # base Metric.compute returns its args as a tuple — update must be
        # called unpacked (review r2 regression)
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 1),
                            nn.Sigmoid())
        model = paddle.Model(net)
        prec = paddle.metric.Precision()
        model.prepare(paddle.optimizer.SGD(learning_rate=0.01,
                                           parameters=net.parameters()),
                      nn.BCELoss(), metrics=prec, jit=True)
        rng = np.random.RandomState(0)
        x = rng.randn(16, 8).astype(np.float32)
        y = rng.randint(0, 2, (16, 1)).astype(np.float32)
        model.train_batch([paddle.to_tensor(x)], [paddle.to_tensor(y)])
        val = prec.accumulate()
        assert 0.0 <= val <= 1.0


class TestJitDefaultFallback:
    def test_untraceable_forward_falls_back_loudly(self):
        """r5: fit runs through TrainStep by default; a forward that
        cannot trace warns ONCE and falls back to the eager loop."""
        import warnings

        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi import Model

        class DataDependent(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 1)

            def forward(self, x):
                # bool() on a traced value: untraceable on purpose
                if float(x.sum()) > 0:
                    return self.lin(x)
                return self.lin(x) * 2.0

        net = DataDependent()
        model = Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(
                learning_rate=0.1, parameters=net.parameters()),
            loss=nn.loss.MSELoss())
        assert model._train_step is not None     # jit default ON
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        y = paddle.to_tensor(np.ones((4, 1), np.float32))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            (l1,) = model.train_batch([x], [y])
            assert any("cannot be traced" in str(wi.message) for wi in w)
        assert model._train_step is None          # eager from now on
        (l2,) = model.train_batch([x], [y])       # trains eagerly
        assert np.isfinite(l1) and np.isfinite(l2) and l2 < l1


class TestMultiLabelTrainBatch:
    def test_jit_matches_eager_with_two_labels(self):
        """ADVICE r5: `*xs, y = batch` split fed the first label into the
        network when two labels were passed. The jit loss path must split
        by the compiled label count and hand EVERY label to the loss."""

        class SumLoss(nn.Layer):
            def __init__(self):
                super().__init__()
                self.mse = nn.loss.MSELoss()

            def forward(self, out, y1, y2):
                return self.mse(out, y1) + 0.5 * self.mse(out, y2)

        def run(jit):
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                nn.Linear(16, 4))
            model = paddle.Model(net)
            model.prepare(paddle.optimizer.SGD(learning_rate=0.0,
                                               parameters=net.parameters()),
                          SumLoss(), jit=jit)
            rng = np.random.RandomState(0)
            x = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
            y1 = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
            y2 = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
            losses = [model.train_batch([x], [y1, y2])[0]
                      for _ in range(3)]
            return model, losses

        m_jit, l_jit = run(jit=True)
        # the step prepared for 1 label was rebuilt for 2, and STAYED jit
        assert m_jit._train_step is not None
        assert m_jit._train_step_labels == 2
        _, l_eager = run(jit=False)
        np.testing.assert_allclose(l_jit, l_eager, rtol=1e-5, atol=1e-6)

    def test_user_not_implemented_error_surfaces(self):
        """ADVICE r5: a genuine NotImplementedError raised by the user's
        forward must propagate, not silently downgrade fit() to eager."""
        import warnings

        class Broken(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 1)

            def forward(self, x):
                raise NotImplementedError("user forward bug")

        net = Broken()
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=net.parameters()),
                      nn.loss.MSELoss())
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        y = paddle.to_tensor(np.ones((4, 1), np.float32))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            with pytest.raises(NotImplementedError, match="user forward"):
                model.train_batch([x], [y])
            assert not any("cannot be traced" in str(wi.message)
                           for wi in w)
        # the jit path was NOT torn down by the user bug
        assert model._train_step is not None
