"""Ring / Ulysses context-parallel attention parity tests on the 8-device
CPU mesh (SURVEY.md §5 long-context first-class)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from paddle_tpu.distributed.shard_map_compat import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.ring_attention import (
    ring_flash_attention_arrays,
    ulysses_attention_arrays,
)

SEP = 4
B, S, H, D = 2, 64, 4, 16


@pytest.fixture()
def mesh():
    dist.set_hybrid_communicate_group(None)
    hcg = dist.create_hybrid_communicate_group(dp=2, sep=SEP)
    return hcg.mesh


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randn(B, S, H, D).astype(np.float32) for _ in range(3)]


def _ref(q, k, v, causal):
    s = jnp.einsum("bshd,bthd->bhst", q, k) / np.sqrt(D)
    if causal:
        m = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(m, s, -1e30)
    return jnp.einsum("bhst,bthd->bshd", jax.nn.softmax(s, -1), v)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_parity(self, mesh, causal):
        q, k, v = _qkv()

        f = shard_map(
            lambda a, b, c: ring_flash_attention_arrays(a, b, c, causal=causal),
            mesh=mesh, in_specs=(P(None, "sep"),) * 3,
            out_specs=P(None, "sep"), check_vma=False)
        out = np.asarray(f(q, k, v))
        ref = np.asarray(_ref(q, k, v, causal))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grad_parity(self, mesh, causal):
        q, k, v = _qkv(1)

        def ring_loss(a, b, c):
            out = ring_flash_attention_arrays(a, b, c, causal=causal)
            return (out.astype(jnp.float32) ** 2).sum()

        def body(a, b, c):
            g = jax.grad(lambda *t: ring_loss(*t), argnums=(0, 1, 2))(a, b, c)
            return g

        f = shard_map(body, mesh=mesh, in_specs=(P(None, "sep"),) * 3,
                      out_specs=(P(None, "sep"),) * 3, check_vma=False)
        g = f(q, k, v)
        g_ref = jax.grad(
            lambda a, b, c: (_ref(a, b, c, causal) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)


class TestRingGQA:
    def test_gqa_parity_and_grad(self, mesh):
        """k/v carry fewer heads; ring shares them across query heads via the
        flash kernel's BlockSpec index maps (no HBM repeat)."""
        rng = np.random.RandomState(5)
        hkv = 2
        q = rng.randn(B, S, H, D).astype(np.float32)
        k = rng.randn(B, S, hkv, D).astype(np.float32)
        v = rng.randn(B, S, hkv, D).astype(np.float32)
        krep = np.repeat(k, H // hkv, axis=2)
        vrep = np.repeat(v, H // hkv, axis=2)

        def body(a, b, c):
            out = ring_flash_attention_arrays(a, b, c, causal=True)
            g = jax.grad(
                lambda *t: (ring_flash_attention_arrays(*t, causal=True)
                            .astype(jnp.float32) ** 2).sum(),
                argnums=(0, 1, 2))(a, b, c)
            return (out,) + g

        f = shard_map(body, mesh=mesh, in_specs=(P(None, "sep"),) * 3,
                      out_specs=(P(None, "sep"),) * 4, check_vma=False)
        out, gq, gk, gv = f(q, k, v)
        ref = _ref(q, krep, vrep, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        gq_ref, gk_ref, gv_ref = jax.grad(
            lambda a, b, c: (_ref(a, jnp.repeat(b, H // hkv, 2),
                                  jnp.repeat(c, H // hkv, 2), True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(gq_ref),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gk_ref),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(gv_ref),
                                   rtol=1e-4, atol=1e-4)


class TestRingLongSequence:
    def test_16k_local_causal(self):
        """VERDICT r1 #4: >=16k tokens per rank through the ring path. Dense
        reference is impossible at this length (32k^2 scores); the oracle is
        the single-device Pallas flash kernel on the full sequence, so this
        checks the ring machinery (rotation, causal schedule, global-lse
        combine) at scale."""
        prev = dist.get_hybrid_communicate_group()
        dist.set_hybrid_communicate_group(None)
        try:
            self._run()
        finally:
            dist.set_hybrid_communicate_group(prev)

    def _run(self):
        hcg = dist.create_hybrid_communicate_group(dp=4, sep=2)
        s_local, h, d = 16384, 1, 8
        s_glob = 2 * s_local
        rng = np.random.RandomState(7)
        q, k, v = [0.3 * rng.randn(1, s_glob, h, d).astype(np.float32)
                   for _ in range(3)]

        f = shard_map(
            lambda a, b, c: ring_flash_attention_arrays(a, b, c, causal=True),
            mesh=hcg.mesh, in_specs=(P(None, "sep"),) * 3,
            out_specs=P(None, "sep"), check_vma=False)
        out = np.asarray(f(q, k, v))

        from paddle_tpu.ops.pallas.flash import flash_attention
        ref = np.asarray(flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_parity(self, mesh, causal):
        q, k, v = _qkv(2)

        f = shard_map(
            lambda a, b, c: ulysses_attention_arrays(a, b, c, causal=causal),
            mesh=mesh, in_specs=(P(None, "sep"),) * 3,
            out_specs=P(None, "sep"), check_vma=False)
        out = np.asarray(f(q, k, v))
        ref = np.asarray(_ref(q, k, v, causal))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_grad_parity(self, mesh):
        q, k, v = _qkv(3)

        def body(a, b, c):
            return jax.grad(
                lambda *t: (ulysses_attention_arrays(*t, causal=True)
                            .astype(jnp.float32) ** 2).sum(),
                argnums=(0, 1, 2))(a, b, c)

        f = shard_map(body, mesh=mesh, in_specs=(P(None, "sep"),) * 3,
                      out_specs=(P(None, "sep"),) * 3, check_vma=False)
        g = f(q, k, v)
        g_ref = jax.grad(
            lambda a, b, c: (_ref(a, b, c, True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)


class TestTensorWrapper:
    def test_sep1_degenerate(self):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(dp=8)
        from paddle_tpu.distributed.ring_attention import ring_flash_attention
        q, k, v = [paddle.to_tensor(a) for a in _qkv(4)]
        out = ring_flash_attention(q, k, v, causal=True)
        ref = np.asarray(_ref(q._data, k._data, v._data, True))
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-5, atol=2e-5)
