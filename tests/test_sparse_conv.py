"""Sparse conv/pool OpTests vs dense references (SURVEY.md §2.1 N26,
VERDICT r1 item 8): the rulebook gather-GEMM-scatter path must match a dense
conv applied to the densified input, and gradients must flow to values and
weights."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.sparse as S


def _rand_coo_2d(n=1, h=6, w=7, c=3, nse=9, seed=0):
    rng = np.random.RandomState(seed)
    sites = rng.choice(n * h * w, size=nse, replace=False)
    bi, rem = np.divmod(sites, h * w)
    hi, wi = np.divmod(rem, w)
    idx = np.stack([bi, hi, wi])
    vals = rng.randn(nse, c).astype(np.float32)
    t = S.sparse_coo_tensor(paddle.to_tensor(idx.astype(np.int64)),
                            paddle.to_tensor(vals), [n, h, w, c])
    return t, idx, vals


def _rand_coo_3d(n=1, d=4, h=5, w=5, c=2, nse=10, seed=1):
    rng = np.random.RandomState(seed)
    sites = rng.choice(n * d * h * w, size=nse, replace=False)
    bi, rem = np.divmod(sites, d * h * w)
    di, rem2 = np.divmod(rem, h * w)
    hi, wi = np.divmod(rem2, w)
    idx = np.stack([bi, di, hi, wi])
    vals = rng.randn(nse, c).astype(np.float32)
    t = S.sparse_coo_tensor(paddle.to_tensor(idx.astype(np.int64)),
                            paddle.to_tensor(vals), [n, d, h, w, c])
    return t, idx, vals


def _dense_conv_ref(x_dense, w, stride, padding):
    """NHWC/NDHWC conv via explicit loops (trusted NumPy reference)."""
    nd = w.ndim - 2
    ksz = w.shape[:nd]
    pad_width = [(0, 0)] + [(p, p) for p in padding] + [(0, 0)]
    xp = np.pad(x_dense, pad_width)
    spatial = x_dense.shape[1:-1]
    out_sp = tuple((spatial[i] + 2 * padding[i] - ksz[i]) // stride[i] + 1
                   for i in range(nd))
    out = np.zeros((x_dense.shape[0],) + out_sp + (w.shape[-1],), np.float32)
    for o in np.ndindex(*out_sp):
        sl = tuple(slice(o[i] * stride[i], o[i] * stride[i] + ksz[i])
                   for i in range(nd))
        patch = xp[(slice(None),) + sl + (slice(None),)]
        out[(slice(None),) + o] = np.tensordot(
            patch, w, axes=(list(range(1, nd + 2)), list(range(nd + 1))))
    return out


class TestSparseConv2D:
    @pytest.mark.parametrize("stride,padding", [(1, 1), (2, 1), (1, 0)])
    def test_matches_dense(self, stride, padding):
        t, idx, vals = _rand_coo_2d()
        rng = np.random.RandomState(5)
        w = rng.randn(3, 3, 3, 4).astype(np.float32)
        out = S.nn.functional.conv2d(t, paddle.to_tensor(w), stride=stride,
                                     padding=padding)
        ref = _dense_conv_ref(t.to_dense().numpy(), w, (stride,) * 2,
                              (padding,) * 2)
        np.testing.assert_allclose(out.to_dense().numpy(), ref, atol=1e-5)

    def test_subm_keeps_coordinates(self):
        t, idx, vals = _rand_coo_2d()
        rng = np.random.RandomState(6)
        w = rng.randn(3, 3, 3, 3).astype(np.float32)
        out = S.nn.functional.subm_conv2d(t, paddle.to_tensor(w), padding=1)
        # output sites == input sites
        got = set(map(tuple, out.indices().numpy().T.tolist()))
        want = set(map(tuple, idx.T.tolist()))
        assert got == want
        # values match the dense conv sampled at the input sites
        ref = _dense_conv_ref(t.to_dense().numpy(), w, (1, 1), (1, 1))
        dense_out = out.to_dense().numpy()
        for b, h, w_ in want:
            np.testing.assert_allclose(dense_out[b, h, w_], ref[b, h, w_],
                                       atol=1e-5)

    def test_grads_flow_to_values_and_weight(self):
        t, idx, vals = _rand_coo_2d()
        layer = S.nn.Conv2D(3, 4, kernel_size=3, padding=1)
        out = layer(t)
        loss = out.values().sum()
        loss.backward()
        assert layer.weight.grad is not None
        g = layer.weight.grad.numpy()
        assert np.abs(g).sum() > 0
        # numeric check on one weight entry
        eps = 1e-3
        w0 = layer.weight.numpy().copy()
        def loss_at(wv):
            layer.weight.set_value(paddle.to_tensor(wv))
            return float(layer(t).values().sum())
        wp = w0.copy(); wp[0, 0, 0, 0] += eps
        wm = w0.copy(); wm[0, 0, 0, 0] -= eps
        num = (loss_at(wp) - loss_at(wm)) / (2 * eps)
        np.testing.assert_allclose(g[0, 0, 0, 0], num, rtol=1e-2, atol=1e-3)


class TestSparseConv3D:
    def test_matches_dense(self):
        t, idx, vals = _rand_coo_3d()
        rng = np.random.RandomState(7)
        w = rng.randn(3, 3, 3, 2, 4).astype(np.float32)
        out = S.nn.functional.conv3d(t, paddle.to_tensor(w), stride=1,
                                     padding=1)
        ref = _dense_conv_ref(t.to_dense().numpy(), w, (1,) * 3, (1,) * 3)
        np.testing.assert_allclose(out.to_dense().numpy(), ref, atol=1e-5)

    def test_layer_and_bias(self):
        t, idx, vals = _rand_coo_3d()
        layer = S.nn.SubmConv3D(2, 5, kernel_size=3, padding=1)
        out = layer(t)
        assert out.shape == [1, 4, 5, 5, 5]
        assert out.values().shape[1] == 5


class TestSparsePool:
    def test_max_pool_matches_dense_on_occupied(self):
        t, idx, vals = _rand_coo_3d(nse=20, seed=3)
        out = S.nn.functional.max_pool3d(t, kernel_size=2, stride=2)
        dense = t.to_dense().numpy()
        n, d, h, w, c = dense.shape
        # reference: block max ONLY over occupied sites (sparse semantics:
        # empty sites don't contribute zeros)
        occ = np.zeros(dense.shape[:-1], bool)
        occ[tuple(idx)] = True
        out_d = out.to_dense().numpy()
        for o in np.ndindex(d // 2, h // 2, w // 2):
            blk = dense[0, 2*o[0]:2*o[0]+2, 2*o[1]:2*o[1]+2, 2*o[2]:2*o[2]+2]
            ob = occ[0, 2*o[0]:2*o[0]+2, 2*o[1]:2*o[1]+2, 2*o[2]:2*o[2]+2]
            if ob.any():
                ref = blk[ob].max(0)
                np.testing.assert_allclose(out_d[0, o[0], o[1], o[2]], ref,
                                           atol=1e-6)
            else:
                np.testing.assert_allclose(out_d[0, o[0], o[1], o[2]], 0.0)

    def test_avg_pool_counts_occupied_only(self):
        t, idx, vals = _rand_coo_3d(nse=20, seed=4)
        out = S.nn.functional.avg_pool3d(t, kernel_size=2, stride=2)
        dense = t.to_dense().numpy()
        occ = np.zeros(dense.shape[:-1], bool)
        occ[tuple(idx)] = True
        out_d = out.to_dense().numpy()
        d, h, w = dense.shape[1:-1]
        for o in np.ndindex(d // 2, h // 2, w // 2):
            blk = dense[0, 2*o[0]:2*o[0]+2, 2*o[1]:2*o[1]+2, 2*o[2]:2*o[2]+2]
            ob = occ[0, 2*o[0]:2*o[0]+2, 2*o[1]:2*o[1]+2, 2*o[2]:2*o[2]+2]
            if ob.any():
                np.testing.assert_allclose(
                    out_d[0, o[0], o[1], o[2]], blk[ob].mean(0), atol=1e-6)


class TestSparseBatchNormReLU:
    def test_bn_relu_pipeline(self):
        t, idx, vals = _rand_coo_3d(nse=16, seed=8)
        bn = S.nn.BatchNorm(2)
        relu = S.nn.ReLU()
        out = relu(bn(t))
        assert isinstance(out, S.SparseCooTensor)
        v = out.values().numpy()
        assert (v >= 0).all()
        # normalized-then-clipped values: mean of pre-relu ~ 0
        pre = bn(t).values().numpy()
        np.testing.assert_allclose(pre.mean(0), 0.0, atol=1e-4)

    def test_traced_indices_raise(self):
        import jax

        t, idx, vals = _rand_coo_2d()
        w = paddle.to_tensor(np.zeros((3, 3, 3, 4), np.float32))

        def f(data, indices):
            from jax.experimental import sparse as jsp

            tt = S._wrap(jsp.BCOO((data, indices), shape=(1, 6, 7, 3)))
            return S.nn.functional.conv2d(tt, w).values()._data

        with pytest.raises(Exception, match="concrete|Tracer|traced"):
            jax.jit(f)(t.bcoo.data, t.bcoo.indices)
