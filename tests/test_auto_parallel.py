"""Semi-auto parallel API (paddle.distributed.auto_parallel parity — SURVEY.md
P23) on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import (
    Partial, ProcessMesh, Replicate, Shard,
    dtensor_from_fn, reshard, shard_layer, shard_optimizer, shard_tensor,
    unshard_dtensor,
)
from paddle_tpu.distributed.auto_parallel.api import get_placements, get_process_mesh


def make_mesh():
    return ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])


class TestProcessMesh:
    def test_shape_and_names(self):
        m = make_mesh()
        assert m.shape == [2, 4]
        assert m.dim_names == ["x", "y"]
        assert m.process_ids == list(range(8))
        assert m.get_dim_size("y") == 4

    def test_jax_mesh(self):
        jm = make_mesh().jax_mesh()
        assert jm.axis_names == ("x", "y")
        assert jm.devices.shape == (2, 4)

    def test_submesh(self):
        m = make_mesh()
        sub = m[0]
        assert sub.shape == [4]
        assert sub.dim_names == ["y"]

    def test_eq_hash(self):
        assert make_mesh() == make_mesh()
        assert hash(make_mesh()) == hash(make_mesh())


class TestShardTensor:
    def test_shard_rows(self):
        m = make_mesh()
        x = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(8, 4))
        d = shard_tensor(x, m, [Shard(0), Replicate()])
        # each of the 2 x-coordinate groups holds half the rows
        shard_shapes = {s.data.shape for s in d._data.addressable_shards}
        assert shard_shapes == {(4, 4)}
        np.testing.assert_array_equal(np.asarray(d._data), np.asarray(x._data))
        assert get_placements(d)[0] == Shard(0)
        assert get_process_mesh(d) == m

    def test_shard_both_axes(self):
        m = make_mesh()
        x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
        d = shard_tensor(x, m, [Shard(0), Shard(1)])
        assert {s.data.shape for s in d._data.addressable_shards} == {(4, 2)}

    def test_bad_placement_count(self):
        with pytest.raises(ValueError):
            shard_tensor(paddle.ones([4]), make_mesh(), [Replicate()])

    def test_dtensor_from_fn(self):
        m = make_mesh()
        d = dtensor_from_fn(paddle.ones, m, [Replicate(), Shard(0)], [8, 2])
        assert d.shape == [8, 2]
        assert {s.data.shape for s in d._data.addressable_shards} == {(2, 2)}


class TestReshard:
    def test_shard_to_replicate(self):
        m = make_mesh()
        x = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
        d = shard_tensor(x, m, [Shard(0), Replicate()])
        r = reshard(d, m, [Replicate(), Replicate()])
        assert {s.data.shape for s in r._data.addressable_shards} == {(8, 4)}
        np.testing.assert_allclose(np.asarray(r._data), np.asarray(x._data))

    def test_replicate_to_shard(self):
        m = make_mesh()
        x = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
        d = shard_tensor(x, m, [Replicate(), Replicate()])
        r = reshard(d, m, [Replicate(), Shard(1)])
        assert {s.data.shape for s in r._data.addressable_shards} == {(8, 1)}

    def test_partial_sum_to_replicate(self):
        m = make_mesh()
        x = paddle.to_tensor(np.full((4, 4), 6.0, np.float32))
        d = shard_tensor(x, m, [Partial(), Replicate()])
        assert get_placements(d)[0].is_partial()
        r = reshard(d, m, [Replicate(), Replicate()])
        np.testing.assert_allclose(np.asarray(r._data), 6.0)

    def test_partial_avg(self):
        m = make_mesh()
        x = paddle.to_tensor(np.full((4,), 8.0, np.float32))
        d = shard_tensor(x, m, [Partial("avg"), Replicate()])
        r = reshard(d, m, [Replicate(), Replicate()])
        np.testing.assert_allclose(np.asarray(r._data), 4.0)  # /mesh dim size 2

    def test_unshard(self):
        m = make_mesh()
        x = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
        d = shard_tensor(x, m, [Shard(0), Shard(1)])
        u = unshard_dtensor(d)
        np.testing.assert_allclose(np.asarray(u._data), np.asarray(x._data))


class TestShardLayer:
    def test_default_replicates(self):
        m = make_mesh()
        layer = nn.Linear(8, 8)
        shard_layer(layer, m)
        for _, p in layer.named_parameters():
            assert get_placements(p) == [Replicate(), Replicate()]

    def test_custom_shard_fn(self):
        m = make_mesh()
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 8))

        def shard_fn(name, sub, mesh):
            if isinstance(sub, nn.Linear):
                from paddle_tpu.distributed.auto_parallel.api import shard_parameter
                shard_parameter(sub.weight, mesh, [Replicate(), Shard(1)])

        shard_layer(net, m, shard_fn)
        w0 = net[0].weight
        assert get_placements(w0)[1] == Shard(1)
        # forward still numerically identical to unsharded
        x = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
        y = net(x)
        assert y.shape == [4, 8]

    def test_sharded_forward_parity(self):
        m = make_mesh()
        paddle.seed(7)
        net = nn.Sequential(nn.Linear(8, 32), nn.GELU(), nn.Linear(32, 4))
        x = paddle.to_tensor(np.random.rand(16, 8).astype(np.float32))
        ref = np.asarray(net(x)._data)

        def shard_fn(name, sub, mesh):
            if isinstance(sub, nn.Linear):
                from paddle_tpu.distributed.auto_parallel.api import shard_parameter
                shard_parameter(sub.weight, mesh, [Replicate(), Shard(1)])

        shard_layer(net, m, shard_fn)
        out = np.asarray(net(x)._data)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


class TestShardOptimizer:
    def test_states_follow_param_sharding(self):
        m = make_mesh()
        paddle.seed(0)
        layer = nn.Linear(8, 16)
        from paddle_tpu.distributed.auto_parallel.api import shard_parameter
        shard_parameter(layer.weight, m, [Replicate(), Shard(1)])
        opt = shard_optimizer(paddle.optimizer.AdamW(
            learning_rate=1e-3, parameters=layer.parameters()))
        st = opt._state_for(layer.weight)
        assert st["moment1"].sharding == layer.weight._data.sharding

    def test_training_parity_with_serial(self):
        m = make_mesh()

        def build():
            paddle.seed(3)
            net = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 1))
            opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=net.parameters())
            return net, opt

        rng = np.random.RandomState(0)
        X = rng.rand(64, 8).astype(np.float32)
        Y = X.sum(-1, keepdims=True).astype(np.float32)

        def run(net, opt, steps=5):
            losses = []
            for _ in range(steps):
                loss = nn.functional.mse_loss(net(paddle.to_tensor(X)),
                                              paddle.to_tensor(Y))
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss._data))
            return losses

        net_s, opt_s = build()
        serial = run(net_s, opt_s)

        net_d, opt_d = build()

        def shard_fn(name, sub, mesh):
            if isinstance(sub, nn.Linear) and sub.weight.shape[1] % mesh.get_dim_size("y") == 0:
                from paddle_tpu.distributed.auto_parallel.api import shard_parameter
                shard_parameter(sub.weight, mesh, [Replicate(), Shard(1)])

        shard_layer(net_d, m, shard_fn)
        dist_losses = run(net_d, shard_optimizer(opt_d))
        np.testing.assert_allclose(dist_losses, serial, rtol=1e-4, atol=1e-6)


class TestDistModel:
    def test_to_static_train_loop(self):
        m = make_mesh()
        paddle.seed(11)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        shard_layer(net, m)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        model = dist.auto_parallel.to_static(
            net, loss=nn.functional.mse_loss, optimizer=opt)
        model.train()
        rng = np.random.RandomState(1)
        X = rng.rand(32, 8).astype(np.float32)
        Y = X.sum(-1, keepdims=True).astype(np.float32)
        first = float(model(paddle.to_tensor(X), paddle.to_tensor(Y))._data)
        for _ in range(20):
            last = float(model(paddle.to_tensor(X), paddle.to_tensor(Y))._data)
        assert last < first * 0.5
        model.eval()
        eval_loss = model(paddle.to_tensor(X), paddle.to_tensor(Y))
        assert float(eval_loss._data) > 0
