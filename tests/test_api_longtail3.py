"""Round-2 tier-2 surface: optimizers (Rprop/ASGD/NAdam/RAdam/LBFGS), vision
transforms, distributions, incubate wrappers, dtype info, hub."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _quadratic_losses(opt_ctor, steps=30):
    paddle.seed(0)
    p = paddle.Parameter(np.array([3.0, -2.0], np.float32))
    opt = opt_ctor([p])
    for _ in range(steps):
        loss = (p * p).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float((p * p).sum())


class TestNewOptimizers:
    @pytest.mark.parametrize("ctor", [
        lambda ps: paddle.optimizer.Rprop(learning_rate=0.1, parameters=ps),
        lambda ps: paddle.optimizer.ASGD(learning_rate=0.1, parameters=ps),
        lambda ps: paddle.optimizer.NAdam(learning_rate=0.1, parameters=ps),
        lambda ps: paddle.optimizer.RAdam(learning_rate=0.1, parameters=ps),
    ])
    def test_minimizes_quadratic(self, ctor):
        # 30 steps from ||p||^2 = 13; NAdam lands at 0.741 — exactly what
        # torch.optim.NAdam gives on the same problem (verified), so the
        # bound is 0.8 rather than something tighter
        final = _quadratic_losses(ctor)
        assert final < 0.8, final

    def test_asgd_average_tracks(self):
        # reference d/y scheme: each step applies the mean of the last
        # batch_num gradients (circular buffer), count saturating at n
        p = paddle.Parameter(np.array([0.0], np.float32))
        opt = paddle.optimizer.ASGD(learning_rate=1.0, batch_num=3,
                                    parameters=[p])
        grads = [1.0, 2.0, 3.0, 4.0]
        expect = 0.0
        window = []
        for g in grads:
            p.grad = paddle.to_tensor(np.array([g], np.float32))
            opt.step()
            window = (window + [g])[-3:]
            expect -= sum(window) / len(window)
            np.testing.assert_allclose(np.asarray(p._data), [expect],
                                       rtol=1e-6)

    @pytest.mark.parametrize("cls", ["NAdam", "RAdam"])
    def test_nadam_radam_survive_late_steps(self, cls):
        # beta2_pow underflows to f32 zero around step ~88k (beta2=0.999);
        # the step counter must be explicit state, not recovered from the
        # log of the power, or RAdam's rho_t becomes NaN forever
        p = paddle.Parameter(np.array([1.0, -2.0], np.float32))
        opt = getattr(paddle.optimizer, cls)(learning_rate=0.01,
                                             parameters=[p])
        p.grad = paddle.to_tensor(np.array([0.1, -0.1], np.float32))
        opt.step()
        st = opt._accumulators[id(p)]
        import jax.numpy as jnp
        st["beta2_pow"] = jnp.zeros((), jnp.float32)   # underflowed
        st["beta1_pow"] = jnp.zeros((), jnp.float32)
        st["step"] = jnp.asarray(100000.0, jnp.float32)
        before = np.asarray(p._data).copy()
        opt.step()
        after = np.asarray(p._data)
        assert np.all(np.isfinite(after))
        assert not np.allclose(after, before)
        assert float(st["step"]) == 100000.0  # state dict rebind check

    def test_adadelta_matches_torch(self):
        import torch

        w0 = np.array([1.0, -2.0, 0.5], np.float32)
        p = paddle.Parameter(w0.copy())
        opt = paddle.optimizer.Adadelta(learning_rate=0.7, rho=0.9,
                                        epsilon=1e-6, parameters=[p])
        tp = torch.nn.Parameter(torch.tensor(w0))
        topt = torch.optim.Adadelta([tp], lr=0.7, rho=0.9, eps=1e-6)
        rng = np.random.RandomState(0)
        for _ in range(5):
            g = rng.randn(3).astype(np.float32)
            p.grad = paddle.to_tensor(g)
            opt.step()
            tp.grad = torch.tensor(g)
            topt.step()
            np.testing.assert_allclose(np.asarray(p._data),
                                       tp.detach().numpy(), rtol=1e-5)

    def test_adadelta_multi_precision_bf16(self):
        # without a f32 master weight, sub-ulp bf16 updates round away
        w0 = np.full(4, 100.0, np.float32)
        p = paddle.Parameter(w0).astype("bfloat16")
        p = paddle.Parameter(np.asarray(p._data))
        opt = paddle.optimizer.Adadelta(learning_rate=1.0,
                                        multi_precision=True,
                                        parameters=[p])
        st = None
        for _ in range(20):
            p.grad = paddle.to_tensor(np.full(4, 1.0, np.float32)
                                      ).astype("bfloat16")
            opt.step()
            st = opt._accumulators[id(p)]
        assert "master_weight" in st
        master = np.asarray(st["master_weight"], np.float32)
        assert np.all(master < 100.0)  # progress accumulated in f32

    def test_swiglu_and_fused_ec_moe(self):
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(1)
        x = rng.randn(2, 3, 8).astype(np.float32)
        y = rng.randn(2, 3, 8).astype(np.float32)
        tx, ty = paddle.to_tensor(x), paddle.to_tensor(y)
        silu = x / (1 + np.exp(-x))
        np.testing.assert_allclose(IF.swiglu(tx, ty).numpy(), silu * y,
                                   rtol=1e-5)
        # single-arg form splits in half
        cat = np.concatenate([x, y], axis=-1)
        np.testing.assert_allclose(IF.swiglu(paddle.to_tensor(cat)).numpy(),
                                   silu * y, rtol=1e-5)
        # fused_ec_moe vs a per-expert numpy reference
        e, d, f = 4, 8, 16
        gate = rng.randn(2, 3, e).astype(np.float32)
        w0 = rng.randn(e, d, f).astype(np.float32) * 0.1
        b0 = rng.randn(e, 1, f).astype(np.float32) * 0.1
        w1 = rng.randn(e, f, d).astype(np.float32) * 0.1
        b1 = rng.randn(e, 1, d).astype(np.float32) * 0.1
        out = IF.fused_ec_moe(tx, paddle.to_tensor(gate),
                              paddle.to_tensor(w0), paddle.to_tensor(b0),
                              paddle.to_tensor(w1), paddle.to_tensor(b1),
                              act_type="relu").numpy()
        eg = np.exp(gate - gate.max(-1, keepdims=True))
        probs = eg / eg.sum(-1, keepdims=True)
        expect = np.zeros_like(x)
        for ei in range(e):
            h = np.maximum(x @ w0[ei] + b0[ei][0], 0.0)
            expect += probs[..., ei:ei + 1] * (h @ w1[ei] + b1[ei][0])
        np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)

    def test_lbfgs_rosenbrock_ish(self):
        paddle.seed(0)
        p = paddle.Parameter(np.array([-1.0, 2.0], np.float32))
        opt = paddle.optimizer.LBFGS(learning_rate=0.5, max_iter=40,
                                     history_size=10,
                                     line_search_fn="strong_wolfe",
                                     parameters=[p])

        def closure():
            opt.clear_grad()
            x = p[0]
            y = p[1]
            loss = (1 - x) ** 2 + 5.0 * (y - x * x) ** 2
            loss.backward()
            return loss

        loss = opt.step(closure)
        for _ in range(5):
            loss = opt.step(closure)
        assert float(loss) < 1e-2, float(loss)
        np.testing.assert_allclose(p.numpy(), [1.0, 1.0], atol=0.15)


class TestTransforms2:
    def _img(self):
        rng = np.random.RandomState(0)
        return rng.randint(0, 255, (8, 10, 3)).astype(np.uint8)

    def test_pad_rotate_flip(self):
        from paddle_tpu.vision import transforms as T

        img = self._img()
        out = T.pad(img, 2)
        assert out.shape == (12, 14, 3)
        assert (out[:2] == 0).all()
        r180 = T.rotate(img, 180)
        np.testing.assert_array_equal(r180, img[::-1, ::-1])
        np.testing.assert_array_equal(T.vflip(img), img[::-1])

    def test_adjusts(self):
        from paddle_tpu.vision import transforms as T

        img = self._img()
        b = T.adjust_brightness(img, 2.0)
        assert b.mean() >= img.mean()
        c = T.adjust_contrast(img, 0.0)
        assert c.std() < img.std()
        g = T.to_grayscale(img, 3)
        assert g.shape == img.shape
        np.testing.assert_array_equal(g[..., 0], g[..., 1])
        # hue identity: factor 0 keeps the image (within rounding)
        h = T.adjust_hue(img, 0.0)
        assert np.abs(h.astype(int) - img.astype(int)).max() <= 2

    def test_transform_classes_run(self):
        from paddle_tpu.vision import transforms as T

        img = self._img()
        pipeline = T.Compose([
            T.ColorJitter(0.2, 0.2, 0.2, 0.1),
            T.RandomRotation(10),
            T.Pad(1),
            T.RandomErasing(prob=1.0),
            T.Grayscale(3),
        ])
        out = pipeline(img)
        assert out.shape == (10, 12, 3)

    def test_erase(self):
        from paddle_tpu.vision import transforms as T

        img = self._img()
        out = T.erase(img, 1, 2, 3, 4, 0)
        assert (out[1:4, 2:6] == 0).all()
        assert out[0, 0, 0] == img[0, 0, 0]


class TestDistributions2:
    def test_binomial_logprob(self):
        from scipy import stats

        from paddle_tpu.distribution import Binomial

        d = Binomial(paddle.to_tensor(10.0), paddle.to_tensor(0.3))
        for k in [0.0, 3.0, 10.0]:
            np.testing.assert_allclose(
                float(d.log_prob(paddle.to_tensor(k))),
                stats.binom.logpmf(k, 10, 0.3), rtol=1e-4)
        np.testing.assert_allclose(float(d.mean), 3.0, rtol=1e-6)
        s = d.sample([500])
        assert 2.0 < float(s.numpy().mean()) < 4.0

    def test_independent_sums_event_dims(self):
        from paddle_tpu.distribution import Independent, Normal

        base = Normal(paddle.to_tensor(np.zeros(3, np.float32)),
                      paddle.to_tensor(np.ones(3, np.float32)))
        ind = Independent(base, 1)
        v = paddle.to_tensor(np.array([0.5, -0.5, 1.0], np.float32))
        np.testing.assert_allclose(
            float(ind.log_prob(v)), base.log_prob(v).numpy().sum(), rtol=1e-6)

    def test_register_kl(self):
        from paddle_tpu.distribution import (Independent, Normal,
                                             kl_divergence, register_kl)

        @register_kl(Independent, Independent)
        def _kl_ind(p, q):
            import jax.numpy as jnp

            from paddle_tpu.core.tensor import Tensor

            inner = kl_divergence(p.base, q.base)
            return Tensor(jnp.sum(inner._data, axis=tuple(range(-p.rank, 0))))

        a = Independent(Normal(paddle.to_tensor(np.zeros(2, np.float32)),
                               paddle.to_tensor(np.ones(2, np.float32))), 1)
        b = Independent(Normal(paddle.to_tensor(np.ones(2, np.float32)),
                               paddle.to_tensor(np.ones(2, np.float32))), 1)
        np.testing.assert_allclose(float(kl_divergence(a, b)), 1.0, rtol=1e-5)

    def test_continuous_bernoulli(self):
        from paddle_tpu.distribution import ContinuousBernoulli

        d = ContinuousBernoulli(paddle.to_tensor(0.3))
        lp = float(d.log_prob(paddle.to_tensor(0.5)))
        assert np.isfinite(lp)
        s = d.sample([200]).numpy()
        assert ((s >= 0) & (s <= 1)).all()


class TestIncubate2:
    def test_segment_reexports(self):
        import paddle_tpu.incubate as inc

        x = paddle.to_tensor(np.array([[1.0], [2.0], [3.0]], np.float32))
        ids = paddle.to_tensor(np.array([0, 0, 1], np.int32))
        np.testing.assert_allclose(inc.segment_sum(x, ids).numpy(),
                                   [[3.0], [3.0]])

    def test_lookahead_and_model_average(self):
        import paddle_tpu.incubate as inc

        paddle.seed(0)
        p = paddle.Parameter(np.array([4.0], np.float32))
        inner = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
        la = inc.LookAhead(inner, alpha=0.5, k=2)
        for _ in range(4):
            loss = (p * p).sum()
            loss.backward()
            la.step()
            la.clear_grad()
        assert float(p.numpy()[0]) < 4.0

        p2 = paddle.Parameter(np.array([1.0], np.float32))
        ma = inc.ModelAverage(parameters=[p2])
        for v in (1.0, 3.0):
            p2.set_value(np.array([v], np.float32))
            ma.step()
        with ma.apply():
            np.testing.assert_allclose(p2.numpy(), [2.0])
        np.testing.assert_allclose(p2.numpy(), [3.0])  # restored

    def test_graph_send_recv(self):
        import paddle_tpu.incubate as inc

        x = paddle.to_tensor(np.array([[1.0], [2.0], [3.0]], np.float32))
        src = paddle.to_tensor(np.array([0, 1], np.int32))
        dst = paddle.to_tensor(np.array([2, 2], np.int32))
        out = inc.graph_send_recv(x, src, dst, pool_type="sum")
        np.testing.assert_allclose(out.numpy()[2], [3.0])


class TestDtypeInfoHub:
    def test_iinfo_finfo(self):
        ii = paddle.iinfo("int32")
        assert ii.max == 2**31 - 1 and ii.bits == 32
        fi = paddle.finfo("float32")
        assert fi.bits == 32 and 0 < fi.eps < 1e-6
        bf = paddle.finfo("bfloat16")
        assert bf.bits == 16 and bf.max > 3e38

    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def make(n=2):\n"
            "    'builds a list'\n"
            "    return list(range(n))\n")
        import paddle_tpu.hub as hub

        assert "make" in hub.list(str(tmp_path))
        assert hub.help(str(tmp_path), "make") == "builds a list"
        assert hub.load(str(tmp_path), "make", n=3) == [0, 1, 2]
        with pytest.raises(RuntimeError, match="egress"):
            hub.load("user/repo", "make", source="github")

    def test_batch_reader(self):
        def reader():
            yield from range(7)

        batches = [b for b in paddle.batch(reader, 3)()]
        assert batches == [[0, 1, 2], [3, 4, 5], [6]]
        batches = [b for b in paddle.batch(reader, 3, drop_last=True)()]
        assert batches == [[0, 1, 2], [3, 4, 5]]


class TestFusedGeneration:
    """P25 closure: masked_multihead_attention and fused_multi_transformer
    are real implementations, checked against the unfused composition."""

    def _mt_params(self, rng, L, dim, n_head, ffn):
        hd = dim // n_head
        mk = lambda *sh: paddle.to_tensor(  # noqa: E731
            (rng.randn(*sh) * 0.05).astype(np.float32))
        return dict(
            ln_scales=[mk(dim) + 1 for _ in range(L)],
            ln_biases=[mk(dim) for _ in range(L)],
            qkv_weights=[mk(3, n_head, hd, dim) for _ in range(L)],
            qkv_biases=[mk(3 * n_head * hd) for _ in range(L)],
            linear_weights=[mk(dim, dim) for _ in range(L)],
            linear_biases=[mk(dim) for _ in range(L)],
            ffn_ln_scales=[mk(dim) + 1 for _ in range(L)],
            ffn_ln_biases=[mk(dim) for _ in range(L)],
            ffn1_weights=[mk(dim, ffn) for _ in range(L)],
            ffn1_biases=[mk(ffn) for _ in range(L)],
            ffn2_weights=[mk(ffn, dim) for _ in range(L)],
            ffn2_biases=[mk(dim) for _ in range(L)],
        )

    def _ref_layer(self, h, P, i, n_head):
        # unfused reference: pre-LN -> causal MHA -> residual -> FFN
        import paddle_tpu.nn.functional as F

        dim = h.shape[-1]
        hd = dim // n_head
        ln = F.layer_norm(h, [dim], P["ln_scales"][i], P["ln_biases"][i])
        qw = P["qkv_weights"][i].numpy()            # [3, h, d, dim]
        qkv = np.einsum("bsd,thed->bsthe", ln.numpy(), qw) \
            + P["qkv_biases"][i].numpy().reshape(1, 1, 3, n_head, hd)
        q, k, v = (paddle.to_tensor(qkv[:, :, j]) for j in range(3))
        att = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             training=False)
        b, s = h.shape[0], h.shape[1]
        att = att.reshape([b, s, dim])
        out = F.linear(att, P["linear_weights"][i], P["linear_biases"][i])
        h = h + out
        ln2 = F.layer_norm(h, [dim], P["ffn_ln_scales"][i],
                           P["ffn_ln_biases"][i])
        f1 = F.gelu(F.linear(ln2, P["ffn1_weights"][i], P["ffn1_biases"][i]))
        return h + F.linear(f1, P["ffn2_weights"][i], P["ffn2_biases"][i])

    def test_fused_multi_transformer_prefill_matches_unfused(self):
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(0)
        L, dim, n_head, ffn = 2, 32, 4, 64
        P = self._mt_params(rng, L, dim, n_head, ffn)
        x = paddle.to_tensor(rng.randn(2, 8, dim).astype(np.float32) * 0.3)
        out = IF.fused_multi_transformer(x, **P)
        ref = x
        for i in range(L):
            ref = self._ref_layer(ref, P, i, n_head)
        np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                   rtol=2e-4, atol=2e-4)

    def test_fused_multi_transformer_decode_matches_prefill(self):
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(1)
        L, dim, n_head, ffn = 2, 32, 4, 64
        hd = dim // n_head
        P = self._mt_params(rng, L, dim, n_head, ffn)
        seq, max_seq = 6, 16
        x = paddle.to_tensor(rng.randn(1, seq, dim).astype(np.float32) * 0.3)
        full = IF.fused_multi_transformer(x, **P)
        # decode token-by-token against the cache
        caches = [paddle.to_tensor(np.zeros((2, 1, n_head, max_seq, hd),
                                            np.float32))
                  for _ in range(L)]
        for t in range(seq):
            step_out, caches = IF.fused_multi_transformer(
                x[:, t:t + 1], cache_kvs=caches,
                time_step=paddle.to_tensor(np.asarray(t, np.int32)), **P)
        np.testing.assert_allclose(step_out.numpy()[:, 0],
                                   full.numpy()[:, -1],
                                   rtol=2e-4, atol=2e-4)

    def test_masked_multihead_attention_matches_dense(self):
        import paddle_tpu.incubate.nn.functional as IF
        import paddle_tpu.nn.functional as F

        rng = np.random.RandomState(2)
        b, n_head, hd, max_seq = 2, 4, 8, 12
        # pre-fill 5 cached positions, then decode position 5
        hist = rng.randn(b, 5, n_head, hd).astype(np.float32)
        cache = np.zeros((2, b, n_head, max_seq, hd), np.float32)
        cache[0, :, :, :5] = np.transpose(hist, (0, 2, 1, 3))
        cache[1, :, :, :5] = np.transpose(hist, (0, 2, 1, 3)) * 0.5
        xq = rng.randn(b, 3 * n_head * hd).astype(np.float32)
        out, new_cache = IF.masked_multihead_attention(
            paddle.to_tensor(xq), cache_kv=paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(
                np.full((b,), 5, np.int32)))
        qkv = xq.reshape(b, 3, n_head, hd)
        q = paddle.to_tensor(qkv[:, 0][:, None])    # [b,1,h,d]
        nk = new_cache.numpy()
        k = paddle.to_tensor(np.transpose(nk[0, :, :, :6], (0, 2, 1, 3)))
        v = paddle.to_tensor(np.transpose(nk[1, :, :, :6], (0, 2, 1, 3)))
        ref = F.scaled_dot_product_attention(q, k, v, training=False)
        np.testing.assert_allclose(out.numpy(),
                                   ref.numpy().reshape(b, -1),
                                   rtol=1e-4, atol=1e-5)
        # the new token landed at slot 5
        np.testing.assert_allclose(
            nk[0, :, :, 5], qkv[:, 1], rtol=1e-6)

    def test_prefill_writes_cache_then_decode(self):
        # the canonical generation flow: one prefill call with cache_kvs
        # (no time_step) must WRITE the prompt's k/v, so decode continues
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(3)
        L, dim, n_head, ffn = 2, 32, 4, 64
        hd = dim // n_head
        P = self._mt_params(rng, L, dim, n_head, ffn)
        seq, max_seq = 5, 12
        x = paddle.to_tensor(rng.randn(1, seq + 1, dim).astype(np.float32)
                             * 0.3)
        full = IF.fused_multi_transformer(x, **P)
        caches = [paddle.to_tensor(np.zeros((2, 1, n_head, max_seq, hd),
                                            np.float32))
                  for _ in range(L)]
        _, caches = IF.fused_multi_transformer(x[:, :seq],
                                               cache_kvs=caches, **P)
        step_out, caches = IF.fused_multi_transformer(
            x[:, seq:], cache_kvs=caches,
            time_step=paddle.to_tensor(np.asarray(seq, np.int32)), **P)
        np.testing.assert_allclose(step_out.numpy()[:, 0],
                                   full.numpy()[:, -1],
                                   rtol=2e-4, atol=2e-4)

    @staticmethod
    def _rope_tables(b, max_seq, hd, neox=True):
        """Packed [2, b, 1, max_seq, hd] cos/sin FULL-dim tables matching
        ops/rope.rope_arrays' half-table convention."""
        inv = 1.0 / (10000.0 ** (np.arange(0, hd, 2, np.float64) / hd))
        fr = np.outer(np.arange(max_seq, dtype=np.float64), inv)  # [S, d/2]
        if neox:
            cos = np.concatenate([np.cos(fr), np.cos(fr)], -1)
            sin = np.concatenate([np.sin(fr), np.sin(fr)], -1)
        else:
            cos = np.repeat(np.cos(fr), 2, -1)
            sin = np.repeat(np.sin(fr), 2, -1)
        t = np.stack([cos, sin]).astype(np.float32)     # [2, S, d]
        return np.broadcast_to(t[:, None, None], (2, b, 1, max_seq, hd))

    def test_fused_multi_transformer_rotary_matches_eager_rope(self):
        # prefill with inline rope == unfused composition with the
        # standalone fused_rope op applied to q/k (LLaMA-block math)
        import paddle_tpu.incubate.nn.functional as IF
        import paddle_tpu.nn.functional as F
        from paddle_tpu.ops.rope import rope_arrays

        rng = np.random.RandomState(7)
        L, dim, n_head, ffn = 2, 32, 4, 64
        hd = dim // n_head
        P = self._mt_params(rng, L, dim, n_head, ffn)
        b, s = 2, 8
        x = paddle.to_tensor(rng.randn(b, s, dim).astype(np.float32) * 0.3)
        rot = self._rope_tables(b, s, hd)
        out = IF.fused_multi_transformer(
            x, rotary_embs=paddle.to_tensor(rot),
            use_neox_rotary_style=True, **P)

        h = x
        for i in range(L):
            ln = F.layer_norm(h, [dim], P["ln_scales"][i], P["ln_biases"][i])
            qw = P["qkv_weights"][i].numpy()
            qkv = np.einsum("bsd,thed->bsthe", ln.numpy(), qw) \
                + P["qkv_biases"][i].numpy().reshape(1, 1, 3, n_head, hd)
            q = rope_arrays(jnp.asarray(qkv[:, :, 0]), neox=True)
            k = rope_arrays(jnp.asarray(qkv[:, :, 1]), neox=True)
            att = F.scaled_dot_product_attention(
                paddle.to_tensor(np.asarray(q)),
                paddle.to_tensor(np.asarray(k)),
                paddle.to_tensor(qkv[:, :, 2]), is_causal=True,
                training=False).reshape([b, s, dim])
            h = h + F.linear(att, P["linear_weights"][i],
                             P["linear_biases"][i])
            ln2 = F.layer_norm(h, [dim], P["ffn_ln_scales"][i],
                               P["ffn_ln_biases"][i])
            f1 = F.gelu(F.linear(ln2, P["ffn1_weights"][i],
                                 P["ffn1_biases"][i]))
            h = h + F.linear(f1, P["ffn2_weights"][i], P["ffn2_biases"][i])
        np.testing.assert_allclose(out.numpy(), h.numpy(),
                                   rtol=2e-4, atol=2e-4)

    def test_rotary_generation_decode_matches_recompute(self):
        # the VERDICT done-criterion: a rope model generating greedily via
        # prefill->cached-decode produces IDENTICAL tokens to full
        # recompute at every step (both styles)
        import paddle_tpu.incubate.nn.functional as IF

        for neox in (True, False):
            rng = np.random.RandomState(8)
            L, dim, n_head, ffn = 2, 32, 4, 64
            hd = dim // n_head
            P = self._mt_params(rng, L, dim, n_head, ffn)
            vocab = 17
            emb = rng.randn(vocab, dim).astype(np.float32) * 0.3
            head = rng.randn(dim, vocab).astype(np.float32)
            prompt = [3, 1, 4, 1, 5]
            max_seq = 16
            rot = paddle.to_tensor(
                self._rope_tables(1, max_seq, hd, neox=neox))
            kw = dict(rotary_embs=rot, use_neox_rotary_style=neox)

            def logits_full(ids):
                x = paddle.to_tensor(emb[np.asarray(ids)][None])
                out = IF.fused_multi_transformer(x, **kw, **P)
                return out.numpy()[0, -1] @ head

            # eager reference: full recompute each step
            ref_ids = list(prompt)
            for _ in range(4):
                ref_ids.append(int(np.argmax(logits_full(ref_ids))))

            # fused path: prefill writes cache, then single-token decode
            caches = [paddle.to_tensor(
                np.zeros((2, 1, n_head, max_seq, hd), np.float32))
                for _ in range(L)]
            x0 = paddle.to_tensor(emb[np.asarray(prompt)][None])
            out, caches = IF.fused_multi_transformer(
                x0, cache_kvs=caches, **kw, **P)
            ids = list(prompt)
            ids.append(int(np.argmax(out.numpy()[0, -1] @ head)))
            for t in range(len(prompt), len(prompt) + 3):
                xt = paddle.to_tensor(emb[np.asarray([ids[-1]])][None])
                out, caches = IF.fused_multi_transformer(
                    xt, cache_kvs=caches,
                    time_step=paddle.to_tensor(np.asarray(t, np.int32)),
                    **kw, **P)
                ids.append(int(np.argmax(out.numpy()[0, -1] @ head)))
            assert ids == ref_ids, (neox, ids, ref_ids)

    def test_masked_multihead_attention_rotary(self):
        # single-step decode with inline rope at each row's position ==
        # manual rope (standalone op, per-row position_ids) + attend
        import paddle_tpu.incubate.nn.functional as IF
        import paddle_tpu.nn.functional as F
        from paddle_tpu.ops.rope import rope_arrays

        rng = np.random.RandomState(9)
        b, n_head, hd, max_seq = 2, 2, 8, 12
        lens = np.array([5, 2], np.int32)
        cache = np.zeros((2, b, n_head, max_seq, hd), np.float32)
        for r in range(b):
            hist = rng.randn(lens[r], n_head, hd).astype(np.float32)
            cache[0, r, :, :lens[r]] = np.transpose(hist, (1, 0, 2))
            cache[1, r, :, :lens[r]] = np.transpose(hist, (1, 0, 2)) * 0.5
        xq = rng.randn(b, 3 * n_head * hd).astype(np.float32)
        rot = self._rope_tables(b, max_seq, hd)
        out, new_cache = IF.masked_multihead_attention(
            paddle.to_tensor(xq), cache_kv=paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(lens),
            rotary_tensor=paddle.to_tensor(rot),
            use_neox_rotary_style=True)

        qkv = xq.reshape(b, 3, n_head, hd)
        pos = jnp.asarray(lens)[:, None]            # [b, 1]
        q = np.asarray(rope_arrays(jnp.asarray(qkv[:, 0][:, None]),
                                   position_ids=pos, neox=True))
        k = np.asarray(rope_arrays(jnp.asarray(qkv[:, 1][:, None]),
                                   position_ids=pos, neox=True))
        nk = new_cache.numpy()
        for r in range(b):
            # rope'd new k landed at slot lens[r]
            np.testing.assert_allclose(nk[0, r, :, lens[r]], k[r, 0],
                                       rtol=1e-5, atol=1e-5)
            kr = paddle.to_tensor(np.transpose(
                nk[0, r:r + 1, :, :lens[r] + 1], (0, 2, 1, 3)))
            vr = paddle.to_tensor(np.transpose(
                nk[1, r:r + 1, :, :lens[r] + 1], (0, 2, 1, 3)))
            ref = F.scaled_dot_product_attention(
                paddle.to_tensor(q[r:r + 1]), kr, vr, training=False)
            np.testing.assert_allclose(out.numpy()[r],
                                       ref.numpy().reshape(-1),
                                       rtol=1e-4, atol=1e-5)

    def test_prefill_attn_mask_honored(self):
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(4)
        L, dim, n_head, ffn = 1, 16, 2, 32
        P = self._mt_params(rng, L, dim, n_head, ffn)
        x = rng.randn(1, 6, dim).astype(np.float32) * 0.3
        # masking the last two positions must equal running on the prefix
        mask = np.zeros((1, 1, 1, 6), np.float32)
        mask[..., 4:] = -1e30
        out_masked = IF.fused_multi_transformer(
            paddle.to_tensor(x), attn_mask=paddle.to_tensor(mask), **P)
        out_prefix = IF.fused_multi_transformer(
            paddle.to_tensor(x[:, :4]), **P)
        np.testing.assert_allclose(out_masked.numpy()[:, :4],
                                   out_prefix.numpy(),
                                   rtol=2e-4, atol=2e-4)

    def test_fused_multi_transformer_gradients_flow(self):
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(5)
        L, dim, n_head, ffn = 1, 16, 2, 32
        P = self._mt_params(rng, L, dim, n_head, ffn)
        for lst in P.values():
            for t in lst:
                t.stop_gradient = False
        x = paddle.to_tensor(rng.randn(1, 4, dim).astype(np.float32) * 0.3)
        x.stop_gradient = False
        out = IF.fused_multi_transformer(x, **P)
        (out ** 2).sum().backward()
        assert x.grad is not None and np.abs(x.grad.numpy()).max() > 0
        qg = P["qkv_weights"][0].grad
        assert qg is not None and np.abs(qg.numpy()).max() > 0

    def test_mmha_cache_full_raises_and_short_mask_ok(self):
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(6)
        b, n_head, hd, max_seq = 1, 2, 4, 8
        cache = np.zeros((2, b, n_head, max_seq, hd), np.float32)
        xq = rng.randn(b, 3 * n_head * hd).astype(np.float32)
        with pytest.raises(ValueError, match="cache full"):
            IF.masked_multihead_attention(
                paddle.to_tensor(xq), cache_kv=paddle.to_tensor(cache),
                sequence_lengths=paddle.to_tensor(
                    np.full((b,), max_seq, np.int32)))
        # upstream contract: src_mask of length step+1 (< max_seq)
        short_mask = np.zeros((b, 1, 1, 4), np.float32)
        out, _ = IF.masked_multihead_attention(
            paddle.to_tensor(xq), cache_kv=paddle.to_tensor(cache),
            src_mask=paddle.to_tensor(short_mask),
            sequence_lengths=paddle.to_tensor(np.full((b,), 3, np.int32)))
        assert np.isfinite(out.numpy()).all()

    def test_fmt_cache_full_and_downscale_infer(self):
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(7)
        L, dim, n_head, ffn = 1, 16, 2, 32
        hd = dim // n_head
        P = self._mt_params(rng, L, dim, n_head, ffn)
        caches = [paddle.to_tensor(np.zeros((2, 1, n_head, 4, hd),
                                            np.float32))]
        x1 = paddle.to_tensor(rng.randn(1, 1, dim).astype(np.float32))
        with pytest.raises(ValueError, match="cache full"):
            IF.fused_multi_transformer(
                x1, cache_kvs=caches,
                time_step=paddle.to_tensor(np.asarray(4, np.int32)), **P)
        # downscale_in_infer at eval multiplies residual adds by keep
        x = paddle.to_tensor(rng.randn(1, 4, dim).astype(np.float32) * 0.3)
        out_p = IF.fused_multi_transformer(x, dropout_rate=0.3,
                                           mode="downscale_in_infer",
                                           training=False, **P)
        out_0 = IF.fused_multi_transformer(x, dropout_rate=0.0,
                                           training=False, **P)
        assert np.abs(out_p.numpy() - out_0.numpy()).max() > 1e-4


class TestDecodeCacheOverflow:
    def test_overflowing_time_step_drops_write(self):
        """r5: the dynamic_update_slice cache write must DROP an
        out-of-capacity token (the pre-r5 where() semantics) — DUS alone
        would clamp and silently corrupt the last slot."""
        import jax
        import paddle_tpu.incubate.nn.functional as IF

        rng = np.random.RandomState(3)
        L, dim, n_head, ffn = 1, 32, 4, 64
        hd = dim // n_head
        tc = TestFusedGeneration()
        P = tc._mt_params(rng, L, dim, n_head, ffn)
        max_seq = 4
        x = paddle.to_tensor(rng.randn(1, 1, dim).astype(np.float32))
        caches = [paddle.to_tensor(
            rng.randn(2, 1, n_head, max_seq, hd).astype(np.float32))]
        before = caches[0].numpy().copy()

        def run(ts):
            out, cs = IF.fused_multi_transformer(
                x, cache_kvs=[paddle.to_tensor(before.copy())],
                time_step=paddle.to_tensor(np.asarray(ts, np.int32)), **P)
            return out, cs

        # in-range write modifies exactly the ts slot
        _, cs = run(2)
        after = cs[0].numpy()
        changed = np.abs(after - before).max(axis=(0, 1, 2, 4))
        assert changed[2] > 0 and changed[[0, 1, 3]].max() == 0
        # eager overflow raises loudly (pre-existing contract)
        import pytest as _pytest
        with _pytest.raises(ValueError, match="cache full"):
            run(max_seq)
        # traced overflow (jit decode run past capacity): output is
        # NaN-poisoned AND the returned cache is UNTOUCHED — DUS alone
        # would clamp and overwrite the last slot
        from paddle_tpu.core.tensor import Tensor

        def jit_run(x_a, cache_a, ts_a):
            out, cs = IF.fused_multi_transformer(
                Tensor(x_a), cache_kvs=[Tensor(cache_a)],
                time_step=Tensor(ts_a), **P)
            return out._data, cs[0]._data

        out_a, cache_a = jax.jit(jit_run)(
            x._data, jax.numpy.asarray(before),
            jax.numpy.asarray(max_seq, jax.numpy.int32))
        assert np.isnan(np.asarray(out_a)).all()
        np.testing.assert_array_equal(np.asarray(cache_a), before)
