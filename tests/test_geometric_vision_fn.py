"""paddle.geometric segment ops, grid_sample/affine_grid/temporal_shift,
sequence_mask, margin CE, and new tensor math vs NumPy/scipy references."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.geometric as G
import paddle_tpu.nn.functional as F


def _t(x):
    return paddle.to_tensor(np.asarray(x))


class TestSegmentOps:
    def test_segment_sum_mean_max_min(self):
        data = _t(np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]],
                           np.float32))
        ids = _t(np.array([0, 0, 1, 1], np.int64))
        np.testing.assert_allclose(G.segment_sum(data, ids).numpy(),
                                   [[4, 6], [12, 14]])
        np.testing.assert_allclose(G.segment_mean(data, ids).numpy(),
                                   [[2, 3], [6, 7]])
        np.testing.assert_allclose(G.segment_max(data, ids).numpy(),
                                   [[3, 4], [7, 8]])
        np.testing.assert_allclose(G.segment_min(data, ids).numpy(),
                                   [[1, 2], [5, 6]])

    def test_send_u_recv(self):
        x = _t(np.array([[1.0], [2.0], [4.0]], np.float32))
        src = _t(np.array([0, 1, 2, 0], np.int64))
        dst = _t(np.array([1, 2, 1, 0], np.int64))
        out = G.send_u_recv(x, src, dst, reduce_op="sum").numpy()
        np.testing.assert_allclose(out, [[1.0], [5.0], [2.0]])

    def test_send_ue_recv_mul(self):
        x = _t(np.array([[2.0], [3.0]], np.float32))
        e = _t(np.array([[10.0], [100.0]], np.float32))
        src = _t(np.array([0, 1], np.int64))
        dst = _t(np.array([0, 0], np.int64))
        out = G.send_ue_recv(x, e, src, dst, message_op="mul",
                             reduce_op="sum").numpy()
        np.testing.assert_allclose(out, [[320.0], [0.0]])


class TestGridSample:
    def test_identity_grid(self):
        rng = np.random.RandomState(0)
        x = rng.randn(1, 2, 5, 7).astype(np.float32)
        ys, xs = np.meshgrid(np.linspace(-1, 1, 5), np.linspace(-1, 1, 7),
                             indexing="ij")
        grid = np.stack([xs, ys], axis=-1)[None].astype(np.float32)
        out = F.grid_sample(_t(x), _t(grid), align_corners=True).numpy()
        np.testing.assert_allclose(out, x, atol=1e-5)

    def test_zeros_padding_outside(self):
        x = np.ones((1, 1, 4, 4), np.float32)
        grid = np.full((1, 1, 1, 2), 5.0, np.float32)  # far outside
        out = F.grid_sample(_t(x), _t(grid), padding_mode="zeros").numpy()
        np.testing.assert_allclose(out, 0.0, atol=1e-6)

    def test_border_padding(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        grid = np.array([[[[-2.0, -2.0]]]], np.float32)  # clamps to (0,0)
        out = F.grid_sample(_t(x), _t(grid), padding_mode="border").numpy()
        np.testing.assert_allclose(out[0, 0, 0, 0], 0.0, atol=1e-6)

    def test_nearest_mode(self):
        x = np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2)
        grid = np.array([[[[1.0, 1.0]]]], np.float32)  # bottom-right
        out = F.grid_sample(_t(x), _t(grid), mode="nearest").numpy()
        assert out[0, 0, 0, 0] == 3.0

    def test_affine_grid_identity(self):
        theta = np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32)
        grid = F.affine_grid(_t(theta), [1, 1, 3, 3]).numpy()
        np.testing.assert_allclose(grid[0, 0, 0], [-1, -1], atol=1e-6)
        np.testing.assert_allclose(grid[0, 2, 2], [1, 1], atol=1e-6)
        # composing with grid_sample reproduces the input
        x = np.random.RandomState(0).randn(1, 1, 3, 3).astype(np.float32)
        out = F.grid_sample(_t(x), _t(grid), align_corners=True).numpy()
        np.testing.assert_allclose(out, x, atol=1e-5)


class TestTemporalShift:
    def test_shift_semantics(self):
        # N=1, T=2, C=4, fold=1: ch0 shifts from future, ch1 from past
        x = np.zeros((2, 4, 1, 1), np.float32)
        x[0, :, 0, 0] = [1, 2, 3, 4]
        x[1, :, 0, 0] = [5, 6, 7, 8]
        out = F.temporal_shift(_t(x), seg_num=2, shift_ratio=0.25).numpy()
        # fold 0 (ch0) reads from t-1, fold 1 (ch1) reads from t+1,
        # remaining channels unchanged; out-of-range reads are zero-padded
        assert out[0, 0, 0, 0] == 0.0   # t=0 ch0: t-1 doesn't exist
        assert out[1, 0, 0, 0] == 1.0   # t=1 ch0 <- t=0
        assert out[0, 1, 0, 0] == 6.0   # t=0 ch1 <- t=1
        assert out[1, 1, 0, 0] == 0.0   # t=1 ch1: t+1 doesn't exist
        assert out[0, 2, 0, 0] == 3.0   # untouched channels
        assert out[1, 3, 0, 0] == 8.0


class TestMiscNewOps:
    def test_sequence_mask(self):
        m = F.sequence_mask(_t(np.array([2, 0, 3], np.int64)), maxlen=4).numpy()
        np.testing.assert_array_equal(
            m, [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]])

    def test_margin_cross_entropy_reduces_to_ce_at_zero_margin(self):
        rng = np.random.RandomState(0)
        cos = np.clip(rng.randn(4, 10) * 0.3, -1, 1).astype(np.float32)
        y = np.array([1, 5, 2, 9], np.int64)
        loss = F.margin_cross_entropy(_t(cos), _t(y), margin1=1.0,
                                      margin2=0.0, margin3=0.0,
                                      scale=1.0).numpy()
        import scipy.special as sp

        logp = cos - sp.logsumexp(cos, axis=-1, keepdims=True)
        ref = -logp[np.arange(4), y].mean()
        np.testing.assert_allclose(loss, ref, rtol=1e-5)

    def test_bincount_trapezoid_vander(self):
        b = paddle.bincount(_t(np.array([0, 2, 2, 5], np.int64))).numpy()
        np.testing.assert_array_equal(b, [1, 0, 2, 0, 0, 1])
        y = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(paddle.trapezoid(_t(y)).numpy(),
                                   np.trapezoid(y), rtol=1e-6)
        ct = paddle.cumulative_trapezoid(_t(y)).numpy()
        np.testing.assert_allclose(ct, [1.5, 4.0], rtol=1e-6)
        v = paddle.vander(_t(np.array([2.0, 3.0], np.float32))).numpy()
        np.testing.assert_allclose(v, np.vander(np.array([2.0, 3.0])),
                                   rtol=1e-6)


class TestReviewRegressions2:
    def test_param_attr_reg_suppresses_optimizer_l2(self):
        from paddle_tpu.framework.param_attr import ParamAttr
        from paddle_tpu.regularizer import L1Decay

        paddle.seed(0)
        import paddle_tpu.nn as nn

        lin = nn.Linear(4, 4, bias_attr=False,
                        weight_attr=ParamAttr(regularizer=L1Decay(0.5)))
        w0 = lin.weight.numpy().copy()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters(),
                                   weight_decay=0.3)
        x = _t(np.zeros((2, 4), np.float32))
        loss = paddle.mean(lin(x))
        loss.backward()
        opt.step()
        # ONLY the per-param L1 applies; the optimizer L2 must be suppressed
        np.testing.assert_allclose(lin.weight.numpy(),
                                   w0 - 0.1 * 0.5 * np.sign(w0), atol=1e-6)

    def test_margin_ce_grad_finite_at_boundary(self):
        cos = _t(np.array([[1.0, -1.0, 0.5]], np.float32))
        cos.stop_gradient = False
        y = _t(np.array([0], np.int64))
        loss = F.margin_cross_entropy(cos, y, margin2=0.5)
        loss.backward()
        assert np.isfinite(cos.grad.numpy()).all()

    def test_segment_max_empty_segment_zero(self):
        data = _t(np.array([[1.0], [2.0]], np.float32))
        ids = _t(np.array([0, 2], np.int64))
        out = G.segment_max(data, ids).numpy()
        np.testing.assert_allclose(out, [[1.0], [0.0], [2.0]])

    def test_send_ue_recv_max(self):
        x = _t(np.array([[2.0], [5.0]], np.float32))
        e = _t(np.array([[1.0], [1.0]], np.float32))
        src = _t(np.array([0, 1], np.int64))
        dst = _t(np.array([0, 0], np.int64))
        out = G.send_ue_recv(x, e, src, dst, message_op="add",
                             reduce_op="max").numpy()
        np.testing.assert_allclose(out, [[6.0], [0.0]])


class TestReviewRegressions3:
    def test_segment_max_int_dtype_and_fill(self):
        data = _t(np.array([[1], [2]], np.int32))
        ids = _t(np.array([0, 2], np.int64))
        out = G.segment_max(data, ids).numpy()
        assert out.dtype == np.int32
        np.testing.assert_array_equal(out, [[1], [0], [2]])

    def test_bincount_negative_raises(self):
        import pytest as _pytest

        with _pytest.raises(ValueError, match="non-negative"):
            paddle.bincount(_t(np.array([-1, 2], np.int64)))

    def test_sequence_mask_empty(self):
        m = F.sequence_mask(_t(np.array([], np.int64))).numpy()
        assert m.shape == (0, 0)

    def test_vjp_list_output(self):
        import paddle_tpu.autograd as AG

        x = _t(np.array([1.0, 2.0], np.float32))
        v = [_t(np.array([1.0, 1.0], np.float32))]
        out, g = AG.vjp(lambda t: [t * t], x, v)
        np.testing.assert_allclose(g.numpy(), [2.0, 4.0], atol=1e-6)
