"""paddle_tpu.observability tests: typed registry semantics, histogram
percentiles vs a numpy reference, chrome-trace export validity, the
jit compile-counter invariant, span nesting, the profiler facade and its
satellite fixes (tuple scheduler, n=1 summary, engine provider GC), and
a CLI smoke via ``python -m``."""

import gc
import json
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu import profiler
from paddle_tpu.observability import events as obs_events
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability.metrics import (
    Counter, Gauge, Histogram, Registry,
)
from paddle_tpu.observability.span import current_span, span, span_depth


class TestRegistry:
    def test_counter_labels_and_monotonicity(self):
        reg = Registry()
        c = reg.counter("requests", "total requests")
        c.inc()
        c.inc(2, route="a")
        c.inc(route="a")
        assert c.value() == 1
        assert c.value(route="a") == 3
        assert c.value(route="missing") == 0
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_set_inc_dec(self):
        reg = Registry()
        g = reg.gauge("depth")
        g.set(7, q="main")
        g.inc(q="main")
        g.dec(3, q="main")
        assert g.value(q="main") == 5

    def test_get_or_create_returns_same_family(self):
        reg = Registry()
        a = reg.counter("x")
        b = reg.counter("x")
        assert a is b

    def test_type_conflict_raises(self):
        reg = Registry()
        reg.counter("x")
        with pytest.raises(ValueError):
            reg.gauge("x")

    def test_label_order_is_canonical(self):
        reg = Registry()
        c = reg.counter("c")
        c.inc(a=1, b=2)
        c.inc(b=2, a=1)
        assert c.value(b=2, a=1) == 2

    def test_snapshot_shape(self):
        reg = Registry()
        reg.counter("n", "help text").inc(5)
        reg.gauge("g").set(1.5)
        reg.histogram("h").observe(0.2)
        snap = reg.snapshot()
        assert snap["metrics"]["n"]["type"] == "counter"
        assert snap["metrics"]["n"]["help"] == "help text"
        assert snap["metrics"]["n"]["values"][""] == 5
        assert snap["metrics"]["g"]["values"][""] == 1.5
        assert snap["metrics"]["h"]["values"][""]["count"] == 1
        json.dumps(snap)  # must be JSON-able as-is

    def test_reset_keeps_families(self):
        reg = Registry()
        c = reg.counter("c")
        c.inc(10)
        reg.reset()
        assert c.value() == 0
        assert reg.get("c") is c
        c.inc()
        assert c.value() == 1


class TestHistogram:
    def test_percentiles_match_numpy(self):
        reg = Registry()
        h = reg.histogram("lat")
        rng = np.random.default_rng(0)
        samples = rng.lognormal(-3, 1.0, size=500)
        for s in samples:
            h.observe(s)
        for q in (50, 95, 99):
            assert h.percentile(q) == pytest.approx(
                float(np.percentile(samples, q)))
        st = h.stats()
        assert st["count"] == 500
        assert st["sum"] == pytest.approx(samples.sum())
        assert st["mean"] == pytest.approx(samples.mean())
        assert st["p50"] == pytest.approx(np.percentile(samples, 50))

    def test_buckets_are_cumulative(self):
        reg = Registry()
        h = reg.histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        b = h.stats()["buckets"]
        assert b[repr(0.1)] == 1
        assert b[repr(1.0)] == 3
        assert b[repr(10.0)] == 4
        assert b["+Inf"] == 5

    def test_reservoir_is_bounded(self):
        reg = Registry()
        h = reg.histogram("lat", reservoir=16)
        for i in range(100):
            h.observe(float(i))
        st = h.stats()
        assert st["count"] == 100          # exact totals survive
        # percentiles slide to the most recent window
        assert h.percentile(50) >= 84.0

    def test_labelled_slots_are_independent(self):
        reg = Registry()
        h = reg.histogram("lat")
        h.observe(1.0, op="a")
        h.observe(100.0, op="b")
        assert h.percentile(50, op="a") == 1.0
        assert h.percentile(50, op="b") == 100.0
        assert h.percentile(50, op="c") is None


class TestPrometheusRendering:
    def test_exposition_format(self):
        reg = Registry()
        reg.counter("jit.compile_count", "compiles").inc(3, fn="f")
        reg.gauge("queue.depth").set(2)
        reg.histogram("lat", buckets=(0.1, 1.0)).observe(0.5)
        text = reg.render_prometheus()
        assert "# TYPE jit_compile_count counter" in text
        assert '# HELP jit_compile_count compiles' in text
        assert 'jit_compile_count{fn="f"} 3' in text
        assert "# TYPE queue_depth gauge" in text
        assert "# TYPE lat histogram" in text
        assert 'lat_bucket{le="0.1"} 0' in text
        assert 'lat_bucket{le="1.0"} 1' in text
        assert 'lat_bucket{le="+Inf"} 1' in text
        assert "lat_sum" in text and "lat_count" in text

    def test_providers_render_as_gauges(self):
        reg = Registry()
        reg.register_provider("serving.engine0",
                              lambda: {"tokens": 42, "note": "text"})
        text = reg.render_prometheus()
        assert '# TYPE serving_engine0 gauge' in text
        assert 'serving_engine0{counter="tokens"} 42' in text
        assert "note" not in text          # non-numeric values skipped

    def test_default_registry_render_nonempty(self):
        text = obs.render_prometheus()
        assert "# TYPE " in text


class TestProviders:
    def test_register_snapshot_unregister(self):
        reg = Registry()
        reg.register_provider("sub", lambda: {"a": 1})
        assert reg.provider_counters() == {"sub": {"a": 1}}
        assert reg.snapshot()["providers"] == {"sub": {"a": 1}}
        reg.unregister_provider("sub")
        assert reg.provider_counters() == {}

    def test_raising_provider_is_isolated(self):
        reg = Registry()

        def bad():
            raise RuntimeError("boom")

        reg.register_provider("bad", bad)
        reg.register_provider("good", lambda: {"x": 1})
        out = reg.provider_counters()
        assert out["good"] == {"x": 1}
        assert "RuntimeError" in out["bad"]["error"]

    def test_non_callable_rejected(self):
        reg = Registry()
        with pytest.raises(TypeError):
            reg.register_provider("x", {"not": "callable"})


class TestEvents:
    def test_ring_is_bounded_and_counts_drops(self):
        log = obs_events.EventLog(capacity=8)
        for i in range(20):
            log.instant(f"e{i}")
        evs = log.events()
        assert len(evs) == 8
        assert evs[0].name == "e12"        # oldest 12 fell off
        assert log.dropped == 12

    def test_chrome_trace_valid_json_monotonic_ts(self, tmp_path):
        log = obs_events.EventLog()
        log.begin("outer", cat="test", k=1)
        log.instant("mark", cat="test")
        log.end("outer", cat="test")
        path = tmp_path / "trace.json"
        text = log.export_chrome_trace(file=str(path))
        with open(path) as f:
            doc = json.load(f)             # must be loadable by json.load
        assert json.loads(text) == doc
        evs = doc["traceEvents"]
        assert len(evs) == 3
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)            # monotonically ordered
        assert {e["ph"] for e in evs} == {"B", "i", "E"}
        assert all("pid" in e and "tid" in e for e in evs)
        assert evs[0]["args"] == {"k": 1}

    def test_filtering(self):
        log = obs_events.EventLog()
        log.instant("a", cat="x")
        log.instant("b", cat="y")
        assert [e.name for e in log.events(cat="x")] == ["a"]
        assert [e.name for e in log.events(name="b")] == ["b"]


class TestSpan:
    def test_nesting_and_histogram(self):
        reg_before = obs_metrics.value("span.seconds", name="outer-span")
        n_before = reg_before["count"] if reg_before else 0
        assert current_span() is None
        with span("outer-span", cat="test"):
            assert current_span() == "outer-span"
            d = span_depth()
            with span("inner-span", cat="test"):
                assert current_span() == "inner-span"
                assert span_depth() == d + 1
            assert current_span() == "outer-span"
        assert current_span() is None
        st = obs_metrics.value("span.seconds", name="outer-span")
        assert st["count"] == n_before + 1
        # begin/end pairs landed on the timeline with depth recorded
        begins = [e for e in obs_events.events(name="inner-span")
                  if e.phase == obs_events.BEGIN]
        assert begins and begins[-1].args["depth"] == d

    def test_elapsed_and_error_annotation(self):
        s = span("failing-span", cat="test")
        with pytest.raises(ValueError):
            with s:
                raise ValueError("x")
        assert s.elapsed is not None and s.elapsed >= 0
        ends = [e for e in obs_events.events(name="failing-span")
                if e.phase == obs_events.END]
        assert ends[-1].args["error"] == "ValueError"

    def test_event_args_stay_off_histogram_labels(self):
        with span("arg-span", cat="test", event_args={"path": "/tmp/x"}):
            pass
        st = obs_metrics.value("span.seconds", name="arg-span")
        assert st["count"] >= 1            # labeled only by name
        begins = [e for e in obs_events.events(name="arg-span")
                  if e.phase == obs_events.BEGIN]
        assert begins[-1].args["path"] == "/tmp/x"


class TestJitInstrumentation:
    def test_compile_counter_invariant(self):
        """Two calls with identical avals = one compile + one cache hit;
        a new input signature = a second compile, not a hit."""
        import paddle_tpu.jit as jit

        @jit.to_static
        def obs_fn(x):
            return x * 2 + 1

        def vals():
            c = obs.value("jit.compile_count", fn="obs_fn") or 0
            h = obs.value("jit.cache_hit", fn="obs_fn") or 0
            return c, h

        c0, h0 = vals()
        a = paddle.to_tensor(np.ones((2, 3), np.float32))
        obs_fn(a)
        obs_fn(paddle.to_tensor(np.zeros((2, 3), np.float32)))
        c1, h1 = vals()
        assert c1 == c0 + 1
        assert h1 == h0 + 1
        obs_fn(paddle.to_tensor(np.ones((4, 3), np.float32)))
        c2, h2 = vals()
        assert c2 == c0 + 2
        assert h2 == h0 + 1
        # compile begin/end pairs match the compile count
        begins = [e for e in obs_events.events(name="jit.compile")
                  if e.phase == obs_events.BEGIN
                  and e.args.get("fn") == "obs_fn"]
        ends = [e for e in obs_events.events(name="jit.compile")
                if e.phase == obs_events.END
                and e.args.get("fn") == "obs_fn"]
        assert len(begins) == len(ends) == 2
        assert all(e.args["seconds"] >= 0 for e in ends)
        # the miss also explains itself on the timeline
        causes = [e.args["cause"] for e in
                  obs_events.events(name="jit.retrace")
                  if e.args.get("fn") == "obs_fn"]
        assert causes == ["first_call", "new_input_signature"]
        st = obs.value("jit.compile_seconds", fn="obs_fn")
        assert st["count"] >= 2


class TestProfilerSatellites:
    def test_make_scheduler_tuple_records_once(self):
        """(start, end) = record [start, end) ONCE — regression for the
        repeat=0 form that cycled the window forever."""
        from paddle_tpu.profiler import Profiler, ProfilerState

        p = Profiler(scheduler=(2, 5), timer_only=True)
        states = [p._scheduler(i) for i in range(12)]
        assert states[:2] == [ProfilerState.CLOSED] * 2
        assert states[2:4] == [ProfilerState.RECORD] * 2
        assert states[4] == ProfilerState.RECORD_AND_RETURN
        # the old bug: step 7 re-entered RECORD; now closed forever
        assert states[5:] == [ProfilerState.CLOSED] * 7

    def test_summary_single_step(self):
        from paddle_tpu.profiler import Profiler

        p = Profiler(timer_only=True)
        p.start()
        p.step()
        text = p.summary()
        assert "steps: 1" in text
        assert "p50" in text and "p99" in text

    def test_summary_includes_observability_histograms(self):
        from paddle_tpu.profiler import Profiler

        obs_metrics.histogram("test.profiler_summary").observe(0.25)
        p = Profiler(timer_only=True)
        p.start()
        p.step()
        p.step()
        assert "test.profiler_summary" in p.summary()

    def test_facade_register_and_counters(self):
        profiler.register_counter_provider("facade.test",
                                           lambda: {"v": 7})
        try:
            assert profiler.counters()["facade.test"] == {"v": 7}
            # one registry: visible through observability too
            assert obs_metrics.provider_counters()["facade.test"] == \
                {"v": 7}
            assert obs.snapshot()["providers"]["facade.test"] == {"v": 7}
        finally:
            profiler.unregister_counter_provider("facade.test")
        assert "facade.test" not in profiler.counters()


class TestEngineProviderLifecycle:
    """Repeated engine construction must not leak stale providers
    (regression: bound-method providers pinned engines forever)."""

    def _tiny_engine(self, register=True):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving import Engine, EngineConfig

        cfg = GPTConfig(vocab_size=64, hidden_size=32,
                        intermediate_size=64, num_hidden_layers=1,
                        num_attention_heads=2,
                        max_position_embeddings=32)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        m.eval()
        return Engine(m, EngineConfig(num_slots=1, max_seq_len=16),
                      register_profiler=register)

    def test_close_unregisters_provider(self):
        eng = self._tiny_engine()
        name = eng._profiler_name
        assert name in profiler.counters()
        eng.close()
        assert name not in profiler.counters()

    def test_gc_unregisters_provider(self):
        eng = self._tiny_engine()
        name = eng._profiler_name
        assert name in profiler.counters()
        del eng
        gc.collect()
        assert name not in profiler.counters()

    def test_live_engine_counters_unchanged_via_facade(self):
        eng = self._tiny_engine()
        try:
            via_facade = profiler.counters()[eng._profiler_name]
            assert via_facade == eng.counters()
        finally:
            eng.close()


class TestCLI:
    def test_snapshot_smoke(self, tmp_path):
        script = tmp_path / "load.py"
        script.write_text(
            "from paddle_tpu.observability import metrics, events\n"
            "metrics.counter('cli.test').inc(3)\n"
            "events.instant('cli.mark')\n")
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability",
             "snapshot", "--exec", str(script)],
            capture_output=True, text=True, timeout=120,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        snap = json.loads(out.stdout)
        assert snap["metrics"]["cli.test"]["values"][""] == 3

    def test_trace_and_prometheus_modes(self, tmp_path):
        script = tmp_path / "load.py"
        script.write_text(
            "from paddle_tpu.observability import metrics, events\n"
            "metrics.histogram('cli.h').observe(0.1)\n"
            "events.instant('cli.mark')\n")
        env = {**__import__("os").environ, "JAX_PLATFORMS": "cpu"}
        trace_file = tmp_path / "t.json"
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability", "trace",
             "--exec", str(script), "-o", str(trace_file)],
            capture_output=True, text=True, timeout=120, env=env)
        assert out.returncode == 0, out.stderr
        with open(trace_file) as f:
            doc = json.load(f)
        assert any(e["name"] == "cli.mark" for e in doc["traceEvents"])
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability",
             "prometheus", "--exec", str(script)],
            capture_output=True, text=True, timeout=120, env=env)
        assert out.returncode == 0, out.stderr
        assert "# TYPE cli_h histogram" in out.stdout


class TestFlightRecorder:
    """RequestTrace flight records + bounded FlightRecorder retention."""

    @staticmethod
    def _finished_trace(rid, tokens=3):
        from paddle_tpu.observability import tracing

        tr = tracing.RequestTrace(rid, engine="e0")
        tr.add(tracing.QUEUED, prompt_len=4)
        tr.add(tracing.PREFILL, slot=0, prefill_tokens=4,
               prefix_hit_tokens=2)
        tr.add(tracing.FIRST_TOKEN, token=7)
        tr.add(tracing.DECODE, horizon=4, tokens=tokens - 1, accepted=1)
        tr.add(tracing.FINISH, reason="eos", n_generated=tokens)
        return tr

    def test_counts_reconstruct_lifecycle(self):
        from paddle_tpu.observability import tracing

        tr = tracing.RequestTrace(5)
        tr.add(tracing.QUEUED)
        tr.add(tracing.PREFILL, prefix_hit_tokens=4)
        tr.add(tracing.FIRST_TOKEN, token=1)
        tr.add(tracing.DECODE, tokens=3, accepted=2, horizon=4)
        tr.add(tracing.PREEMPT)
        tr.add(tracing.SWAP_OUT, blocks=2, bytes=4096, n_tokens=8)
        tr.add(tracing.SWAP_IN, blocks=2, bytes=4096,
               averted_tokens=6, source="lane")
        tr.add(tracing.RESUME, prefix_hit_tokens=6)
        tr.add(tracing.DECODE, tokens=2, accepted=0, horizon=2)
        tr.add(tracing.FAILOVER, from_replica="r0", resumed_tokens=6)
        tr.add(tracing.FINISH, reason="length")
        c = tr.counts()
        # resumed tokens are NOT tokens_emitted: per-engine trace sums
        # must still reconcile against engine counters exactly
        assert c == {"tokens_emitted": 6, "prefix_hit_tokens": 6,
                     "preemptions": 1, "decode_horizons": 2,
                     "spec_accepted_tokens": 2, "spec_forced_tokens": 0,
                     "aborted": 0, "failovers": 1, "resumed_tokens": 6,
                     "swap_ins": 1, "swap_outs": 1,
                     "swap_in_bytes": 4096, "swap_out_bytes": 4096,
                     "flops_est": 0.0, "bytes_est": 0.0}
        assert tr.finished
        # monotonic event times
        ts = [t for _, t, _ in tr.events]
        assert ts == sorted(ts) and all(t >= 0 for t in ts)

    def test_bounded_retention_drops_oldest_finished(self):
        from paddle_tpu.observability import tracing

        rec = tracing.FlightRecorder(capacity=3)
        for i in range(10):
            tr = self._finished_trace(i)
            rec.attach(tr)
            rec.finish(tr)
        assert [t.request_id for t in rec.recent()] == [7, 8, 9]
        assert rec.dropped == 7
        assert rec.to_json()["finished_total"] == 10
        assert rec.get(9) is not None and rec.get(0) is None

    def test_live_traces_are_pinned(self):
        from paddle_tpu.observability import tracing

        rec = tracing.FlightRecorder(capacity=2)
        live = tracing.RequestTrace(100)
        live.add(tracing.QUEUED)
        rec.attach(live)
        for i in range(8):          # churn far past capacity
            tr = self._finished_trace(i)
            rec.attach(tr)
            rec.finish(tr)
        assert rec.get(100) is live          # still reachable
        assert [t.request_id for t in rec.live()] == [100]
        doc = rec.to_json()
        assert doc["live_count"] == 1
        assert doc["finished_retained"] == 2
        assert not doc["live"][0]["finished"]
        json.dumps(doc)                      # fully JSON-able

    def test_chrome_async_span_export(self):
        from paddle_tpu.observability import tracing

        rec = tracing.FlightRecorder()
        tr = self._finished_trace(42)
        rec.attach(tr)
        rec.finish(tr)
        doc = json.loads(rec.export_chrome_trace())
        evs = [e for e in doc["traceEvents"] if e["id"] == "42"]
        phases = [e["ph"] for e in evs]
        assert phases[0] == "b" and phases[-1] == "e"
        assert phases.count("n") == 5        # one per lifecycle event
        ts = [e["ts"] for e in doc["traceEvents"]]
        assert ts == sorted(ts)
        # mergeable into the process event ring export
        merged = json.loads(obs_events.EventLog().export_chrome_trace(
            extra=rec.chrome_events()))
        assert len(merged["traceEvents"]) == 7


class TestSLO:
    """Deterministic step-window burn-rate math (no clocks)."""

    def _tracker(self, **kw):
        from paddle_tpu.observability.slo import SLOTracker

        reg = Registry()
        t = SLOTracker("t", registry=reg)
        kw.setdefault("target", 0.9)
        kw.setdefault("fast_window", 4)
        kw.setdefault("slow_window", 8)
        t.declare("ttft", 0.5, **kw)
        return t, reg

    def test_empty_window_is_compliant(self):
        t, _ = self._tracker()
        obj = t.objective("ttft")
        assert obj.compliance("fast") == 1.0
        assert obj.burn_rate("slow") == 0.0
        assert t.healthy

    def test_window_math_exact(self):
        t, _ = self._tracker()
        obj = t.objective("ttft")
        for v in (0.1, 0.1, 2.0, 0.1):       # 1 breach in 4
            t.observe("ttft", v)
        assert obj.compliance("fast") == pytest.approx(0.75)
        # burn = (1 - 0.75) / (1 - 0.9) = 2.5x budget
        assert obj.burn_rate("fast") == pytest.approx(2.5)
        assert obj.compliance("slow") == pytest.approx(0.75)

    def test_multiwindow_and_breach_and_recovery(self):
        t, reg = self._tracker()
        obj = t.objective("ttft")
        # one bad observation: fast window burns, slow doesn't -> healthy
        for _ in range(7):
            t.observe("ttft", 0.1)
        t.observe("ttft", 2.0)
        assert obj.burn_rate("fast") > 1.0
        assert obj.burn_rate("slow") > 1.0  # 1/8 breach > 10% budget
        # sustained outage: both windows burn -> unhealthy
        for _ in range(8):
            t.observe("ttft", 2.0)
        assert not obj.healthy and not t.healthy
        assert reg.value("slo.healthy", tracker="t") == 0
        assert reg.value("slo.burn_rate", tracker="t", objective="ttft",
                         window="fast") == pytest.approx(10.0)
        # recovery: the fast window forgives as soon as it refills
        for _ in range(4):
            t.observe("ttft", 0.1)
        assert obj.burn_rate("fast") == 0.0
        assert obj.healthy and t.healthy
        assert reg.value("slo.healthy", tracker="t") == 1
        assert reg.value("slo.compliance", tracker="t", objective="ttft",
                         window="fast") == 1

    def test_unknown_objective_ignored(self):
        t, _ = self._tracker()
        t.observe("nope", 1.0)               # must not raise
        assert t.healthy

    def test_invalid_declarations_rejected(self):
        from paddle_tpu.observability.slo import Objective

        with pytest.raises(ValueError):
            Objective("x", 1.0, target=1.0)
        with pytest.raises(ValueError):
            Objective("x", 1.0, fast_window=8, slow_window=4)


class TestExpositionConformance:
    """validate_exposition: the renderer's output parses, and the
    validator actually rejects malformed documents."""

    def test_renderer_output_parses(self):
        from paddle_tpu.observability.metrics import validate_exposition

        reg = Registry()
        reg.counter("c.plain", "simple").inc(3)
        g = reg.gauge("g.hard", 'help with "quotes", \\slash\nnewline')
        g.set(1.5, path='va"l\\ue', msg="line\nbreak")
        g.set(float("inf"), k="inf")
        g.set(float("nan"), k="nan")
        h = reg.histogram("h.lat", "lat", buckets=(0.1, 1.0))
        h.observe(0.5, op="a")
        reg.register_provider("sub.sys", lambda: {"n": 2})
        n = validate_exposition(reg.render_prometheus())
        assert n >= 9       # every emitted sample line parsed
        text = reg.render_prometheus()
        assert "NaN" in text and "+Inf" in text
        assert "\\n" in text          # newlines escaped, never raw

    def test_default_registry_conforms(self):
        from paddle_tpu.observability.metrics import validate_exposition

        with span("expo-conform", cat="test"):
            pass
        assert validate_exposition(obs.render_prometheus()) > 0

    @pytest.mark.parametrize("doc", [
        "9bad_name 1\n",                       # name starts with digit
        'm{l="unterminated} 1\n',              # unbalanced quote
        'm{l="x"} notanumber\n',               # bad value
        'm{l="x"}\n',                          # missing value
        'm{bad-label="x"} 1\n',                # bad label name
        "# TYPE m wrongtype\nm 1\n",           # unknown type
        "m 1\nm 1\n",                          # duplicate sample
        "# TYPE h histogram\nh_bucket 1\n",    # bucket without le
    ])
    def test_rejects_malformed(self, doc):
        from paddle_tpu.observability.metrics import validate_exposition

        with pytest.raises(ValueError):
            validate_exposition(doc)


class TestSpanErrorPath:
    """Regression: the span histogram must be observed on the exception
    path (with error=1), even if the event sink itself raises."""

    def test_error_observation_labeled(self):
        st0 = obs_metrics.value("span.seconds", name="err-span",
                                error="1")
        n0 = st0["count"] if st0 else 0
        with pytest.raises(RuntimeError):
            with span("err-span", cat="test"):
                raise RuntimeError("boom")
        st = obs_metrics.value("span.seconds", name="err-span",
                               error="1")
        assert st["count"] == n0 + 1
        # the success path stays on the unlabeled slot
        with span("err-span", cat="test"):
            pass
        ok = obs_metrics.value("span.seconds", name="err-span")
        assert ok["count"] >= 1

    def test_histogram_observed_even_if_event_sink_raises(self,
                                                          monkeypatch):
        import importlib

        span_mod = importlib.import_module(
            "paddle_tpu.observability.span")

        def boom(*a, **k):
            raise RuntimeError("sink down")

        st0 = obs_metrics.value("span.seconds", name="sink-span")
        n0 = st0["count"] if st0 else 0
        s = span_mod.span("sink-span", cat="test")
        s.__enter__()
        monkeypatch.setattr(span_mod._events, "record", boom)
        with pytest.raises(RuntimeError):
            s.__exit__(None, None, None)
        st = obs_metrics.value("span.seconds", name="sink-span")
        assert st["count"] == n0 + 1       # observed despite the raise
        assert s.elapsed is not None


class TestChromeTraceMetadata:
    def test_header_has_process_identity_and_drops(self):
        log = obs_events.EventLog(capacity=2)
        for i in range(5):
            log.instant(f"e{i}")
        doc = json.loads(log.export_chrome_trace())
        meta = doc["metadata"]
        assert meta["dropped_events"] == 3
        assert meta["process_name"].startswith("python:")
        assert meta["git_sha"]          # short sha or "unknown"


class TestTelemetryEndpoint:
    """Scrape a LIVE engine's telemetry endpoint."""

    def _engine(self, **extra):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving import Engine, EngineConfig

        cfg = GPTConfig(vocab_size=64, hidden_size=32,
                        intermediate_size=64, num_hidden_layers=1,
                        num_attention_heads=2,
                        max_position_embeddings=32)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        m.eval()
        kw = dict(num_slots=1, max_seq_len=16, telemetry_port=0,
                  slo_ttft_s=60.0, slo_target=0.9,
                  slo_fast_window=4, slo_slow_window=8)
        kw.update(extra)
        return Engine(m, EngineConfig(**kw), register_profiler=False)

    @staticmethod
    def _get(url):
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    @pytest.mark.slow
    def test_scrape_running_engine(self):
        from paddle_tpu.observability.metrics import validate_exposition
        from paddle_tpu.serving import SamplingParams

        eng = self._engine()
        try:
            eng.generate([3, 1, 4], SamplingParams(max_new_tokens=4))
            assert eng.telemetry.port > 0
            code, body = self._get(eng.telemetry.url("/metrics"))
            assert code == 200
            assert validate_exposition(body) > 0
            assert "serving_kv_pool_occupancy_ratio" in body
            assert "serving_decode_bucket_count" in body
            assert "slo_burn_rate" in body
            code, body = self._get(eng.telemetry.url("/healthz"))
            assert (code, body) == (200, "ok\n")
            code, body = self._get(eng.telemetry.url("/debug/requests"))
            assert code == 200
            doc = json.loads(body)
            assert doc["finished_total"] == 1
            rec = doc["recent"][0]
            kinds = [e["kind"] for e in rec["events"]]
            assert kinds[0] == "queued" and kinds[-1] == "finish"
            assert rec["counts"]["tokens_emitted"] == 4
            code, body = self._get(eng.telemetry.url("/trace"))
            assert code == 200
            trace = json.loads(body)
            assert any(e.get("cat") == "serving.request"
                       for e in trace["traceEvents"])
            assert self._get(eng.telemetry.url("/nope"))[0] == 404
        finally:
            url = eng.telemetry.url("/healthz")
            eng.close()
        # clean shutdown: the port no longer answers
        assert not eng.telemetry or not eng.telemetry.running
        with pytest.raises(Exception):
            self._get(url)

    @pytest.mark.slow
    def test_readyz_flips_on_ttft_breach_and_recovers(self):
        eng = self._engine()
        try:
            code, body = self._get(eng.telemetry.url("/readyz"))
            assert code == 200 and json.loads(body)["ready"]
            # injected sustained TTFT breach fills both windows
            for _ in range(8):
                eng.slo.observe("ttft", 120.0)
            code, body = self._get(eng.telemetry.url("/readyz"))
            assert code == 503
            doc = json.loads(body)
            assert not doc["ready"]
            burn = doc["slo"]["objectives"]["ttft"]["fast"]["burn_rate"]
            assert burn > 1.0
            # the burn-rate gauge is visible in the same scrape
            _, metrics_body = self._get(eng.telemetry.url("/metrics"))
            assert 'slo_burn_rate{' in metrics_body
            # recovery: fast window refills with good observations
            for _ in range(4):
                eng.slo.observe("ttft", 0.01)
            code, body = self._get(eng.telemetry.url("/readyz"))
            assert code == 200 and json.loads(body)["ready"]
        finally:
            eng.close()


class TestProgramCards:
    """Phase 3 program cards: capture from a real Lowered, process-wide
    memoization, renderers, and NaN exposition for backends without an
    analysis."""

    def _capture_tiny(self, fn_name="test.prog", key="k0", **kw):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.observability import profiling

        f = jax.jit(lambda x: (x * 2.0).sum())
        lowered = f.lower(jnp.ones((8, 8), jnp.float32))
        return profiling.capture(fn_name, key, lowered,
                                 compile_seconds=0.012,
                                 donated_bytes=256,
                                 meta={"bucket": 8}, backend="cpu", **kw)

    def test_capture_from_lowered(self):
        from paddle_tpu.observability import profiling

        reg = profiling.ProgramCardRegistry()
        card = self._capture_tiny(registry=reg)
        assert card.flops and card.flops > 0
        assert card.bytes_accessed and card.bytes_accessed > 0
        assert card.analysis_source in ("lowered", "compiled")
        assert card.compile_seconds == pytest.approx(0.012)
        assert card.donated_bytes == 256
        assert card.meta == {"bucket": 8}
        # gauges published per (fn, key)
        assert obs_metrics.value("compile.program_flops",
                                 fn="test.prog", key="k0") == card.flops
        assert obs_metrics.value("compile.programs",
                                 fn="test.prog") == 1
        # memoization handle: the registry serves the same card back
        assert reg.get("test.prog", "k0") is card
        assert reg.get("test.prog", "other") is None

    def test_registry_json_totals_and_render(self):
        from paddle_tpu.observability import profiling

        reg = profiling.ProgramCardRegistry()
        card = self._capture_tiny(registry=reg)
        card.dispatches = 3
        doc = reg.to_json()
        assert doc["count"] == 1
        assert doc["total_flops_dispatched"] == pytest.approx(
            card.flops * 3)
        assert doc["total_bytes_dispatched"] == pytest.approx(
            card.bytes_accessed * 3)
        json.dumps(doc)                       # JSON-able as-is
        text = reg.render_text()
        assert "test.prog" in text and "bucket=8" in text
        assert profiling.ProgramCardRegistry().render_text().startswith(
            "no program cards")

    def test_capture_never_raises_and_records_nones(self):
        """A backend without any analysis still yields a card; its
        gauges render as NaN, and the exposition stays parseable."""
        from paddle_tpu.observability import profiling
        from paddle_tpu.observability.metrics import validate_exposition

        class _DeadLowered:
            def cost_analysis(self):
                raise NotImplementedError("no analysis on this backend")

            def compile(self):
                raise NotImplementedError

        reg = profiling.ProgramCardRegistry()
        card = profiling.capture("test.dead", "kx", _DeadLowered(),
                                 compile_seconds=0.5, backend="cpu",
                                 registry=reg)
        assert card.flops is None and card.bytes_accessed is None
        assert card.analysis_source is None
        v = obs_metrics.value("compile.program_flops",
                              fn="test.dead", key="kx")
        assert v != v                          # NaN
        text = obs_metrics.render_prometheus()
        assert validate_exposition(text) > 0
        assert "compile_program_flops" in text and "NaN" in text

    def test_deep_probe_fills_memory_stats(self):
        """deep=True reads the executable's memory_analysis (where the
        backend provides one) — argument bytes at minimum."""
        card = self._capture_tiny(fn_name="test.deep", key="kd",
                                  deep=True)
        # cpu's memory_analysis may legitimately be absent; when it is
        # present the fields must be ints, and to_json carries them
        doc = card.to_json()
        for f in ("argument_bytes", "output_bytes", "temp_bytes"):
            assert doc[f] is None or isinstance(doc[f], int)


class TestMemoryLedger:
    """Phase 3 device-memory ledger: component accounting, leak-delta
    baseline, gauge publication, and the roofline helpers."""

    def test_account_and_raising_component(self):
        from paddle_tpu.observability.memory import MemoryLedger

        led = MemoryLedger("t")
        led.register("a", lambda: 100).register("b", lambda: 28)

        def boom():
            raise RuntimeError("accounting down")

        led.register("bad", boom)
        assert led.account() == {"a": 100, "b": 28, "bad": 0}
        led.unregister("bad")
        assert sorted(led.components()) == ["a", "b"]
        with pytest.raises(TypeError):
            led.register("notfn", 42)

    def test_snapshot_reconciles_and_publishes(self):
        from paddle_tpu.observability.memory import MemoryLedger

        led = MemoryLedger("snap-test")
        led.register("kv", lambda: 64)
        snap = led.snapshot()
        assert snap["accounted_total_bytes"] == 64
        assert snap["live_bytes"] >= 0
        assert snap["unaccounted_bytes"] == snap["live_bytes"] - 64
        # first snapshot self-baselines -> zero leak
        assert snap["leak_delta_bytes"] == 0
        assert obs_metrics.value("memory.accounted_bytes",
                                 ledger="snap-test", component="kv") == 64
        assert obs_metrics.value(
            "memory.accounted_total_bytes", ledger="snap-test") == 64
        # the memory.* gauges render as a parseable exposition
        from paddle_tpu.observability.metrics import validate_exposition

        text = obs_metrics.render_prometheus()
        assert validate_exposition(text) > 0
        for name in ("memory_accounted_bytes", "memory_live_bytes",
                     "memory_unaccounted_bytes",
                     "memory_leak_delta_bytes"):
            assert name in text
        # ...and survive snapshot() too (NaN-bearing registries broke
        # this once: int(NaN) in _as_scalar)
        json.dumps(obs_metrics.snapshot())

    def test_leak_delta_tracks_unaccounted_growth(self, monkeypatch):
        from paddle_tpu.observability import memory as mem

        led = mem.MemoryLedger("leak-test")
        led.register("pool", lambda: 1000)
        live = {"v": 1500}
        monkeypatch.setattr(mem, "live_device_bytes",
                            lambda: live["v"])
        assert led.snapshot()["leak_delta_bytes"] == 0
        # pool growth alone is NOT a leak: accounted grows with live
        led.unregister("pool")
        led.register("pool", lambda: 1400)
        live["v"] = 1900
        assert led.snapshot()["leak_delta_bytes"] == 0
        # unaccounted residue growth IS
        live["v"] = 2100
        assert led.snapshot()["leak_delta_bytes"] == 200
        # re-anchoring forgives the residue
        led.mark_baseline()
        assert led.snapshot()["leak_delta_bytes"] == 0

    def test_publish_roofline(self):
        from paddle_tpu.observability import memory as mem

        bw = mem.backend_bandwidth_gbs("tpu")
        assert bw == 819.0                    # datasheet entry
        # 819 GB in 2 s against an 819 GB/s roofline = 50%
        util = mem.publish_roofline("e0", 8, 819.0e9, 2.0, "tpu")
        assert util == pytest.approx(0.5)
        assert obs_metrics.value("memory.roofline_utilization",
                                 engine="e0", horizon=8) == \
            pytest.approx(0.5, abs=1e-4)
        assert obs_metrics.value("memory.achieved_bandwidth_gbs",
                                 engine="e0", horizon=8) == \
            pytest.approx(409.5, rel=1e-3)
        # degenerate dispatches publish nothing
        assert mem.publish_roofline("e0", 8, 0, 1.0, "tpu") is None
        assert mem.publish_roofline("e0", 8, 100.0, 0.0, "tpu") is None

    def test_bandwidth_probe_memoized(self):
        from paddle_tpu.observability import memory as mem

        a = mem.backend_bandwidth_gbs("cpu")
        b = mem.backend_bandwidth_gbs("cpu")
        assert a == b and a > 0               # one probe per process


class TestRegressionGate:
    """Phase 3 bench-regression gate over synthetic fixtures."""

    @staticmethod
    def _doc(tok_s=100.0, ttft_ms=50.0, kv_bytes=4096,
             decode_compiles=2):
        return {"backend": "cpu", "results": [
            {"metric": "engine decode tokens/s b1 (cpu)",
             "value": tok_s, "unit": "tokens/s",
             "kv_bytes_read_per_step": kv_bytes,
             "decode_compiles": decode_compiles},
            {"metric": "engine ttft (cpu)",
             "value": ttft_ms, "unit": "ms"},
        ]}

    def test_identical_docs_pass(self):
        from paddle_tpu.observability import regression

        rep = regression.compare(self._doc(), self._doc(), tolerance=0.0)
        assert rep["ok"] and rep["regressions"] == 0
        assert rep["compared_metrics"] == 2
        assert rep["compared_values"] == 4    # 2 values + 2 det fields
        assert regression.render_text(rep).rstrip().endswith("PASS")

    def test_injected_20pct_tok_s_regression_detected(self):
        """The acceptance fixture: 20% tok/s drop must trip a 10%
        tolerance gate, and the finding must carry the numbers."""
        from paddle_tpu.observability import regression

        rep = regression.compare(self._doc(tok_s=100.0),
                                 self._doc(tok_s=80.0), tolerance=0.10)
        assert not rep["ok"] and rep["regressions"] == 1
        f = rep["findings"][0]
        assert f["field"] == "value"
        assert f["regression_pct"] == pytest.approx(20.0)
        assert f["direction"] == "higher_is_better"
        assert "FAIL: 1 regression(s)" in regression.render_text(rep)
        # the same drop under a generous tolerance passes
        rep = regression.compare(self._doc(tok_s=100.0),
                                 self._doc(tok_s=80.0), tolerance=0.25)
        assert rep["ok"]
        # tok/s going UP is an improvement, never a finding
        rep = regression.compare(self._doc(tok_s=100.0),
                                 self._doc(tok_s=130.0), tolerance=0.10)
        assert rep["ok"] and not rep["findings"]

    def test_latency_direction_from_unit(self):
        from paddle_tpu.observability import regression

        assert regression.higher_is_better("tokens/s")
        assert not regression.higher_is_better("ms")
        assert not regression.higher_is_better("s avg ttft")
        # ttft (ms) rising 40% trips; falling is an improvement
        rep = regression.compare(self._doc(ttft_ms=50.0),
                                 self._doc(ttft_ms=70.0), tolerance=0.10)
        assert not rep["ok"]
        assert rep["findings"][0]["metric"] == "engine ttft (cpu)"
        rep = regression.compare(self._doc(ttft_ms=50.0),
                                 self._doc(ttft_ms=30.0), tolerance=0.10)
        assert rep["ok"]

    def test_deterministic_fields_gate_exact(self):
        """KV traffic doubling fails at det_tolerance=0 even when tok/s
        noise hides it behind the loose value tolerance."""
        from paddle_tpu.observability import regression

        rep = regression.compare(self._doc(kv_bytes=4096),
                                 self._doc(kv_bytes=8192),
                                 tolerance=0.5, det_tolerance=0.0)
        assert not rep["ok"]
        assert rep["findings"][0]["field"] == "kv_bytes_read_per_step"
        # compile-count creep is likewise deterministic
        rep = regression.compare(self._doc(decode_compiles=2),
                                 self._doc(decode_compiles=3),
                                 tolerance=0.5)
        assert not rep["ok"]
        assert rep["findings"][0]["field"] == "decode_compiles"
        # det_tolerance loosens it explicitly
        rep = regression.compare(self._doc(decode_compiles=2),
                                 self._doc(decode_compiles=3),
                                 tolerance=0.5, det_tolerance=0.6)
        assert rep["ok"]

    def test_allow_regress_acknowledges(self):
        from paddle_tpu.observability import regression

        rep = regression.compare(
            self._doc(tok_s=100.0), self._doc(tok_s=70.0),
            tolerance=0.10,
            allow_regress=["decode tokens/s b1 (cpu)::value"])
        assert rep["ok"] and rep["regressions"] == 0
        assert rep["allowed_regressions"] == 1
        assert rep["findings"][0]["allowed"]
        assert "ALLOWED" in regression.render_text(rep)
        # the allowlist is per metric::field, not a blanket waiver
        rep = regression.compare(
            self._doc(tok_s=70.0, ttft_ms=90.0), self._doc(tok_s=70.0,
                                                           ttft_ms=90.0))
        assert rep["ok"]

    def test_only_shared_metrics_gate(self):
        """A --only fresh run re-measures one section; baseline-only
        rows are skipped and listed, never failed."""
        from paddle_tpu.observability import regression

        fresh = {"results": [self._doc()["results"][0]]}
        rep = regression.compare(self._doc(), fresh, tolerance=0.0)
        assert rep["ok"] and rep["compared_metrics"] == 1
        assert rep["skipped_baseline_only"] == ["engine ttft (cpu)"]
        extra = {"results": self._doc()["results"] + [
            {"metric": "brand new (cpu)", "value": 1.0, "unit": "x"}]}
        rep = regression.compare(self._doc(), extra, tolerance=0.0)
        assert rep["skipped_fresh_only"] == ["brand new (cpu)"]

    def test_check_bench_files(self, tmp_path):
        from paddle_tpu.observability import regression

        b = tmp_path / "base.json"
        f = tmp_path / "fresh.json"
        b.write_text(json.dumps(self._doc()))
        f.write_text(json.dumps(self._doc(tok_s=75.0)))
        rep = regression.check_bench(str(b), str(f), tolerance=0.10)
        assert not rep["ok"]
        assert rep["baseline"] == str(b) and rep["fresh"] == str(f)

    def test_committed_bench_self_check_passes(self):
        """The committed DECODE_BENCH.json gates cleanly against
        itself (the CI job's degenerate case)."""
        import os

        from paddle_tpu.observability import regression

        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "DECODE_BENCH.json")
        doc = regression.load(path)
        rep = regression.compare(doc, doc, tolerance=0.0,
                                 det_tolerance=0.0)
        assert rep["ok"] and rep["regressions"] == 0
        assert rep["compared_metrics"] > 10


class TestProgramsEndpointAndCLI:
    """/debug/programs routing + the programs / check-bench CLI modes."""

    def test_debug_programs_route(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.observability import profiling
        from paddle_tpu.observability.server import TelemetryServer

        f = jax.jit(lambda x: x + 1)
        lowered = f.lower(jnp.ones((4,), jnp.float32))
        profiling.capture("test.route", "rk", lowered, backend="cpu")
        try:
            srv = TelemetryServer(port=0)
            status, ctype, body = srv.handle("/debug/programs")
            assert status == 200 and ctype == "application/json"
            doc = json.loads(body)
            assert doc["count"] >= 1
            assert any(c["fn"] == "test.route" for c in doc["cards"])
            # the index advertises the route
            _, _, idx = srv.handle("/")
            assert "/debug/programs" in json.loads(idx)["endpoints"]
        finally:
            profiling.clear()

    @pytest.mark.slow
    def test_programs_cli_mode(self, tmp_path):
        script = tmp_path / "load.py"
        script.write_text(
            "import jax, jax.numpy as jnp\n"
            "from paddle_tpu.observability import profiling\n"
            "f = jax.jit(lambda x: x * 3.0)\n"
            "low = f.lower(jnp.ones((8,), jnp.float32))\n"
            "profiling.capture('cli.prog', 'ck', low,\n"
            "                  compile_seconds=0.02, backend='cpu',\n"
            "                  meta={'bucket': 8})\n")
        env = {**__import__("os").environ, "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability",
             "programs", "--exec", str(script)],
            capture_output=True, text=True, timeout=120, env=env)
        assert out.returncode == 0, out.stderr
        assert "cli.prog" in out.stdout and "bucket=8" in out.stdout
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability",
             "programs", "--exec", str(script), "--json"],
            capture_output=True, text=True, timeout=120, env=env)
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        assert doc["cards"][0]["fn"] == "cli.prog"
        assert doc["cards"][0]["flops"] > 0

    @pytest.mark.slow
    def test_check_bench_cli_mode(self, tmp_path):
        base = tmp_path / "base.json"
        fresh = tmp_path / "fresh.json"
        row = {"metric": "m (cpu)", "value": 100.0, "unit": "tokens/s"}
        base.write_text(json.dumps({"results": [row]}))
        fresh.write_text(json.dumps(
            {"results": [{**row, "value": 79.0}]}))
        env = {**__import__("os").environ, "JAX_PLATFORMS": "cpu"}

        def run(*extra):
            return subprocess.run(
                [sys.executable, "-m", "paddle_tpu.observability",
                 "check-bench", "--baseline", str(base), *extra],
                capture_output=True, text=True, timeout=120, env=env)

        # missing --fresh is usage error 2
        assert run().returncode == 2
        # 21% drop vs 10% tolerance: rc 1, FAIL rendered
        out = run("--fresh", str(fresh), "--tolerance", "0.10")
        assert out.returncode == 1, out.stderr
        assert "FAIL: 1 regression(s)" in out.stdout
        # allow-regress turns the same comparison green
        report = tmp_path / "report.json"
        out = run("--fresh", str(fresh), "--tolerance", "0.10",
                  "--allow-regress", "m (cpu)::value",
                  "-o", str(report))
        assert out.returncode == 0, out.stderr
        assert "PASS" in out.stdout
        rep = json.loads(report.read_text())
        assert rep["ok"] and rep["allowed_regressions"] == 1
        # baseline vs itself: rc 0
        out = run("--fresh", str(base), "--tolerance", "0.0")
        assert out.returncode == 0, out.stderr


class TestTelemetryServerLifecycle:
    """Satellite: the server's own provider registers on start(),
    unregisters on stop()/GC, and the serving thread is joined."""

    def test_provider_registered_while_running(self):
        from paddle_tpu.observability.server import TelemetryServer

        reg = Registry()
        srv = TelemetryServer(port=0, registry=reg)
        assert reg.provider_counters() == {}
        srv.start()
        name = srv._provider_name
        try:
            assert name.startswith("telemetry.server")
            provided = reg.provider_counters()[name]
            assert provided == {"up": 1, "port": srv.port}
        finally:
            srv.stop()
        assert name not in reg.provider_counters()
        assert not srv.running and srv._thread is None

    def test_stop_joins_thread_and_is_idempotent(self):
        import urllib.request

        from paddle_tpu.observability.server import TelemetryServer

        srv = TelemetryServer(port=0, registry=Registry())
        srv.start()
        thread = srv._thread
        url = srv.url("/healthz")
        with urllib.request.urlopen(url, timeout=10) as r:
            assert r.status == 200
        srv.stop()
        assert not thread.is_alive()
        srv.stop()                            # idempotent
        with pytest.raises(Exception):
            urllib.request.urlopen(url, timeout=2)

    def test_gc_unregisters_provider(self):
        from paddle_tpu.observability.server import TelemetryServer

        reg = Registry()
        srv = TelemetryServer(port=0, registry=reg)
        srv.start()
        name = srv._provider_name
        assert name in reg.provider_counters()
        del srv
        gc.collect()
        assert name not in reg.provider_counters()

    def test_repeated_cycles_leave_no_stale_providers(self):
        from paddle_tpu.observability.server import TelemetryServer

        reg = Registry()
        for _ in range(3):
            srv = TelemetryServer(port=0, registry=reg)
            srv.start()
            assert len([n for n in reg.provider_counters()
                        if n.startswith("telemetry.server")]) == 1
            srv.stop()
        assert not [n for n in reg.provider_counters()
                    if n.startswith("telemetry.server")]
