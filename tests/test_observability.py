"""paddle_tpu.observability tests: typed registry semantics, histogram
percentiles vs a numpy reference, chrome-trace export validity, the
jit compile-counter invariant, span nesting, the profiler facade and its
satellite fixes (tuple scheduler, n=1 summary, engine provider GC), and
a CLI smoke via ``python -m``."""

import gc
import json
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu import profiler
from paddle_tpu.observability import events as obs_events
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability.metrics import (
    Counter, Gauge, Histogram, Registry,
)
from paddle_tpu.observability.span import current_span, span, span_depth


class TestRegistry:
    def test_counter_labels_and_monotonicity(self):
        reg = Registry()
        c = reg.counter("requests", "total requests")
        c.inc()
        c.inc(2, route="a")
        c.inc(route="a")
        assert c.value() == 1
        assert c.value(route="a") == 3
        assert c.value(route="missing") == 0
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_set_inc_dec(self):
        reg = Registry()
        g = reg.gauge("depth")
        g.set(7, q="main")
        g.inc(q="main")
        g.dec(3, q="main")
        assert g.value(q="main") == 5

    def test_get_or_create_returns_same_family(self):
        reg = Registry()
        a = reg.counter("x")
        b = reg.counter("x")
        assert a is b

    def test_type_conflict_raises(self):
        reg = Registry()
        reg.counter("x")
        with pytest.raises(ValueError):
            reg.gauge("x")

    def test_label_order_is_canonical(self):
        reg = Registry()
        c = reg.counter("c")
        c.inc(a=1, b=2)
        c.inc(b=2, a=1)
        assert c.value(b=2, a=1) == 2

    def test_snapshot_shape(self):
        reg = Registry()
        reg.counter("n", "help text").inc(5)
        reg.gauge("g").set(1.5)
        reg.histogram("h").observe(0.2)
        snap = reg.snapshot()
        assert snap["metrics"]["n"]["type"] == "counter"
        assert snap["metrics"]["n"]["help"] == "help text"
        assert snap["metrics"]["n"]["values"][""] == 5
        assert snap["metrics"]["g"]["values"][""] == 1.5
        assert snap["metrics"]["h"]["values"][""]["count"] == 1
        json.dumps(snap)  # must be JSON-able as-is

    def test_reset_keeps_families(self):
        reg = Registry()
        c = reg.counter("c")
        c.inc(10)
        reg.reset()
        assert c.value() == 0
        assert reg.get("c") is c
        c.inc()
        assert c.value() == 1


class TestHistogram:
    def test_percentiles_match_numpy(self):
        reg = Registry()
        h = reg.histogram("lat")
        rng = np.random.default_rng(0)
        samples = rng.lognormal(-3, 1.0, size=500)
        for s in samples:
            h.observe(s)
        for q in (50, 95, 99):
            assert h.percentile(q) == pytest.approx(
                float(np.percentile(samples, q)))
        st = h.stats()
        assert st["count"] == 500
        assert st["sum"] == pytest.approx(samples.sum())
        assert st["mean"] == pytest.approx(samples.mean())
        assert st["p50"] == pytest.approx(np.percentile(samples, 50))

    def test_buckets_are_cumulative(self):
        reg = Registry()
        h = reg.histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        b = h.stats()["buckets"]
        assert b[repr(0.1)] == 1
        assert b[repr(1.0)] == 3
        assert b[repr(10.0)] == 4
        assert b["+Inf"] == 5

    def test_reservoir_is_bounded(self):
        reg = Registry()
        h = reg.histogram("lat", reservoir=16)
        for i in range(100):
            h.observe(float(i))
        st = h.stats()
        assert st["count"] == 100          # exact totals survive
        # percentiles slide to the most recent window
        assert h.percentile(50) >= 84.0

    def test_labelled_slots_are_independent(self):
        reg = Registry()
        h = reg.histogram("lat")
        h.observe(1.0, op="a")
        h.observe(100.0, op="b")
        assert h.percentile(50, op="a") == 1.0
        assert h.percentile(50, op="b") == 100.0
        assert h.percentile(50, op="c") is None


class TestPrometheusRendering:
    def test_exposition_format(self):
        reg = Registry()
        reg.counter("jit.compile_count", "compiles").inc(3, fn="f")
        reg.gauge("queue.depth").set(2)
        reg.histogram("lat", buckets=(0.1, 1.0)).observe(0.5)
        text = reg.render_prometheus()
        assert "# TYPE jit_compile_count counter" in text
        assert '# HELP jit_compile_count compiles' in text
        assert 'jit_compile_count{fn="f"} 3' in text
        assert "# TYPE queue_depth gauge" in text
        assert "# TYPE lat histogram" in text
        assert 'lat_bucket{le="0.1"} 0' in text
        assert 'lat_bucket{le="1.0"} 1' in text
        assert 'lat_bucket{le="+Inf"} 1' in text
        assert "lat_sum" in text and "lat_count" in text

    def test_providers_render_as_gauges(self):
        reg = Registry()
        reg.register_provider("serving.engine0",
                              lambda: {"tokens": 42, "note": "text"})
        text = reg.render_prometheus()
        assert '# TYPE serving_engine0 gauge' in text
        assert 'serving_engine0{counter="tokens"} 42' in text
        assert "note" not in text          # non-numeric values skipped

    def test_default_registry_render_nonempty(self):
        text = obs.render_prometheus()
        assert "# TYPE " in text


class TestProviders:
    def test_register_snapshot_unregister(self):
        reg = Registry()
        reg.register_provider("sub", lambda: {"a": 1})
        assert reg.provider_counters() == {"sub": {"a": 1}}
        assert reg.snapshot()["providers"] == {"sub": {"a": 1}}
        reg.unregister_provider("sub")
        assert reg.provider_counters() == {}

    def test_raising_provider_is_isolated(self):
        reg = Registry()

        def bad():
            raise RuntimeError("boom")

        reg.register_provider("bad", bad)
        reg.register_provider("good", lambda: {"x": 1})
        out = reg.provider_counters()
        assert out["good"] == {"x": 1}
        assert "RuntimeError" in out["bad"]["error"]

    def test_non_callable_rejected(self):
        reg = Registry()
        with pytest.raises(TypeError):
            reg.register_provider("x", {"not": "callable"})


class TestEvents:
    def test_ring_is_bounded_and_counts_drops(self):
        log = obs_events.EventLog(capacity=8)
        for i in range(20):
            log.instant(f"e{i}")
        evs = log.events()
        assert len(evs) == 8
        assert evs[0].name == "e12"        # oldest 12 fell off
        assert log.dropped == 12

    def test_chrome_trace_valid_json_monotonic_ts(self, tmp_path):
        log = obs_events.EventLog()
        log.begin("outer", cat="test", k=1)
        log.instant("mark", cat="test")
        log.end("outer", cat="test")
        path = tmp_path / "trace.json"
        text = log.export_chrome_trace(file=str(path))
        with open(path) as f:
            doc = json.load(f)             # must be loadable by json.load
        assert json.loads(text) == doc
        evs = doc["traceEvents"]
        assert len(evs) == 3
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)            # monotonically ordered
        assert {e["ph"] for e in evs} == {"B", "i", "E"}
        assert all("pid" in e and "tid" in e for e in evs)
        assert evs[0]["args"] == {"k": 1}

    def test_filtering(self):
        log = obs_events.EventLog()
        log.instant("a", cat="x")
        log.instant("b", cat="y")
        assert [e.name for e in log.events(cat="x")] == ["a"]
        assert [e.name for e in log.events(name="b")] == ["b"]


class TestSpan:
    def test_nesting_and_histogram(self):
        reg_before = obs_metrics.value("span.seconds", name="outer-span")
        n_before = reg_before["count"] if reg_before else 0
        assert current_span() is None
        with span("outer-span", cat="test"):
            assert current_span() == "outer-span"
            d = span_depth()
            with span("inner-span", cat="test"):
                assert current_span() == "inner-span"
                assert span_depth() == d + 1
            assert current_span() == "outer-span"
        assert current_span() is None
        st = obs_metrics.value("span.seconds", name="outer-span")
        assert st["count"] == n_before + 1
        # begin/end pairs landed on the timeline with depth recorded
        begins = [e for e in obs_events.events(name="inner-span")
                  if e.phase == obs_events.BEGIN]
        assert begins and begins[-1].args["depth"] == d

    def test_elapsed_and_error_annotation(self):
        s = span("failing-span", cat="test")
        with pytest.raises(ValueError):
            with s:
                raise ValueError("x")
        assert s.elapsed is not None and s.elapsed >= 0
        ends = [e for e in obs_events.events(name="failing-span")
                if e.phase == obs_events.END]
        assert ends[-1].args["error"] == "ValueError"

    def test_event_args_stay_off_histogram_labels(self):
        with span("arg-span", cat="test", event_args={"path": "/tmp/x"}):
            pass
        st = obs_metrics.value("span.seconds", name="arg-span")
        assert st["count"] >= 1            # labeled only by name
        begins = [e for e in obs_events.events(name="arg-span")
                  if e.phase == obs_events.BEGIN]
        assert begins[-1].args["path"] == "/tmp/x"


class TestJitInstrumentation:
    def test_compile_counter_invariant(self):
        """Two calls with identical avals = one compile + one cache hit;
        a new input signature = a second compile, not a hit."""
        import paddle_tpu.jit as jit

        @jit.to_static
        def obs_fn(x):
            return x * 2 + 1

        def vals():
            c = obs.value("jit.compile_count", fn="obs_fn") or 0
            h = obs.value("jit.cache_hit", fn="obs_fn") or 0
            return c, h

        c0, h0 = vals()
        a = paddle.to_tensor(np.ones((2, 3), np.float32))
        obs_fn(a)
        obs_fn(paddle.to_tensor(np.zeros((2, 3), np.float32)))
        c1, h1 = vals()
        assert c1 == c0 + 1
        assert h1 == h0 + 1
        obs_fn(paddle.to_tensor(np.ones((4, 3), np.float32)))
        c2, h2 = vals()
        assert c2 == c0 + 2
        assert h2 == h0 + 1
        # compile begin/end pairs match the compile count
        begins = [e for e in obs_events.events(name="jit.compile")
                  if e.phase == obs_events.BEGIN
                  and e.args.get("fn") == "obs_fn"]
        ends = [e for e in obs_events.events(name="jit.compile")
                if e.phase == obs_events.END
                and e.args.get("fn") == "obs_fn"]
        assert len(begins) == len(ends) == 2
        assert all(e.args["seconds"] >= 0 for e in ends)
        # the miss also explains itself on the timeline
        causes = [e.args["cause"] for e in
                  obs_events.events(name="jit.retrace")
                  if e.args.get("fn") == "obs_fn"]
        assert causes == ["first_call", "new_input_signature"]
        st = obs.value("jit.compile_seconds", fn="obs_fn")
        assert st["count"] >= 2


class TestProfilerSatellites:
    def test_make_scheduler_tuple_records_once(self):
        """(start, end) = record [start, end) ONCE — regression for the
        repeat=0 form that cycled the window forever."""
        from paddle_tpu.profiler import Profiler, ProfilerState

        p = Profiler(scheduler=(2, 5), timer_only=True)
        states = [p._scheduler(i) for i in range(12)]
        assert states[:2] == [ProfilerState.CLOSED] * 2
        assert states[2:4] == [ProfilerState.RECORD] * 2
        assert states[4] == ProfilerState.RECORD_AND_RETURN
        # the old bug: step 7 re-entered RECORD; now closed forever
        assert states[5:] == [ProfilerState.CLOSED] * 7

    def test_summary_single_step(self):
        from paddle_tpu.profiler import Profiler

        p = Profiler(timer_only=True)
        p.start()
        p.step()
        text = p.summary()
        assert "steps: 1" in text
        assert "p50" in text and "p99" in text

    def test_summary_includes_observability_histograms(self):
        from paddle_tpu.profiler import Profiler

        obs_metrics.histogram("test.profiler_summary").observe(0.25)
        p = Profiler(timer_only=True)
        p.start()
        p.step()
        p.step()
        assert "test.profiler_summary" in p.summary()

    def test_facade_register_and_counters(self):
        profiler.register_counter_provider("facade.test",
                                           lambda: {"v": 7})
        try:
            assert profiler.counters()["facade.test"] == {"v": 7}
            # one registry: visible through observability too
            assert obs_metrics.provider_counters()["facade.test"] == \
                {"v": 7}
            assert obs.snapshot()["providers"]["facade.test"] == {"v": 7}
        finally:
            profiler.unregister_counter_provider("facade.test")
        assert "facade.test" not in profiler.counters()


class TestEngineProviderLifecycle:
    """Repeated engine construction must not leak stale providers
    (regression: bound-method providers pinned engines forever)."""

    def _tiny_engine(self, register=True):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving import Engine, EngineConfig

        cfg = GPTConfig(vocab_size=64, hidden_size=32,
                        intermediate_size=64, num_hidden_layers=1,
                        num_attention_heads=2,
                        max_position_embeddings=32)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        m.eval()
        return Engine(m, EngineConfig(num_slots=1, max_seq_len=16),
                      register_profiler=register)

    def test_close_unregisters_provider(self):
        eng = self._tiny_engine()
        name = eng._profiler_name
        assert name in profiler.counters()
        eng.close()
        assert name not in profiler.counters()

    def test_gc_unregisters_provider(self):
        eng = self._tiny_engine()
        name = eng._profiler_name
        assert name in profiler.counters()
        del eng
        gc.collect()
        assert name not in profiler.counters()

    def test_live_engine_counters_unchanged_via_facade(self):
        eng = self._tiny_engine()
        try:
            via_facade = profiler.counters()[eng._profiler_name]
            assert via_facade == eng.counters()
        finally:
            eng.close()


class TestCLI:
    def test_snapshot_smoke(self, tmp_path):
        script = tmp_path / "load.py"
        script.write_text(
            "from paddle_tpu.observability import metrics, events\n"
            "metrics.counter('cli.test').inc(3)\n"
            "events.instant('cli.mark')\n")
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability",
             "snapshot", "--exec", str(script)],
            capture_output=True, text=True, timeout=120,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        snap = json.loads(out.stdout)
        assert snap["metrics"]["cli.test"]["values"][""] == 3

    def test_trace_and_prometheus_modes(self, tmp_path):
        script = tmp_path / "load.py"
        script.write_text(
            "from paddle_tpu.observability import metrics, events\n"
            "metrics.histogram('cli.h').observe(0.1)\n"
            "events.instant('cli.mark')\n")
        env = {**__import__("os").environ, "JAX_PLATFORMS": "cpu"}
        trace_file = tmp_path / "t.json"
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability", "trace",
             "--exec", str(script), "-o", str(trace_file)],
            capture_output=True, text=True, timeout=120, env=env)
        assert out.returncode == 0, out.stderr
        with open(trace_file) as f:
            doc = json.load(f)
        assert any(e["name"] == "cli.mark" for e in doc["traceEvents"])
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability",
             "prometheus", "--exec", str(script)],
            capture_output=True, text=True, timeout=120, env=env)
        assert out.returncode == 0, out.stderr
        assert "# TYPE cli_h histogram" in out.stdout
