"""dy2static-lite (SURVEY.md §2.2 P8): AST conversion of Python if/while
over traced tensors into staged lax control flow under paddle.jit.to_static
— concrete predicates keep exact Python semantics, traced predicates stage
through static.nn.cond / while_loop."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit.dy2static import convert_to_static


def _t(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


class TestEagerSemantics:
    def test_branches_and_python_if_preserved(self):
        def f(x, flag=True):
            if paddle.sum(x) > 0:
                t = x + 1.0
                y = t * 2.0
            else:
                y = x - 1.0
            if flag:
                y = y + 10.0
            return y

        conv = convert_to_static(f)
        assert conv.__dy2static_converted__
        xp = np.array([1.0, 2.0], np.float32)
        xn = np.array([-3.0, -3.0], np.float32)
        np.testing.assert_allclose(conv(_t(xp)).numpy(), (xp + 1) * 2 + 10)
        np.testing.assert_allclose(conv(_t(xn)).numpy(), xn - 1 + 10)
        np.testing.assert_allclose(conv(_t(xp), flag=False).numpy(),
                                   (xp + 1) * 2)

    def test_python_while_still_runs(self):
        def f(n):
            i, s = 0, 0
            while i < n:               # pure python: untouched semantics
                s += i
                i += 1
            return s

        conv = convert_to_static(f)
        assert conv(5) == 10

    def test_eager_runs_exactly_one_branch(self):
        calls = []

        def probe(tag, v):
            calls.append(tag)
            return v

        def f(x):
            if paddle.sum(x) > 0:
                y = probe("true", x * 2.0)
            else:
                y = probe("false", x * 3.0)
            return y

        conv = convert_to_static(f)
        conv(_t([1.0]))
        assert calls == ["true"]       # dygraph parity: one branch only

    def test_elif_chain(self):
        def f(x):
            if paddle.sum(x) > 10.0:
                y = x * 1.0
            elif paddle.sum(x) > 0.0:
                y = x * 2.0
            else:
                y = x * 3.0
            return y

        conv = convert_to_static(f)
        np.testing.assert_allclose(conv(_t([20.0])).numpy(), [20.0])
        np.testing.assert_allclose(conv(_t([2.0])).numpy(), [4.0])
        np.testing.assert_allclose(conv(_t([-2.0])).numpy(), [-6.0])


class TestStagedUnderJit:
    def test_if_stages_one_compiled_fn_serves_both_branches(self):
        def f(x):
            if paddle.sum(x) > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y

        sf = paddle.jit.to_static(f)
        xp = np.array([1.0, 2.0], np.float32)
        xn = np.array([-1.0, -2.0], np.float32)
        np.testing.assert_allclose(sf(_t(xp)).numpy(), xp * 2)
        np.testing.assert_allclose(sf(_t(xn)).numpy(), xn - 1)
        # same shapes -> ONE cache entry serving both predicate values:
        # the branch is staged, not trace-specialized
        assert len(sf._cache) == 1

    def test_data_dependent_while(self):
        def steps_to_100(x):
            s = paddle.zeros([])
            i = paddle.zeros([])
            while s < 100.0:
                s = s + x
                i = i + 1.0
            return i

        sf = paddle.jit.to_static(steps_to_100)
        assert float(sf(_t(7.0)).numpy()) == 15.0
        assert float(sf(_t(50.0)).numpy()) == 2.0
        assert len(sf._cache) == 1

    def test_nested_if(self):
        def f(x):
            if paddle.sum(x) > 0:
                if paddle.max(x) > 5.0:
                    y = x * 10.0
                else:
                    y = x * 2.0
            else:
                y = x - 1.0
            return y

        sf = paddle.jit.to_static(f)
        np.testing.assert_allclose(sf(_t([7.0])).numpy(), [70.0])
        np.testing.assert_allclose(sf(_t([1.0])).numpy(), [2.0])
        np.testing.assert_allclose(sf(_t([-1.0])).numpy(), [-2.0])

    def test_layer_forward_converts(self):
        class Gate(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)

            def forward(self, x):
                h = self.lin(x)
                if paddle.mean(h) > 0:
                    out = paddle.nn.functional.relu(h)
                else:
                    out = h * 0.1
                return out

        paddle.seed(0)
        layer = Gate()
        x = _t(np.random.RandomState(0).randn(2, 4))
        eager = layer(x).numpy()
        paddle.jit.to_static(layer)
        got = layer(x).numpy()
        np.testing.assert_allclose(got, eager, rtol=1e-5, atol=1e-6)

    def test_mixed_python_and_tensor_predicates(self):
        def f(x, mode="double"):
            if mode == "double":       # python: specializes per trace
                y = x * 2.0
            else:
                y = x * 3.0
            if paddle.sum(y) > 100.0:  # tensor: stages
                y = y / 10.0
            return y

        sf = paddle.jit.to_static(f)
        np.testing.assert_allclose(sf(_t([1.0])).numpy(), [2.0])
        np.testing.assert_allclose(sf(_t([100.0])).numpy(), [20.0])
        np.testing.assert_allclose(sf(_t([1.0]), mode="triple").numpy(),
                                   [3.0])


class TestBooleanPredicates:
    """and/or/not and chained comparisons inside converted predicates
    rewrite to logical_and/or/not (ref convert_logical_*): traced
    operands stage, concrete values keep short-circuit semantics."""

    def test_and_or_not_stage_one_program(self):
        def f(x, y):
            if paddle.sum(x) > 0 and paddle.sum(y) > 0:
                out = x + y
            elif paddle.sum(x) > 0 or not (paddle.sum(y) > -10.0):
                out = x - y
            else:
                out = x * 0.0
            return out

        def ref(xv, yv):
            if xv.sum() > 0 and yv.sum() > 0:
                return xv + yv
            if xv.sum() > 0 or not (yv.sum() > -10.0):
                return xv - yv
            return xv * 0.0

        sf = paddle.jit.to_static(f)
        for xv, yv in ([1.0, 2.0], [3.0, 4.0]), ([1.0, 2.0], [-9.0, -9.0]), \
                ([-1.0, -2.0], [-20.0, -20.0]), ([-1.0, -2.0], [1.0, 1.0]):
            xa = np.array(xv, np.float32)
            ya = np.array(yv, np.float32)
            np.testing.assert_allclose(sf(_t(xa), _t(ya)).numpy(),
                                       ref(xa, ya), rtol=1e-6)
        assert len(sf._cache) == 1

    def test_chained_comparison_in_while(self):
        def g(x):
            i = paddle.zeros([])
            s = paddle.zeros([])
            while 0.0 <= i < 4.0:
                s = s + x
                i = i + 1.0
            return s

        sg = paddle.jit.to_static(g)
        assert float(sg(_t(2.0)).numpy()) == 8.0

    def test_walrus_in_predicate_keeps_python_semantics(self):
        """A `:=` binding in the test must stay visible to the branch
        body (regression: the lambda wrap once hid it)."""
        def f(x, flag=True):
            if flag and (n := 5) > 0:
                y = x + n
            else:
                y = x
            return y

        conv = convert_to_static(f)
        np.testing.assert_allclose(conv(_t(1.0)).numpy(), 6.0)

    def test_concrete_short_circuit_preserved(self):
        calls = []

        def probe():
            calls.append(1)
            return True

        def h(x, flag=False):
            if flag and probe():
                y = x + 1.0
            else:
                y = x
            return y

        conv = convert_to_static(h)
        conv(_t(1.0))
        assert calls == []             # rhs never evaluated


class TestForRange:
    def test_concrete_range_unrolls_with_target_after_loop(self):
        def g(x):
            total = paddle.zeros([])
            for i in range(2, 8, 3):
                total = total + x * i
            return total, i

        conv = convert_to_static(g)
        assert conv.__dy2static_converted__
        t, last = conv(_t(1.0))
        assert float(t.numpy()) == 7.0 and last == 5

    def test_traced_bound_stages_one_program(self):
        def f(x, n):
            s = paddle.zeros([])
            for i in range(n):
                s = s + x * (i + 1.0)
            return s

        sf = paddle.jit.to_static(f)
        assert float(sf(_t(2.0), paddle.to_tensor(4)).numpy()) == 20.0
        assert float(sf(_t(2.0), paddle.to_tensor(2)).numpy()) == 6.0
        assert float(sf(_t(2.0), paddle.to_tensor(0)).numpy()) == 0.0
        assert len(sf._cache) == 1     # staged, not unrolled per n

    def test_greedy_decode_style_loop(self):
        """The dy2static canonical case: a decode loop whose length is a
        traced tensor."""
        def decode(logits_scale, steps):
            tok = paddle.zeros([])
            acc = paddle.zeros([])
            for i in range(steps):
                tok = tok * 0.5 + logits_scale
                acc = acc + tok
            return acc

        sf = paddle.jit.to_static(decode)
        def ref(scale, n):
            tok = acc = 0.0
            for _ in range(n):
                tok = tok * 0.5 + scale
                acc += tok
            return acc
        np.testing.assert_allclose(
            float(sf(_t(1.0), paddle.to_tensor(5)).numpy()), ref(1.0, 5),
            rtol=1e-6)

    def test_empty_concrete_range_leaves_target_undefined(self):
        def f(x):
            for i in range(0):
                x = x + 1.0
            return i * 1.0             # unbound in Python -> loud here

        conv = convert_to_static(f)
        with pytest.raises(NameError, match="'i'"):
            conv(_t(1.0))

    def test_empty_range_keeps_prior_target_binding(self):
        def f(x):
            i = 5
            for i in range(0):
                x = x + 1.0
            return i * 1.0             # Python: prior binding survives

        assert convert_to_static(f)(_t(1.0)) == 5.0

    def test_body_rebinding_target_falls_back_to_python(self):
        def f(x):
            for i in range(3):
                i = i * 10             # body rebinds the target
            return i

        assert convert_to_static(f)(_t(1.0)) == 20

    def test_zero_step_raises_like_python(self):
        def f(x, n):
            s = paddle.zeros([])
            for i in range(0, n, 0):
                s = s + x
            return s

        with pytest.raises(ValueError, match="must not be zero"):
            paddle.jit.to_static(f)(_t(1.0), paddle.to_tensor(5))

    def test_break_in_for_converts(self):
        def f(x, n=5):
            total = 0.0
            for i in range(n):
                if i == 3:
                    break
                total = total + float(x.numpy()) * 1.0
            return total

        conv = convert_to_static(f)
        assert conv(_t(2.0)) == 6.0    # python semantics preserved

    def test_non_range_iterables_untouched(self):
        def f(items):
            out = 0.0
            for v in items:
                out = out + v
            return out

        assert convert_to_static(f)([1.0, 2.0, 3.0]) == 6.0


class TestLiteScopeEdges:
    def test_return_inside_if_stages(self):
        """r5: return in a traced branch converts (flag + site dispatch)
        — the old lite-scope fallback is gone."""
        def f(x):
            if paddle.sum(x) > 0:
                return x * 2.0
            return x - 1.0

        conv = convert_to_static(f)
        assert conv.__dy2static_converted__
        np.testing.assert_allclose(conv(_t([2.0])).numpy(), [4.0])
        np.testing.assert_allclose(conv(_t([-2.0])).numpy(), [-3.0])
        out = paddle.jit.to_static(f)(_t([2.0]))
        np.testing.assert_allclose(out.numpy(), [4.0])
        out = paddle.jit.to_static(f)(_t([-2.0]))
        np.testing.assert_allclose(out.numpy(), [-3.0])

    def test_one_path_temp_raises_on_downstream_use(self):
        def f(x):
            if paddle.sum(x) > 0:
                t = x * 2.0
            else:
                y = x - 1.0
                t2 = y
            return t * 1.0     # defined on the true path only

        sf = paddle.jit.to_static(f)
        with pytest.raises(NameError, match="'t'"):
            sf(_t([1.0]))

    def test_loop_carried_undefined_raises_with_name(self):
        def f(x):
            i = paddle.zeros([])
            while i < 3.0:
                acc = acc + x                      # noqa: F821
                i = i + 1.0
            return acc

        sf = paddle.jit.to_static(f)
        with pytest.raises(NameError, match="acc"):
            sf(_t(1.0))

    def test_body_local_temp_is_fine(self):
        def f(x):
            i = paddle.zeros([])
            s = paddle.zeros([])
            while i < 4.0:
                tmp = x * 2.0          # defined-and-used within one pass
                s = s + tmp
                i = i + 1.0
            return s

        sf = paddle.jit.to_static(f)
        assert float(sf(_t(3.0)).numpy()) == 24.0

    def test_zero_arg_super_method_not_converted(self):
        """Module-level recompile can't rebuild the __class__ cell, so
        methods using zero-arg super() stay unconverted (and keep working
        for concrete predicates)."""

        class Base(nn.Layer):
            def forward(self, x):
                return x + 1.0

        class Child(Base):
            def forward(self, x, double=True):
                if double:                      # concrete predicate
                    x = x * 2.0
                return super().forward(x)

        layer = Child()
        paddle.jit.to_static(layer)
        np.testing.assert_allclose(layer(_t([3.0])).numpy(), [7.0])

    def test_side_effect_only_branch_raises_under_trace(self):
        """A names-less branch acts only by side effects — under a traced
        predicate that must be a LOUD error, not a silent both-branches
        execution."""
        log = []

        def f(x):
            if paddle.sum(x) > 0:
                log.append("taken")
            return x * 1.0

        conv = convert_to_static(f)
        conv(_t([1.0]))                        # concrete: python semantics
        assert log == ["taken"]
        with pytest.raises(Exception, match="side effect|assigns no"):
            paddle.jit.to_static(f)(_t([-1.0]))

    def test_side_effect_only_if(self):
        def f(x):
            out = x * 1.0
            if paddle.sum(x) > 0:
                out = out + 1.0
            return out

        sf = paddle.jit.to_static(f)
        np.testing.assert_allclose(sf(_t([1.0])).numpy(), [2.0])
        np.testing.assert_allclose(sf(_t([-1.0])).numpy(), [-1.0])


class TestClosureSiblings:
    def test_sibling_closures_keep_their_own_cells(self):
        """Closures from one factory share a code object; each must
        convert with ITS OWN captured values (regression: the conversion
        cache used to serve the first sibling's snapshot)."""

        def make(scale):
            def f(x):
                if paddle.sum(x) > 0:
                    y = x * scale
                else:
                    y = x - scale
                return y
            return convert_to_static(f)

        c1, c2 = make(1.0), make(10.0)
        np.testing.assert_allclose(c1(_t([2.0])).numpy(), [2.0])
        np.testing.assert_allclose(c2(_t([2.0])).numpy(), [20.0])
        np.testing.assert_allclose(c2(_t([-2.0])).numpy(), [-12.0])


class TestStaticProgramRecording:
    def test_converted_fn_stages_into_static_program(self):
        import paddle_tpu.static as static

        def f(x):
            if paddle.sum(x) > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y

        conv = convert_to_static(f)
        paddle.enable_static()
        try:
            with static.program_guard(static.Program()):
                x = static.data("x", [None, 2], "float32")
                y = conv(x)
                exe = static.Executor()
                pos = exe.run(feed={"x": np.array([[1.0, 2.0]],
                                                  np.float32)},
                              fetch_list=[y])[0]
                neg = exe.run(feed={"x": np.array([[-1.0, -2.0]],
                                                  np.float32)},
                              fetch_list=[y])[0]
        finally:
            paddle.disable_static()
        np.testing.assert_allclose(pos, [[2.0, 4.0]])
        np.testing.assert_allclose(neg, [[-2.0, -3.0]])


class TestLiveGlobals:
    """Converted functions must see their module's globals LIVE (advisor
    r4 high finding: exec into a snapshot copy made helpers defined after
    decoration raise NameError, and rebinds were silently ignored)."""

    def test_helper_defined_after_conversion(self):
        g = globals()
        assert "_defined_later_helper" not in g

        def f(x):
            if paddle.sum(x) > 0:
                y = _defined_later_helper(x)
            else:
                y = x
            return y

        conv = convert_to_static(f)
        assert conv.__dy2static_converted__
        try:
            g["_defined_later_helper"] = lambda t: t * 3.0
            np.testing.assert_allclose(conv(_t([2.0])).numpy(), [6.0])
        finally:
            g.pop("_defined_later_helper", None)

    def test_global_rebind_is_seen(self):
        g = globals()
        g["_rebindable_helper"] = lambda t: t + 1.0

        def f(x):
            if paddle.sum(x) > 0:
                y = _rebindable_helper(x)
            else:
                y = x
            return y

        conv = convert_to_static(f)
        try:
            np.testing.assert_allclose(conv(_t([1.0])).numpy(), [2.0])
            g["_rebindable_helper"] = lambda t: t + 100.0
            np.testing.assert_allclose(conv(_t([1.0])).numpy(), [101.0])
        finally:
            g.pop("_rebindable_helper", None)

    def test_module_namespace_not_polluted(self):
        def f(x):
            if paddle.sum(x) > 0:
                y = x * 2.0
            else:
                y = x
            return y

        before = set(globals())
        conv = convert_to_static(f)
        conv(_t([1.0]))
        leaked = set(globals()) - before - {"__jst"}
        assert not leaked, f"conversion leaked globals: {leaked}"
        # the exec'd def must not overwrite a module-level name
        assert "f" not in globals()

    def test_nested_self_recursive_function(self):
        """A nested converted function that calls itself must resolve its
        own name to the CONVERTED function (review r5: the exec-into-
        locals change briefly broke this with a NameError)."""

        def outer():
            def g(x, n):
                y = x
                if n > 0:
                    y = g(x * 2.0, n - 1)
                return y
            return convert_to_static(g)

        conv = outer()
        assert conv.__dy2static_converted__
        np.testing.assert_allclose(conv(_t([1.0]), 2).numpy(), [4.0])

    def test_module_level_self_recursion(self, tmp_path):
        """A module-level converted function calling itself must hit the
        CONVERTED function even when the module global still names the
        original (review r5)."""
        import importlib.util
        mod_file = tmp_path / "selfrec_mod.py"
        mod_file.write_text(
            "import paddle_tpu as paddle\n"
            "def mf(x, n):\n"
            "    y = x\n"
            "    if n > 0:\n"
            "        y = mf(x * 2.0, n - 1)\n"
            "    return y\n")
        spec = importlib.util.spec_from_file_location("selfrec_mod",
                                                      mod_file)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        conv = convert_to_static(mod.mf)
        assert conv.__dy2static_converted__
        np.testing.assert_allclose(conv(_t([1.0]), 2).numpy(), [4.0])


class TestEarlyExitStaging:
    """r5 (VERDICT r4 item 1): return/break/continue convert into
    flag-guarded dataflow — a greedy decode with a data-dependent early
    exit stages as ONE program. The rewrite is carry-free for return
    VALUES (flags are two scalars; the return expression re-evaluates
    once at the function-end dispatch from the frozen locals), unlike the
    reference's magic-number placeholder carries
    (dy2static/transformers/return_transformer.py (U))."""

    def test_return_in_while_early_exit_both_paths(self):
        def decode(x, lim):
            y = x
            while paddle.sum(y) < lim:
                t = y * 2.0
                if paddle.sum(t) > 50.0:
                    return t            # data-dependent early exit
                y = t
            return y

        conv = convert_to_static(decode)
        assert conv.__dy2static_converted__
        # eager: both exits
        np.testing.assert_allclose(conv(_t([1.0]), _t(10.0)).numpy(), [16.0])
        np.testing.assert_allclose(conv(_t([1.0]), _t(1e6)).numpy(), [64.0])
        # staged: ONE program, both exits reachable at runtime
        import jax

        jf = jax.jit(lambda x, l: conv(paddle.Tensor(x),
                                       paddle.Tensor(l))._data)
        np.testing.assert_allclose(
            np.asarray(jf(_t([1.0])._data, _t(10.0)._data)), [16.0])
        np.testing.assert_allclose(
            np.asarray(jf(_t([1.0])._data, _t(1e6)._data)), [64.0])

    def test_break_in_while_stages_mid_loop(self):
        """A concrete bound whose loop gains a traced break flag
        continues as one staged while (unrolled head + staged rest)."""
        def f(x):
            s = x
            i = 0
            while i < 100:
                s = s + x
                if paddle.sum(s) > 10.0:
                    break
                i += 1
            return s, i

        conv = convert_to_static(f)
        s, i = conv(_t([2.0]))
        np.testing.assert_allclose(s.numpy(), [12.0])
        assert int(np.asarray(i if not hasattr(i, "numpy") else i.numpy())) == 4
        import jax

        def j(x):
            s, i = conv(paddle.Tensor(x))
            return s._data, (i._data if hasattr(i, "_data") else i)

        sj, ij = jax.jit(j)(_t([2.0])._data)
        np.testing.assert_allclose(np.asarray(sj), [12.0])
        assert int(np.asarray(ij)) == 4

    def test_break_in_for_range_traced_predicate(self):
        def f(x):
            acc = x * 0.0
            for i in range(10):
                acc = acc + x
                if paddle.sum(acc) > 5.0:
                    break
            return acc

        conv = convert_to_static(f)
        np.testing.assert_allclose(conv(_t([2.0])).numpy(), [6.0])
        import jax

        out = jax.jit(lambda x: conv(paddle.Tensor(x))._data)(_t([2.0])._data)
        np.testing.assert_allclose(np.asarray(out), [6.0])

    def test_continue_in_while(self):
        def f(x):
            s = x * 0.0
            i = 0
            while i < 6:
                i += 1
                if i % 2 == 0:
                    continue
                s = s + x * float(i)
            return s

        conv = convert_to_static(f)
        np.testing.assert_allclose(conv(_t([1.0])).numpy(), [9.0])  # 1+3+5

    def test_multi_site_returns_in_branches(self):
        def f(x):
            if paddle.sum(x) > 10.0:
                return x * 3.0
            elif paddle.sum(x) > 0.0:
                return x * 2.0
            else:
                return -x

        conv = convert_to_static(f)
        assert conv.__dy2static_converted__
        np.testing.assert_allclose(conv(_t([20.0])).numpy(), [60.0])
        np.testing.assert_allclose(conv(_t([1.0])).numpy(), [2.0])
        np.testing.assert_allclose(conv(_t([-5.0])).numpy(), [5.0])
        import jax

        jf = jax.jit(lambda x: conv(paddle.Tensor(x))._data)
        np.testing.assert_allclose(np.asarray(jf(_t([20.0])._data)), [60.0])
        np.testing.assert_allclose(np.asarray(jf(_t([1.0])._data)), [2.0])
        np.testing.assert_allclose(np.asarray(jf(_t([-5.0])._data)), [5.0])

    def test_tuple_return_sites(self):
        def f(x):
            if paddle.sum(x) > 0:
                return x * 2.0, x + 1.0
            return x, x - 1.0

        conv = convert_to_static(f)
        a, b = conv(_t([3.0]))
        np.testing.assert_allclose(a.numpy(), [6.0])
        np.testing.assert_allclose(b.numpy(), [4.0])
        import jax

        def j(x):
            a, b = conv(paddle.Tensor(x))
            return a._data, b._data

        aj, bj = jax.jit(j)(_t([-3.0])._data)
        np.testing.assert_allclose(np.asarray(aj), [-3.0])
        np.testing.assert_allclose(np.asarray(bj), [-4.0])

    def test_return_in_with_or_try_falls_back(self):
        """Exits the guard rewrite cannot reach keep today's behavior."""
        def f(x):
            try:
                if paddle.sum(x) > 0:
                    return x * 2.0
            finally:
                pass
            return x

        conv = convert_to_static(f)
        # not staged (return inside try) — eager exact, trace still errors
        np.testing.assert_allclose(conv(_t([2.0])).numpy(), [4.0])

    def test_greedy_argmax_decode_one_program(self):
        """The canonical dy2static demo: token-by-token greedy decode
        with an EOS early exit, staged end to end."""
        W = _t(np.eye(4, dtype=np.float32) * 0.5)

        def decode(h, steps):
            n = 0
            while n < steps:
                h = paddle.matmul(h, W)
                if paddle.max(h) < 0.1:     # "EOS": magnitudes decayed
                    return h * 0.0
                n = n + 1
            return h

        conv = convert_to_static(decode)
        assert conv.__dy2static_converted__
        import jax

        jf = jax.jit(
            lambda h, s: conv(paddle.Tensor(h), paddle.Tensor(s))._data)
        # decays below 0.1 after 4 halvings of 1.0 -> early exit zeros
        out = np.asarray(jf(_t([[1.0, 1.0, 1.0, 1.0]])._data,
                            _t(100)._data))
        np.testing.assert_allclose(out, [[0.0] * 4])
        # few steps: exits via the bound, no zeroing
        out2 = np.asarray(jf(_t([[1.0, 1.0, 1.0, 1.0]])._data,
                             _t(2)._data))
        np.testing.assert_allclose(out2, [[0.25] * 4])


class TestTensorIterableScan:
    def test_scan_matches_python_and_differentiates(self):
        def f(seq, h):
            for row in seq:
                h = h * 0.5 + row
            return h

        conv = convert_to_static(f)
        assert conv.__dy2static_converted__
        seq = _t([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        h0 = _t([0.0, 0.0])
        exp = np.zeros(2, np.float32)
        for r in np.asarray(seq._data):
            exp = exp * 0.5 + r
        np.testing.assert_allclose(conv(seq, h0).numpy(), exp)
        import jax

        # staged as ONE lax.scan — and unlike while_loop, differentiable
        def loss(seq_a, h_a):
            return (conv(paddle.Tensor(seq_a),
                         paddle.Tensor(h_a))._data ** 2).sum()

        g = jax.grad(loss, argnums=1)(seq._data, h0._data)
        eps = 1e-3
        num = (loss(seq._data, h0._data + np.array([eps, 0], np.float32))
               - loss(seq._data, h0._data)) / eps
        np.testing.assert_allclose(np.asarray(g)[0], num, rtol=2e-2)

    def test_python_iterables_keep_exact_semantics(self):
        def f(items, x):
            out = x
            for v in items:
                out = out + v
            return out

        conv = convert_to_static(f)
        assert conv(
            [1.0, 2.0], 0.5) == 3.5
        # generators too (consumed once, eagerly)
        assert conv((v for v in (1, 2, 3)), 0) == 6

    def test_post_return_bindings_stage(self):
        """Code after a may-return point (inside the generated guard)
        binds variables the dispatch reads — must stage, not NameError
        (review r5 finding 1)."""
        def f(x):
            if paddle.sum(x) > 0:
                return x * 2.0
            y = x + 1.0
            return y

        conv = convert_to_static(f)
        assert conv.__dy2static_converted__
        np.testing.assert_allclose(conv(_t([2.0])).numpy(), [4.0])
        np.testing.assert_allclose(conv(_t([-2.0])).numpy(), [-1.0])
        import jax

        jf = jax.jit(lambda x: conv(paddle.Tensor(x))._data)
        np.testing.assert_allclose(np.asarray(jf(_t([2.0])._data)), [4.0])
        np.testing.assert_allclose(np.asarray(jf(_t([-2.0])._data)), [-1.0])

    def test_implicit_none_fallthrough_raises_clearly(self):
        """Mixing a tensor return with an implicit None fall-through
        under a traced predicate fails with the purpose-built message
        (review r5 finding 3)."""
        def f(x):
            if paddle.sum(x) > 0:
                return x * 2.0

        conv = convert_to_static(f)
        assert conv(_t([-1.0])) is None   # concrete: exact Python
        import jax

        with pytest.raises(TypeError, match="every path|final return"):
            jax.jit(lambda x: conv(paddle.Tensor(x)))(_t([1.0])._data)

    def test_side_effect_only_tensor_for_raises(self):
        """A traced tensor-for whose body only has side effects raises
        loudly instead of silently running once (review r5 finding 2)."""
        calls = []

        def f(seq):
            for row in seq:
                calls.append(1)
            return seq

        conv = convert_to_static(f)
        import jax

        with pytest.raises(TypeError, match="side effects"):
            jax.jit(lambda s: conv(paddle.Tensor(s))._data)(
                _t([[1.0], [2.0]])._data)


class TestTransitiveConversion:
    """r5: conversion is transitive through calls (ref convert_call) —
    undecorated helpers stage when called from a converted function."""

    def test_undecorated_helper_stages(self):
        def helper(x):
            if paddle.sum(x) > 0:       # traced predicate inside HELPER
                return x * 2.0
            return x - 1.0

        def entry(x):
            y = helper(x)               # entry has no control flow itself
            return y + 10.0

        conv = convert_to_static(entry)
        assert conv.__dy2static_converted__
        np.testing.assert_allclose(conv(_t([2.0])).numpy(), [14.0])
        import jax

        jf = jax.jit(lambda x: conv(paddle.Tensor(x))._data)
        np.testing.assert_allclose(np.asarray(jf(_t([2.0])._data)), [14.0])
        np.testing.assert_allclose(np.asarray(jf(_t([-2.0])._data)), [7.0])

    def test_two_levels_deep(self):
        def inner(x):
            while paddle.sum(x) < 10.0:
                x = x * 2.0
            return x

        def mid(x):
            return inner(x) + 1.0

        def entry(x):
            return mid(x) * 1.0

        conv = convert_to_static(entry)
        import jax

        out = jax.jit(lambda x: conv(paddle.Tensor(x))._data)(
            _t([1.0])._data)
        np.testing.assert_allclose(np.asarray(out), [17.0])

    def test_not_to_static_opts_out(self):
        def helper(x):
            if paddle.sum(x) > 0:
                return x * 2.0
            return x
        helper._not_to_static = True

        def entry(x):
            return helper(x)

        conv = convert_to_static(entry)
        # helper untouched: concrete works, traced raises the standard
        # concretization error
        np.testing.assert_allclose(conv(_t([2.0])).numpy(), [4.0])
        import jax
        import pytest as _pytest

        with _pytest.raises(Exception, match="[Tt]race|[Cc]oncrete"):
            jax.jit(lambda x: conv(paddle.Tensor(x))._data)(_t([2.0])._data)

    def test_framework_calls_pass_through(self):
        def entry(x):
            return paddle.sum(x) + len([1, 2])

        conv = convert_to_static(entry)
        assert float(conv(_t([1.0, 2.0]))) == 5.0

    def test_while_true_return_only_exit(self):
        """`while True: ... if done: return x` — the loop's only exit is
        a return; the dispatch must not add a None fall-through leaf
        (review r5)."""
        def f(x):
            while True:
                x = x * 2.0
                if paddle.sum(x) > 10.0:
                    return x

        conv = convert_to_static(f)
        np.testing.assert_allclose(conv(_t([1.0])).numpy(), [16.0])
        import jax

        out = jax.jit(lambda x: conv(paddle.Tensor(x))._data)(
            _t([1.0])._data)
        np.testing.assert_allclose(np.asarray(out), [16.0])

    def test_bound_method_after_plain_call_keeps_self(self):
        """The convert_call cache must not serve a bound method the
        UNBOUND conversion of its underlying function (review r5:
        methods proxy attribute reads to __func__)."""
        from paddle_tpu.jit.dy2static import convert_call

        def f(self_or_x, x=None):
            if x is None:
                return self_or_x + 1.0
            return self_or_x.scale * x

        class C:
            scale = 10.0
            m = f

        # plain call first: populates the function-object cache
        assert convert_call(f)(1.0) == 2.0
        # bound-method call next: must keep self bound
        assert convert_call(C().m)(3.0) == 30.0


class TestBeamSearchDecode:
    def test_beam_search_with_early_exit_stages(self):
        """Capstone (VERDICT r4 item 1's 'canonical dy2static demo'): a
        beam-search decode — per-step TOPK over the flattened
        (beam x vocab) scores, GATHER of the winning beams' states,
        score carries, and a data-dependent early exit when the best
        score saturates — converts to ONE staged program. The reference
        values come from the ORIGINAL (unconverted) function."""
        V, B = 6, 3
        W = _t((np.linspace(-0.5, 0.5, 4 * V)
                .reshape(4, V) * 1.0).astype(np.float32))
        E = _t(np.linspace(-0.2, 0.2, V * 4)
               .reshape(V, 4).astype(np.float32))

        def beam_decode(h, scores, steps, thresh):
            # h: [B, 4] beam states; scores: [B]
            n = 0
            while n < steps:
                logits = paddle.matmul(h, W)              # [B, V]
                cand = scores.unsqueeze(-1) + logits      # [B, V]
                flat = cand.reshape([B * V])
                scores, idx = paddle.topk(flat, k=B)      # beam expansion
                beam = idx // V                           # winning beams
                tok = idx % V
                h = paddle.tanh(h[beam] + E[tok])         # gathered state
                if paddle.max(scores) > thresh:           # early exit
                    return h, scores
                n = n + 1
            return h, scores

        h0 = _t(np.ones((B, 4), np.float32))
        s0 = _t(np.zeros(B, np.float32))
        # ORIGINAL function, plain Python: ground truth for both exits
        eh1, es1 = beam_decode(h0, s0, 50, 1.0)
        eh2, es2 = beam_decode(h0, s0, 3, 1e9)
        assert float(es1.numpy().max()) > 1.0   # early exit really fired

        conv = convert_to_static(beam_decode)
        assert conv.__dy2static_converted__
        # converted, concrete: exact Python semantics
        ch1, cs1 = conv(h0, s0, 50, 1.0)
        np.testing.assert_allclose(cs1.numpy(), es1.numpy(), rtol=1e-5)
        import jax

        def j(h, s, steps, thresh):
            a, b = conv(paddle.Tensor(h), paddle.Tensor(s),
                        paddle.Tensor(steps), paddle.Tensor(thresh))
            return a._data, b._data

        jf = jax.jit(j)
        jh1, js1 = jf(h0._data, s0._data, _t(50)._data, _t(1.0)._data)
        np.testing.assert_allclose(np.asarray(jh1), eh1.numpy(), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(js1), es1.numpy(), rtol=1e-5)
        jh2, js2 = jf(h0._data, s0._data, _t(3)._data, _t(1e9)._data)
        np.testing.assert_allclose(np.asarray(jh2), eh2.numpy(), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(js2), es2.numpy(), rtol=1e-5)
