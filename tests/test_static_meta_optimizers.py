"""Static meta-optimizers (SURVEY.md §2.2 P20): fleet.distributed_optimizer
under paddle.enable_static() returns a program-rewriting wrapper — amp cast
rewrite (+ fp16 dynamic loss scaling), recompute over declared checkpoints,
k-step gradient merge, and the Lamb swap — the TPU-native analog of the
reference's fleet/meta_optimizers ProgramDesc passes."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_optimizers.static_meta_optimizer import (
    StaticMetaOptimizer,
)


@pytest.fixture
def static_mode():
    paddle.enable_static()
    try:
        yield
    finally:
        paddle.disable_static()


def _problem(n=64, d=8):
    rng = np.random.RandomState(0)
    X = rng.randn(n, d).astype(np.float32)
    Y = (X @ rng.randn(d, 1).astype(np.float32)
         + 0.1 * rng.randn(n, 1).astype(np.float32))
    return X, Y


def _mlp_program(hidden=16, seed=0):
    """Build x -> fc -> relu -> fc -> mse inside the CURRENT program guard;
    returns (x, y, hidden_act, loss)."""
    paddle.seed(seed)
    x = static.data("x", [None, 8], "float32")
    y = static.data("y", [None, 1], "float32")
    h = paddle.nn.functional.relu(static.nn.fc(x, hidden))
    pred = static.nn.fc(h, 1)
    loss = paddle.mean((pred - y) ** 2)
    return x, y, h, loss


class TestStaticAMP:
    def test_bf16_rewrite_casts_white_ops_and_trains(self, static_mode):
        X, Y = _problem()
        strat = fleet.DistributedStrategy()
        strat.amp = True                      # bf16 default: no loss scaling
        with static.program_guard(static.Program()):
            x, y, h, loss = _mlp_program()
            opt = fleet.distributed_optimizer(
                paddle.optimizer.SGD(learning_rate=0.05), strategy=strat)
            assert isinstance(opt, StaticMetaOptimizer)
            opt.minimize(loss)
            exe = static.Executor()
            losses, hv = [], None
            for _ in range(15):
                lv, hv = exe.run(feed={"x": X, "y": Y},
                                 fetch_list=[loss, h], return_numpy=False)
                losses.append(float(lv.numpy()))
        # the white-listed matmul now computes (and emits) bf16 — proof the
        # REWRITE happened, not an eager autocast scope
        assert str(hv.dtype) in ("paddle.bfloat16", "bfloat16") \
            or "bfloat16" in str(hv.dtype)
        # the black-listed mean keeps the loss in f32
        assert np.asarray(losses).dtype == np.float64  # floats from f32
        assert losses[-1] < 0.5 * losses[0]

    def test_fp16_dynamic_loss_scaling_skips_and_recovers(self, static_mode):
        X, Y = _problem()
        strat = fleet.DistributedStrategy()
        strat.amp = True
        strat.amp_configs = {
            "use_bf16": False,                # fp16: scaling is load-bearing
            "init_loss_scaling": 1e9,         # overflows fp16 cotangents
            "decr_every_n_nan_or_inf": 1,
            "incr_every_n_steps": 1000,
        }
        with static.program_guard(static.Program()):
            x, y, h, loss = _mlp_program()
            opt = fleet.distributed_optimizer(
                paddle.optimizer.SGD(learning_rate=0.05), strategy=strat)
            _, pairs = opt.minimize(loss)
            w = pairs[0][0]
            w_before = np.asarray(w._data).copy()
            exe = static.Executor()
            assert opt.loss_scaling == pytest.approx(1e9)
            exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
            # overflow step: scale halves, parameters untouched
            assert opt.loss_scaling == pytest.approx(5e8)
            np.testing.assert_array_equal(np.asarray(w._data), w_before)
            losses = []
            for _ in range(30):               # scale decays until finite
                (lv,) = exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
                losses.append(float(lv))
            assert opt.loss_scaling < 1e5     # shrank out of overflow
            assert not np.array_equal(np.asarray(w._data), w_before)
            assert losses[-1] < 0.5 * losses[0]   # trains after recovery

    def test_fp16_scale_grows_after_good_steps(self, static_mode):
        X, Y = _problem()
        strat = fleet.DistributedStrategy()
        strat.amp = True
        strat.amp_configs = {
            "use_bf16": False,
            "init_loss_scaling": 1024.0,
            "incr_every_n_steps": 3,
            "incr_ratio": 2.0,
        }
        with static.program_guard(static.Program()):
            x, y, h, loss = _mlp_program()
            opt = fleet.distributed_optimizer(
                paddle.optimizer.SGD(learning_rate=0.01), strategy=strat)
            opt.minimize(loss)
            exe = static.Executor()
            for _ in range(3):
                exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
            assert opt.loss_scaling == pytest.approx(2048.0)


class TestStaticAmpDecorate:
    def test_reference_decorate_workflow(self, static_mode):
        """paddle.static.amp.decorate(optimizer) — the reference's
        non-fleet AMP entry point — routes through the same program
        rewrite + loss-scaling machinery."""
        X, Y = _problem()
        with static.program_guard(static.Program()):
            x, y, h, loss = _mlp_program()
            opt = static.amp.decorate(
                paddle.optimizer.Adam(learning_rate=0.02),
                amp_lists=static.amp.AutoMixedPrecisionLists(
                    custom_black_list=["relu"]),
                init_loss_scaling=1024.0)
            assert isinstance(opt, StaticMetaOptimizer)
            opt.minimize(loss)
            opt.amp_init(None)                 # parity no-op
            exe = static.Executor()
            losses = []
            for _ in range(20):
                (lv,) = exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
                losses.append(float(lv))
        assert losses[-1] < 0.5 * losses[0]
        assert opt.get_loss_scaling() == pytest.approx(1024.0)

    def test_bf16_dtype_skips_loss_scaling(self, static_mode):
        X, Y = _problem()
        with static.program_guard(static.Program()):
            x, y, h, loss = _mlp_program()
            opt = static.amp.decorate(
                paddle.optimizer.SGD(learning_rate=0.05), dtype="bfloat16")
            opt.minimize(loss)
            assert opt._static_amp_scaler is None   # bf16 needs none
            exe = static.Executor()
            (lv0,) = exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
            for _ in range(10):
                (lv,) = exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
        assert float(lv) < float(lv0)


class TestStaticRecompute:
    def test_checkpointed_losses_match_plain(self, static_mode):
        X, Y = _problem()

        def run(with_recompute):
            with static.program_guard(static.Program()):
                paddle.seed(7)
                x = static.data("x", [None, 8], "float32")
                y = static.data("y", [None, 1], "float32")
                h1 = paddle.nn.functional.relu(static.nn.fc(x, 16))
                h2 = paddle.nn.functional.relu(static.nn.fc(h1, 16))
                pred = static.nn.fc(h2, 1)
                loss = paddle.mean((pred - y) ** 2)
                strat = fleet.DistributedStrategy()
                if with_recompute:
                    strat.recompute = True
                    strat.recompute_configs = {"checkpoints": [h1, h2]}
                opt = fleet.distributed_optimizer(
                    paddle.optimizer.Adam(learning_rate=0.02),
                    strategy=strat)
                opt.minimize(loss)
                if with_recompute:
                    ck = static.default_main_program()._recompute_checkpoints
                    assert len(ck) == 2
                exe = static.Executor()
                out = []
                for _ in range(8):
                    (lv,) = exe.run(feed={"x": X, "y": Y},
                                    fetch_list=[loss])
                    out.append(float(lv))
                return out

        plain = run(False)
        ckpt = run(True)
        assert ckpt[-1] < 0.5 * ckpt[0]
        np.testing.assert_allclose(ckpt, plain, rtol=2e-5, atol=1e-6)

    def test_unreachable_checkpoint_raises(self, static_mode):
        X, Y = _problem()
        with static.program_guard(static.Program()):
            x, y, h, loss = _mlp_program()
            stray = static.data("stray", [4, 4], "float32")
            other = stray * 2.0               # not an ancestor of loss
            strat = fleet.DistributedStrategy()
            strat.recompute = True
            strat.recompute_configs = {"checkpoints": [other]}
            opt = fleet.distributed_optimizer(
                paddle.optimizer.SGD(learning_rate=0.1), strategy=strat)
            opt.minimize(loss)
            exe = static.Executor()
            with pytest.raises(static.StaticGraphError,
                               match="not reachable"):
                exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])


class TestGradientMerge:
    def test_k2_avg_equals_full_batch_step(self, static_mode):
        X, Y = _problem(n=64)
        A, B = (X[:32], Y[:32]), (X[32:], Y[32:])
        with static.program_guard(static.Program()):
            paddle.seed(3)
            x = static.data("x", [None, 8], "float32")
            y = static.data("y", [None, 1], "float32")
            pred = static.nn.fc(x, 1)
            loss = paddle.mean((pred - y) ** 2)
            strat = fleet.DistributedStrategy()
            strat.gradient_merge = True
            strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
            opt = fleet.distributed_optimizer(
                paddle.optimizer.SGD(learning_rate=0.1), strategy=strat)
            _, pairs = opt.minimize(loss)
            w, b = pairs[0][0], pairs[1][0]
            w0, b0 = np.asarray(w._data).copy(), np.asarray(b._data).copy()
            exe = static.Executor()
            exe.run(feed={"x": A[0], "y": A[1]}, fetch_list=[loss])
            # first micro-step: accumulated only, no update
            np.testing.assert_array_equal(np.asarray(w._data), w0)
            exe.run(feed={"x": B[0], "y": B[1]}, fetch_list=[loss])
            w2, b2 = np.asarray(w._data), np.asarray(b._data)
        assert not np.array_equal(w2, w0)
        # avg of the two half-batch grads == full-batch grad (mean loss),
        # so one merged update == one full-batch SGD step
        paddle.disable_static()
        r = X @ w0 + b0 - Y
        gw = 2 * X.T @ r / len(X)
        gb = 2 * r.mean(0)
        np.testing.assert_allclose(w2, w0 - 0.1 * gw, rtol=2e-5, atol=1e-6)
        np.testing.assert_allclose(b2, b0 - 0.1 * gb, rtol=2e-5, atol=1e-6)

    def test_merge_with_fp16_divides_by_landed_steps(self, static_mode):
        """A non-finite micro-step must not bias the merged average: the
        divisor is the number of micro-steps that actually accumulated."""
        X, Y = _problem(n=64)
        A, B = (X[:32], Y[:32]), (X[32:], Y[32:])
        strat = fleet.DistributedStrategy()
        strat.amp = True
        strat.amp_configs = {
            "use_bf16": False,
            "init_loss_scaling": 1e9,     # micro-step 1 overflows fp16
            "decr_every_n_nan_or_inf": 1,
            "decr_ratio": 1e-6,           # ...and drops to 1e3: step 2 lands
        }
        strat.gradient_merge = True
        strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
        with static.program_guard(static.Program()):
            paddle.seed(5)
            x = static.data("x", [None, 8], "float32")
            y = static.data("y", [None, 1], "float32")
            pred = static.nn.fc(x, 1)
            loss = paddle.mean((pred - y) ** 2)
            opt = fleet.distributed_optimizer(
                paddle.optimizer.SGD(learning_rate=0.1), strategy=strat)
            _, pairs = opt.minimize(loss)
            w, b = pairs[0][0], pairs[1][0]
            w0, b0 = np.asarray(w._data).copy(), np.asarray(b._data).copy()
            exe = static.Executor()
            exe.run(feed={"x": A[0], "y": A[1]}, fetch_list=[loss])
            exe.run(feed={"x": B[0], "y": B[1]}, fetch_list=[loss])
            w2 = np.asarray(w._data)
        paddle.disable_static()
        # only micro-batch B landed: the update must be ONE SGD step on
        # B's grad alone (divided by 1, not by k=2). fp16 matmuls in the
        # forward loosen the tolerance.
        r = B[0] @ w0 + b0 - B[1]
        gw = 2 * B[0].T @ r / len(B[0])
        np.testing.assert_allclose(w2, w0 - 0.1 * gw, rtol=5e-3, atol=5e-4)

    def test_merge_composes_with_amp_bf16(self, static_mode):
        X, Y = _problem()
        strat = fleet.DistributedStrategy()
        strat.amp = True
        strat.gradient_merge = True
        strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
        with static.program_guard(static.Program()):
            x, y, h, loss = _mlp_program()
            opt = fleet.distributed_optimizer(
                paddle.optimizer.SGD(learning_rate=0.05), strategy=strat)
            opt.minimize(loss)
            exe = static.Executor()
            losses = []
            for _ in range(20):
                (lv,) = exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
                losses.append(float(lv))
        assert losses[-1] < 0.5 * losses[0]


class TestStaticDataParallel:
    """Static DATA-PARALLEL training (the reference's fleet static path,
    SURVEY §3.3/§3.5): feeds shard over the dp mesh axis, params stay
    replicated, GSPMD inserts the grad allreduce — losses must equal the
    serial full-batch run exactly."""

    def _run(self, dp_degree, steps=10):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.topology import (
            create_hybrid_communicate_group,
            set_hybrid_communicate_group,
        )

        X, Y = _problem(n=64)
        set_hybrid_communicate_group(None)
        if dp_degree > 1:
            create_hybrid_communicate_group(dp=dp_degree)
        try:
            with static.program_guard(static.Program()):
                paddle.seed(21)
                x = static.data("x", [None, 8], "float32")
                y = static.data("y", [None, 1], "float32")
                h = paddle.nn.functional.relu(static.nn.fc(x, 16))
                pred = static.nn.fc(h, 1)
                loss = paddle.mean((pred - y) ** 2)
                opt = fleet.distributed_optimizer(
                    paddle.optimizer.Adam(learning_rate=0.02),
                    strategy=fleet.DistributedStrategy())
                opt.minimize(loss)
                if dp_degree > 1:
                    assert opt._static_dp_mesh is not None
                exe = static.Executor()
                out = []
                for _ in range(steps):
                    (lv,) = exe.run(feed={"x": X, "y": Y},
                                    fetch_list=[loss])
                    out.append(float(lv))
                return out
        finally:
            set_hybrid_communicate_group(None)

    def test_dp4_matches_serial(self, static_mode):
        serial = self._run(1)
        dp4 = self._run(4)
        assert dp4[-1] < 0.5 * dp4[0]
        np.testing.assert_allclose(dp4, serial, rtol=2e-5, atol=1e-6)

    def test_fixed_shape_aux_feed_replicates(self, static_mode):
        """A non-batch auxiliary feed (fixed declared shape) must
        replicate, not trip the divisibility check."""
        from paddle_tpu.distributed.topology import (
            create_hybrid_communicate_group,
            set_hybrid_communicate_group,
        )

        X, Y = _problem(n=64)
        set_hybrid_communicate_group(None)
        create_hybrid_communicate_group(dp=4)
        try:
            with static.program_guard(static.Program()):
                paddle.seed(3)
                x = static.data("x", [None, 8], "float32")
                y = static.data("y", [None, 1], "float32")
                w = static.data("w", [3], "float32")   # 3 % 4 != 0: aux
                pred = static.nn.fc(x, 1)
                loss = paddle.mean((pred - y) ** 2) * paddle.sum(w)
                opt = fleet.distributed_optimizer(
                    paddle.optimizer.SGD(learning_rate=0.05),
                    strategy=fleet.DistributedStrategy())
                opt.minimize(loss)
                exe = static.Executor()
                (lv,) = exe.run(
                    feed={"x": X, "y": Y,
                          "w": np.array([0.5, 0.25, 0.25], np.float32)},
                    fetch_list=[loss])
                assert np.isfinite(float(lv))
        finally:
            set_hybrid_communicate_group(None)

    def test_indivisible_batch_raises(self, static_mode):
        from paddle_tpu.distributed.topology import (
            create_hybrid_communicate_group,
            set_hybrid_communicate_group,
        )

        set_hybrid_communicate_group(None)
        create_hybrid_communicate_group(dp=8)
        try:
            with static.program_guard(static.Program()):
                x = static.data("x", [None, 8], "float32")
                y = static.data("y", [None, 1], "float32")
                loss = paddle.mean((static.nn.fc(x, 1) - y) ** 2)
                opt = fleet.distributed_optimizer(
                    paddle.optimizer.SGD(learning_rate=0.1),
                    strategy=fleet.DistributedStrategy())
                opt.minimize(loss)
                exe = static.Executor()
                bad = np.ones((6, 8), np.float32)   # 6 % 8 != 0
                with pytest.raises(static.StaticGraphError,
                                   match="divisible"):
                    exe.run(feed={"x": bad, "y": np.ones((6, 1),
                                                         np.float32)},
                            fetch_list=[loss])
        finally:
            set_hybrid_communicate_group(None)


class TestLambSwap:
    def test_strategy_lamb_swaps_and_matches_eager(self, static_mode):
        from paddle_tpu.optimizer.optimizers import Lamb

        X, Y = _problem()
        strat = fleet.DistributedStrategy()
        strat.lamb = True
        strat.lamb_configs = {"lamb_weight_decay": 0.02}
        with static.program_guard(static.Program()):
            paddle.seed(11)
            x = static.data("x", [None, 8], "float32")
            y = static.data("y", [None, 1], "float32")
            pred = static.nn.fc(x, 1)
            loss = paddle.mean((pred - y) ** 2)
            opt = fleet.distributed_optimizer(
                paddle.optimizer.Adam(learning_rate=0.05), strategy=strat)
            _, pairs = opt.minimize(loss)
            assert isinstance(opt.inner_opt, Lamb)
            w0 = pairs[0][0]._data
            b0 = pairs[1][0]._data
            exe = static.Executor()
            losses = []
            for _ in range(10):
                (lv,) = exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
                losses.append(float(lv))
        paddle.disable_static()
        model = nn.Linear(8, 1)
        model.weight._data = w0
        model.bias._data = b0
        ref_opt = Lamb(learning_rate=0.05, lamb_weight_decay=0.02,
                       parameters=model.parameters())
        ref = []
        for _ in range(10):
            lv = nn.functional.mse_loss(model(paddle.to_tensor(X)),
                                        paddle.to_tensor(Y))
            ref.append(float(lv))
            lv.backward()
            ref_opt.step()
            ref_opt.clear_grad()
        np.testing.assert_allclose(losses, ref, rtol=2e-5, atol=1e-6)


class TestAmpRewriteIdempotence:
    def test_re_rewrite_with_new_dtype_replaces_cast(self, static_mode):
        """Re-minimizing the same program under a DIFFERENT amp dtype must
        replace the cast wrapper, not stack a second one where the stale
        inner cast runs last and wins (advisor r4)."""
        from paddle_tpu.distributed.fleet.meta_optimizers.static_meta_optimizer import (
            amp_rewrite,
        )
        import jax.numpy as jnp

        X, Y = _problem()
        with static.program_guard(static.Program()):
            x, y, h, loss = _mlp_program()
            n1 = amp_rewrite(loss, "bfloat16")
            assert n1 > 0
            # same dtype again: true idempotence, nothing rewritten
            assert amp_rewrite(loss, "bfloat16") == 0
            # white-listed ops re-cast to fp16; black-listed keep their
            # (identical) f32 cast and are skipped — so 0 < n2 <= n1
            n2 = amp_rewrite(loss, "float16")
            assert 0 < n2 <= n1
            # every surviving wrapper is ONE level deep over the original
            from paddle_tpu.distributed.fleet.meta_optimizers.static_meta_optimizer import (
                _iter_nodes,
            )
            for node in _iter_nodes([loss._data]):
                fn = node.fn
                if getattr(fn, "_amp_static", None) is not None:
                    assert fn._amp_static in (jnp.float16, jnp.float32)
                    inner = fn._amp_orig
                    assert getattr(inner, "_amp_static", None) is None
            exe = static.Executor()
            hv = exe.run(feed={"x": X, "y": Y}, fetch_list=[h],
                         return_numpy=False)[0]
        assert "float16" in str(hv.dtype) and "bfloat16" not in str(hv.dtype)


class TestDpLocalCount:
    def test_hybrid_mesh_counts_dp_axis_only(self):
        """On a dp×mp mesh the per-process batch divisor is the number of
        dp coordinates the process owns, NOT its total device count
        (advisor r4: a dp4×mp2 mesh demanded divisibility by 8)."""
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.static.graph import _dp_local_count

        devs = np.array(jax.devices()[:8])
        assert devs.size == 8  # conftest forces the 8-device CPU mesh
        mesh = Mesh(devs.reshape(4, 2), ("dp", "mp"))
        assert _dp_local_count(mesh) == 4
        mesh2 = Mesh(devs.reshape(2, 4), ("mp", "dp"))  # dp not leading
        assert _dp_local_count(mesh2) == 4
        mesh3 = Mesh(devs.reshape(8), ("dp",))
        assert _dp_local_count(mesh3) == 8


class TestStaticTensorParallel:
    def test_mp_sharded_training_matches_serial(self, static_mode):
        """r5 (VERDICT r4 item 6): static tensor parallel — recorded
        params shard over the hybrid mesh's mp axis (column policy, the
        static analog of tensor_parallel_optimizer) and training matches
        the serial program."""
        import jax
        import paddle_tpu.distributed as dist

        X, Y = _problem()

        def run(mp):
            dist.set_hybrid_communicate_group(None)
            if mp:
                devs = list(np.array(jax.devices()[:8]).ravel())
                dist.create_hybrid_communicate_group(dp=2, mp=4,
                                                     devices=devs)
            try:
                with static.program_guard(static.Program()):
                    x, y, h, loss = _mlp_program()
                    opt = fleet.distributed_optimizer(
                        paddle.optimizer.Adam(learning_rate=0.02),
                        strategy=fleet.DistributedStrategy())
                    _, pairs = opt.minimize(loss)
                    if mp:
                        assert opt._static_dp_mesh is not None
                    exe = static.Executor()
                    losses = []
                    for _ in range(12):
                        (lv,) = exe.run(feed={"x": X, "y": Y},
                                        fetch_list=[loss])
                        losses.append(float(lv))
                    if mp:
                        specs = [str(getattr(p._data.sharding, "spec",
                                             None)) for p, _ in pairs]
                        assert any("mp" in s for s in specs), specs
            finally:
                dist.set_hybrid_communicate_group(None)
            return losses

        serial = run(False)
        mp = run(True)
        np.testing.assert_allclose(serial, mp, rtol=2e-4, atol=1e-5)
        assert mp[-1] < 0.5 * mp[0]


class TestStaticZero1:
    def test_sharded_opt_state_matches_serial(self, static_mode):
        """r5: static ZeRO-1 — optimizer state (incl. Adam moments and
        master weights) shards its leading dim over the mesh's
        'sharding' axis; params stay replicated; training matches
        serial. The static analog of the reference's static
        sharding_optimizer (fleet/meta_optimizers/ (U))."""
        import jax
        import paddle_tpu.distributed as dist

        X, Y = _problem()

        def run(zero):
            dist.set_hybrid_communicate_group(None)
            if zero:
                devs = list(np.array(jax.devices()[:8]).ravel())
                dist.create_hybrid_communicate_group(
                    dp=2, sharding=4, devices=devs)
            try:
                with static.program_guard(static.Program()):
                    x, y, h, loss = _mlp_program()
                    opt = fleet.distributed_optimizer(
                        paddle.optimizer.Adam(learning_rate=0.02),
                        strategy=fleet.DistributedStrategy())
                    _, pairs = opt.minimize(loss)
                    exe = static.Executor()
                    losses = []
                    for _ in range(12):
                        (lv,) = exe.run(feed={"x": X, "y": Y},
                                        fetch_list=[loss])
                        losses.append(float(lv))
                    if zero:
                        # some moment leaf is genuinely sharded
                        inner = opt.inner_opt
                        specs = []
                        for p, _ in pairs:
                            st = inner._accumulators[id(p)]
                            for leaf in jax.tree.leaves(st):
                                specs.append(str(getattr(
                                    leaf.sharding, "spec", None)))
                        assert any("sharding" in s for s in specs), specs
                        # params themselves stay replicated
                        for p, _ in pairs:
                            assert "sharding" not in str(
                                p._data.sharding.spec)
            finally:
                dist.set_hybrid_communicate_group(None)
            return losses

        serial = run(False)
        z = run(True)
        np.testing.assert_allclose(serial, z, rtol=2e-4, atol=1e-5)


class TestHybridComposition:
    def test_mp_amp_gradient_merge_compose(self, static_mode):
        """r5: the static meta-optimizers compose with the new mesh
        axes — bf16 amp rewrite + k-step gradient merge on an mp-sharded
        program trains and matches the same composition run serially."""
        import jax
        import paddle_tpu.distributed as dist

        X, Y = _problem()

        def run(mp):
            dist.set_hybrid_communicate_group(None)
            if mp:
                devs = list(np.array(jax.devices()[:8]).ravel())
                dist.create_hybrid_communicate_group(dp=2, mp=4,
                                                     devices=devs)
            strat = fleet.DistributedStrategy()
            strat.amp = True
            strat.gradient_merge = True
            strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
            try:
                with static.program_guard(static.Program()):
                    x, y, h, loss = _mlp_program()
                    opt = fleet.distributed_optimizer(
                        paddle.optimizer.SGD(learning_rate=0.05),
                        strategy=strat)
                    opt.minimize(loss)
                    exe = static.Executor()
                    losses = []
                    for _ in range(8):
                        (lv,) = exe.run(feed={"x": X, "y": Y},
                                        fetch_list=[loss])
                        losses.append(float(lv))
            finally:
                dist.set_hybrid_communicate_group(None)
            return losses

        serial = run(False)
        mp = run(True)
        # bf16 compute: slightly looser tolerance than the f32 parity
        np.testing.assert_allclose(serial, mp, rtol=2e-2, atol=1e-3)
        assert mp[-1] < 0.7 * mp[0]
