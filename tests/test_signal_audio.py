"""paddle.signal (stft/istft) and paddle.audio numerics vs scipy/librosa-style
references (SURVEY.md §4 op-test pattern: NumPy reference + tolerance)."""

import numpy as np
import pytest
import scipy.signal as sps

import paddle_tpu as paddle
from paddle_tpu import signal as psignal
from paddle_tpu.audio import functional as AF
from paddle_tpu.audio import Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC


def _sig(n=2048, ch=1, seed=0):
    rng = np.random.RandomState(seed)
    t = np.arange(n) / 16000.0
    x = (np.sin(2 * np.pi * 440 * t) + 0.5 * np.sin(2 * np.pi * 880 * t)
         + 0.1 * rng.randn(n)).astype(np.float32)
    return np.tile(x, (ch, 1)) if ch > 1 else x[None, :]


class TestWindows:
    @pytest.mark.parametrize("name", ["hann", "hamming", "blackman",
                                      "bartlett", "cosine", "bohman",
                                      "triang", "tukey"])
    def test_matches_scipy(self, name):
        n = 128
        ours = AF.get_window(name, n, fftbins=True).numpy()
        ref = sps.get_window(name, n, fftbins=True)
        np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-7)

    def test_gaussian_kaiser(self):
        ours = AF.get_window(("gaussian", 7.0), 64, fftbins=False).numpy()
        ref = sps.get_window(("gaussian", 7.0), 64, fftbins=False)
        np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-7)
        ours = AF.get_window(("kaiser", 12.0), 64, fftbins=True).numpy()
        ref = sps.get_window(("kaiser", 12.0), 64, fftbins=True)
        np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-7)


class TestStft:
    def test_matches_scipy_stft(self):
        x = _sig()
        n_fft, hop = 256, 64
        win = AF.get_window("hann", n_fft)
        out = psignal.stft(paddle.to_tensor(x), n_fft, hop_length=hop,
                           window=win, center=True).numpy()[0]
        _, _, ref = sps.stft(x[0], nperseg=n_fft, noverlap=n_fft - hop,
                             window="hann", boundary="even",
                             padded=False, return_onesided=True)
        # scipy normalizes by window sum; rescale
        ref = ref * np.sum(sps.get_window("hann", n_fft))
        n = min(out.shape[-1], ref.shape[-1])
        np.testing.assert_allclose(out[:, :n], ref[:, :n], rtol=1e-4,
                                   atol=1e-3)

    def test_istft_roundtrip(self):
        x = _sig(n=1600)
        n_fft, hop = 256, 64
        win = AF.get_window("hann", n_fft)
        sp = psignal.stft(paddle.to_tensor(x), n_fft, hop_length=hop,
                          window=win, center=True)
        rec = psignal.istft(sp, n_fft, hop_length=hop, window=win,
                            center=True, length=x.shape[-1]).numpy()
        np.testing.assert_allclose(rec[0], x[0], rtol=1e-4, atol=1e-4)


class TestMel:
    def test_hz_mel_roundtrip(self):
        for htk in (False, True):
            for hz in (60.0, 440.0, 4000.0):
                mel = AF.hz_to_mel(hz, htk=htk)
                back = AF.mel_to_hz(mel, htk=htk)
                assert abs(back - hz) < 1e-3 * max(hz, 1.0)

    def test_fbank_shape_and_coverage(self):
        fb = AF.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        # every filter has some support
        assert (fb.sum(axis=1) > 0).all()

    def test_power_to_db(self):
        s = paddle.to_tensor(np.array([1.0, 10.0, 100.0], np.float32))
        db = AF.power_to_db(s, top_db=None).numpy()
        np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-5)


class TestFeatureLayers:
    def test_spectrogram_shape(self):
        sp = Spectrogram(n_fft=256, hop_length=128)
        out = sp(paddle.to_tensor(_sig()))
        assert out.shape[1] == 129  # n_fft//2+1
        assert np.isfinite(out.numpy()).all()

    def test_melspectrogram_and_log(self):
        mel = MelSpectrogram(sr=16000, n_fft=256, hop_length=128, n_mels=32,
                             f_min=0.0)
        out = mel(paddle.to_tensor(_sig()))
        assert out.shape[1] == 32
        logmel = LogMelSpectrogram(sr=16000, n_fft=256, hop_length=128,
                                   n_mels=32, f_min=0.0)
        lout = logmel(paddle.to_tensor(_sig()))
        assert lout.shape == out.shape
        assert np.isfinite(lout.numpy()).all()

    def test_mfcc_shape(self):
        mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=256, hop_length=128,
                    n_mels=32, f_min=0.0)
        out = mfcc(paddle.to_tensor(_sig()))
        assert out.shape[1] == 13
        assert np.isfinite(out.numpy()).all()

    def test_dct_orthonormal(self):
        d = AF.create_dct(32, 32).numpy()
        np.testing.assert_allclose(d.T @ d, np.eye(32), atol=1e-4)


class TestWindowParamForms:
    def test_taylor_one_param(self):
        # our taylor normalizes by max sample, scipy by the analytic center
        # value — shapes agree to ~5e-4
        ours = AF.get_window(("taylor", 6), 64, fftbins=False).numpy()
        ref = sps.windows.taylor(64, nbar=6, sll=30, sym=True)
        np.testing.assert_allclose(ours, ref, atol=5e-4)

    def test_exponential_center_tau(self):
        ours = AF.get_window(("exponential", None, 3.0), 64,
                             fftbins=False).numpy()
        ref = sps.get_window(("exponential", None, 3.0), 64, fftbins=False)
        np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-8)
