"""paddle.sparse: BCOO-backed COO tensors stay sparse through ops."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.sparse as S


def _coo():
    # [[0, 2, 0], [3, 0, 4]]
    idx = np.array([[0, 1, 1], [1, 0, 2]], np.int64)
    vals = np.array([2.0, 3.0, 4.0], np.float32)
    return S.sparse_coo_tensor(paddle.to_tensor(idx), paddle.to_tensor(vals),
                               [2, 3])


class TestSparseCoo:
    def test_construction_and_dense(self):
        t = _coo()
        assert t.nnz() == 3
        np.testing.assert_allclose(t.to_dense().numpy(),
                                   [[0, 2, 0], [3, 0, 4]])
        np.testing.assert_allclose(t.values().numpy(), [2, 3, 4])
        assert t.indices().numpy().shape == (2, 3)

    def test_csr_construction(self):
        t = S.sparse_csr_tensor(paddle.to_tensor(np.array([0, 1, 3], np.int64)),
                                paddle.to_tensor(np.array([1, 0, 2], np.int64)),
                                paddle.to_tensor(np.array([2.0, 3.0, 4.0],
                                                          np.float32)),
                                [2, 3])
        np.testing.assert_allclose(t.to_dense().numpy(),
                                   [[0, 2, 0], [3, 0, 4]])

    def test_sparse_matmul_no_densify(self):
        t = _coo()
        d = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(3, 2))
        out = S.matmul(t, d)
        ref = t.to_dense().numpy() @ d.numpy()
        np.testing.assert_allclose(out.numpy(), ref, atol=1e-6)
        # the sparse operand's dense cache was never built by matmul
        t2 = _coo()
        S.matmul(t2, d)
        assert t2._dense_cache is None

    def test_sparse_add(self):
        a, b = _coo(), _coo()
        out = S.add(a, b)
        assert isinstance(out, S.SparseCooTensor)
        np.testing.assert_allclose(out.to_dense().numpy(),
                                   2 * a.to_dense().numpy())

    def test_zero_preserving_unary(self):
        t = _coo()
        out = S.relu(S.neg(t))
        assert isinstance(out, S.SparseCooTensor)
        np.testing.assert_allclose(out.to_dense().numpy(), 0.0)
        s = S.sin(t)
        np.testing.assert_allclose(s.values().numpy(),
                                   np.sin([2.0, 3.0, 4.0]), rtol=1e-6)

    def test_scalar_multiply_stays_sparse(self):
        t = _coo()
        out = S.multiply(t, 2.0)
        assert isinstance(out, S.SparseCooTensor)
        np.testing.assert_allclose(out.values().numpy(), [4, 6, 8])

    def test_masked_matmul_sddmm(self):
        rng = np.random.RandomState(0)
        a = rng.randn(4, 5).astype(np.float32)
        b = rng.randn(5, 3).astype(np.float32)
        idx = np.array([[0, 2, 3], [1, 0, 2]], np.int64)
        mask = S.sparse_coo_tensor(paddle.to_tensor(idx),
                                   paddle.to_tensor(np.ones(3, np.float32)),
                                   [4, 3])
        out = S.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b), mask)
        full = a @ b
        np.testing.assert_allclose(out.values().numpy(),
                                   full[idx[0], idx[1]], rtol=1e-5)

    def test_coalesce(self):
        idx = np.array([[0, 0], [1, 1]], np.int64)  # duplicate entry
        vals = np.array([1.0, 2.0], np.float32)
        t = S.sparse_coo_tensor(paddle.to_tensor(idx),
                                paddle.to_tensor(vals), [2, 2])
        c = t.coalesce()
        np.testing.assert_allclose(c.to_dense().numpy(), [[0, 3], [0, 0]])

    def test_dense_tensor_interop(self):
        # plain Tensor ops touch the lazy dense view
        t = _coo()
        out = paddle.sum(t)
        np.testing.assert_allclose(float(out), 9.0)


class TestSparseReviewRegressions:
    def test_inplace_mutation_syncs_bcoo(self):
        t = _coo()
        t.add_(1.0)
        # both views agree post-mutation (zeros became 1.0 too — dense add_)
        np.testing.assert_allclose(t.to_dense().numpy(),
                                   [[1, 3, 1], [4, 1, 5]])
        assert paddle.sum(t).numpy() == t.to_dense().numpy().sum()

    def test_add_shape_mismatch_raises(self):
        import pytest as _pytest

        idx = np.array([[0], [0]], np.int64)
        small = S.sparse_coo_tensor(paddle.to_tensor(idx),
                                    paddle.to_tensor(np.ones(1, np.float32)),
                                    [1, 1])
        with _pytest.raises(ValueError, match="shape mismatch"):
            S.add(_coo(), small)

    def test_batched_sparse_matmul(self):
        # sparse [2,3] @ dense [3] (vector) and vs dense reference
        t = _coo()
        v = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        out = S.matmul(t, v)
        np.testing.assert_allclose(out.numpy(),
                                   t.to_dense().numpy() @ v.numpy(),
                                   atol=1e-6)

    def test_trainable_invariant(self):
        t = _coo()
        assert t.stop_gradient and not t.trainable

    def test_sparse_add_under_jit(self):
        import jax

        a, b = _coo(), _coo()

        def f(da, ia, db, ib):
            import paddle_tpu.sparse as SS
            from jax.experimental import sparse as jsp

            xa = SS._wrap(jsp.BCOO((da, ia), shape=(2, 3)))
            xb = SS._wrap(jsp.BCOO((db, ib), shape=(2, 3)))
            return SS.add(xa, xb).bcoo.todense()

        out = jax.jit(f)(a.bcoo.data, a.bcoo.indices,
                         b.bcoo.data, b.bcoo.indices)
        np.testing.assert_allclose(np.asarray(out),
                                   2 * a.to_dense().numpy())

    def test_batched_rhs_rejected(self):
        import pytest as _pytest

        t = _coo()
        dense3 = paddle.to_tensor(np.zeros((4, 3, 2), np.float32))
        with _pytest.raises(NotImplementedError, match="1-D or 2-D"):
            S.matmul(t, dense3)

    def test_dense_fallback_unary(self):
        d = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
        np.testing.assert_allclose(S.relu(d).numpy(), [0.0, 2.0])
        np.testing.assert_allclose(S.tanh(d).numpy(), np.tanh([-1.0, 2.0]),
                                   rtol=1e-6)

    def test_sparse_sparse_multiply_stays_sparse(self):
        # elementwise sparse*sparse at the index intersection (ADVICE r1)
        a = _coo()
        idx = np.array([[0, 1], [1, 1]], np.int64)  # overlaps a at (0,1) only
        vals = np.array([5.0, 7.0], np.float32)
        b = S.sparse_coo_tensor(paddle.to_tensor(idx), paddle.to_tensor(vals),
                                [2, 3])
        out = S.multiply(a, b)
        assert isinstance(out, S.SparseCooTensor)
        ref = a.to_dense().numpy() * b.to_dense().numpy()
        np.testing.assert_allclose(out.to_dense().numpy(), ref)

    def test_dense_setter_traceable_under_jit(self):
        # assigning a traced dense value must not crash on concrete-nse
        # derivation (ADVICE r1): static full-size bound keeps it traceable
        import jax

        t = _coo()

        def f(dense):
            tt = S.sparse_coo_tensor(
                paddle.to_tensor(np.array([[0], [0]], np.int64)),
                paddle.to_tensor(np.array([1.0], np.float32)), [2, 3])
            tt._data = dense
            return tt.bcoo.todense()

        out = jax.jit(f)(t.to_dense()._data)
        np.testing.assert_allclose(np.asarray(out), t.to_dense().numpy())
