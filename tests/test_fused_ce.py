"""Chunked fused linear+CE: numerics and gradient parity vs the unfused
materialise-the-logits path (SURVEY.md §7.4 sharded/fused softmax-CE)."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy


def _ref_ce(h, w, y, ignore_index=-100, transpose_weight=False):
    logits = (h @ (w.T if transpose_weight else w)).astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    valid = y != ignore_index
    safe = jnp.where(valid, y, 0)
    true_logit = jnp.take_along_axis(logits, safe[:, None], -1)[:, 0]
    loss = jnp.where(valid, lse - true_logit, 0.0)
    return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)


class TestFusedLinearCE:
    def _data(self, n=96, h=32, v=200, seed=0, ignored=True):
        rng = np.random.RandomState(seed)
        hid = jnp.asarray(rng.randn(n, h).astype(np.float32) * 0.3)
        w = jnp.asarray(rng.randn(h, v).astype(np.float32) * 0.1)
        y = rng.randint(0, v, n)
        if ignored:
            y[:7] = -100
        return hid, w, jnp.asarray(y, jnp.int32)

    def test_forward_matches_reference(self):
        hid, w, y = self._data()
        out = fused_linear_cross_entropy(hid, w, y, chunk_rows=32)
        ref = _ref_ce(hid, w, y)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6)

    def test_grads_match_reference(self):
        hid, w, y = self._data()
        gf = jax.grad(lambda h_, w_: fused_linear_cross_entropy(
            h_, w_, y, chunk_rows=32), argnums=(0, 1))(hid, w)
        gr = jax.grad(lambda h_, w_: _ref_ce(h_, w_, y),
                      argnums=(0, 1))(hid, w)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_transposed_weight_tied_embedding_layout(self):
        hid, w, y = self._data()
        wt = w.T  # [V, H] tied-embedding layout
        out = fused_linear_cross_entropy(hid, wt, y, chunk_rows=32,
                                         transpose_weight=True)
        ref = _ref_ce(hid, w, y)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6)

    def test_non_divisible_rows_padded(self):
        hid, w, y = self._data(n=101)  # prime: forces the padding path
        out = fused_linear_cross_entropy(hid, w, y, chunk_rows=32)
        ref = _ref_ce(hid, w, y)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6)

    def test_sum_reduction_and_all_ignored(self):
        hid, w, y = self._data()
        s = fused_linear_cross_entropy(hid, w, y, chunk_rows=32,
                                       reduction="sum")
        valid = np.asarray(y) != -100
        per_mean = np.asarray(fused_linear_cross_entropy(hid, w, y,
                                                         chunk_rows=32))
        np.testing.assert_allclose(np.asarray(s), per_mean * valid.sum(),
                                   rtol=1e-6)
        y_ign = jnp.full_like(y, -100)
        out = fused_linear_cross_entropy(hid, w, y_ign, chunk_rows=32)
        np.testing.assert_allclose(np.asarray(out), 0.0)

    def test_bf16_hidden_f32_accumulate(self):
        hid, w, y = self._data()
        out = fused_linear_cross_entropy(hid.astype(jnp.bfloat16),
                                         w.astype(jnp.bfloat16), y,
                                         chunk_rows=32)
        ref = _ref_ce(hid, w, y)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-2)

    def test_jit_traceable(self):
        hid, w, y = self._data()
        f = jax.jit(lambda h_, w_, y_: fused_linear_cross_entropy(
            h_, w_, y_, chunk_rows=32))
        np.testing.assert_allclose(np.asarray(f(hid, w, y)),
                                   np.asarray(_ref_ce(hid, w, y)), rtol=1e-6)


class TestModelFusedLoss:
    def test_gpt_fused_vs_unfused_loss_and_grads(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
                   num_hidden_layers=2, num_attention_heads=4,
                   max_position_embeddings=64)
        paddle.seed(0)
        m1 = GPTForCausalLM(GPTConfig(**cfg))
        paddle.seed(0)
        m2 = GPTForCausalLM(GPTConfig(**cfg, fused_lm_loss=True))

        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int32))
        loss1, logits = m1(ids, labels=ids)
        loss2, none = m2(ids, labels=ids)
        assert none is None
        np.testing.assert_allclose(loss1.numpy(), loss2.numpy(), rtol=1e-5)

        loss1.backward()
        loss2.backward()
        g1 = m1.model.embed_tokens.weight.grad.numpy()
        g2 = m2.model.embed_tokens.weight.grad.numpy()
        np.testing.assert_allclose(g1, g2, rtol=1e-4, atol=1e-6)
        h1 = m1.lm_head.weight.grad.numpy()
        h2 = m2.lm_head.weight.grad.numpy()
        np.testing.assert_allclose(h1, h2, rtol=1e-4, atol=1e-6)

    def test_tied_embedding_fused(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
                   num_hidden_layers=1, num_attention_heads=4,
                   max_position_embeddings=64, tie_word_embeddings=True)
        paddle.seed(0)
        m1 = GPTForCausalLM(GPTConfig(**cfg))
        paddle.seed(0)
        m2 = GPTForCausalLM(GPTConfig(**cfg, fused_lm_loss=True))
        ids = paddle.to_tensor(
            np.random.RandomState(1).randint(0, 128, (2, 16)).astype(np.int32))
        loss1, _ = m1(ids, labels=ids)
        loss2, _ = m2(ids, labels=ids)
        np.testing.assert_allclose(loss1.numpy(), loss2.numpy(), rtol=1e-5)
