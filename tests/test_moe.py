"""MoE tests: gate capacity/dispatch invariants + expert-parallel all_to_all
parity with the single-device layer (SURVEY.md §4 pattern)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from paddle_tpu.distributed.shard_map_compat import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.incubate.distributed.models.moe import (
    GShardGate, MoELayer, NaiveGate, SwitchGate,
)


class TestGates:
    def _logits(self, t=32, e=4, seed=0):
        return jnp.asarray(np.random.RandomState(seed).randn(t, e), jnp.float32)

    @pytest.mark.parametrize("gate_cls", [SwitchGate, GShardGate, NaiveGate])
    def test_dispatch_shapes_and_capacity(self, gate_cls):
        logits = self._logits()
        gate = gate_cls()
        disp, comb, aux = gate(logits)
        t, e = logits.shape
        assert disp.shape[0] == t and disp.shape[1] == e
        # each buffer slot holds at most one token
        assert float(jnp.max(jnp.sum(disp, axis=0))) <= 1.0 + 1e-6
        # each token occupies at most top_k slots
        assert float(jnp.max(jnp.sum(disp, axis=(1, 2)))) <= gate.top_k + 1e-6
        assert np.isfinite(float(aux))

    def test_switch_top1_weights(self):
        logits = self._logits(16, 4, 1)
        disp, comb, aux = SwitchGate(capacity_factor=4.0)(logits)
        probs = jax.nn.softmax(logits, -1)
        # kept tokens carry their top-1 prob
        w = jnp.sum(comb, axis=(1, 2))
        top1 = jnp.max(probs, axis=-1)
        kept = jnp.sum(disp, axis=(1, 2)) > 0
        np.testing.assert_allclose(np.asarray(w[kept]), np.asarray(top1[kept]),
                                   rtol=1e-6)

    def test_gshard_top2_weights_normalized(self):
        logits = self._logits(16, 8, 2)
        disp, comb, aux = GShardGate(capacity_factor=8.0)(logits)
        w = jnp.sum(comb, axis=(1, 2))
        np.testing.assert_allclose(np.asarray(w), np.ones(16), rtol=1e-5)


class TestMoELayer:
    def test_forward_local(self):
        paddle.seed(0)
        layer = MoELayer(16, 32, 4, gate="switch", capacity_factor=4.0)
        x = paddle.randn([8, 10, 16])
        y = layer(x)
        assert y.shape == [8, 10, 16]
        assert layer.l_aux is not None and np.isfinite(float(layer.l_aux))

    def test_gradients_flow(self):
        paddle.seed(1)
        layer = MoELayer(8, 16, 2, gate="gshard", capacity_factor=4.0)
        x = paddle.randn([4, 6, 8])
        x.stop_gradient = False
        y = layer(x)
        loss = (y * y).sum() + layer.l_aux * 0.01
        loss.backward()
        assert layer.w1.grad is not None
        assert float(jnp.abs(layer.gate_weight.grad._data).sum()) > 0

    def test_expert_parallel_parity(self):
        """all_to_all dispatch over 4 ranks == single-device forward when the
        tokens are identical (replicated input, capacity scaled)."""
        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(dp=4)
        paddle.seed(2)
        layer = MoELayer(8, 16, 4, gate="switch", capacity_factor=16.0,
                         axis_name="dp")
        rng = np.random.RandomState(3)
        x = rng.randn(16, 8).astype(np.float32)  # 16 tokens over 4 ranks
        ref = layer(paddle.Tensor(x)).numpy()

        names = list(layer.state_dict())
        params = [layer.state_dict()[k]._data for k in names]

        def body(xa, *ps):
            with dist.axis_scope("dp"):
                with layer.use_state(dict(zip(names, ps))):
                    out = layer(paddle.Tensor(xa))
            return out._data

        f = shard_map(body, mesh=hcg.mesh,
                      in_specs=(P("dp"),) + tuple(P() for _ in params),
                      out_specs=P("dp"), check_vma=False)
        out = np.asarray(f(x, *params))
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
