"""Vision model zoo forward-shape tests (ref test strategy SURVEY.md §4:
test/legacy_test model tests assert output shapes + train/eval modes)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models as M


def _img(b=1, hw=64):
    rng = np.random.RandomState(0)
    return paddle.to_tensor(rng.randn(b, 3, hw, hw).astype(np.float32))


@pytest.mark.parametrize("ctor,kwargs,hw", [
    (M.mobilenet_v1, dict(num_classes=10), 64),
    (M.mobilenet_v3_small, dict(num_classes=10), 64),
    (M.densenet121, dict(num_classes=10), 64),
    (M.squeezenet1_1, dict(num_classes=10), 64),
    (M.shufflenet_v2_x0_25, dict(num_classes=10), 64),
    (M.inception_v3, dict(num_classes=10), 75),
])
def test_forward_shape(ctor, kwargs, hw):
    model = ctor(**kwargs)
    model.eval()
    out = model(_img(hw=hw))
    assert tuple(out.shape) == (1, 10)
    assert np.isfinite(np.asarray(out._data)).all()


def test_googlenet_eval():
    model = M.googlenet(num_classes=10)
    model.eval()
    out = model(_img(hw=64))
    assert tuple(out.shape) == (1, 10)


def test_googlenet_aux_head():
    # aux heads consume the 14x14 stage-4 feature maps at 224 input; testing
    # them directly on a synthetic map avoids a full 224px forward on CPU
    from paddle_tpu.vision.models.googlenet import InceptionAux

    aux = InceptionAux(512, 10)
    aux.train()
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(1, 512, 14, 14).astype(np.float32))
    out = aux(x)
    assert tuple(out.shape) == (1, 10)


def test_channel_shuffle_roundtrip():
    from paddle_tpu.vision.models.shufflenetv2 import channel_shuffle

    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 4, 2, 2))
    y = channel_shuffle(channel_shuffle(x, 2), 2)
    np.testing.assert_allclose(np.asarray(y._data), np.asarray(x._data))


def test_dense_layer_grad_flows():
    # targeted check that gradient flows through the concat-based dense
    # connectivity (full densenet121 backward is too slow for CI CPU)
    from paddle_tpu.vision.models.densenet import _DenseLayer

    layer = _DenseLayer(8, growth_rate=4, bn_size=2, drop_rate=0.0)
    layer.train()
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 8, 8, 8).astype(np.float32))
    out = layer(x)
    assert out.shape[1] == 12  # input channels + growth_rate
    loss = paddle.mean(out * out)
    loss.backward()
    g = layer.conv1.weight.grad
    assert g is not None
    assert np.isfinite(np.asarray(g._data)).all()
