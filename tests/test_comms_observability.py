"""Distributed observability (phase 4): collective-comms ledger tests.

Covers the jaxpr comms walker against hand-derived censuses for every
MULTICHIP config (on the conftest's 8 virtual CPU devices), the ring
wire-byte model, the eager world-size-1 collective ticks, group-lifecycle
accounting, the /debug/comms + /debug/mesh telemetry routes, pipeline
bubble and expert-load skew gauges, ProgramCard comms sections, and the
check-bench --bench-file override.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import observability as obs
from paddle_tpu.observability import comms
from paddle_tpu.observability import metrics as obs_metrics

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_multichip():
    spec = importlib.util.spec_from_file_location(
        "multichip_comms", os.path.join(_ROOT, "benchmarks",
                                        "multichip_comms.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------- wire model

class TestWireModel:
    def test_world_size_one_is_free(self):
        for op in comms.COLLECTIVE_OPS:
            assert comms.wire_bytes(op, 1, 4096) == 0.0

    def test_ring_allreduce(self):
        # 2(n-1)/n * B
        assert comms.wire_bytes("psum", 8, 16) == pytest.approx(28.0)
        assert comms.wire_bytes("pmax", 4, 100) == pytest.approx(150.0)

    def test_all_gather_counts_shard_bytes(self):
        assert comms.wire_bytes("all_gather", 4, 10) == pytest.approx(30.0)

    def test_scatter_reduce_and_a2a(self):
        assert comms.wire_bytes("psum_scatter", 4, 16) == pytest.approx(12.0)
        assert comms.wire_bytes("all_to_all", 4, 16) == pytest.approx(12.0)

    def test_ppermute_is_one_hop(self):
        assert comms.wire_bytes("ppermute", 8, 123.0) == pytest.approx(123.0)

    def test_modeled_seconds_uses_datasheet(self):
        rep = comms.CommsReport()
        rep.add("psum", "dp", 1, 1 << 30, 8)  # one 1-GiB psum on an 8-ring
        secs = comms.modeled_comms_seconds(rep, "tpu")
        bw = comms.interconnect_bandwidth_gbs("tpu", tier="ici")
        expect = comms.wire_bytes("psum", 8, 1 << 30) / (bw * 1e9)
        assert secs == pytest.approx(expect)


# ------------------------------------------------------ walker vs configs

class TestWalkerCensus:
    """The jaxpr walker must reproduce the hand-derived collective census
    of every MULTICHIP config exactly (the check-bench gate relies on it)."""

    @pytest.fixture(scope="class")
    def mc(self):
        return _load_multichip()

    @pytest.mark.parametrize("name", ["dp8", "dp4xmp2", "pp2_1f1b",
                                      "ring_sep4", "zero3_sharding8",
                                      "moe_ep4", "sharded_decode_tp2"])
    def test_census_exact(self, mc, name):
        fn, args, expected = mc.CONFIGS[name]()
        report = comms.analyze_fn(fn, *args)
        assert report.counts() == expected
        assert report.total_calls == sum(expected.values())
        assert report.unbounded_loops == 0
        # every site resolved its axis size -> nonzero modeled wire bytes
        assert report.total_wire_bytes > 0
        assert not report.unknown_axes

    def test_scan_multiplies_trip_count(self):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import Mesh, PartitionSpec as P

        from paddle_tpu.distributed.shard_map_compat import NO_CHECK, shard_map

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("dp",))

        def body(x):
            def step(c, _):
                return lax.psum(c, "dp"), None
            out, _ = lax.scan(step, x, None, length=5)
            return out

        f = shard_map(body, mesh=mesh, in_specs=P("dp"),
                      out_specs=P("dp"), **NO_CHECK)
        rep = comms.analyze_fn(f, np.ones((4, 8), np.float32))
        assert rep.counts() == {("psum", "dp"): 5}

    def test_report_publish_and_json(self):
        obs.reset()
        rep = comms.CommsReport()
        rep.add("all_gather", "mp", 1, 64, 2)
        rep.publish()
        assert obs_metrics.value("comms.collective_calls",
                                 op="all_gather", axis="mp") == 1
        doc = rep.to_json()
        assert doc["collective_calls"] == 1
        assert doc["by_op_axis"][0]["op"] == "all_gather"
        assert doc["by_op_axis"][0]["axis"] == "mp"


# ----------------------------------------------------- eager world-size-1

class TestEagerCollectiveTicks:
    def test_all_reduce_ticks_psum_world(self):
        # a live HCG (leaked by an earlier test) would re-point the default
        # group at its dp axis; this test asserts the world-size-1 path
        dist.set_hybrid_communicate_group(None)
        obs.reset()
        t = paddle.to_tensor(np.ones((4,), np.float32))
        dist.all_reduce(t)
        assert obs_metrics.value("comms.collective_calls",
                                 op="psum", axis="world") == 1
        # world size 1 -> wire bytes stay 0 under the ring model
        assert obs_metrics.value("comms.wire_bytes",
                                 op="psum", axis="world") == 0

    def test_alltoall_and_shift_tick(self):
        dist.set_hybrid_communicate_group(None)
        obs.reset()
        t = paddle.to_tensor(np.ones((4,), np.float32))
        out = [paddle.to_tensor(np.zeros((4,), np.float32))]
        dist.alltoall(out, [t])
        dist.shift(t, offset=1)
        assert obs_metrics.value("comms.collective_calls",
                                 op="all_to_all", axis="world") == 1
        assert obs_metrics.value("comms.collective_calls",
                                 op="ppermute", axis="world") == 1


# -------------------------------------------------------- group lifecycle

class TestGroupLifecycle:
    def test_create_destroy_cycles_leak_nothing(self):
        from paddle_tpu.distributed import communication as comm

        base_live = len(comm._GROUPS)
        base_created = comm._GROUPS_CREATED
        providers_before = len(obs_metrics.default_registry()._providers) \
            if hasattr(obs_metrics, "default_registry") else None
        for _ in range(3):
            g = comm.new_group(axis_name="dp")
            assert len(comm._GROUPS) == base_live + 1
            comm.destroy_process_group(g)
            assert len(comm._GROUPS) == base_live
        assert comm._GROUPS_CREATED == base_created + 3
        snap = comm._groups_provider()
        assert snap["live_groups"] == base_live
        assert snap["created_total"] == base_created + 3
        if providers_before is not None:
            assert len(obs_metrics.default_registry()._providers) \
                == providers_before

    def test_groups_provider_in_exposition(self):
        text = obs_metrics.render_prometheus()
        assert "distributed" in text and "groups" in text
        # returns the sample count; raises ValueError on any violation
        assert obs_metrics.validate_exposition(text) > 0


# ------------------------------------------------------- telemetry routes

class TestMeshTelemetry:
    def test_debug_comms_route(self):
        from paddle_tpu.observability.server import TelemetryServer

        obs.reset()
        comms.record_collective("psum", "dp", world_size=8, operand_bytes=16)
        srv = TelemetryServer(port=0)
        status, ctype, body = srv.handle("/debug/comms")
        assert status == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert doc["collective_calls_total"] >= 1
        assert "interconnect_gbs" in doc
        _, _, idx = srv.handle("/")
        eps = json.loads(idx)["endpoints"]
        assert "/debug/comms" in eps and "/debug/mesh" in eps

    def test_debug_mesh_route_no_hcg(self):
        from paddle_tpu.observability.server import TelemetryServer

        dist.set_hybrid_communicate_group(None)
        srv = TelemetryServer(port=0)
        status, _, body = srv.handle("/debug/mesh")
        assert status == 200
        assert json.loads(body)["mesh"]["initialized"] is False

    def test_mesh_snapshot_with_hcg(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet import DistributedStrategy

        dist.set_hybrid_communicate_group(None)
        try:
            s = DistributedStrategy()
            s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                "pp_degree": 2}
            fleet.init(is_collective=True, strategy=s)
            snap = comms.mesh_snapshot()
            assert snap["initialized"] is True
            assert snap["world_size"] == 8
            dims = {a["name"]: a["dim"] for a in snap["axes"]}
            assert dims.get("data") == 2 and dims.get("pipe") == 2
            meta = comms.mesh_meta()
            assert meta and meta.get("world_size") == 8
        finally:
            dist.set_hybrid_communicate_group(None)

    def test_comms_families_validate(self):
        obs.reset()
        comms.record_collective("all_gather", "sharding", world_size=8,
                                operand_bytes=1024)
        text = obs_metrics.render_prometheus()
        assert "comms" in text
        assert obs_metrics.validate_exposition(text) > 0


# ------------------------------------------------------------ skew gauges

class TestSkewGauges:
    def test_pipeline_bubble_formulas(self):
        obs.reset()
        # gpipe S=4 M=8: T=11, bubble 3/11
        b = comms.publish_pipeline_schedule("gpipe", 4, 8)
        assert b == pytest.approx(3 / 11)
        # 1f1b S=4 M=8: T=8+2*3=14, bubble 6/14
        b = comms.publish_pipeline_schedule("1f1b", 4, 8)
        assert b == pytest.approx(6 / 14)
        # interleaved S=4 V=2 M=8: D=8, T=15, bubble 7/15
        b = comms.publish_pipeline_schedule("interleaved", 4, 8, virtual=2)
        assert b == pytest.approx(7 / 15)
        assert obs_metrics.value("comms.pipeline_bubble_ratio",
                                 schedule="interleaved") \
            == pytest.approx(7 / 15)

    def test_expert_load_imbalance(self):
        obs.reset()
        imb = comms.observe_expert_load(np.array([3.0, 1.0]), layer="l0")
        assert imb == pytest.approx(1.5)
        assert obs_metrics.value("comms.moe_expert_load_imbalance",
                                 layer="l0") == pytest.approx(1.5)
        assert comms.observe_expert_load(np.zeros((4,))) is None

    def test_moe_layer_records_tokens_per_expert(self):
        from paddle_tpu.incubate.distributed.models.moe import MoELayer

        layer = MoELayer(d_model=8, d_hidden=16, num_experts=4)
        x = paddle.to_tensor(np.random.default_rng(0).standard_normal(
            (16, 8)).astype(np.float32))
        layer(x)
        tok = layer.tokens_per_expert
        assert tok is not None
        imb = comms.observe_expert_load(tok, layer="moe_test")
        assert imb is None or imb >= 1.0


# ------------------------------------------------- program cards + gating

class TestCardsAndGate:
    def test_program_card_comms_section(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.observability import profiling

        rep = comms.CommsReport()
        rep.add("psum", "dp", 1, 256, 8)
        f = jax.jit(lambda x: x * 2)
        lowered = f.lower(jnp.ones((4,), jnp.float32))
        try:
            card = profiling.capture("test.comms_card", "rk", lowered,
                                     backend="cpu", comms=rep)
            doc = card.to_json()
            assert doc["comms"]["collective_calls"] == 1
            assert doc["comms"]["by_op_axis"][0]["op"] == "psum"
        finally:
            profiling.clear()

    def test_check_bench_bench_file_override(self, tmp_path):
        from paddle_tpu.observability import regression

        row = {"metric": "multichip comms fake step (cpu8)", "value": 1.0,
               "unit": "ms", "psum_calls": 2, "collective_calls_total": 2}
        alt = tmp_path / "alt_bench.json"
        alt.write_text(json.dumps({"results": [row]}))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps({"results": [dict(row, value=1.1)]}))
        rep = regression.check_bench("/nonexistent/baseline.json",
                                     str(fresh), tolerance=0.25,
                                     bench_file=str(alt))
        assert rep["ok"] and rep["bench_file"] == str(alt)
        # deterministic field drift must fail exactly
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"results": [dict(row, psum_calls=3)]}))
        rep = regression.check_bench("/nonexistent/baseline.json",
                                     str(bad), tolerance=0.25,
                                     bench_file=str(alt))
        assert not rep["ok"]

    def test_committed_multichip_bench_schema(self):
        path = os.path.join(_ROOT, "MULTICHIP_BENCH.json")
        with open(path) as f:
            doc = json.load(f)
        rows = doc["results"]
        assert len(rows) >= 6
        for row in rows:
            assert row["schema_version"] == 1
            assert row["git_sha"] and row["run_id"] >= 1
            assert row["collective_calls_total"] >= 1

    def test_chrome_trace_carries_mesh_meta(self):
        from paddle_tpu.observability import events as obs_events

        doc = json.loads(obs_events.export_chrome_trace())
        assert "mesh" in doc.get("metadata", {})
