"""ZeRO/group_sharded tests (SURVEY.md §4: parity-vs-serial invariant on the
8-device CPU mesh)."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.sharding import (
    GroupShardedTrainStep,
    group_sharded_parallel,
    sharding_spec_for,
)
from paddle_tpu.jit.train_step import TrainStep


def _mlp():
    paddle.seed(42)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))


def _loss_fn(model, x, y):
    return nn.functional.mse_loss(model(x), y)


def _batch(n=16):
    rng = np.random.RandomState(0)
    return (rng.randn(n, 16).astype(np.float32),
            rng.randn(n, 8).astype(np.float32))


def _run(step, n=3):
    x, y = _batch()
    for _ in range(n):
        loss = step(paddle.to_tensor(x), paddle.to_tensor(y))
    return float(loss)


class TestShardingSpec:
    def test_prefers_first_divisible_dim(self):
        assert sharding_spec_for((32, 8), 8) == P("sharding")
        assert sharding_spec_for((6, 16), 8) == P(None, "sharding")
        assert sharding_spec_for((3, 5), 8) == P()


@pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
class TestZeroParity:
    def test_matches_serial(self, level):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(sharding=8)

        model_ref = _mlp()
        opt_ref = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=model_ref.parameters())
        ref_loss = _run(TrainStep(model_ref, _loss_fn, opt_ref))

        model = _mlp()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        step = GroupShardedTrainStep(model, _loss_fn, opt, level=level)
        loss = _run(step)

        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
        for (n, p), (_, pr) in zip(model.named_parameters(),
                                   model_ref.named_parameters()):
            np.testing.assert_allclose(np.asarray(p._data), np.asarray(pr._data),
                                       rtol=1e-5, atol=1e-6, err_msg=n)


class TestPlacement:
    def test_stage3_params_sharded(self):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(sharding=8)
        model = _mlp()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        step = GroupShardedTrainStep(model, _loss_fn, opt, level="p_g_os")
        _run(step, n=1)
        w = model.state_dict()["0.weight"]  # [16, 32]
        spec = w._data.sharding.spec
        assert "sharding" in str(spec)

    def test_stage1_params_replicated_states_sharded(self):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(sharding=8)
        model = _mlp()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        step = GroupShardedTrainStep(model, _loss_fn, opt, level="os")
        _run(step, n=1)
        sd = model.state_dict()
        w = sd["0.weight"]
        assert "sharding" not in str(w._data.sharding.spec)
        st = opt._accumulators[id(w)]
        leaves = jax.tree.leaves(st)
        assert any("sharding" in str(l.sharding.spec) for l in leaves
                   if hasattr(l, "sharding") and np.ndim(l) > 0)


class TestGroupShardedParallel:
    def test_api_and_train_step(self, tmp_path):
        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(sharding=8)
        model = _mlp()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        wrapped, opt2, scaler = group_sharded_parallel(model, opt, "p_g_os")
        x, y = _batch()
        out = wrapped(paddle.to_tensor(x))
        assert out.shape == [16, 8]
        step = wrapped.build_train_step(_loss_fn)
        l1 = _run(step, n=2)
        assert np.isfinite(l1)
        from paddle_tpu.distributed.sharding import save_group_sharded_model
        save_group_sharded_model(wrapped, str(tmp_path), optimizer=opt2)
        import os
        assert os.path.exists(os.path.join(str(tmp_path), "model.pdparams"))
