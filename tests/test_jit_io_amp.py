"""to_static / TrainStep / io / amp tests (SURVEY.md §4 dy2static pattern:
eager vs compiled parity)."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestToStatic:
    def test_eager_static_parity(self):
        def fn(x, y):
            return paddle.tanh(x) @ y + x.sum()

        static_fn = paddle.jit.to_static(fn)
        a, b = paddle.randn([4, 4]), paddle.randn([4, 4])
        np.testing.assert_allclose(static_fn(a, b).numpy(), fn(a, b).numpy(), rtol=1e-5, atol=1e-6)

    def test_cache_by_shape(self):
        calls = []

        @paddle.jit.to_static
        def fn(x):
            calls.append(1)
            return x * 2

        fn(paddle.ones([2, 3]))
        fn(paddle.ones([2, 3]))
        assert len(calls) == 1  # traced once
        fn(paddle.ones([4, 3]))
        assert len(calls) == 2  # retraced on new shape

    def test_layer_to_static_updates_buffers(self):
        bn = nn.BatchNorm1D(4)
        bn = paddle.jit.to_static(bn)
        x = paddle.randn([8, 4]) * 3 + 1
        bn(x)
        assert abs(float(bn._mean.numpy().mean())) > 1e-4  # running stats moved

    def test_randomness_varies_across_calls(self):
        drop = nn.Dropout(0.5)
        drop = paddle.jit.to_static(drop)
        x = paddle.ones([100])
        a = drop(x).numpy()
        b = drop(x).numpy()
        assert not np.array_equal(a, b)  # rng key threaded per call

    def test_alternating_state_signatures_keep_own_captures(self):
        """ADVICE r5: one StaticFunction cache entry holds several jax.jit
        traces when the STATE changes aval (inputs identical, so _spec_key
        matches) — e.g. amp rebinding a param's dtype. The out-tree /
        mutation capture must be keyed per trace signature: with the old
        single last-trace box, alternating calls applied the most recent
        trace's output structure to the other signature's results."""
        import jax.numpy as jnp

        class DtypeDependent(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)

            def forward(self, x):
                y = self.lin(x)
                # trace-time static on the PARAM dtype, not the input:
                # both traces live under one _spec_key cache entry
                if str(self.lin.weight.dtype) == "float32":
                    return y
                return {"out": y, "casted": True}

        layer = paddle.jit.to_static(DtypeDependent())
        x = paddle.ones([2, 4])
        out_f32 = layer(x)
        assert isinstance(out_f32, paddle.Tensor)
        w = layer.lin.weight
        w32 = w._data
        w._data = w32.astype(jnp.bfloat16)
        out_bf16 = layer(x)
        assert isinstance(out_bf16, dict) and out_bf16["casted"] is True
        # flip back: the f32 trace's capture must be found again
        w._data = w32
        again = layer(x)
        assert isinstance(again, paddle.Tensor)
        np.testing.assert_array_equal(again.numpy(), out_f32.numpy())
        # and forward once more on the bf16 signature
        w._data = w32.astype(jnp.bfloat16)
        assert isinstance(layer(x), dict)

    def test_jit_save_load_roundtrip(self, tmp_path):
        layer = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        layer.eval()
        path = str(tmp_path / "model")
        paddle.jit.save(layer, path, input_spec=[paddle.jit.InputSpec([1, 4])])
        loaded = paddle.jit.load(path)
        x = paddle.randn([1, 4])
        np.testing.assert_allclose(loaded(x).numpy(), layer(x).numpy(), rtol=1e-5, atol=1e-6)


class TestTrainStep:
    def test_matches_eager_training(self):
        paddle.seed(3)
        X = np.random.RandomState(0).rand(32, 4).astype(np.float32)
        Y = X.sum(-1, keepdims=True)

        def build():
            paddle.seed(7)
            m = nn.Linear(4, 1)
            o = paddle.optimizer.Adam(learning_rate=0.1, parameters=m.parameters())
            return m, o

        # eager
        m1, o1 = build()
        for _ in range(5):
            loss = F.mse_loss(m1(paddle.to_tensor(X)), paddle.to_tensor(Y))
            loss.backward()
            o1.step()
            o1.clear_grad()
        # jitted
        m2, o2 = build()
        step = paddle.jit.TrainStep(m2, lambda net, x, y: F.mse_loss(net(x), y), o2)
        for _ in range(5):
            step(paddle.to_tensor(X), paddle.to_tensor(Y))
        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(), rtol=1e-4, atol=1e-5)

    def test_to_static_model_trains_with_eager_backward(self):
        """Paddle parity: `model = to_static(model); loss.backward();
        opt.step()` — the jitted forward records as ONE tape node whose
        vjp flows grads to the parameters."""
        X = np.random.RandomState(0).rand(32, 4).astype(np.float32)
        Y = X.sum(-1, keepdims=True)

        def build():
            paddle.seed(7)
            m = nn.Linear(4, 1)
            o = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=m.parameters())
            return m, o

        m1, o1 = build()                       # eager reference
        for _ in range(5):
            loss = F.mse_loss(m1(paddle.to_tensor(X)), paddle.to_tensor(Y))
            loss.backward()
            o1.step()
            o1.clear_grad()
        m2, o2 = build()
        paddle.jit.to_static(m2)               # jitted forward, eager loop
        for _ in range(5):
            loss = F.mse_loss(m2(paddle.to_tensor(X)), paddle.to_tensor(Y))
            loss.backward()
            o2.step()
            o2.clear_grad()
        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                                   rtol=1e-5, atol=1e-6)
        # grads also flow to differentiable INPUTS through the jit node
        x = paddle.to_tensor(X)
        x.stop_gradient = False
        m2(x).sum().backward()
        assert x.grad is not None and x.grad.shape == [32, 4]

    def test_many_matches_sequential_steps(self):
        """many(K): one scanned program == K sequential __call__s (same
        updates, K× fewer dispatches — the tunnel-latency amortizer)."""
        rng = np.random.RandomState(1)
        batches = [(paddle.to_tensor(rng.rand(16, 4).astype(np.float32)),
                    paddle.to_tensor(rng.rand(16, 1).astype(np.float32)))
                   for _ in range(4)]

        def build():
            paddle.seed(11)
            m = nn.Linear(4, 1)
            o = paddle.optimizer.Adam(learning_rate=0.05,
                                      parameters=m.parameters())
            return m, o

        m1, o1 = build()
        step1 = paddle.jit.TrainStep(m1, lambda net, x, y: F.mse_loss(net(x), y), o1)
        seq_losses = [float(step1(*b)) for b in batches]
        m2, o2 = build()
        step2 = paddle.jit.TrainStep(m2, lambda net, x, y: F.mse_loss(net(x), y), o2)
        many_losses = step2.many(batches).numpy()
        np.testing.assert_allclose(many_losses, seq_losses, rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                                   rtol=1e-5, atol=1e-6)
        assert o2._step_count == 4

    def test_grad_clip_inside_step(self):
        m = nn.Linear(4, 1)
        o = paddle.optimizer.SGD(learning_rate=1.0, parameters=m.parameters(),
                                 grad_clip=nn.ClipGradByGlobalNorm(0.01))
        step = paddle.jit.TrainStep(m, lambda net, x, y: F.mse_loss(net(x), y) * 1000, o)
        w0 = m.weight.numpy().copy()
        step(paddle.randn([8, 4]), paddle.randn([8, 1]))
        delta = np.linalg.norm(
            np.concatenate([(m.weight.numpy() - w0).ravel(),
                            (m.bias.numpy() - 0 * m.bias.numpy()).ravel() * 0])
        )
        assert delta < 0.02  # bounded by clip * lr plus bias


class TestIO:
    def test_dataloader_shapes_order(self):
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 10

            def __getitem__(self, i):
                return np.full((2,), i, np.float32), i

        dl = DataLoader(DS(), batch_size=3, drop_last=False)
        batches = list(dl)
        assert len(batches) == 4
        assert batches[0][0].shape == [3, 2]
        assert batches[-1][0].shape == [1, 2]
        np.testing.assert_array_equal(batches[0][1].numpy(), [0, 1, 2])

    def test_threaded_loader_preserves_order(self):
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 50

            def __getitem__(self, i):
                import time

                time.sleep(0.001 * (i % 3))
                return np.asarray([i], np.float32)

        dl = DataLoader(DS(), batch_size=5, num_workers=3)
        got = np.concatenate([b.numpy().ravel() for b in dl])
        np.testing.assert_array_equal(got, np.arange(50, dtype=np.float32))

    def test_distributed_batch_sampler_partitions(self):
        from paddle_tpu.io import DistributedBatchSampler, Dataset

        class DS(Dataset):
            def __len__(self):
                return 12

            def __getitem__(self, i):
                return i

        seen = []
        for rank in range(3):
            s = DistributedBatchSampler(DS(), batch_size=2, num_replicas=3, rank=rank)
            for batch in s:
                seen.extend(batch)
        assert sorted(seen) == list(range(12))

    def test_random_split_and_concat(self):
        from paddle_tpu.io import random_split, ConcatDataset, TensorDataset

        ds = TensorDataset([paddle.arange(10).reshape([10, 1])])
        a, b = random_split(ds, [7, 3])
        assert len(a) == 7 and len(b) == 3
        cat = ConcatDataset([a, b])
        assert len(cat) == 10


class TestAmp:
    def test_autocast_matmul_bf16(self):
        a = paddle.randn([4, 4])
        with paddle.amp.auto_cast(dtype="bfloat16", level="O1"):
            out = paddle.matmul(a, a)
        assert out.dtype == paddle.bfloat16

    def test_blacklist_stays_fp32(self):
        a = paddle.randn([4, 4])
        with paddle.amp.auto_cast(dtype="bfloat16", level="O1"):
            out = F.softmax(a)
        assert out.dtype == paddle.float32

    def test_o2_decorate_casts_params(self):
        m = nn.Linear(4, 4)
        opt = paddle.optimizer.Adam(parameters=m.parameters())
        m, opt = paddle.amp.decorate(m, opt, level="O2", dtype="bfloat16")
        assert m.weight.dtype == paddle.bfloat16
        assert opt._multi_precision

    def test_grad_flows_through_autocast(self):
        m = nn.Linear(4, 4)
        x = paddle.randn([2, 4])
        with paddle.amp.auto_cast(dtype="bfloat16"):
            out = m(x).sum()
        out.backward()
        assert m.weight.grad is not None
        assert m.weight.grad.dtype == paddle.float32  # grads back in param dtype


class TestPyLayer:
    def test_custom_vjp(self):
        from paddle_tpu.autograd import PyLayer

        class Exp2(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return paddle.exp(x * 2)

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 2 * paddle.exp(x * 2)

        x = paddle.to_tensor(0.5, stop_gradient=False)
        y = Exp2.apply(x)
        y.backward()
        np.testing.assert_allclose(float(x.grad), 2 * np.exp(1.0), rtol=1e-5)


class TestCheckpointing:
    def test_model_save_load(self, tmp_path):
        net = nn.Linear(3, 3)
        m = paddle.Model(net)
        m.prepare(paddle.optimizer.Adam(parameters=net.parameters()), nn.MSELoss())
        p = str(tmp_path / "ck")
        m.save(p)
        assert os.path.exists(p + ".pdparams")
        net2 = nn.Linear(3, 3)
        m2 = paddle.Model(net2)
        m2.prepare(paddle.optimizer.Adam(parameters=net2.parameters()), nn.MSELoss())
        m2.load(p)
        np.testing.assert_array_equal(net.weight.numpy(), net2.weight.numpy())


class TestTrainStepScaler:
    def test_dynamic_loss_scaling_in_train_step(self):
        """Scaler staged into the jitted step: scale grows on good steps,
        halves on inf, and an inf step leaves params untouched."""
        paddle.seed(0)
        m = nn.Linear(4, 4)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
        scaler = paddle.amp.GradScaler(
            init_loss_scaling=2.0**10, incr_every_n_steps=2,
            decr_every_n_nan_or_inf=1)
        step = paddle.jit.TrainStep(
            m, lambda net, x, y: nn.functional.mse_loss(net(x), y), opt,
            scaler=scaler)
        x = paddle.randn([8, 4])
        y = paddle.randn([8, 4])
        l0 = float(step(x, y))
        for _ in range(3):
            l1 = float(step(x, y))
        assert l1 < l0
        assert float(scaler._scale) == 2.0**12  # two incr_every_n_steps=2 bumps
        w_before = m.weight.numpy().copy()
        xinf = paddle.to_tensor(np.full((8, 4), 1e30, np.float32))
        step(xinf, y)
        np.testing.assert_array_equal(m.weight.numpy(), w_before)
        assert float(scaler._scale) == 2.0**11  # halved on inf


class _PicklableDS:
    """Module-level (spawn-picklable) dataset for the process-worker test."""

    def __len__(self):
        return 24

    def __getitem__(self, i):
        import os

        return np.asarray([i, os.getpid()], np.int64)


class TestProcessWorkers:
    def test_process_loader_matches_sync_and_uses_other_pids(self):
        import os

        from paddle_tpu.io import DataLoader

        dl = DataLoader(_PicklableDS(), batch_size=4, num_workers=2,
                        use_process_workers=True, timeout=120)
        batches = [b.numpy() for b in dl]
        assert len(batches) == 6
        ids = np.concatenate([b[:, 0] for b in batches])
        np.testing.assert_array_equal(ids, np.arange(24))  # order preserved
        pids = set(np.concatenate([b[:, 1] for b in batches]).tolist())
        assert os.getpid() not in pids  # fetched in child processes
        assert len(pids) >= 1

    def test_process_worker_error_propagates(self):
        from paddle_tpu.io import DataLoader

        dl = DataLoader(_FailingDS(), batch_size=2, num_workers=2,
                        use_process_workers=True, timeout=120)
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="worker .* failed"):
            list(dl)


class _FailingDS:
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom")
        return np.asarray([i], np.float32)


class TestInferencePredictor:
    """paddle.inference over jit-saved StableHLO: the reference's
    handle-based workflow end to end."""

    def test_handle_workflow_roundtrip(self, tmp_path):
        from paddle_tpu import inference
        from paddle_tpu.static import InputSpec

        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 8).astype(np.float32))
        expect = model(x).numpy()
        prefix = str(tmp_path / "m")
        paddle.jit.save(model, prefix,
                        input_spec=[InputSpec([4, 8], "float32", "feats")])

        cfg = inference.Config(prefix + ".pdmodel", prefix + ".pdiparams")
        pred = inference.create_predictor(cfg)
        assert pred.get_input_names() == ["feats"]
        h = pred.get_input_handle("feats")
        h.copy_from_cpu(x.numpy())
        # output handles are wireable BEFORE the first run, and persist
        out_h = pred.get_output_handle(pred.get_output_names()[0])
        pred.run()
        np.testing.assert_allclose(out_h.copy_to_cpu(), expect,
                                   rtol=1e-5, atol=1e-6)
        # the SAME handle observes the next run's results (serving loop)
        h.copy_from_cpu(x.numpy() * 2.0)
        pred.run()
        expect2 = model(paddle.to_tensor(x.numpy() * 2.0)).numpy()
        np.testing.assert_allclose(out_h.copy_to_cpu(), expect2,
                                   rtol=1e-5, atol=1e-6)
        # legacy list mode still works
        legacy = pred.run([x.numpy()])
        np.testing.assert_allclose(legacy[0], expect, rtol=1e-5, atol=1e-6)

    def test_missing_input_raises(self, tmp_path):
        from paddle_tpu import inference
        from paddle_tpu.static import InputSpec

        model = nn.Linear(4, 2)
        prefix = str(tmp_path / "m2")
        paddle.jit.save(model, prefix,
                        input_spec=[InputSpec([2, 4], "float32")])
        pred = inference.create_predictor(inference.Config(prefix))
        with pytest.raises(RuntimeError, match="inputs not set"):
            pred.run()
        with pytest.raises(KeyError):
            pred.get_input_handle("nope")

    def test_params_path_honored_and_dup_names_rejected(self, tmp_path):
        import shutil

        from paddle_tpu import inference
        from paddle_tpu.static import InputSpec

        model = nn.Linear(4, 2)
        prefix = str(tmp_path / "m3")
        paddle.jit.save(model, prefix,
                        input_spec=[InputSpec([2, 4], "float32")])
        # params living elsewhere (real paddle layout)
        alt = str(tmp_path / "weights" / "final.pdiparams")
        (tmp_path / "weights").mkdir()
        shutil.move(prefix + ".pdiparams", alt)
        pred = inference.create_predictor(
            inference.Config(prefix + ".pdmodel", alt))
        out = pred.run([np.zeros((2, 4), np.float32)])
        assert out[0].shape == (2, 2)
        with pytest.raises(ValueError, match="unique"):
            paddle.jit.save(model, str(tmp_path / "m4"),
                            input_spec=[InputSpec([2, 4], "float32", "x"),
                                        InputSpec([2, 4], "float32", "x")])


class TestOnnxExportAdapter:
    """r4: paddle.onnx.export is a functional adapter — it writes the
    StableHLO serving artifact (with a loud format warning) instead of
    raising; jit.save now exports None dims batch-polymorphically."""

    def test_export_serves_any_batch(self, tmp_path):
        import warnings

        import paddle_tpu.onnx as ponnx
        import paddle_tpu.inference as inference
        from paddle_tpu.static import InputSpec

        paddle.seed(0)
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            p = ponnx.export(m, str(tmp_path / "model.onnx"),
                             input_spec=[InputSpec([None, 4], "float32")])
            assert any("StableHLO" in str(x.message) for x in w)
        pred = inference.create_predictor(inference.Config(p))
        for bs in (1, 3, 7):
            out = pred.run([np.ones((bs, 4), np.float32)])[0]
            assert out.shape == (bs, 2)
        ref = m(paddle.to_tensor(np.ones((3, 4), np.float32))).numpy()
        np.testing.assert_allclose(pred.run([np.ones((3, 4), np.float32)])[0],
                                   ref, rtol=1e-5)

    def test_export_requires_input_spec(self, tmp_path):
        import paddle_tpu.onnx as ponnx

        with pytest.raises(ValueError, match="input_spec"):
            ponnx.export(nn.Linear(2, 2), str(tmp_path / "m"))

    def test_jit_save_polymorphic_roundtrip(self, tmp_path):
        from paddle_tpu.static import InputSpec

        paddle.seed(1)
        m = nn.Linear(6, 3)
        paddle.jit.save(m, str(tmp_path / "poly"),
                        input_spec=[InputSpec([None, 6], "float32")])
        layer = paddle.jit.load(str(tmp_path / "poly"))
        for bs in (2, 5):
            x = np.random.RandomState(bs).randn(bs, 6).astype(np.float32)
            np.testing.assert_allclose(
                layer(paddle.to_tensor(x)).numpy(),
                m(paddle.to_tensor(x)).numpy(), rtol=1e-5)

    def test_jit_save_polymorphic_shared_batch_two_inputs(self, tmp_path):
        # two inputs whose batch dims must be EQUAL (a + b): independent
        # symbols can't be related, so export retries with per-axis
        # shared symbols
        from paddle_tpu.static import InputSpec

        class TwoIn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 2)

            def forward(self, a, b):
                return self.fc(a + b)

        paddle.seed(2)
        m = TwoIn()
        paddle.jit.save(m, str(tmp_path / "two"),
                        input_spec=[InputSpec([None, 4], "float32"),
                                    InputSpec([None, 4], "float32")])
        layer = paddle.jit.load(str(tmp_path / "two"))
        for bs in (2, 6):
            a = np.random.RandomState(bs).randn(bs, 4).astype(np.float32)
            np.testing.assert_allclose(
                layer(paddle.to_tensor(a), paddle.to_tensor(a)).numpy(),
                m(paddle.to_tensor(a), paddle.to_tensor(a)).numpy(),
                rtol=1e-5)


class TestToStaticParamMutation:
    def test_param_mutation_survives_grad_path(self):
        """A traced forward that rewrites a parameter must have the update
        applied on BOTH call paths — the no-grad one and the tape-enabled
        one used during training (advisor r4: the grad path silently
        dropped it)."""

        class EmaLayer(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)
                self.ema = self.create_parameter(
                    [4, 4], default_initializer=nn.initializer.Constant(0.0))

            def forward(self, x):
                # parameter rewritten inside the forward (EMA-style)
                self.ema.set_value(self.ema * 0.5 + self.lin.weight * 0.5)
                return self.lin(x).sum()

        paddle.seed(0)
        m = EmaLayer()
        sm = paddle.jit.to_static(m)
        x = paddle.randn([2, 4])

        # tape enabled + a differentiable input → the grad-aware path
        loss = sm(x)
        after_one = m.ema.numpy().copy()
        assert np.abs(after_one).max() > 1e-6, \
            "param mutation dropped on the grad-aware to_static path"
        expect = after_one * 0.5 + m.lin.weight.numpy() * 0.5
        loss2 = sm(x)
        np.testing.assert_allclose(m.ema.numpy(), expect, rtol=1e-5)
        # grads still flow to the ordinary parameters
        loss2.backward()
        assert m.lin.weight.grad is not None

    def test_untouched_params_not_churned(self):
        """States the forward does not touch keep their exact arrays on
        the grad path (the writeback is trace-time mutation-gated)."""
        lin = nn.Linear(4, 2)
        sm = paddle.jit.to_static(lin)
        w_arr = lin.weight._data
        sm(paddle.randn([3, 4]))
        assert lin.weight._data is w_arr

    def test_optimizer_over_param_subset(self):
        """TrainStep with an optimizer managing only SOME trainable params
        must still build (review r5: the sharding-constraint pass did an
        unguarded accumulator lookup)."""
        class TwoPart(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(4, 4)
                self.b = nn.Linear(4, 1)

            def forward(self, x):
                return self.b(self.a(x))

        paddle.seed(0)
        m = TwoPart()
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=m.b.parameters())
        step = paddle.jit.TrainStep(
            m, lambda net, x, y: ((net(x) - y) ** 2).mean(), opt)
        x = paddle.randn([8, 4]); y = paddle.randn([8, 1])
        l1 = float(step(x, y)); l2 = float(step(x, y))
        assert np.isfinite(l1) and np.isfinite(l2)


class TestManyRngDelta:
    def test_rng_free_steps_bitwise_and_dropout_statistical(self):
        """Quantify many()'s documented RNG contract (VERDICT r4 item 8):
        RNG-free steps match sequential BITWISE; with dropout, the K keys
        come from ONE split of the stream, so masks differ from the K
        sequential draws — but the realized drop RATE and the resulting
        training trajectory stay statistically equivalent."""
        rng = np.random.RandomState(3)
        batches = [(paddle.to_tensor(rng.rand(64, 8).astype(np.float32)),
                    paddle.to_tensor(rng.rand(64, 1).astype(np.float32)))
                   for _ in range(4)]

        def build(with_dropout):
            paddle.seed(123)
            layers = [nn.Linear(8, 32)]
            if with_dropout:
                layers.append(nn.Dropout(0.5))
            layers += [nn.ReLU(), nn.Linear(32, 1)]
            m = nn.Sequential(*layers)
            m.train()
            o = paddle.optimizer.SGD(learning_rate=0.05,
                                     parameters=m.parameters())
            return m, o

        # RNG-free: bitwise identical params after K steps
        m1, o1 = build(False)
        s1 = paddle.jit.TrainStep(m1, lambda n, x, y: F.mse_loss(n(x), y),
                                  o1)
        for b in batches:
            s1(*b)
        m2, o2 = build(False)
        s2 = paddle.jit.TrainStep(m2, lambda n, x, y: F.mse_loss(n(x), y),
                                  o2)
        s2.many(batches)
        np.testing.assert_array_equal(m1[0].weight.numpy(),
                                      m2[0].weight.numpy())

        # dropout: per-step losses DIFFER (different masks)...
        m3, o3 = build(True)
        s3 = paddle.jit.TrainStep(m3, lambda n, x, y: F.mse_loss(n(x), y),
                                  o3)
        seq_losses = np.array([float(s3(*b)) for b in batches])
        m4, o4 = build(True)
        s4 = paddle.jit.TrainStep(m4, lambda n, x, y: F.mse_loss(n(x), y),
                                  o4)
        many_losses = s4.many(batches).numpy()
        assert not np.allclose(seq_losses, many_losses, rtol=1e-6), \
            "masks should differ (documented: statistical, not bitwise)"
        # ...but the trajectories stay in the same band (same loss scale,
        # same descent) and the final params are close in distribution
        assert abs(seq_losses.mean() - many_losses.mean()) \
            < 0.5 * seq_losses.mean() + 0.05
        w1, w2 = m3[0].weight.numpy(), m4[0].weight.numpy()
        assert abs(w1.std() - w2.std()) < 0.1 * max(w1.std(), w2.std())


class TestSaveEarlyExit:
    def test_jit_save_load_early_exit_decode(self, tmp_path):
        """r5: jit.save must export the dy2static-CONVERTED forward —
        an early-exit decode serializes to StableHLO and round-trips."""
        class Dec(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)

            def forward(self, h):
                n = 0
                while n < 8:
                    h = self.lin(h)
                    if paddle.max(paddle.abs(h)) < 0.05:
                        return h * 0.0
                    n = n + 1
                return h

        paddle.seed(0)
        m = Dec()
        m.eval()
        # ref from the EAGER forward (concrete control flow is exact);
        # m stays unwrapped so jit.save itself must do the conversion
        x = paddle.to_tensor(np.ones((1, 4), np.float32))
        ref = m(x).numpy()
        path = str(tmp_path / "dec")
        paddle.jit.save(m, path, input_spec=[paddle.jit.InputSpec([1, 4])])
        # the export shadow is fully removed afterwards
        assert "forward" not in m.__dict__
        loaded = paddle.jit.load(path)
        np.testing.assert_allclose(loaded(x).numpy(), ref, rtol=1e-5)

    def test_save_restores_instance_forward(self, tmp_path):
        """A pre-existing instance-level forward survives jit.save
        (review r5: the shadow cleanup used to delete it)."""
        import types

        lin = nn.Linear(4, 2)

        def custom_fwd(self, x):
            return lin.__class__.forward(self, x) + 1.0

        lin.eval()
        inst = types.MethodType(custom_fwd, lin)
        object.__setattr__(lin, "forward", inst)
        x = paddle.randn([3, 4])
        before = lin(x).numpy()
        paddle.jit.save(lin, str(tmp_path / "m"),
                        input_spec=[paddle.jit.InputSpec([3, 4])])
        assert lin.__dict__.get("forward") is inst
        np.testing.assert_allclose(lin(x).numpy(), before, rtol=1e-6)
