"""paddle.static: the r3 lazy static-graph mode — build via recorded
dispatch, execute as one jitted program, serve via the shared StableHLO
artifact (SURVEY.md §2.1 N10/N11)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static


@pytest.fixture
def static_mode():
    paddle.enable_static()
    try:
        yield
    finally:
        paddle.disable_static()


class TestStaticGraph:
    def test_build_run_matches_eager(self, static_mode):
        with static.program_guard(static.Program()):
            x = static.data("x", [None, 8], "float32")
            w = paddle.to_tensor(np.random.RandomState(0)
                                 .randn(8, 4).astype(np.float32))
            y = paddle.nn.functional.softmax(paddle.matmul(x, w))
            exe = static.Executor()
            feed = np.random.RandomState(1).randn(5, 8).astype(np.float32)
            out = exe.run(feed={"x": feed}, fetch_list=[y])[0]
        paddle.disable_static()
        expect = paddle.nn.functional.softmax(
            paddle.matmul(paddle.to_tensor(feed), paddle.to_tensor(
                np.asarray(w._data)))).numpy()
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)

    def test_nn_layers_stage_into_graph(self, static_mode):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
        with static.program_guard(static.Program()):
            x = static.data("x", [4, 8], "float32")
            y = model(x)
            assert y.shape == [4, 3]          # InferMeta worked
            exe = static.Executor()
            feed = np.random.RandomState(2).randn(4, 8).astype(np.float32)
            got = exe.run(feed={"x": feed}, fetch_list=[y])[0]
        paddle.disable_static()
        expect = model(paddle.to_tensor(feed)).numpy()
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

    def test_dynamic_batch_retraces(self, static_mode):
        x = static.data("xb", [None, 4], "float32")
        y = (x * 2.0).sum()
        exe = static.Executor()
        for bs in (2, 6):
            out = exe.run(feed={"xb": np.ones((bs, 4), np.float32)},
                          fetch_list=[y])[0]
            np.testing.assert_allclose(out, 8.0 * bs)

    def test_static_nn_fc(self, static_mode):
        x = static.data("xf", [3, 8], "float32")
        h = static.nn.fc(x, 16, activation="relu")
        y = static.nn.fc(h, 2)
        out = static.Executor().run(
            feed={"xf": np.random.RandomState(3)
                  .randn(3, 8).astype(np.float32)},
            fetch_list=[y])[0]
        assert out.shape == (3, 2) and np.isfinite(out).all()

    def test_missing_feed_and_concrete_touch_raise(self, static_mode):
        x = static.data("xm", [2, 2], "float32")
        y = x + 1.0
        with pytest.raises(static.StaticGraphError, match="missing feed"):
            static.Executor().run(feed={}, fetch_list=[y])
        with pytest.raises(static.StaticGraphError):
            y.numpy()   # symbolic: no concrete data

    def test_eager_unaffected_outside_and_after(self, static_mode):
        t = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose((t + t).numpy(), [2.0, 2.0])
        assert not paddle.in_dynamic_mode()
        paddle.disable_static()
        assert paddle.in_dynamic_mode()

    def test_save_inference_model_serves_via_predictor(self, tmp_path,
                                                       static_mode):
        from paddle_tpu import inference

        paddle.seed(0)
        model = nn.Linear(8, 3)
        x = static.data("feats", [4, 8], "float32")
        y = paddle.nn.functional.softmax(model(x))
        prefix = str(tmp_path / "static_m")
        static.save_inference_model(prefix, [x], [y])
        paddle.disable_static()

        pred = inference.create_predictor(inference.Config(prefix))
        assert pred.get_input_names() == ["feats"]
        feed = np.random.RandomState(4).randn(4, 8).astype(np.float32)
        h = pred.get_input_handle("feats")
        h.copy_from_cpu(feed)
        pred.run()
        got = pred.get_output_handle("output_0").copy_to_cpu()
        expect = paddle.nn.functional.softmax(
            model(paddle.to_tensor(feed))).numpy()
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

    def test_load_inference_model(self, tmp_path, static_mode):
        model = nn.Linear(4, 2)
        x = static.data("inp", [2, 4], "float32")
        y = model(x)
        prefix = str(tmp_path / "lim")
        static.save_inference_model(prefix, [x], [y])
        paddle.disable_static()
        layer, feed_names, fetch = static.load_inference_model(prefix)
        assert feed_names == ["inp"]
        out = layer(paddle.to_tensor(np.zeros((2, 4), np.float32)))
        assert out.shape == [2, 2]

    def test_deep_sequential_graph_evaluates(self, static_mode):
        # deeper than Python's recursion limit: the DAG walk is iterative
        x = static.data("xd", [2, 2], "float32")
        y = x
        for _ in range(1500):
            y = y + 1.0
        out = static.Executor().run(
            feed={"xd": np.zeros((2, 2), np.float32)}, fetch_list=[y])[0]
        np.testing.assert_allclose(out, np.full((2, 2), 1500.0))

    def test_namedtuple_output_op_stages(self, static_mode):
        x = static.data("xs", [4, 4], "float32")
        u, s, vt = paddle.linalg.svd(x)
        feed = np.random.RandomState(5).randn(4, 4).astype(np.float32)
        got_s = static.Executor().run(feed={"xs": feed},
                                      fetch_list=[s])[0]
        np.testing.assert_allclose(got_s, np.linalg.svd(feed)[1],
                                   rtol=1e-4, atol=1e-5)

    def test_fc_layers_get_distinct_weights(self, static_mode):
        paddle.seed(123)
        x = static.data("xw", [2, 8], "float32")
        h1 = static.nn.fc(x, 8)
        h2 = static.nn.fc(h1, 8)
        out = static.Executor().run(
            feed={"xw": np.ones((2, 8), np.float32)},
            fetch_list=[h1, h2])
        assert not np.allclose(out[0], out[1])

    def test_name_scope_and_amp_shim_survive(self, static_mode):
        with static.name_scope("block"):
            pass
        assert not hasattr(static.amp, "decorate")  # informative AttributeError
        with pytest.raises(NotImplementedError):
            static.amp.decorate

    def test_tensor_namespace_in_dynamic_mode_tracks_static(self,
                                                            static_mode):
        import paddle_tpu.tensor as T

        assert T.in_dynamic_mode() is False
        paddle.disable_static()
        assert T.in_dynamic_mode() is True

    def test_fc_dynamic_batch_with_flatten(self, static_mode):
        x = static.data("xfd", [None, 2, 3], "float32")
        y = static.nn.fc(x, 4)
        exe = static.Executor()
        for bs in (2, 5):
            out = exe.run(feed={"xfd": np.ones((bs, 2, 3), np.float32)},
                          fetch_list=[y])[0]
            assert out.shape == (bs, 4)

    def test_save_dynamic_batch_serves_any_size(self, tmp_path,
                                                static_mode):
        from paddle_tpu import inference

        paddle.seed(1)
        model = nn.Linear(4, 2)
        x = static.data("dynb", [None, 4], "float32")
        y = model(x)
        prefix = str(tmp_path / "dyn")
        static.save_inference_model(prefix, [x], [y])
        paddle.disable_static()
        pred = inference.create_predictor(inference.Config(prefix))
        for bs in (1, 3, 7):
            out = pred.run([np.ones((bs, 4), np.float32)])[0]
            assert out.shape == (bs, 2)

    def test_symbolic_tensor_protocols(self, static_mode):
        import copy

        x = static.data("xp", [2, 2], "float32")
        y = x * 3.0
        copy.deepcopy(x)                     # protocol probe falls back
        with pytest.raises(static.StaticGraphError):
            np.asarray(y.numpy())            # loud, not object-array
        with pytest.raises(static.StaticGraphError):
            float(y._data)
