"""paddle.static: the r3 lazy static-graph mode — build via recorded
dispatch, execute as one jitted program, serve via the shared StableHLO
artifact (SURVEY.md §2.1 N10/N11)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static


@pytest.fixture
def static_mode():
    paddle.enable_static()
    try:
        yield
    finally:
        paddle.disable_static()


class TestStaticGraph:
    def test_build_run_matches_eager(self, static_mode):
        with static.program_guard(static.Program()):
            x = static.data("x", [None, 8], "float32")
            w = paddle.to_tensor(np.random.RandomState(0)
                                 .randn(8, 4).astype(np.float32))
            y = paddle.nn.functional.softmax(paddle.matmul(x, w))
            exe = static.Executor()
            feed = np.random.RandomState(1).randn(5, 8).astype(np.float32)
            out = exe.run(feed={"x": feed}, fetch_list=[y])[0]
        paddle.disable_static()
        expect = paddle.nn.functional.softmax(
            paddle.matmul(paddle.to_tensor(feed), paddle.to_tensor(
                np.asarray(w._data)))).numpy()
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)

    def test_nn_layers_stage_into_graph(self, static_mode):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
        with static.program_guard(static.Program()):
            x = static.data("x", [4, 8], "float32")
            y = model(x)
            assert y.shape == [4, 3]          # InferMeta worked
            exe = static.Executor()
            feed = np.random.RandomState(2).randn(4, 8).astype(np.float32)
            got = exe.run(feed={"x": feed}, fetch_list=[y])[0]
        paddle.disable_static()
        expect = model(paddle.to_tensor(feed)).numpy()
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

    def test_dynamic_batch_retraces(self, static_mode):
        x = static.data("xb", [None, 4], "float32")
        y = (x * 2.0).sum()
        exe = static.Executor()
        for bs in (2, 6):
            out = exe.run(feed={"xb": np.ones((bs, 4), np.float32)},
                          fetch_list=[y])[0]
            np.testing.assert_allclose(out, 8.0 * bs)

    def test_static_nn_fc(self, static_mode):
        x = static.data("xf", [3, 8], "float32")
        h = static.nn.fc(x, 16, activation="relu")
        y = static.nn.fc(h, 2)
        out = static.Executor().run(
            feed={"xf": np.random.RandomState(3)
                  .randn(3, 8).astype(np.float32)},
            fetch_list=[y])[0]
        assert out.shape == (3, 2) and np.isfinite(out).all()

    def test_missing_feed_and_concrete_touch_raise(self, static_mode):
        x = static.data("xm", [2, 2], "float32")
        y = x + 1.0
        with pytest.raises(static.StaticGraphError, match="missing feed"):
            static.Executor().run(feed={}, fetch_list=[y])
        with pytest.raises(static.StaticGraphError):
            y.numpy()   # symbolic: no concrete data

    def test_eager_unaffected_outside_and_after(self, static_mode):
        t = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose((t + t).numpy(), [2.0, 2.0])
        assert not paddle.in_dynamic_mode()
        paddle.disable_static()
        assert paddle.in_dynamic_mode()

    def test_save_inference_model_serves_via_predictor(self, tmp_path,
                                                       static_mode):
        from paddle_tpu import inference

        paddle.seed(0)
        model = nn.Linear(8, 3)
        x = static.data("feats", [4, 8], "float32")
        y = paddle.nn.functional.softmax(model(x))
        prefix = str(tmp_path / "static_m")
        static.save_inference_model(prefix, [x], [y])
        paddle.disable_static()

        pred = inference.create_predictor(inference.Config(prefix))
        assert pred.get_input_names() == ["feats"]
        feed = np.random.RandomState(4).randn(4, 8).astype(np.float32)
        h = pred.get_input_handle("feats")
        h.copy_from_cpu(feed)
        pred.run()
        got = pred.get_output_handle("output_0").copy_to_cpu()
        expect = paddle.nn.functional.softmax(
            model(paddle.to_tensor(feed))).numpy()
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

    def test_load_inference_model(self, tmp_path, static_mode):
        model = nn.Linear(4, 2)
        x = static.data("inp", [2, 4], "float32")
        y = model(x)
        prefix = str(tmp_path / "lim")
        static.save_inference_model(prefix, [x], [y])
        paddle.disable_static()
        layer, feed_names, fetch = static.load_inference_model(prefix)
        assert feed_names == ["inp"]
        out = layer(paddle.to_tensor(np.zeros((2, 4), np.float32)))
        assert out.shape == [2, 2]

    def test_deep_sequential_graph_evaluates(self, static_mode):
        # deeper than Python's recursion limit: the DAG walk is iterative
        x = static.data("xd", [2, 2], "float32")
        y = x
        for _ in range(1500):
            y = y + 1.0
        out = static.Executor().run(
            feed={"xd": np.zeros((2, 2), np.float32)}, fetch_list=[y])[0]
        np.testing.assert_allclose(out, np.full((2, 2), 1500.0))

    def test_namedtuple_output_op_stages(self, static_mode):
        x = static.data("xs", [4, 4], "float32")
        u, s, vt = paddle.linalg.svd(x)
        feed = np.random.RandomState(5).randn(4, 4).astype(np.float32)
        got_s = static.Executor().run(feed={"xs": feed},
                                      fetch_list=[s])[0]
        np.testing.assert_allclose(got_s, np.linalg.svd(feed)[1],
                                   rtol=1e-4, atol=1e-5)

    def test_fc_layers_get_distinct_weights(self, static_mode):
        paddle.seed(123)
        x = static.data("xw", [2, 8], "float32")
        h1 = static.nn.fc(x, 8)
        h2 = static.nn.fc(h1, 8)
        out = static.Executor().run(
            feed={"xw": np.ones((2, 8), np.float32)},
            fetch_list=[h1, h2])
        assert not np.allclose(out[0], out[1])

    def test_name_scope_and_amp_module(self, static_mode):
        with static.name_scope("block"):
            pass
        # static.amp is REAL since late r4 (decorate -> the static
        # meta-optimizer rewrite; see test_static_meta_optimizers.py)
        assert callable(static.amp.decorate)
        assert callable(static.amp.AutoMixedPrecisionLists)

    def test_tensor_namespace_in_dynamic_mode_tracks_static(self,
                                                            static_mode):
        import paddle_tpu.tensor as T

        assert T.in_dynamic_mode() is False
        paddle.disable_static()
        assert T.in_dynamic_mode() is True

    def test_fc_dynamic_batch_with_flatten(self, static_mode):
        x = static.data("xfd", [None, 2, 3], "float32")
        y = static.nn.fc(x, 4)
        exe = static.Executor()
        for bs in (2, 5):
            out = exe.run(feed={"xfd": np.ones((bs, 2, 3), np.float32)},
                          fetch_list=[y])[0]
            assert out.shape == (bs, 4)

    def test_save_dynamic_batch_serves_any_size(self, tmp_path,
                                                static_mode):
        from paddle_tpu import inference

        paddle.seed(1)
        model = nn.Linear(4, 2)
        x = static.data("dynb", [None, 4], "float32")
        y = model(x)
        prefix = str(tmp_path / "dyn")
        static.save_inference_model(prefix, [x], [y])
        paddle.disable_static()
        pred = inference.create_predictor(inference.Config(prefix))
        for bs in (1, 3, 7):
            out = pred.run([np.ones((bs, 4), np.float32)])[0]
            assert out.shape == (bs, 2)

    def test_symbolic_tensor_protocols(self, static_mode):
        import copy

        x = static.data("xp", [2, 2], "float32")
        y = x * 3.0
        copy.deepcopy(x)                     # protocol probe falls back
        with pytest.raises(static.StaticGraphError):
            np.asarray(y.numpy())            # loud, not object-array
        with pytest.raises(static.StaticGraphError):
            float(y._data)


class TestStaticTraining:
    """r4 (VERDICT r3 item 4): minimal static-mode training — the
    reference's canonical `exe.run(startup); exe.run(main, feed, [loss])`
    loop, with parameters promoted from closure constants to traced
    inputs and jax.value_and_grad through the recorded DAG."""

    def _problem(self):
        rng = np.random.RandomState(0)
        X = rng.randn(64, 8).astype(np.float32)
        Y = (X @ rng.randn(8, 1).astype(np.float32)
             + 0.1 * rng.randn(64, 1).astype(np.float32))
        return X, Y

    def _eager_losses(self, opt_ctor, w0, b0, X, Y, steps):
        model = nn.Linear(8, 1)
        model.weight._data = w0
        model.bias._data = b0
        opt = opt_ctor(model.parameters())
        losses = []
        for _ in range(steps):
            loss = nn.functional.mse_loss(
                model(paddle.to_tensor(X)), paddle.to_tensor(Y))
            losses.append(float(loss))
            loss.backward()
            opt.step()
            opt.clear_grad()
        return losses

    @pytest.mark.parametrize("which", ["sgd", "adam"])
    def test_minimize_matches_eager(self, which, static_mode):
        X, Y = self._problem()
        ctor = {"sgd": lambda ps=None: paddle.optimizer.SGD(
                    learning_rate=0.05, parameters=ps),
                "adam": lambda ps=None: paddle.optimizer.Adam(
                    learning_rate=0.05, parameters=ps)}[which]
        with static.program_guard(static.Program()):
            x = static.data("x", [None, 8], "float32")
            y = static.data("y", [None, 1], "float32")
            pred = static.nn.fc(x, 1)
            loss = paddle.mean((pred - y) ** 2)
            opt = ctor()                      # parameters=None: static mode
            _, params_grads = opt.minimize(loss)
            w0 = params_grads[0][0]._data     # snapshot init for eager ref
            b0 = params_grads[1][0]._data
            exe = static.Executor()
            exe.run(static.default_startup_program())
            losses = []
            for _ in range(15):
                (lv,) = exe.run(static.default_main_program(),
                                feed={"x": X, "y": Y}, fetch_list=[loss])
                losses.append(float(lv))
        paddle.disable_static()
        ref = self._eager_losses(lambda ps: ctor(ps), w0, b0, X, Y, 15)
        assert losses[-1] < 0.5 * losses[0]   # it actually trains
        np.testing.assert_allclose(losses, ref, rtol=2e-5, atol=1e-6)

    def test_append_backward_grads_numerically_correct(self, static_mode):
        X, Y = self._problem()
        with static.program_guard(static.Program()):
            x = static.data("x", [None, 8], "float32")
            y = static.data("y", [None, 1], "float32")
            pred = static.nn.fc(x, 1)
            loss = paddle.mean((pred - y) ** 2)
            pairs = static.append_backward(loss)
            assert len(pairs) == 2            # weight + bias
            (w, gw), (b, gb) = pairs
            exe = static.Executor()
            gwv, gbv = exe.run(feed={"x": X, "y": Y}, fetch_list=[gw, gb])
            # manual grads of mean((Xw+b - Y)^2)
            r = X @ np.asarray(w._data) + np.asarray(b._data) - Y
            np.testing.assert_allclose(gwv, 2 * X.T @ r / len(X),
                                       rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(gbv, 2 * r.mean(0), rtol=1e-4,
                                       atol=1e-5)

    def test_clone_for_test_strips_train_op(self, static_mode):
        X, Y = self._problem()
        with static.program_guard(static.Program()):
            x = static.data("x", [None, 8], "float32")
            y = static.data("y", [None, 1], "float32")
            pred = static.nn.fc(x, 1)
            loss = paddle.mean((pred - y) ** 2)
            opt = paddle.optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)
            main = static.default_main_program()
            test_prog = main.clone(for_test=True)
            exe = static.Executor()
            before = exe.run(test_prog, feed={"x": X, "y": Y},
                             fetch_list=[loss])[0]
            for _ in range(10):
                exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            # eval on the test clone must NOT step the optimizer, but must
            # see the trained parameters (live, not frozen at first run)
            after = exe.run(test_prog, feed={"x": X, "y": Y},
                            fetch_list=[loss])[0]
            again = exe.run(test_prog, feed={"x": X, "y": Y},
                            fetch_list=[loss])[0]
        assert float(after) < float(before)
        np.testing.assert_allclose(float(after), float(again), rtol=1e-6)

    def test_grad_clip_and_lr_schedule_apply(self, static_mode):
        X, Y = self._problem()
        with static.program_guard(static.Program()):
            x = static.data("x", [None, 8], "float32")
            y = static.data("y", [None, 1], "float32")
            pred = static.nn.fc(x, 1)
            loss = paddle.mean((pred - y) ** 2)
            sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1,
                                                  step_size=2, gamma=0.5)
            opt = paddle.optimizer.SGD(
                learning_rate=sched,
                grad_clip=nn.ClipGradByGlobalNorm(0.01))
            opt.minimize(loss)
            exe = static.Executor()
            losses = []
            for _ in range(6):
                (lv,) = exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
                losses.append(float(lv))
                sched.step()
            # tiny clip norm -> slow but monotone-ish descent, no blowup
            assert losses[-1] < losses[0]


class TestControlFlowStaging:
    """r4 (VERDICT r3 item 5): static.nn.cond / while_loop / case /
    switch_case work in eager mode, under jit.to_static, and inside
    static Program recording."""

    def test_cond_eager_and_jit(self):
        def branchy(x):
            return static.nn.cond(
                paddle.mean(x) > 0,
                lambda: x * 2.0,
                lambda: x - 1.0)

        xp = np.array([1.0, 2.0], np.float32)
        xn = np.array([-1.0, -2.0], np.float32)
        np.testing.assert_allclose(
            branchy(paddle.to_tensor(xp)).numpy(), xp * 2)
        np.testing.assert_allclose(
            branchy(paddle.to_tensor(xn)).numpy(), xn - 1)
        jb = paddle.jit.to_static(branchy)
        np.testing.assert_allclose(jb(paddle.to_tensor(xp)).numpy(), xp * 2)
        np.testing.assert_allclose(jb(paddle.to_tensor(xn)).numpy(), xn - 1)

    def test_cond_gradients_flow_through_taken_branch(self):
        x = paddle.to_tensor(np.array([3.0], np.float32))
        x.stop_gradient = False
        out = static.nn.cond(x.sum() > 0, lambda: x * 5.0, lambda: x * 7.0)
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])

    def test_cond_structures_and_mismatch(self):
        x = paddle.to_tensor(np.ones((2,), np.float32))
        a, b = static.nn.cond(x.sum() > 0,
                              lambda: (x, x * 2), lambda: (x * 3, x * 4))
        np.testing.assert_allclose(b.numpy(), [2, 2])
        # structure mismatch surfaces when both branches are built, i.e.
        # under tracing (eager executes only the taken branch)
        with pytest.raises(Exception, match="different structures"):
            paddle.jit.to_static(
                lambda t: static.nn.cond(t.sum() > 0,
                                         lambda: (t, t), lambda: t))(x)

    def test_while_loop_eager_and_jit(self):
        def count_to(limit):
            i = paddle.to_tensor(np.asarray(0, np.int32))
            s = paddle.to_tensor(np.asarray(0, np.int32))
            i, s = static.nn.while_loop(
                lambda i, s: i < limit,
                lambda i, s: (i + 1, s + i),
                [i, s])
            return s

        assert int(count_to(paddle.to_tensor(np.asarray(5, np.int32)))) == 10
        jc = paddle.jit.to_static(count_to)
        # data-dependent trip count under ONE traced program
        assert int(jc(paddle.to_tensor(np.asarray(5, np.int32)))) == 10
        assert int(jc(paddle.to_tensor(np.asarray(7, np.int32)))) == 21

    def test_case_and_switch_case(self):
        x = paddle.to_tensor(np.asarray(2.0, np.float32))
        out = static.nn.case(
            [(x < 1, lambda: x * 10), (x < 3, lambda: x * 100)],
            default=lambda: x * 1000)
        np.testing.assert_allclose(float(out), 200.0)
        out2 = static.nn.switch_case(
            paddle.to_tensor(np.asarray(1, np.int32)),
            {0: lambda: x * 1, 1: lambda: x * 2, 2: lambda: x * 3})
        np.testing.assert_allclose(float(out2), 4.0)

    def test_cond_stages_into_static_program(self, static_mode):
        with static.program_guard(static.Program()):
            x = static.data("cf_x", [4], "float32")
            out = static.nn.cond(paddle.mean(x) > 0,
                                 lambda: x * 2.0, lambda: x - 1.0)
            exe = static.Executor()
            xp = np.array([1, 2, 3, 4], np.float32)
            xn = -xp
            (o1,) = exe.run(feed={"cf_x": xp}, fetch_list=[out])
            (o2,) = exe.run(feed={"cf_x": xn}, fetch_list=[out])
        np.testing.assert_allclose(o1, xp * 2)
        np.testing.assert_allclose(o2, xn - 1)

    def test_while_loop_stages_into_static_program(self, static_mode):
        with static.program_guard(static.Program()):
            n = static.data("cf_n", [], "int32")
            i = paddle.to_tensor(np.asarray(0, np.int32))
            s = paddle.to_tensor(np.asarray(0, np.int32))
            # symbolic outer value rides through loop_vars, per the doc
            _, s_out, _ = static.nn.while_loop(
                lambda i, s, lim: i < lim,
                lambda i, s, lim: (i + 1, s + i, lim),
                [i, s, n])
            exe = static.Executor()
            (sv,) = exe.run(feed={"cf_n": np.asarray(6, np.int32)},
                            fetch_list=[s_out])
        assert int(sv) == 15

    def test_branchy_model_trains_eagerly(self):
        # a data-dependent-branch model end to end (the VERDICT's "branchy
        # model" criterion): gate picks a head by the sample mean
        lin = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        rng = np.random.RandomState(0)
        X = rng.randn(8, 4).astype(np.float32)
        losses = []
        for _ in range(10):
            h = lin(paddle.to_tensor(X))
            out = static.nn.cond(paddle.mean(h) > 0,
                                 lambda: paddle.tanh(h), lambda: h * 0.5)
            loss = (out ** 2).mean()
            losses.append(float(loss))
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert losses[-1] < losses[0]


class TestExecutorStructuralCache:
    """r4 (VERDICT r3 item 8): the Executor keys compiled programs on a
    STRUCTURAL hash of the fetched subgraph, not fetch-tensor identity."""

    def _build_and_run(self, exe, scale, feed):
        with static.program_guard(static.Program()):
            x = static.data("sc_x", [None, 4], "float32")
            w = paddle.to_tensor(
                np.arange(8, dtype=np.float32).reshape(4, 2) * scale)
            out = paddle.nn.functional.softmax(paddle.matmul(x, w))
            return exe.run(feed={"sc_x": feed}, fetch_list=[out])[0]

    def test_rebuilt_program_hits_cache(self):
        paddle.enable_static()
        try:
            exe = static.Executor()
            feed = np.random.RandomState(0).randn(3, 4).astype(np.float32)
            r1 = self._build_and_run(exe, 1.0, feed)
            n1 = len(exe._cache)
            r2 = self._build_and_run(exe, 1.0, feed)   # rebuilt, identical
            assert len(exe._cache) == n1               # ONE compiled entry
            np.testing.assert_allclose(r1, r2, rtol=1e-6)
            # same structure, different CONSTANT content -> new entry and
            # (crucially) different results — content is program identity
            r3 = self._build_and_run(exe, 2.0, feed)
            assert len(exe._cache) == n1 + 1
            assert not np.allclose(r1, r3)
        finally:
            paddle.disable_static()

    def test_trained_params_ride_positionally_on_cache_hit(self):
        # two structurally identical programs with DIFFERENT param values:
        # the shared executable must produce each program's own result
        paddle.enable_static()
        try:
            exe = static.Executor()
            feed = np.ones((2, 4), np.float32)
            outs = []
            for seed in (1, 2):
                with static.program_guard(static.Program()):
                    paddle.seed(seed)
                    x = static.data("pp_x", [None, 4], "float32")
                    y = static.nn.fc(x, 3)
                    outs.append(exe.run(feed={"pp_x": feed},
                                        fetch_list=[y])[0])
            assert len(exe._cache) == 1
            assert not np.allclose(outs[0], outs[1])
        finally:
            paddle.disable_static()

    def test_lru_bound(self):
        paddle.enable_static()
        try:
            exe = static.Executor()
            exe.CACHE_SIZE = 3
            feed = np.ones((1, 4), np.float32)
            for scale in (1.0, 2.0, 3.0, 4.0, 5.0):
                self._build_and_run(exe, scale, feed)
            assert len(exe._cache) <= 3
        finally:
            paddle.disable_static()


class TestArtifactOutputNames:
    """r4 (VERDICT r3 item 7): fetch names + out avals persist in the
    .pdmodel artifact; the Predictor exposes the REAL names."""

    def test_names_roundtrip_through_predictor(self, tmp_path, static_mode):
        import paddle_tpu.inference as inference

        with static.program_guard(static.Program()):
            x = static.data("feat", [None, 4], "float32")
            w = paddle.to_tensor(np.eye(4, 3, dtype=np.float32))
            logits = paddle.matmul(x, w)
            logits.name = "logits"
            probs = paddle.nn.functional.softmax(logits)
            probs.name = "probs"
            prefix = str(tmp_path / "named")
            static.save_inference_model(prefix, [x], [logits, probs])
        paddle.disable_static()
        pred = inference.create_predictor(inference.Config(prefix))
        assert pred.get_output_names() == ["logits", "probs"]
        inp = pred.get_input_handle("feat")
        inp.copy_from_cpu(np.ones((2, 4), np.float32))
        pred.run()
        lg = pred.get_output_handle("logits").copy_to_cpu()
        pb = pred.get_output_handle("probs").copy_to_cpu()
        assert lg.shape == (2, 3) and pb.shape == (2, 3)
        np.testing.assert_allclose(pb.sum(-1), 1.0, rtol=1e-5)
        with pytest.raises(KeyError):
            pred.get_output_handle("output_0")

    def test_unnamed_fetches_default_and_jit_save_unaffected(
            self, tmp_path, static_mode):
        import paddle_tpu.inference as inference

        with static.program_guard(static.Program()):
            x = static.data("u_x", [None, 4], "float32")
            y = x * 2.0
            prefix = str(tmp_path / "unnamed")
            static.save_inference_model(prefix, [x], [y])
        paddle.disable_static()
        pred = inference.create_predictor(inference.Config(prefix))
        assert pred.get_output_names() == ["output_0"]
        out = pred.run([np.ones((3, 4), np.float32)])[0]
        np.testing.assert_allclose(out, 2.0)


class TestCondGradSafety:
    def test_eager_cond_executes_one_branch_no_nan(self):
        # the classic where-grad trap: sqrt at 0 in the UNTAKEN branch
        # must not poison gradients in eager mode (one branch executes)
        x = paddle.to_tensor(np.array([0.0], np.float32))
        x.stop_gradient = False
        out = static.nn.cond(x.sum() > 0,
                             lambda: paddle.sqrt(x), lambda: x * 2.0)
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])

    def test_cond_dtype_mismatch_raises_under_tracing(self):
        def f(x):
            return static.nn.cond(
                x.sum() > 0,
                lambda: x.astype("int32"), lambda: x * 1.0)

        with pytest.raises(Exception, match="matching dtypes"):
            paddle.jit.to_static(f)(
                paddle.to_tensor(np.ones(2, np.float32)))
