"""Multi-host communication backend, actually exercised (SURVEY.md §5
"comm backend", §4 distributed-test pattern A): the launcher spawns two
REAL processes that rendezvous through the jax.distributed coordination
service (the TPU build's TCPStore, wired through the reference's
PADDLE_TRAINER_* env contract at import time) and train over the combined
8-device mesh with cross-process gloo collectives — data-parallel (ZeRO-1
step), tensor-parallel (mp=8 spanning both processes) and pipeline-parallel
(cross-process ppermute handoffs). Invariant, same as the reference's
TestDistBase: per-rank losses identical to each other AND to the
single-process serial run of the IDENTICAL companion (MP_SERIAL=1)."""

import os
import re
import subprocess
import sys

import numpy as np

_HERE = os.path.dirname(__file__)
_REPO = os.path.dirname(os.path.abspath(_HERE))


def _companion(name):
    return os.path.join(_HERE, "companions", name)


def _clean_env():
    return {k: v for k, v in os.environ.items()
            if not k.startswith(("PADDLE_", "RANK", "WORLD_SIZE", "MASTER_"))}


def _parse(marker, out):
    m = re.search(marker + r" (\d) (\[.*\])", out)
    assert m, out[-1500:]
    return int(m.group(1)), eval(m.group(2))  # noqa: S307 — our own output


def _spawn_ranks(companion, port, nranks):
    return [
        subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", str(nranks), "--master", f"localhost:{port}",
             "--rank", str(r), companion],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=_REPO, env=_clean_env())
        for r in range(nranks)
    ]


def _collect(procs, deadline=480):
    """(returncode, output) per spawn index, one SHARED wall-clock budget;
    a failed/timed-out rank must not leave siblings orphaned on the
    rendezvous port."""
    import time as _time

    outs = {}
    t0 = _time.time()
    try:
        for i, p in enumerate(procs):
            remain = max(10, deadline - (_time.time() - t0))
            out, _ = p.communicate(timeout=remain)
            outs[i] = (p.returncode, out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def _run_multi_process(companion, port, marker, nranks=2):
    outs = _collect(_spawn_ranks(companion, port, nranks))
    losses = {}
    for rc, out in outs.values():
        assert rc == 0, out[-2000:]
        rank, ls = _parse(marker, out)
        losses[rank] = ls
    return losses


def _run_serial(companion, marker):
    """The SAME companion, single process, 8 local devices (MP_SERIAL=1)."""
    env = dict(_clean_env(), MP_SERIAL="1", JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, companion], capture_output=True,
                       text=True, timeout=600, cwd=_REPO, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    _, ls = _parse(marker, r.stdout)
    return ls


def _check(companion, port, marker, nranks=2):
    losses = _run_multi_process(_companion(companion), port, marker, nranks)
    assert set(losses) == set(range(nranks))
    # every rank observed the same global loss (real cross-process psum)
    for r in range(1, nranks):
        assert losses[0] == losses[r], losses
    # and the distributed run equals the serial run of the same program
    serial = _run_serial(_companion(companion), marker)
    np.testing.assert_allclose(losses[0], serial, rtol=1e-4, atol=1e-5)
    # training actually progressed
    assert losses[0][-1] < losses[0][0]


class TestMultiProcessSPMD:
    def test_two_process_dp_matches_serial(self):
        _check("mp_dp_train.py", 12513, "MP_LOSSES")

    def test_two_process_tensor_parallel_matches_serial(self):
        """Column/RowParallelLinear over an mp=8 axis spanning both
        processes: the row-parallel psum and column-backward all-reduce
        cross the process boundary."""
        _check("mp_tp_train.py", 12541, "MP_TP_LOSSES")

    def test_two_process_pipeline_matches_serial(self):
        """The compiled ppermute pipeline schedule with stage handoffs
        CROSSING the process boundary (pp=4 x dp=2 over 2 processes)."""
        _check("mp_pp_train.py", 12533, "MP_PP_LOSSES")

    def test_two_process_1f1b_tied_vpp_matches_serial(self):
        """r4: the literal 1F1B schedule with tied embeddings AND virtual
        stages (pp=4 x v=2 x dp=2 over 2 processes) — the per-slot
        activation/cotangent rings and the tied-weight grad psum all
        cross the process boundary."""
        _check("mp_pp_1f1b_tied.py", 12623, "MP_1F1B_TIED_LOSSES")

    def test_two_process_static_dp_matches_serial(self):
        """late r4: STATIC-GRAPH dp training across processes — each
        trainer feeds its own batch shard to Executor.run (reference
        per-trainer dp feeding); the executor assembles the global
        sharded feed and GSPMD's grad allreduce crosses the boundary."""
        _check("mp_static_dp_train.py", 12651, "MP_LOSSES")

    def test_four_process_dp_pp_matches_serial(self):
        """nnodes=4 rendezvous (VERDICT r2 item 8): dp=2 x pp=2 with ONE
        device per process — every collective edge crosses a process
        boundary."""
        _check("mp4_dp_pp_train.py", 12571, "MP4_LOSSES", nranks=4)

    def test_rank_death_takes_pod_down_and_propagates_status(self):
        """Failure path (VERDICT r2 item 8): rank 1 dies hard mid-step.
        Its launcher must propagate the child's exit status, and the
        SURVIVING rank must come down with an error (coordination service
        surfaces the lost peer) instead of hanging forever."""
        outs = _collect(_spawn_ranks(_companion("mp_kill_train.py"),
                                     12587, 2), deadline=420)
        rc1, out1 = outs[1]
        # the dying rank's launcher propagates the child's status (7)
        assert rc1 == 7, (rc1, out1[-1500:])
        rc0, out0 = outs[0]
        # the survivor made progress, then came down NON-ZERO (no hang)
        assert "KILLSTEP 0 3" in out0, out0[-1500:]
        assert rc0 != 0, (rc0, out0[-1500:])

    def test_object_collectives_cross_process(self):
        """broadcast_object_list / scatter_object_list over 2 real
        processes: non-src ranks receive rank 0's objects (they'd silently
        keep their own under the old no-op) and their scatter slot."""
        outs = _collect(_spawn_ranks(_companion("mp_obj_collectives.py"),
                                     12599, 2), deadline=300)
        got = {}
        for rc, out in outs.values():
            assert rc == 0, out[-2000:]
            m = re.search(r"OBJ_RESULT (\d) (.*)", out)
            assert m, out[-1500:]
            got[int(m.group(1))] = m.group(2)
        assert set(got) == {0, 1}, got
        assert got[0] == "from-rank-0|[1, 2, 3]|slot-a", got
        assert got[1] == "from-rank-0|[1, 2, 3]|slot-b", got

    def test_sep_ring_and_moe_ep_cross_process(self):
        """Long-context + MoE across the process boundary (the two axes
        the 2-process suite didn't cover): sep=8 ring attention (k/v
        ppermute hops cross processes) and ep=8 MoE all_to_all, identical
        results on both ranks and equal to the serial 8-device run."""
        losses = _run_multi_process(_companion("mp_sep_ep_train.py"),
                                    12611, "SEP_EP_RESULT", 2)
        assert set(losses) == {0, 1}
        assert losses[0] == losses[1], losses
        serial = _run_serial(_companion("mp_sep_ep_train.py"),
                             "SEP_EP_RESULT")
        np.testing.assert_allclose(losses[0], serial, rtol=1e-4)
        assert all(v > 0 for v in losses[0])

    def test_two_process_static_mp_matches_serial(self):
        """r5: STATIC-GRAPH tensor-parallel training across processes —
        recorded params shard over an mp=4 axis spanning both processes
        (dp=2 x mp=4); GSPMD's TP collectives cross the boundary."""
        _check("mp_static_mp_train.py", 12663, "MP_SMP_LOSSES")
