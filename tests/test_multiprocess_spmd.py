"""Multi-host communication backend, actually exercised (SURVEY.md §5
"comm backend", §4 distributed-test pattern A): the launcher spawns two
REAL processes that rendezvous through the jax.distributed coordination
service (the TPU build's TCPStore, wired through the reference's
PADDLE_TRAINER_* env contract at import time) and train over the combined
8-device mesh with cross-process gloo collectives — data-parallel (ZeRO-1
step), tensor-parallel (mp=8 spanning both processes) and pipeline-parallel
(cross-process ppermute handoffs). Invariant, same as the reference's
TestDistBase: per-rank losses identical to each other AND to the
single-process serial run of the IDENTICAL companion (MP_SERIAL=1)."""

import os
import re
import subprocess
import sys

import numpy as np

_HERE = os.path.dirname(__file__)
_REPO = os.path.dirname(os.path.abspath(_HERE))


def _companion(name):
    return os.path.join(_HERE, "companions", name)


def _clean_env():
    return {k: v for k, v in os.environ.items()
            if not k.startswith(("PADDLE_", "RANK", "WORLD_SIZE", "MASTER_"))}


def _parse(marker, out):
    m = re.search(marker + r" (\d) (\[.*\])", out)
    assert m, out[-1500:]
    return int(m.group(1)), eval(m.group(2))  # noqa: S307 — our own output


def _run_two_process(companion, port, marker):
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--master", f"localhost:{port}",
             "--rank", str(r), companion],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=_REPO, env=_clean_env())
        for r in (0, 1)
    ]
    losses = {}
    try:
        for p in procs:
            out, _ = p.communicate(timeout=480)
            assert p.returncode == 0, out[-2000:]
            rank, ls = _parse(marker, out)
            losses[rank] = ls
    finally:
        # a failed/timed-out rank must not leave its sibling orphaned on
        # the rendezvous port
        for p in procs:
            if p.poll() is None:
                p.kill()
    return losses


def _run_serial(companion, marker):
    """The SAME companion, single process, 8 local devices (MP_SERIAL=1)."""
    env = dict(_clean_env(), MP_SERIAL="1", JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, companion], capture_output=True,
                       text=True, timeout=600, cwd=_REPO, env=env)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    _, ls = _parse(marker, r.stdout)
    return ls


def _check(companion, port, marker):
    losses = _run_two_process(_companion(companion), port, marker)
    assert set(losses) == {0, 1}
    # both ranks observed the same global loss (real cross-process psum)
    assert losses[0] == losses[1], losses
    # and the distributed run equals the serial 8-device run
    serial = _run_serial(_companion(companion), marker)
    np.testing.assert_allclose(losses[0], serial, rtol=1e-4, atol=1e-5)
    # training actually progressed
    assert losses[0][-1] < losses[0][0]


class TestMultiProcessSPMD:
    def test_two_process_dp_matches_serial(self):
        _check("mp_dp_train.py", 12513, "MP_LOSSES")

    def test_two_process_tensor_parallel_matches_serial(self):
        """Column/RowParallelLinear over an mp=8 axis spanning both
        processes: the row-parallel psum and column-backward all-reduce
        cross the process boundary."""
        _check("mp_tp_train.py", 12541, "MP_TP_LOSSES")

    def test_two_process_pipeline_matches_serial(self):
        """The compiled ppermute pipeline schedule with stage handoffs
        CROSSING the process boundary (pp=4 x dp=2 over 2 processes)."""
        _check("mp_pp_train.py", 12533, "MP_PP_LOSSES")
