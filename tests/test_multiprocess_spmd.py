"""Multi-host communication backend, actually exercised (SURVEY.md §5
"comm backend", §4 distributed-test pattern A): the launcher spawns two
REAL processes that rendezvous through the jax.distributed coordination
service (the TPU build's TCPStore, wired through the reference's
PADDLE_TRAINER_* env contract at import time) and train data-parallel over
the combined 8-device mesh with cross-process gloo collectives. Invariant,
same as the reference's TestDistBase: per-rank losses identical to each
other AND to the single-process serial run."""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

_COMPANION = os.path.join(os.path.dirname(__file__), "companions",
                          "mp_dp_train.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _serial_losses():
    """Same model/batch/optimizer on ONE process with 8 virtual devices."""
    code = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed.sharding.group_sharded import GroupShardedTrainStep

hcg = dist.create_hybrid_communicate_group(sharding=8)
paddle.seed(0)
model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=model.parameters())
step = GroupShardedTrainStep(model, lambda m, x, y: nn.functional.mse_loss(m(x), y),
                             opt, level="os", mesh=hcg.mesh)
rng = np.random.RandomState(0)
X = rng.randn(32, 8).astype(np.float32)
Y = X.sum(-1, keepdims=True).astype(np.float32)
losses = []
for _ in range(4):
    losses.append(round(float(step(paddle.to_tensor(X), paddle.to_tensor(Y))), 6))
print("SERIAL_LOSSES", losses)
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=600,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=_REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    m = re.search(r"SERIAL_LOSSES (\[.*\])", r.stdout)
    return eval(m.group(1))  # noqa: S307 — our own printed list


def _run_two_process(companion, port, marker):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PADDLE_", "RANK", "WORLD_SIZE", "MASTER_"))}
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--master", f"localhost:{port}",
             "--rank", str(r), companion],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=_REPO, env=env)
        for r in (0, 1)
    ]
    losses = {}
    for p in procs:
        out, _ = p.communicate(timeout=480)
        assert p.returncode == 0, out[-2000:]
        m = re.search(marker + r" (\d) (\[.*\])", out)
        assert m, out[-1500:]
        losses[int(m.group(1))] = eval(m.group(2))  # noqa: S307
    return losses


class TestMultiProcessSPMD:
    @pytest.mark.timeout(600)
    def test_two_process_dp_matches_serial(self):
        port = 12513
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("PADDLE_", "RANK", "WORLD_SIZE",
                                    "MASTER_"))}
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--nnodes", "2", "--master", f"localhost:{port}",
                 "--rank", str(r), _COMPANION],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=_REPO, env=env)
            for r in (0, 1)
        ]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
            assert p.returncode == 0, out[-2000:]
        losses = {}
        for out in outs:
            m = re.search(r"MP_LOSSES (\d) (\[.*\])", out)
            assert m, out[-1500:]
            losses[int(m.group(1))] = eval(m.group(2))  # noqa: S307
        assert set(losses) == {0, 1}
        # both ranks observed the same global loss (real cross-process psum)
        assert losses[0] == losses[1], losses
        # and the distributed run equals the serial 8-device run
        serial = _serial_losses()
        np.testing.assert_allclose(losses[0], serial, rtol=1e-4, atol=1e-5)
        # training actually progressed
        assert losses[0][-1] < losses[0][0]

    @pytest.mark.timeout(600)
    def test_two_process_pipeline_matches_serial(self):
        """The compiled ppermute pipeline schedule with stage handoffs
        CROSSING the process boundary (pp=4 x dp=2 over 2 processes)."""
        companion = os.path.join(os.path.dirname(__file__), "companions",
                                 "mp_pp_train.py")
        losses = _run_two_process(companion, 12533, "MP_PP_LOSSES")
        assert losses[0] == losses[1], losses
        serial = _serial_pp_losses()
        np.testing.assert_allclose(losses[0], serial, rtol=1e-4, atol=1e-5)
        assert losses[0][-1] < losses[0][0]


def _serial_pp_losses():
    code = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.meta_parallel import (LayerDesc,
    PipelineLayer, PipelineParallel)
H = 16
class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(H, H)
    def forward(self, x):
        return paddle.tanh(self.fc(x))
hcg = dist.create_hybrid_communicate_group(dp=2, pp=4)
paddle.seed(0)
pl = PipelineLayer([LayerDesc(nn.Linear, 8, H)] +
                   [LayerDesc(Block) for _ in range(2)] +
                   [LayerDesc(nn.Linear, H, 4)],
                   loss_fn=lambda o, y: nn.functional.mse_loss(o, y))
runner = PipelineParallel(pl, hcg, {"accumulate_steps": 4})
opt = paddle.optimizer.Momentum(learning_rate=0.05, parameters=pl.parameters())
rng = np.random.RandomState(0)
X = rng.randn(16, 8).astype(np.float32)
Y = rng.randn(16, 4).astype(np.float32)
losses = []
for _ in range(3):
    losses.append(round(float(runner.train_batch(
        (paddle.to_tensor(X), paddle.to_tensor(Y)), opt)), 6))
print("SERIAL_PP", losses)
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=600,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=_REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    m = re.search(r"SERIAL_PP (\[.*\])", r.stdout)
    return eval(m.group(1))  # noqa: S307
