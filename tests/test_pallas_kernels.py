"""Pallas kernel tests, interpret mode on CPU (SURVEY.md §4 op-test pattern:
NumPy/jnp reference + gradient comparison; the same kernels compile on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.flash import flash_attention
from paddle_tpu.ops.pallas.norms import layer_norm, rms_norm


def _ref_attention(q, k, v, causal, scale):
    s = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        m = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(m, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("seq", [64, 100, 256])
    def test_forward(self, causal, seq):
        rng = np.random.RandomState(0)
        B, H, D = 2, 2, 32
        q, k, v = (rng.randn(B, seq, H, D).astype(np.float32) for _ in range(3))
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        ref = _ref_attention(q, k, v, causal, 1.0 / np.sqrt(D))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads(self, causal):
        rng = np.random.RandomState(1)
        B, S, H, D = 2, 100, 2, 16
        q, k, v = (rng.randn(B, S, H, D).astype(np.float32) for _ in range(3))
        scale = 1.0 / np.sqrt(D)

        def loss_fa(q, k, v):
            return (flash_attention(q, k, v, causal=causal, interpret=True) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref_attention(q, k, v, causal, scale) ** 2).sum()

        g1 = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_cross_attention_lengths(self):
        rng = np.random.RandomState(2)
        B, H, D = 1, 2, 16
        q = rng.randn(B, 40, H, D).astype(np.float32)
        k = rng.randn(B, 130, H, D).astype(np.float32)
        v = rng.randn(B, 130, H, D).astype(np.float32)
        out = flash_attention(q, k, v, causal=False, interpret=True)
        ref = _ref_attention(q, k, v, False, 1.0 / np.sqrt(D))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_bf16(self):
        rng = np.random.RandomState(3)
        B, S, H, D = 1, 64, 2, 32
        q, k, v = (rng.randn(B, S, H, D).astype(jnp.bfloat16) for _ in range(3))
        out = flash_attention(q, k, v, causal=True, interpret=True)
        ref = _ref_attention(q, k, v, True, 1.0 / np.sqrt(D))
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=3e-2, atol=3e-2)

    def test_through_tensor_op_and_tape(self):
        """The paddle-level flash_attention op records the pallas custom_vjp
        on the tape."""
        from paddle_tpu.ops.pallas import flash as pf
        rng = np.random.RandomState(4)
        q = paddle.to_tensor(rng.randn(1, 32, 2, 16).astype(np.float32),
                             stop_gradient=False)
        out = paddle.Tensor(
            pf.flash_attention(q._data, q._data, q._data, causal=True,
                               interpret=True))
        assert out.shape == [1, 32, 2, 16]


class TestFusedNorms:
    def test_layer_norm_fwd_bwd(self):
        rng = np.random.RandomState(0)
        x = rng.randn(37, 64).astype(np.float32)
        w = rng.randn(64).astype(np.float32)
        b = rng.randn(64).astype(np.float32)

        def ref(x, w, b):
            m = x.mean(-1, keepdims=True)
            v = ((x - m) ** 2).mean(-1, keepdims=True)
            return (x - m) / jnp.sqrt(v + 1e-5) * w + b

        np.testing.assert_allclose(
            np.asarray(layer_norm(x, w, b, 1e-5, True)),
            np.asarray(ref(x, w, b)), rtol=1e-5, atol=1e-5)
        g1 = jax.grad(lambda *a: (layer_norm(*a, 1e-5, True) ** 2).sum(),
                      argnums=(0, 1, 2))(x, w, b)
        g2 = jax.grad(lambda *a: (ref(*a) ** 2).sum(), argnums=(0, 1, 2))(x, w, b)
        for a, c in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       rtol=1e-4, atol=1e-4)

    def test_layer_norm_3d(self):
        rng = np.random.RandomState(1)
        x = rng.randn(4, 33, 32).astype(np.float32)
        w = np.ones(32, np.float32)
        b = np.zeros(32, np.float32)
        out = layer_norm(x, w, b, 1e-5, True)
        m = x.mean(-1, keepdims=True)
        v = x.var(-1, keepdims=True)
        np.testing.assert_allclose(np.asarray(out), (x - m) / np.sqrt(v + 1e-5),
                                   rtol=1e-5, atol=1e-5)

    def test_rms_norm_fwd_bwd(self):
        rng = np.random.RandomState(2)
        x = rng.randn(50, 48).astype(np.float32)
        w = rng.randn(48).astype(np.float32)

        def ref(x, w):
            return x / jnp.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w

        np.testing.assert_allclose(np.asarray(rms_norm(x, w, 1e-6, True)),
                                   np.asarray(ref(x, w)), rtol=1e-5, atol=1e-5)
        g1 = jax.grad(lambda *a: (rms_norm(*a, 1e-6, True) ** 2).sum(),
                      argnums=(0, 1))(x, w)
        g2 = jax.grad(lambda *a: (ref(*a) ** 2).sum(), argnums=(0, 1))(x, w)
        for a, c in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       rtol=1e-4, atol=1e-4)


class TestFlashGQA:
    """Grouped-query attention: narrow kv heads shared across query groups
    via the kernel's BlockSpec index maps (no HBM repeat)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_matches_repeated(self, causal):
        rng = np.random.RandomState(0)
        b, s, h, hkv, d = 2, 96, 8, 2, 32
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        kr = jnp.repeat(k, h // hkv, axis=2)
        vr = jnp.repeat(v, h // hkv, axis=2)
        ref = flash_attention(q, kr, vr, causal=causal, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_repeated(self, causal):
        rng = np.random.RandomState(1)
        b, s, h, hkv, d = 1, 64, 4, 2, 16
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        r = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

        def loss_gqa(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           interpret=True) * r)

        def loss_rep(q, k, v):
            kr = jnp.repeat(k, h // hkv, axis=2)
            vr = jnp.repeat(v, h // hkv, axis=2)
            return jnp.sum(flash_attention(q, kr, vr, causal=causal,
                                           interpret=True) * r)

        g1 = jax.grad(loss_gqa, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_rep, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-5)


class TestFlashCrossLength:
    """sq != sk with causal=True: the kernel's diagonal offset must match
    the XLA fallback's tril(k=sk-sq) (chunked prefill / cached decode)."""

    def test_short_query_attends_whole_prefix(self):
        rng = np.random.RandomState(0)
        b, h, d = 1, 2, 32
        sq, sk = 128, 256
        q = jnp.asarray(rng.randn(b, sq, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, sk, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, sk, h, d), jnp.float32)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        # reference with diagonal offset sk-sq
        s = jnp.einsum("bshd,bthd->bhst", q, k) / np.sqrt(d)
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        ref = jnp.einsum("bhst,bthd->bshd",
                         jax.nn.softmax(jnp.where(mask, s, -1e30), -1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grad_short_query(self):
        rng = np.random.RandomState(1)
        b, h, d, sq, sk = 1, 1, 16, 64, 128
        q = jnp.asarray(rng.randn(b, sq, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, sk, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, sk, h, d), jnp.float32)
        r = jnp.asarray(rng.randn(b, sq, h, d), jnp.float32)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           interpret=True) * r)

        def loss_ref(q, k, v):
            s = jnp.einsum("bshd,bthd->bhst", q, k) / np.sqrt(d)
            mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            o = jnp.einsum("bhst,bthd->bshd",
                           jax.nn.softmax(jnp.where(mask, s, -1e30), -1), v)
            return jnp.sum(o * r)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-5)


class TestFullyMaskedRows:
    """causal with q_len > kv_len leaves leading query rows with zero visible
    keys. Flash-attn convention: those rows output 0 — the XLA fallback must
    agree with the Pallas kernel (ADVICE r1 dispatch-divergence fix)."""

    def test_fallback_zeroes_fully_masked_rows(self):
        from paddle_tpu.nn.functional.attention import _sdpa_ref

        rng = np.random.RandomState(2)
        b, h, d, sq, sk = 1, 2, 16, 8, 4
        q = jnp.asarray(rng.randn(b, sq, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, sk, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, sk, h, d), jnp.float32)
        out = np.asarray(_sdpa_ref(q, k, v, causal=True))
        # rows i < sq-sk see no keys (tril offset k=sk-sq) -> exactly zero
        np.testing.assert_allclose(out[:, : sq - sk], 0.0)
        # visible rows unchanged vs plain softmax reference
        s = jnp.einsum("bshd,bthd->bhst", q, k) / np.sqrt(d)
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        ref = jnp.einsum("bhst,bthd->bshd",
                         jax.nn.softmax(jnp.where(mask, s, -1e30), -1), v)
        np.testing.assert_allclose(out[:, sq - sk:],
                                   np.asarray(ref)[:, sq - sk:],
                                   rtol=2e-5, atol=2e-5)

    def test_all_false_mask_row(self):
        from paddle_tpu.nn.functional.attention import _sdpa_ref

        rng = np.random.RandomState(3)
        b, h, d, s = 1, 1, 8, 4
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        mask = jnp.ones((b, h, s, s), bool).at[:, :, 0, :].set(False)
        out = np.asarray(_sdpa_ref(q, k, v, mask=mask))
        np.testing.assert_allclose(out[:, 0], 0.0)
        assert np.abs(out[:, 1:]).sum() > 0


class TestFusedGroupNorm:
    def _ref(self, x, w, b, G, eps=1e-5):
        n, c = x.shape[:2]
        sp = x.shape[2:]
        g = x.reshape((n, G, c // G) + sp)
        axes = tuple(range(2, g.ndim))
        mean = g.mean(axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
        shape = (1, c) + (1,) * len(sp)
        return out * w.reshape(shape) + b.reshape(shape)

    @pytest.mark.parametrize("shape,G", [((3, 32, 8, 8), 8),
                                         ((2, 20, 5, 7), 4),
                                         ((4, 16, 10), 16)])
    def test_fwd(self, shape, G):
        from paddle_tpu.ops.pallas.norms import group_norm

        rng = np.random.RandomState(0)
        x = rng.randn(*shape).astype(np.float32)
        w = rng.randn(shape[1]).astype(np.float32)
        b = rng.randn(shape[1]).astype(np.float32)
        out = group_norm(x, w, b, G, 1e-5, True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(self._ref(x, w, b, G)),
                                   rtol=2e-5, atol=2e-5)

    def test_bwd_matches_ref_grads(self):
        from paddle_tpu.ops.pallas.norms import group_norm

        rng = np.random.RandomState(1)
        x = rng.randn(3, 24, 6, 5).astype(np.float32)
        w = rng.randn(24).astype(np.float32)
        b = rng.randn(24).astype(np.float32)
        g1 = jax.grad(lambda *a: (group_norm(*a, 8, 1e-5, True) ** 2).sum(),
                      argnums=(0, 1, 2))(x, w, b)
        g2 = jax.grad(lambda *a: (self._ref(*a, 8) ** 2).sum(),
                      argnums=(0, 1, 2))(x, w, b)
        for a, c in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       rtol=1e-4, atol=1e-4)

    def test_bwd_numeric_grad(self):
        # numeric ground truth from a float64 NumPy reference (f32 finite
        # differences are dominated by rounding noise)
        from paddle_tpu.ops.pallas.norms import group_norm

        rng = np.random.RandomState(2)
        x = rng.randn(2, 8, 4, 4).astype(np.float32)
        w = rng.randn(8).astype(np.float32)
        b = rng.randn(8).astype(np.float32)

        def ref_loss(xv):
            xv = xv.astype(np.float64)
            g4 = xv.reshape(2, 4, 2, 4, 4)
            mean = g4.mean(axis=(2, 3, 4), keepdims=True)
            var = g4.var(axis=(2, 3, 4), keepdims=True)
            out = ((g4 - mean) / np.sqrt(var + 1e-5)).reshape(xv.shape)
            out = out * w.astype(np.float64).reshape(1, 8, 1, 1) \
                + b.astype(np.float64).reshape(1, 8, 1, 1)
            return float((out ** 2).sum())

        g = jax.grad(lambda xv: (group_norm(xv, w, b, 4, 1e-5, True) ** 2
                                 ).sum())(x)
        eps = 1e-4
        for idx in [(0, 0, 0, 0), (1, 3, 2, 1), (0, 7, 3, 3)]:
            xp = x.astype(np.float64); xp[idx] += eps
            xm = x.astype(np.float64); xm[idx] -= eps
            num = (ref_loss(xp) - ref_loss(xm)) / (2 * eps)
            np.testing.assert_allclose(np.asarray(g)[idx], num,
                                       rtol=2e-3, atol=1e-4)

    def test_bf16_stats_in_f32(self):
        from paddle_tpu.ops.pallas.norms import group_norm

        rng = np.random.RandomState(3)
        x = (rng.randn(2, 16, 8, 8) * 3 + 100).astype(jnp.bfloat16)
        w = np.ones(16, np.float32)
        b = np.zeros(16, np.float32)
        out = np.asarray(group_norm(x, w, b, 4, 1e-5, True)
                         ).astype(np.float32)
        ref = np.asarray(self._ref(np.asarray(x, np.float32), w, b, 4))
        np.testing.assert_allclose(out, ref, rtol=0.05, atol=0.05)

    def test_functional_routes_and_matches(self):
        # CPU: F.group_norm keeps the jnp path; parity with the kernel
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu.ops.pallas.norms import group_norm

        rng = np.random.RandomState(4)
        x = rng.randn(2, 12, 6, 6).astype(np.float32)
        w = rng.randn(12).astype(np.float32)
        b = rng.randn(12).astype(np.float32)
        f_out = F.group_norm(paddle.to_tensor(x), 4, 1e-5,
                             paddle.to_tensor(w), paddle.to_tensor(b)).numpy()
        k_out = np.asarray(group_norm(x, w, b, 4, 1e-5, True))
        np.testing.assert_allclose(f_out, k_out, rtol=2e-5, atol=2e-5)
