"""Elastic manager state machine + LLaMA family surface tests."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic import (
    ElasticManager, ElasticStatus, LocalMembershipStore,
)


def _mgr(node_id, np_spec, store):
    return ElasticManager(node_id=node_id, np=np_spec, store=store,
                          heartbeat_interval=0.05)


class TestElastic:
    def test_hold_below_min(self):
        store = LocalMembershipStore()
        m = _mgr("0", "2:4", store).enter()
        try:
            assert m.poll() == ElasticStatus.HOLD
        finally:
            m.exit()

    def test_steady_state_completed(self):
        store = LocalMembershipStore()
        ms = [_mgr(str(i), "2:4", store).enter() for i in range(2)]
        try:
            for m in ms:
                # snapshot at enter() for the last node already holds both
                m._world = sorted(store.live_nodes(m.ttl))
                assert m.poll() == ElasticStatus.COMPLETED
        finally:
            for m in ms:
                m.exit()

    def test_scale_up_triggers_restart(self):
        store = LocalMembershipStore()
        m0 = _mgr("0", "2:4", store).enter()
        m1 = _mgr("1", "2:4", store).enter()
        m0._world = sorted(store.live_nodes(m0.ttl))
        try:
            store.register("2", {})
            seen = []
            st = m0.watch(timeout=1.0, on_restart=seen.append)
            assert st == ElasticStatus.RESTART
            assert seen == [3]
        finally:
            m0.exit(); m1.exit()

    def test_scale_down_via_deregister(self):
        store = LocalMembershipStore()
        ms = [_mgr(str(i), "2:4", store).enter() for i in range(3)]
        ms[0]._world = sorted(store.live_nodes(ms[0].ttl))
        try:
            ms[2].exit()
            assert ms[0].poll() == ElasticStatus.RESTART
            assert ms[0].world_size() == 2
        finally:
            ms[0].exit(); ms[1].exit()

    def test_above_max_extras_exit(self):
        store = LocalMembershipStore()
        ms = [_mgr(str(i), "1:2", store).enter() for i in range(3)]
        try:
            # highest-sorted node beyond max_np is told to exit
            assert ms[2].poll() == ElasticStatus.EXIT
        finally:
            for m in ms:
                m.exit()

    def test_file_store(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import FileMembershipStore

        store = FileMembershipStore(str(tmp_path))
        store.register("a", {"host": "h0"})
        store.register("b", {})
        assert set(store.live_nodes(ttl=10)) == {"a", "b"}
        store.deregister("a")
        assert set(store.live_nodes(ttl=10)) == {"b"}


class TestLlama:
    def test_gqa_forward_backward(self):
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=64)
        model = LlamaForCausalLM(cfg)
        # GQA: kv projections are narrower than q
        assert model.model.layers[0].self_attn.k_proj.weight.shape[1] == 32
        ids = paddle.to_tensor(np.arange(32, dtype=np.int32).reshape(1, 32) % 128)
        loss, logits = model(ids, labels=ids)
        assert tuple(logits.shape) == (1, 32, 128)
        loss.backward()
        g = model.model.layers[0].self_attn.k_proj.weight.grad
        assert g is not None and np.isfinite(np.asarray(g._data)).all()

    def test_presets(self):
        from paddle_tpu.models.llama import LLAMA2_7B, LLAMA2_13B, LLAMA3_8B

        assert LLAMA2_13B.hidden_size == 5120
        assert LLAMA2_7B.num_hidden_layers == 32
        assert LLAMA3_8B.kv_heads == 8
        assert LLAMA3_8B.head_dim == 128


class TestElasticLauncher:
    def test_elastic_completes_and_restarts(self, tmp_path):
        """Elastic supervisor runs a script to completion; a membership
        change mid-run triggers relaunch with a new world size."""
        import os
        import subprocess
        import sys
        import textwrap
        import threading
        import time

        script = tmp_path / "train.py"
        marker = tmp_path / "runs.txt"
        script.write_text(textwrap.dedent(f"""
            import os, time
            with open({str(marker)!r}, "a") as f:
                f.write(os.environ.get("WORLD_SIZE", "?") + "\\n")
            time.sleep(6.0)
        """))
        elastic_dir = str(tmp_path / "members")
        env = dict(os.environ, PADDLE_ELASTIC_DIR=elastic_dir,
                   JAX_PLATFORMS="cpu")

        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--elastic_np", "1:3", str(script)],
            env=env, cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

        # once the first run starts (marker appears), a second node joins
        # with a live heartbeat -> supervisor must RESTART with world=2
        stop = threading.Event()

        def add_node():
            from paddle_tpu.distributed.fleet.elastic import FileMembershipStore

            for _ in range(300):  # wait for the first trainer run
                if marker.exists() or stop.is_set():
                    break
                time.sleep(0.1)
            store = FileMembershipStore(elastic_dir)
            store.register("99", {})
            while not stop.is_set():  # keep the fake node alive
                store.heartbeat("99")
                time.sleep(0.3)

        t = threading.Thread(target=add_node, daemon=True)
        t.start()
        try:
            out, _ = proc.communicate(timeout=90)
        finally:
            stop.set()
            t.join(timeout=5)
            proc.kill()
        runs = marker.read_text().split()
        # first attempt saw world=1, the relaunch saw world=2
        assert "1" in runs and "2" in runs, (runs, out.decode()[-800:])
