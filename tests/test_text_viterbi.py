"""paddle.text.viterbi_decode vs brute-force enumeration."""

import itertools

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.text import ViterbiDecoder, viterbi_decode


def _brute(pot, trans, length, bos_eos):
    t, n = pot.shape
    real_n = n
    best, best_path = -1e30, None
    for path in itertools.product(range(real_n), repeat=length):
        s = pot[0, path[0]]
        if bos_eos:
            # reference: last tag = BOS/start, second-to-last = EOS/stop
            s += trans[n - 1, path[0]]
        for i in range(1, length):
            s += trans[path[i - 1], path[i]] + pot[i, path[i]]
        if bos_eos:
            s += trans[path[length - 1], n - 2]
        if s > best:
            best, best_path = s, path
    return best, list(best_path)


class TestViterbi:
    def test_matches_bruteforce(self):
        rng = np.random.RandomState(0)
        t, n = 5, 4
        pot = rng.randn(2, t, n).astype(np.float32)
        trans = rng.randn(n, n).astype(np.float32)
        lengths = np.array([5, 3], np.int64)
        for bos_eos in (False, True):
            scores, paths = viterbi_decode(
                paddle.to_tensor(pot), paddle.to_tensor(trans),
                paddle.to_tensor(lengths), include_bos_eos_tag=bos_eos)
            for b in range(2):
                ref_s, ref_p = _brute(pot[b], trans, int(lengths[b]), bos_eos)
                assert abs(float(scores.numpy()[b]) - ref_s) < 1e-4
                assert paths.numpy()[b, :int(lengths[b])].tolist() == ref_p

    def test_decoder_layer(self):
        rng = np.random.RandomState(1)
        trans = rng.randn(5, 5).astype(np.float32)
        dec = ViterbiDecoder(paddle.to_tensor(trans))
        pot = rng.randn(3, 6, 5).astype(np.float32)
        lengths = np.array([6, 4, 2], np.int64)
        scores, paths = dec(paddle.to_tensor(pot), paddle.to_tensor(lengths))
        assert scores.shape == [3] or tuple(scores.shape) == (3,)
        assert tuple(paths.shape) == (3, 6)
        # positions past the length repeat the last valid tag
        p = paths.numpy()
        assert (p[1, 4:] == p[1, 3]).all()
        assert (p[2, 2:] == p[2, 1]).all()
