"""Optimizer tests vs hand-rolled NumPy references (SURVEY.md §4: the
reference compares Adam against a NumPy implementation)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def make_param(val):
    return paddle.Parameter(np.asarray(val, np.float32))


def set_grad(p, g):
    p.grad = paddle.to_tensor(np.asarray(g, np.float32))


class TestSGDMomentum:
    def test_sgd(self):
        p = make_param([1.0, 2.0])
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
        set_grad(p, [1.0, 1.0])
        opt.step()
        np.testing.assert_allclose(p.numpy(), [0.9, 1.9], rtol=1e-6)

    def test_momentum_matches_numpy(self):
        p = make_param([1.0])
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
        v = 0.0
        x = 1.0
        for i in range(3):
            g = 2 * x
            set_grad(p, [g])
            opt.step()
            v = 0.9 * v + g
            x = x - 0.1 * v
        np.testing.assert_allclose(p.numpy(), [x], rtol=1e-5)

    def test_weight_decay_l2(self):
        p = make_param([1.0])
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p], weight_decay=0.1)
        set_grad(p, [0.0])
        opt.step()
        np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 0.1], rtol=1e-6)


class TestAdamFamily:
    def np_adam(self, x, grads, lr=0.01, b1=0.9, b2=0.999, eps=1e-8):
        m = v = 0.0
        b1p = b2p = 1.0
        for g in grads:
            b1p *= b1
            b2p *= b2
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            x = x - lr * (m / (1 - b1p)) / (np.sqrt(v / (1 - b2p)) + eps)
        return x

    def test_adam_matches_numpy(self):
        p = make_param([1.0])
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[p])
        grads = [0.5, -0.3, 0.8, 0.1]
        for g in grads:
            set_grad(p, [g])
            opt.step()
        np.testing.assert_allclose(p.numpy(), [self.np_adam(1.0, grads)], rtol=1e-5)

    def test_adamw_decoupled_decay(self):
        p = make_param([1.0])
        opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=[p], weight_decay=0.1)
        set_grad(p, [0.5])
        opt.step()
        # decoupled: (1 - lr*wd) applied to weight before adam update
        ref = self.np_adam(1.0 * (1 - 0.01 * 0.1), [0.5])
        np.testing.assert_allclose(p.numpy(), [ref], rtol=1e-4)

    def test_adamw_exclude_fn(self):
        p1, p2 = make_param([1.0]), make_param([1.0])
        p1.name, p2.name = "w", "bias"
        opt = paddle.optimizer.AdamW(
            learning_rate=0.01, parameters=[p1, p2], weight_decay=0.5,
            apply_decay_param_fun=lambda n: n == "w",
        )
        set_grad(p1, [0.0])
        set_grad(p2, [0.0])
        opt.step()
        assert p1.numpy()[0] < 1.0  # decayed
        np.testing.assert_allclose(p2.numpy(), [1.0], atol=1e-7)  # excluded

    def test_lamb_trust_ratio(self):
        p = make_param(np.ones(4))
        opt = paddle.optimizer.Lamb(learning_rate=0.01, parameters=[p])
        set_grad(p, np.full(4, 0.1))
        opt.step()
        assert p.numpy()[0] < 1.0

    def test_multi_precision_master_weights(self):
        p = paddle.Parameter(np.ones(3, np.float16))
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[p], multi_precision=True)
        set_grad(p, np.full(3, 0.5, np.float16))
        opt.step()
        st = opt._accumulators[id(p)]
        assert "master_weight" in st
        assert str(st["master_weight"].dtype) == "float32"
        assert str(p.dtype) == "float16"


class TestStatePersistence:
    def test_optimizer_state_roundtrip(self):
        p = make_param([1.0])
        p.name = "p0"
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[p])
        set_grad(p, [0.5])
        opt.step()
        sd = opt.state_dict()

        p2 = make_param([1.0])
        p2.name = "p0"
        opt2 = paddle.optimizer.Adam(learning_rate=0.01, parameters=[p2])
        opt2.set_state_dict(sd)
        m1 = opt._accumulators[id(p)]["moment1"]
        m2 = opt2._accumulators[id(p2)]["moment1"]
        np.testing.assert_allclose(np.asarray(m1), np.asarray(m2))


class TestLRSchedulers:
    def test_step_decay(self):
        s = paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(s.get_lr())
            s.step()
        np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025], rtol=1e-6)

    def test_linear_warmup_into_cosine(self):
        base = paddle.optimizer.lr.CosineAnnealingDecay(0.1, T_max=10)
        s = paddle.optimizer.lr.LinearWarmup(base, warmup_steps=4, start_lr=0.0, end_lr=0.1)
        lrs = [s.get_lr()]
        for _ in range(4):
            s.step()
            lrs.append(s.get_lr())
        assert lrs[0] == 0.0
        assert abs(lrs[2] - 0.05) < 1e-6
        assert lrs[4] <= 0.1 + 1e-9

    def test_noam(self):
        s = paddle.optimizer.lr.NoamDecay(d_model=512, warmup_steps=10, learning_rate=1.0)
        for _ in range(9):
            s.step()
        peak_region = s.get_lr()
        for _ in range(100):
            s.step()
        assert s.get_lr() < peak_region

    def test_reduce_on_plateau(self):
        s = paddle.optimizer.lr.ReduceOnPlateau(0.1, patience=1, factor=0.5)
        s.step(1.0)
        s.step(1.0)
        s.step(1.0)
        assert s.get_lr() == pytest.approx(0.05)

    def test_one_cycle(self):
        s = paddle.optimizer.lr.OneCycleLR(max_learning_rate=1.0, total_steps=10)
        lrs = []
        for _ in range(10):
            lrs.append(s.get_lr())
            s.step()
        assert max(lrs) <= 1.0 + 1e-9
        assert lrs[0] < max(lrs)
        assert lrs[-1] < max(lrs)

    def test_scheduler_in_optimizer(self):
        p = make_param([1.0])
        s = paddle.optimizer.lr.ExponentialDecay(0.1, gamma=0.5)
        opt = paddle.optimizer.SGD(learning_rate=s, parameters=[p])
        assert opt.get_lr() == pytest.approx(0.1)
        s.step()
        assert opt.get_lr() == pytest.approx(0.05)


class TestGradScaler:
    def test_scale_unscale_roundtrip(self):
        p = make_param([1.0])
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
        x = paddle.to_tensor(2.0)
        loss = (p * x).sum()
        scaler.scale(loss).backward()
        np.testing.assert_allclose(p.grad.numpy(), [16.0])  # scaled by 8
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 2.0], rtol=1e-6)

    def test_skip_on_inf(self):
        p = make_param([1.0])
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0, decr_every_n_nan_or_inf=1)
        p.grad = paddle.to_tensor(np.array([np.inf], np.float32))
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(p.numpy(), [1.0])  # step skipped
        assert float(scaler.get_loss_scaling()) == 4.0  # halved


class TestRegularizer:
    def test_l1_decay_adds_sign_term(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.regularizer import L1Decay, L2Decay

        paddle.seed(0)
        lin = nn.Linear(4, 4, bias_attr=False)
        w0 = lin.weight.numpy().copy()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters(),
                                   weight_decay=L1Decay(0.5))
        x = paddle.to_tensor(np.zeros((2, 4), np.float32))
        loss = paddle.mean(lin(x))  # zero input -> zero data gradient
        loss.backward()
        opt.step()
        # pure L1 step: w -= lr * coeff * sign(w)
        np.testing.assert_allclose(lin.weight.numpy(),
                                   w0 - 0.1 * 0.5 * np.sign(w0), atol=1e-6)

    def test_l2_decay_coeff_path(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.regularizer import L2Decay

        paddle.seed(0)
        lin = nn.Linear(4, 4, bias_attr=False)
        w0 = lin.weight.numpy().copy()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters(),
                                   weight_decay=L2Decay(0.5))
        x = paddle.to_tensor(np.zeros((2, 4), np.float32))
        loss = paddle.mean(lin(x))
        loss.backward()
        opt.step()
        np.testing.assert_allclose(lin.weight.numpy(), w0 - 0.1 * 0.5 * w0,
                                   atol=1e-6)

    def test_param_attr_regularizer_wins(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.framework.param_attr import ParamAttr
        from paddle_tpu.regularizer import L1Decay

        paddle.seed(0)
        lin = nn.Linear(4, 4, bias_attr=False,
                        weight_attr=ParamAttr(regularizer=L1Decay(0.5)))
        w0 = lin.weight.numpy().copy()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        x = paddle.to_tensor(np.zeros((2, 4), np.float32))
        loss = paddle.mean(lin(x))
        loss.backward()
        opt.step()
        np.testing.assert_allclose(lin.weight.numpy(),
                                   w0 - 0.1 * 0.5 * np.sign(w0), atol=1e-6)

    def test_exempt_param_cancels_coupled_decay(self):
        # no_weight_decay param under a coupled optimizer: the
        # optimizer-level L2 applied inside _update must be cancelled
        # (ADVICE r1 precedence inversion)
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.regularizer import L2Decay

        paddle.seed(0)
        lin = nn.Linear(4, 4, bias_attr=False)
        lin.weight.no_weight_decay = True
        w0 = lin.weight.numpy().copy()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters(),
                                   weight_decay=L2Decay(0.5))
        x = paddle.to_tensor(np.zeros((2, 4), np.float32))
        loss = paddle.mean(lin(x))
        loss.backward()
        opt.step()
        # zero data grad + exempt -> weight unchanged
        np.testing.assert_allclose(lin.weight.numpy(), w0, atol=1e-6)

    def test_exempt_param_still_honors_per_param_regularizer(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.framework.param_attr import ParamAttr
        from paddle_tpu.regularizer import L1Decay, L2Decay

        paddle.seed(0)
        lin = nn.Linear(4, 4, bias_attr=False,
                        weight_attr=ParamAttr(regularizer=L1Decay(0.5)))
        lin.weight.no_weight_decay = True
        w0 = lin.weight.numpy().copy()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters(),
                                   weight_decay=L2Decay(0.25))
        x = paddle.to_tensor(np.zeros((2, 4), np.float32))
        loss = paddle.mean(lin(x))
        loss.backward()
        opt.step()
        # only the explicit per-param L1 applies; coupled L2 cancelled
        np.testing.assert_allclose(lin.weight.numpy(),
                                   w0 - 0.1 * 0.5 * np.sign(w0), atol=1e-6)


class TestAdamWTrainStepParity:
    def test_decoupled_decay_applies_in_train_step(self):
        """AdamW's decoupled weight decay must be identical between eager
        opt.step() and the compiled TrainStep path (review regression)."""
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        def build():
            paddle.seed(42)
            m = nn.Linear(4, 4, bias_attr=False)
            o = paddle.optimizer.AdamW(learning_rate=0.1,
                                       parameters=m.parameters(),
                                       weight_decay=0.5)
            return m, o

        x = paddle.to_tensor(np.ones((2, 4), np.float32))

        m1, o1 = build()
        loss = paddle.mean(m1(x))
        loss.backward()
        o1.step()

        m2, o2 = build()
        step = paddle.jit.TrainStep(m2, lambda m, a: paddle.mean(m(a)), o2)
        step(x)

        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                                   rtol=1e-5, atol=1e-6)
        # and decay actually happened (differs from no-decay run)
        m3, _ = build()
        o3 = paddle.optimizer.AdamW(learning_rate=0.1,
                                    parameters=m3.parameters(),
                                    weight_decay=0.0)
        loss = paddle.mean(m3(x))
        loss.backward()
        o3.step()
        assert not np.allclose(m1.weight.numpy(), m3.weight.numpy())


class TestBf16DtypeStability:
    def test_momentum_train_step_keeps_bf16(self):
        """Strong-typed f32 lr must not promote bf16 params across steps
        (regression: second TrainStep call failed with mixed conv dtypes)."""
        import numpy as np
        import jax.numpy as jnp
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        paddle.seed(0)
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        m.to(dtype="bfloat16")
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                        parameters=m.parameters())
        step = paddle.jit.TrainStep(
            m, lambda n, a: paddle.mean(paddle.cast(n(a), "float32") ** 2), opt)
        x = paddle.cast(paddle.to_tensor(
            np.random.RandomState(0).randn(2, 4).astype(np.float32)),
            "bfloat16")
        step(x)
        step(x)  # regression: used to fail here
        for p in m.parameters():
            assert p._data.dtype == jnp.bfloat16, p.name

    def test_update_for_pins_param_and_state_dtype(self):
        """Drive _update_for directly with a STRONG f32 lr array (what the
        compiled TrainStep passes): params AND optimizer state must keep
        their original dtypes — state promotion would change jit avals and
        force a recompile every step (RMSProp's velocity was the repro)."""
        import numpy as np
        import jax.numpy as jnp
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        for make in (
            lambda ps: paddle.optimizer.Momentum(learning_rate=0.1,
                                                 momentum=0.9, parameters=ps),
            lambda ps: paddle.optimizer.RMSProp(learning_rate=0.1,
                                                parameters=ps),
            lambda ps: paddle.optimizer.AdamW(learning_rate=0.1,
                                              parameters=ps,
                                              weight_decay=0.01),
        ):
            paddle.seed(1)
            lin = nn.Linear(4, 4, bias_attr=False)
            lin.to(dtype="bfloat16")
            opt = make(lin.parameters())
            p = lin.weight
            st = opt._state_for(p)
            lr = jnp.asarray(0.1, jnp.float32)  # strong dtype
            g = jnp.ones_like(p._data)
            new_p, new_st = opt._update_for(p, p._data, g, st, lr)
            assert new_p.dtype == jnp.bfloat16, type(opt).__name__
            import jax

            jax.tree.map(
                lambda n, o: None if not hasattr(o, "dtype")
                else (_ for _ in ()).throw(AssertionError(
                    f"{type(opt).__name__} state {n.dtype} != {o.dtype}"))
                if n.dtype != o.dtype else None,
                new_st, st)


class TestLambExemption:
    def test_lamb_respects_no_weight_decay_and_exclude_fn(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        def run(flag=False, exclude=None):
            paddle.seed(0)
            lin = nn.Linear(4, 4, bias_attr=False)
            if flag:
                lin.weight.no_weight_decay = True
            opt = paddle.optimizer.Lamb(learning_rate=0.1,
                                        lamb_weight_decay=0.5,
                                        parameters=lin.parameters(),
                                        exclude_from_weight_decay_fn=exclude)
            x = paddle.to_tensor(np.zeros((2, 4), np.float32))
            loss = paddle.mean(lin(x))
            loss.backward()
            opt.step()
            return lin.weight.numpy()

        paddle.seed(0)
        lin0 = nn.Linear(4, 4, bias_attr=False)
        w0 = lin0.weight.numpy().copy()
        # zero data grad: with decay the weight moves, exempt leaves it put
        assert np.abs(run() - w0).max() > 1e-4
        np.testing.assert_allclose(run(flag=True), w0, atol=1e-6)
        np.testing.assert_allclose(run(exclude=lambda p: True), w0, atol=1e-6)
