"""Serving gateway tests: SSE wire format and bitwise stream parity over
real HTTP, per-tenant token-bucket quotas (429 -> refill), SLO load
shedding (503 + Retry-After), prefix-affinity routing across replicas,
priority-aware admission (bounded starvation), deadline aborts, and
graceful drain."""

import http.client
import json
import time

import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability.metrics import validate_exposition
from paddle_tpu.serving import (
    Engine, EngineConfig, SamplingParams, Scheduler,
)
from paddle_tpu.serving.gateway import (
    EngineWorker, Gateway, GatewayConfig, PrefixAffinityRouter,
    TenantQuotas, TokenBucket,
)

TINY = GPTConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 max_position_embeddings=64)


def _model(seed=0):
    paddle.seed(seed)
    m = GPTForCausalLM(TINY)
    m.eval()
    return m


def _cfg(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("max_horizon", 4)
    return EngineConfig(**kw)


def _post(port, payload, timeout=60):
    """POST /v1/completions on a fresh connection; returns the
    http.client response (unread)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", "/v1/completions", json.dumps(payload),
                 {"Content-Type": "application/json"})
    return conn.getresponse()


def _parse_sse(raw):
    """Parse an SSE body into (chunks, finish_reason), asserting the
    wire format: every frame is ``data: <json>`` + blank line, the last
    is the ``data: [DONE]`` sentinel, exactly one chunk carries a
    finish_reason."""
    frames = raw.split("\n\n")
    assert frames[-1] == ""                     # body ends on the blank
    frames = frames[:-1]
    assert frames and all(f.startswith("data: ") for f in frames)
    assert frames[-1] == "data: [DONE]"
    chunks = [json.loads(f[len("data: "):]) for f in frames[:-1]]
    reasons = [c["choices"][0]["finish_reason"] for c in chunks]
    assert all(r is None for r in reasons[:-1])
    assert reasons[-1] is not None
    assert all(c["object"] == "text_completion.chunk" for c in chunks)
    toks = [t for c in chunks for t in c["choices"][0]["token_ids"]]
    return toks, reasons[-1]


class _FakeWorker:
    """Duck-typed replica for router-only tests (no engine, no JAX)."""

    def __init__(self, name, healthy=True, load=0, block=4):
        self.name = name
        self._healthy = healthy
        self.load = load
        self.prefix_block_size = block

    @property
    def healthy(self):
        return self._healthy


# --------------------------------------------------------------------- quotas
class TestTokenBucket:
    def test_refill_and_retry_after(self):
        now = [0.0]
        b = TokenBucket(100, 10, clock=lambda: now[0])
        ok, retry = b.try_take(60)
        assert ok and retry == 0.0
        ok, retry = b.try_take(60)               # only 40 left
        assert not ok and retry == pytest.approx(2.0)
        now[0] += 2.0                            # +20 tokens
        ok, _ = b.try_take(60)
        assert ok and b.available == pytest.approx(0.0)

    def test_oversized_request_points_at_full_bucket(self):
        b = TokenBucket(10, 5, clock=lambda: 0.0)
        ok, retry = b.try_take(1000)             # can never be granted
        assert not ok and retry == pytest.approx(0.0)

    def test_tenant_isolation_and_overrides(self):
        now = [0.0]
        q = TenantQuotas(50, 10, clock=lambda: now[0])
        assert q.admit("a", 50) == (True, 0.0)
        ok, retry = q.admit("a", 1)              # a is broke
        assert not ok and retry > 0
        assert q.admit("b", 50)[0]               # b unaffected
        q.set_quota("vip", 500)
        assert q.admit("vip", 400)[0]

    def test_disabled_by_default(self):
        q = TenantQuotas()
        assert not q.enforcing
        assert q.admit("anyone", 10**9) == (True, 0.0)


# --------------------------------------------------------------------- router
class TestPrefixAffinityRouter:
    def test_affinity_key_chunks_like_radix_cache(self):
        r = PrefixAffinityRouter([_FakeWorker("a", block=4)],
                                 affinity_blocks=2)
        assert r.affinity_key([1, 2, 3]) is None          # < one block
        assert r.affinity_key([1, 2, 3, 4, 5]) == (1, 2, 3, 4)
        assert (r.affinity_key(list(range(20)))
                == tuple(range(8)))                       # capped at 2

    def test_same_prefix_same_replica_distinct_prefixes_spread(self):
        ws = [_FakeWorker(f"w{i}") for i in range(4)]
        r = PrefixAffinityRouter(ws)
        picks = set()
        for suffix in range(10):                 # shared system prompt
            w, how = r.route([1, 2, 3, 4, suffix])
            assert how == "affine"
            picks.add(w.name)
        assert len(picks) == 1                   # sticky
        spread = {r.route([p] * 8)[0].name for p in range(32)}
        assert len(spread) >= 2                  # rendezvous spreads keys

    def test_unhealthy_replica_excluded_until_recovery(self):
        ws = [_FakeWorker("w0"), _FakeWorker("w1")]
        r = PrefixAffinityRouter(ws)
        prompt = [9, 9, 9, 9, 1]
        home, _ = r.route(prompt)
        home._healthy = False                    # SLO burn
        w, how = r.route(prompt)
        assert w is not home and how == "affine"
        home._healthy = True                     # recovered
        assert r.route(prompt)[0] is home        # rendezvous is stable
        ws[0]._healthy = ws[1]._healthy = False
        assert r.route(prompt) == (None, "shed")

    def test_short_prompt_falls_back_to_least_loaded(self):
        ws = [_FakeWorker("w0", load=5), _FakeWorker("w1", load=1)]
        w, how = PrefixAffinityRouter(ws).route([1, 2])
        assert how == "least-loaded" and w.name == "w1"


# ---------------------------------------------------------- priority/deadline
class TestPriorityAdmission:
    """Scheduler-level: priority widens the overtake budget but the
    per-victim cap bounds starvation."""

    @staticmethod
    def _bucket(r):
        return r.prompt_len

    def test_priority_overtakes_within_bound(self):
        s = Scheduler(4, reorder_window=2)
        lo = s.submit([1] * 8, SamplingParams(max_new_tokens=2))
        his = [s.submit([2] * 4, SamplingParams(max_new_tokens=2),
                        priority=1)
               for _ in range(8)]
        order = []
        while s.queue_depth:
            order.extend(s.pop_batch(1, bucket_of=self._bucket))
        # cap = w * (1 + dp) = 2 * (1 + 1) = 4 overtakes, then lo runs
        assert order.index(lo) == 4
        assert lo.bypassed == 4
        assert order[:4] == his[:4] and order[5:] == his[4:]

    def test_equal_priority_stays_fifo(self):
        s = Scheduler(4, reorder_window=4)
        rs = [s.submit([1] * 4, SamplingParams(max_new_tokens=2),
                       priority=3)
              for _ in range(6)]
        got = []
        while s.queue_depth:
            got.extend(s.pop_batch(2, bucket_of=self._bucket))
        assert got == rs

    def test_deadline_expired_queued_request_aborts(self):
        m = _model()
        eng = Engine(m, _cfg(num_slots=1), register_profiler=False)
        runner = eng.submit([1, 2, 3, 4], SamplingParams(max_new_tokens=8))
        doomed = eng.submit([5, 6, 7, 8],
                            SamplingParams(max_new_tokens=8),
                            deadline_s=0.01, tenant="t0")
        time.sleep(0.03)                         # let the deadline pass
        eng.run()
        assert runner.finish_reason == "length"
        assert doomed.finish_reason == "abort"
        c = eng.counters()
        assert c["deadline_expired"] == 1
        assert c["requests_aborted"] == 1
        # the flight record shows queued -> abort(cause=deadline)
        kinds = [(k, a) for k, _, a in doomed.trace.events]
        assert kinds[0][0] == "queued"
        assert kinds[-1][0] == "abort"
        assert kinds[-1][1]["cause"] == "deadline"
        assert doomed.trace.counts()["aborted"] == 1
        # tenant ledger billed the submit and the abort
        t = eng.stats()["tenants"]["t0"]
        assert t["submitted"] == 1 and t["aborted"] == 1
        eng.close()

    def test_admitted_requests_outrun_their_deadline(self):
        m = _model()
        eng = Engine(m, _cfg(num_slots=1), register_profiler=False)
        r = eng.submit([1, 2, 3, 4], SamplingParams(max_new_tokens=6),
                       deadline_s=30.0)
        eng.run()                                # admitted immediately
        assert r.finish_reason == "length" and r.n_generated == 6
        eng.close()


# ---------------------------------------------------------------------- drain
class TestDrain:
    def test_drain_finishes_work_and_releases_every_block(self):
        m = _model()
        eng = Engine(m, _cfg(num_slots=2,
                             prefix_cache_bytes=1 << 20),
                     register_profiler=False)
        a = eng.submit([1, 2, 3, 4], SamplingParams(max_new_tokens=6))
        q = eng.submit([5, 6, 7, 8], SamplingParams(max_new_tokens=6))
        eng.step()                               # a+q admitted, cached
        retired = eng.drain()
        assert eng.pool.blocks_in_use == 0       # the invariant drain asserts
        assert {r.request_id for r in retired} >= set()
        assert a.finish_reason == "length" and q.finish_reason == "length"
        # draining refuses new work...
        # ...but a FINISHED drain leaves the engine usable again
        r = eng.submit([9, 9, 9], SamplingParams(max_new_tokens=2))
        eng.run()
        assert r.n_generated == 2
        eng.close()

    def test_drain_aborts_queued_backlog(self):
        m = _model()
        eng = Engine(m, _cfg(num_slots=1), register_profiler=False)
        eng.submit([1, 2, 3, 4], SamplingParams(max_new_tokens=4))
        backlog = eng.submit([5, 6, 7, 8], SamplingParams(max_new_tokens=4))
        eng.step()
        eng.drain()
        assert backlog.finish_reason == "abort"
        assert eng.pool.blocks_in_use == 0
        eng.close()

    def test_mixed_mesh_replica_drain_releases_every_block(self):
        """A router over one single-chip and one tp=2 MeshEngine
        replica (8 virtual CPU devices): EngineWorker drives the mesh
        engine through the same duck type, both replicas take work, and
        drain's block-leak invariant (``kv_blocks_in_use == 0``) holds
        on the mesh-sharded pool too."""
        from paddle_tpu.serving import MeshEngine

        # one model INSTANCE per replica: engines trace through
        # use_state() on their model, and a mesh engine swaps in
        # locally-SLICED weights — sharing one module object between
        # concurrently-stepping workers would race the swap (benign
        # between same-shape single-chip engines, a shape error against
        # a mesh engine; see the MeshEngine docstring)
        e0 = Engine(_model(), _cfg(num_slots=2), register_profiler=False)
        e1 = MeshEngine(_model(), _cfg(num_slots=2), tp=2,
                        register_profiler=False)
        w0, w1 = EngineWorker(e0, "chip"), EngineWorker(e1, "mesh")
        router = PrefixAffinityRouter([w0, w1])
        handles = []
        for i in range(4):                 # spread across both replicas
            h, _, _ = router.submit([1 + i, 2, 3, 4],
                                    SamplingParams(max_new_tokens=4))
            handles.append(h)
        for h in handles:
            kind, reason = _drain_handle(h)
            assert (kind, reason) == ("finish", "length")
        for w in (w0, w1):
            w.drain()
            assert w.engine.pool.blocks_in_use == 0
            assert w.stats()["kv_pool"]["blocks_in_use"] == 0
            w.stop()
        assert e1.stats()["mesh"]["mesh_shape"] == {"dp": 1, "tp": 2}
        e0.close()
        e1.close()

    def test_worker_rejects_non_engine_objects(self):
        """The duck-type assertion: a router-level fake without the
        Engine API fails fast with the missing names, instead of dying
        later on the worker thread."""
        with pytest.raises(TypeError, match="submit"):
            EngineWorker(object(), "bogus")

    def test_router_remove_is_graceful(self):
        m = _model()
        e0 = Engine(m, _cfg(num_slots=2), register_profiler=False)
        e1 = Engine(m, _cfg(num_slots=2), register_profiler=False)
        w0, w1 = EngineWorker(e0, "w0"), EngineWorker(e1, "w1")
        router = PrefixAffinityRouter([w0, w1])
        h, w, _ = router.submit([1, 2, 3, 4],
                                SamplingParams(max_new_tokens=4))
        router.remove(w, close_engine=False)
        assert w not in router.workers
        kind, reason = _drain_handle(h)
        assert (kind, reason) == ("finish", "length")    # work finished
        assert w.engine.pool.blocks_in_use == 0
        other = router.workers[0]
        with pytest.raises(RuntimeError):
            w.submit([1, 2], SamplingParams(max_new_tokens=1))
        other.drain()
        other.stop()
        e0.close()
        e1.close()


def _drain_handle(h, timeout=30.0):
    """Consume a StreamHandle's event queue to its terminal event."""
    deadline = time.monotonic() + timeout
    toks = []
    while True:
        kind, value = h.events.get(timeout=max(0.1,
                                               deadline - time.monotonic()))
        if kind == "finish":
            return kind, value
        toks.extend(value)


# ----------------------------------------------------------------- HTTP layer
@pytest.mark.slow
class TestGatewayHTTP:
    """One live gateway over two tiny replicas, exercised with stdlib
    http.client — wire format, parity, admission errors, metrics."""

    @pytest.fixture()
    def gw(self):
        m = _model()
        engines = [Engine(m, _cfg(), register_profiler=False)
                   for _ in range(2)]
        g = Gateway(engines,
                    GatewayConfig(model_id="tiny")).start()
        yield g
        g.shutdown()
        for e in engines:
            assert e.pool.blocks_in_use == 0

    def test_models_and_health(self, gw):
        conn = http.client.HTTPConnection("127.0.0.1", gw.port, timeout=30)
        conn.request("GET", "/v1/models")
        r = conn.getresponse()
        doc = json.loads(r.read())
        assert r.status == 200 and doc["data"][0]["id"] == "tiny"
        conn.request("GET", "/readyz")
        r = conn.getresponse()
        assert r.status == 200 and json.loads(r.read())["ready"]
        conn.request("GET", "/nope")
        r = conn.getresponse()
        assert r.status == 404
        assert json.loads(r.read())["error"]["code"] == "route_not_found"

    def test_stream_is_bitwise_in_process_output(self, gw):
        """The tentpole parity claim: streamed SSE tokens equal
        ``Engine.generate`` for the same request — greedy AND
        seeded-stochastic (the engine's fold_in(seed, n) sampling makes
        both deterministic)."""
        m = _model()
        ref = Engine(m, _cfg(), register_profiler=False)
        prompt = list(range(1, 17))
        cases = [
            {"max_tokens": 12},
            {"max_tokens": 12, "temperature": 0.8, "top_k": 8, "seed": 7},
        ]
        for extra in cases:
            sp = SamplingParams(
                max_new_tokens=extra["max_tokens"],
                temperature=extra.get("temperature", 0.0),
                top_k=extra.get("top_k", 0),
                seed=extra.get("seed", 0))
            want = ref.generate(list(prompt), sp)
            r = _post(gw.port, dict(extra, prompt=prompt, stream=True))
            assert r.status == 200
            assert r.getheader("Content-Type").startswith(
                "text/event-stream")
            toks, reason = _parse_sse(r.read().decode())
            assert toks == want                  # bitwise, not approx
            assert reason == "length"
        ref.close()

    def test_sync_completion_shape_and_usage(self, gw):
        r = _post(gw.port, {"model": "tiny", "prompt": [3, 1, 4, 1, 5],
                            "max_tokens": 6})
        doc = json.loads(r.read())
        assert r.status == 200
        assert doc["object"] == "text_completion"
        choice = doc["choices"][0]
        assert len(choice["token_ids"]) == 6
        assert choice["finish_reason"] == "length"
        assert doc["usage"] == {"prompt_tokens": 5,
                                "completion_tokens": 6,
                                "total_tokens": 11}

    def test_validation_errors(self, gw):
        for payload, status, code in (
                ({"prompt": "text"}, 400, None),
                ({"prompt": []}, 400, None),
                ({"prompt": [1, 2.5]}, 400, None),
                ({"prompt": [1, 2], "model": "other"}, 404,
                 "model_not_found"),
                ({"prompt": [1, 2], "top_p": 0.0}, 400, None),
                ({"prompt": [1, 2], "priority": 99}, 400, None),
                ({"prompt": [1, 2], "priority": "high"}, 400, None),
                ({"prompt": [1, 2], "priority": -1, "stream": True},
                 400, "batch_no_stream"),
                ({"prompt": [1, 2], "deadline_s": 0}, 400, None),
                ({"prompt": [1, 2], "stream": "yes"}, 400, None),
                ({"prompt": [1] * 100, "max_tokens": 10}, 400, None)):
            r = _post(gw.port, payload)
            err = json.loads(r.read())["error"]
            assert r.status == status, (payload, err)
            assert err["code"] == code
        # malformed JSON body
        conn = http.client.HTTPConnection("127.0.0.1", gw.port,
                                          timeout=30)
        conn.request("POST", "/v1/completions", "{not json",
                     {"Content-Type": "application/json"})
        assert conn.getresponse().status == 400

    def test_metrics_exposition(self, gw):
        _post(gw.port, {"prompt": [1, 2, 3, 4], "max_tokens": 2,
                        "stream": True}).read()
        conn = http.client.HTTPConnection("127.0.0.1", gw.port, timeout=30)
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        text = r.read().decode()
        assert r.status == 200
        validate_exposition(text)
        for fam in ("gateway_requests", "gateway_streams",
                    "gateway_stream_tokens", "gateway_routed",
                    "gateway_ttft_seconds", "gateway_request_seconds"):
            assert fam in text, fam


@pytest.mark.slow
class TestGatewayAdmissionHTTP:
    def test_quota_429_then_refill_grants(self):
        m = _model()
        eng = Engine(m, _cfg(), register_profiler=False)
        now = [0.0]
        quotas = TenantQuotas(40, 10, clock=lambda: now[0])
        gw = Gateway([eng], GatewayConfig(), quotas=quotas).start()
        try:
            ok = _post(gw.port, {"prompt": [1] * 10, "max_tokens": 20,
                                 "tenant": "acme"})
            ok.read()
            assert ok.status == 200              # cost 30 <= 40
            denied = _post(gw.port, {"prompt": [1] * 10, "max_tokens": 20,
                                     "tenant": "acme"})
            body = json.loads(denied.read())
            assert denied.status == 429
            assert body["error"]["type"] == "tenant_quota_exceeded"
            assert int(denied.getheader("Retry-After")) >= 1
            # another tenant is unaffected
            other = _post(gw.port, {"prompt": [1] * 10, "max_tokens": 20,
                                    "tenant": "other"})
            other.read()
            assert other.status == 200
            now[0] += 3.0                        # refill 30 tokens
            again = _post(gw.port, {"prompt": [1] * 10, "max_tokens": 20,
                                    "tenant": "acme"})
            again.read()
            assert again.status == 200
        finally:
            gw.shutdown()

    def test_slo_breach_sheds_503_with_retry_after(self):
        m = _model()
        eng = Engine(m, _cfg(slo_ttft_s=1e-9, slo_fast_window=4,
                             slo_slow_window=4),
                     register_profiler=False)
        gw = Gateway([eng], GatewayConfig(shed_retry_after_s=2.0)).start()
        try:
            assert eng.slo.healthy
            for _ in range(8):                   # burn both windows
                eng.slo.observe("ttft", 1.0)
            assert not eng.slo.healthy
            conn = http.client.HTTPConnection("127.0.0.1", gw.port,
                                              timeout=30)
            conn.request("GET", "/readyz")       # same signal
            assert conn.getresponse().status == 503
            r = _post(gw.port, {"prompt": [1, 2, 3], "max_tokens": 2})
            body = json.loads(r.read())
            assert r.status == 503
            assert body["error"]["code"] == "slo_shedding"
            assert r.getheader("Retry-After") == "2"
            for _ in range(8):                   # recover
                eng.slo.observe("ttft", 0.0)
            r = _post(gw.port, {"prompt": [1, 2, 3], "max_tokens": 2})
            r.read()
            assert r.status == 200
        finally:
            gw.shutdown()


# ---------------------------------------------------------- affinity end2end
@pytest.mark.slow
class TestAffinityEndToEnd:
    def test_affine_routing_beats_round_robin_on_prefix_hits(self):
        """Two replicas, two 16-token system prompts, four sessions
        each: affinity routing keeps every session on its prefix's home
        replica, so the radix cache serves repeats; round-robin splits
        them and halves the hit rate."""
        m = _model()

        def fleet():
            return [Engine(m, _cfg(num_slots=2,
                                   prefix_block_size=8,
                                   prefix_cache_bytes=1 << 22),
                           register_profiler=False)
                    for _ in range(2)]

        sysA, sysB = [7] * 16, [9] * 16
        prompts = [sys + [i, i + 1, i + 2, i + 3]
                   for sys in (sysA, sysB) for i in range(4)]
        sp = SamplingParams(max_new_tokens=2)

        # affinity routing through real workers
        engines = fleet()
        workers = [EngineWorker(e, f"w{i}")
                   for i, e in enumerate(engines)]
        router = PrefixAffinityRouter(workers, affinity_blocks=2)
        homes = set()
        for p in prompts:
            h, w, how = router.submit(list(p), sp)
            assert how == "affine"
            homes.add((tuple(p[:16]), w.name))
            _drain_handle(h)
        # every session with the same system prompt hit ONE replica
        assert len({n for k, n in homes if k == tuple(sysA)}) == 1
        assert len({n for k, n in homes if k == tuple(sysB)}) == 1
        affine_hits = sum(e.counters()["prefix_hit_tokens"]
                          for e in engines)
        for w in workers:
            w.drain()
            w.stop()
        for e in engines:
            e.close()

        # round-robin baseline on a fresh fleet
        engines = fleet()
        for i, p in enumerate(prompts):
            engines[i % 2].submit(list(p), sp)
        for e in engines:
            e.run()
        rr_hits = sum(e.counters()["prefix_hit_tokens"] for e in engines)
        for e in engines:
            e.close()

        assert affine_hits > rr_hits, (affine_hits, rr_hits)
