"""Self-lint gate: the analyzer must report ZERO error-severity
diagnostics over paddle_tpu/ itself (package mode — trace rules under
@to_static functions, self-lint rules PTA401/PTA402 everywhere). Findings
in library code are either fixed or carry an inline `# noqa: PTA4xx`
with a justification."""

import os

import paddle_tpu
from paddle_tpu.analysis import lint_file, ERROR


def _package_files():
    pkg = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
    for root, dirs, files in os.walk(pkg):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def test_package_self_lint_has_zero_errors():
    errors = []
    n = 0
    for path in _package_files():
        n += 1
        for d in lint_file(path, mode="package"):
            if d.severity == ERROR:
                errors.append(d.format(with_hint=False))
    assert n > 100            # the walk actually covered the package
    assert not errors, "self-lint errors:\n" + "\n".join(errors)


def test_cli_exit_zero_over_package():
    from paddle_tpu.analysis.cli import main

    pkg = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
    assert main([pkg]) == 0
