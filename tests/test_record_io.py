"""Native record-file sample store: PTRECD01 writer/reader parity between
the C++ parallel path and the pure-Python fallback."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import RecordDataset, RecordFile, RecordWriter


def _write(tmp_path, n=20, shape=(4, 6)):
    rng = np.random.RandomState(0)
    arrs = [rng.randn(*shape).astype(np.float32) for _ in range(n)]
    path = str(tmp_path / "data.ptrec")
    with RecordWriter(path) as w:
        for a in arrs:
            w.write(a)
    return path, arrs


class TestRecordIO:
    def test_roundtrip_native(self, tmp_path):
        path, arrs = _write(tmp_path)
        rf = RecordFile(path)
        assert len(rf) == len(arrs)
        got = np.frombuffer(rf.read(3), np.float32).reshape(4, 6)
        np.testing.assert_array_equal(got, arrs[3])

    def test_read_batch_packed(self, tmp_path):
        path, arrs = _write(tmp_path)
        rf = RecordFile(path)
        idxs = [7, 0, 13, 13]
        buf, offsets, sizes = rf.read_batch(idxs)
        for k, i in enumerate(idxs):
            o = int(offsets[k])
            got = buf[o:o + int(sizes[k])].view(np.float32).reshape(4, 6)
            np.testing.assert_array_equal(got, arrs[i])

    def test_python_fallback_parity(self, tmp_path):
        path, arrs = _write(tmp_path)
        rf = RecordFile(path)
        # force the pure-Python scan path
        py = RecordFile.__new__(RecordFile)
        py.path = path
        py._lib = None
        py._h = None
        py._threads = 0
        py._index = RecordFile._scan(path)
        assert len(py) == len(rf)
        assert py.read(5) == rf.read(5)
        b1 = rf.read_batch([1, 2])[0]
        b2 = py.read_batch([1, 2])[0]
        np.testing.assert_array_equal(b1, b2)

    def test_dataset_and_loader(self, tmp_path):
        path, arrs = _write(tmp_path)
        ds = RecordDataset(path, ndarray_spec=(np.float32, (4, 6)))
        assert len(ds) == 20
        np.testing.assert_array_equal(ds[2], arrs[2])
        batch = ds.read_batch([0, 1, 2])
        assert batch.shape == (3, 4, 6)
        np.testing.assert_array_equal(batch[1], arrs[1])
        from paddle_tpu.io import DataLoader

        dl = DataLoader(ds, batch_size=5, num_workers=2)
        out = [b for b in dl]
        assert len(out) == 4
        assert out[0].shape == [5, 4, 6]
        np.testing.assert_allclose(out[0].numpy()[0], arrs[0])

    def test_truncated_tail_dropped(self, tmp_path):
        path, arrs = _write(tmp_path, n=3)
        with open(path, "ab") as f:
            import struct

            f.write(struct.pack("<Q", 999))  # length with no payload
            f.write(b"xy")
        rf = RecordFile(path)
        assert len(rf) == 3  # truncated record ignored

    def test_bad_magic_raises_or_negative(self, tmp_path):
        p = tmp_path / "junk.bin"
        p.write_bytes(b"NOTMAGIC" + b"\0" * 64)
        with pytest.raises((ValueError, OSError)):
            rf = RecordFile(str(p))
            if rf._h is None and not rf._index:
                raise ValueError("bad")
