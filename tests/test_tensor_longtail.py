"""OpTests for the round-2 tensor long tail (VERDICT r1 #5): diagonal,
unfold, as_strided, logcumsumexp, renorm, frexp, cdist, pdist, nanquantile,
plus the root-level linalg re-exports."""

import numpy as np
import scipy.spatial.distance as ssd

import paddle_tpu as paddle
from op_test import OpTest


def _rand(*shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).randn(*shape) * scale).astype(np.float32)


class TestDiagonalOp(OpTest):
    op = staticmethod(lambda x: paddle.diagonal(x, offset=1, axis1=1, axis2=2))
    ref = staticmethod(lambda x: np.diagonal(x, offset=1, axis1=1, axis2=2))

    def setup_method(self, _):
        self.inputs = {"x": _rand(2, 4, 5)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestUnfoldOp(OpTest):
    op = staticmethod(lambda x: paddle.unfold(x, axis=1, size=3, step=2))

    @staticmethod
    def ref(x):
        w = np.lib.stride_tricks.sliding_window_view(x, 3, axis=1)
        return w[:, ::2]

    def setup_method(self, _):
        self.inputs = {"x": _rand(2, 9, 4)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestAsStridedOp(OpTest):
    # overlapping windows over a flat 12-element buffer
    op = staticmethod(
        lambda x: paddle.as_strided(x, shape=[3, 4], stride=[2, 1], offset=1))

    @staticmethod
    def ref(x):
        flat = np.ascontiguousarray(x).reshape(-1)
        it = flat.itemsize
        return np.lib.stride_tricks.as_strided(
            flat[1:], shape=(3, 4), strides=(2 * it, 1 * it)).copy()

    def setup_method(self, _):
        self.inputs = {"x": _rand(3, 4)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        # overlapping views must scatter-ADD grads back
        self.check_grad()


class TestLogcumsumexpOp(OpTest):
    op = staticmethod(lambda x: paddle.logcumsumexp(x, axis=1))
    ref = staticmethod(lambda x: np.logaddexp.accumulate(x, axis=1))

    def setup_method(self, _):
        self.inputs = {"x": _rand(3, 6)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestRenormOp(OpTest):
    op = staticmethod(lambda x: paddle.renorm(x, p=2.0, axis=0, max_norm=1.5))

    @staticmethod
    def ref(x):
        norms = np.sqrt((x ** 2).sum(axis=(1, 2), keepdims=True))
        factor = np.where(norms > 1.5, 1.5 / (norms + 1e-7), 1.0)
        return x * factor

    def setup_method(self, _):
        self.inputs = {"x": _rand(4, 3, 2)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestCdistOp(OpTest):
    op = staticmethod(lambda x, y: paddle.cdist(x, y, p=2.0))
    ref = staticmethod(lambda x, y: ssd.cdist(x, y, metric="euclidean"))

    def setup_method(self, _):
        self.inputs = {"x": _rand(5, 3), "y": _rand(4, 3, seed=1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()

    def test_p1_and_inf_and_batched(self):
        x, y = _rand(2, 5, 3, seed=2), _rand(2, 4, 3, seed=3)
        got = paddle.cdist(paddle.to_tensor(x), paddle.to_tensor(y), p=1.0)
        want = np.stack([ssd.cdist(x[i], y[i], metric="cityblock")
                         for i in range(2)])
        np.testing.assert_allclose(got.numpy(), want, rtol=1e-5, atol=1e-5)
        got = paddle.cdist(paddle.to_tensor(x), paddle.to_tensor(y),
                           p=float("inf"))
        want = np.stack([ssd.cdist(x[i], y[i], metric="chebyshev")
                         for i in range(2)])
        np.testing.assert_allclose(got.numpy(), want, rtol=1e-5, atol=1e-5)


class TestPdistOp(OpTest):
    op = staticmethod(lambda x: paddle.pdist(x, p=2.0))
    ref = staticmethod(lambda x: ssd.pdist(x, metric="euclidean"))

    def setup_method(self, _):
        self.inputs = {"x": _rand(6, 4)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestFrexpNanquantile:
    def test_frexp(self):
        x = _rand(3, 4, scale=10.0)
        m, e = paddle.frexp(paddle.to_tensor(x))
        mr, er = np.frexp(x)
        np.testing.assert_allclose(m.numpy(), mr, rtol=1e-6)
        np.testing.assert_allclose(e.numpy(), er.astype(np.float32))
        # recomposition m * 2**e == x
        np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), x, rtol=1e-6)

    def test_nanquantile(self):
        x = _rand(4, 5)
        x[1, 2] = np.nan
        x[3, 0] = np.nan
        got = paddle.nanquantile(paddle.to_tensor(x), 0.35, axis=1)
        want = np.nanquantile(x, 0.35, axis=1)
        np.testing.assert_allclose(got.numpy(), want, rtol=1e-6, atol=1e-6)
        got = paddle.nanquantile(paddle.to_tensor(x), [0.25, 0.75],
                                 keepdim=True)
        want = np.nanquantile(x, [0.25, 0.75], keepdims=True)
        np.testing.assert_allclose(got.numpy(), want, rtol=1e-6, atol=1e-6)


class TestRootReexports:
    def test_linalg_aliases_at_root(self):
        """The reference exposes these at the paddle root (VERDICT r1 #5)."""
        for name in ("pinv", "slogdet", "matrix_power", "matrix_rank",
                     "multi_dot", "cov", "corrcoef", "det", "inv",
                     "cdist", "pdist", "diagonal", "unfold", "as_strided",
                     "logcumsumexp", "renorm", "frexp", "nanquantile"):
            assert callable(getattr(paddle, name)), name
        a = _rand(3, 3)
        np.testing.assert_allclose(
            paddle.det(paddle.to_tensor(a)).numpy(),
            np.linalg.det(a), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            paddle.matrix_power(paddle.to_tensor(a), 2).numpy(),
            a @ a, rtol=1e-4, atol=1e-4)

    def test_unfold_negative_axis(self):
        x = _rand(3, 8)
        got = paddle.unfold(paddle.to_tensor(x), -1, 2, 3).numpy()
        want = np.lib.stride_tricks.sliding_window_view(x, 2, axis=-1)[:, ::3]
        np.testing.assert_allclose(got, want)

    def test_tensor_methods(self):
        x = paddle.to_tensor(_rand(4, 4))
        assert x.diagonal().shape == [4]
        assert x.unfold(0, 2, 2).shape == [2, 4, 2]
        assert x.logcumsumexp(axis=0).shape == [4, 4]
