"""Fleet observatory tests (observability phase 5): deterministic
workload-trace generation (byte-identical across processes, heavy-tail
and burstiness moments), the discrete-event capacity simulator against
a hand-computed timeline, sim-vs-live calibration plumbing, the
offline batch lane (scheduler + gateway), per-tenant metric gauges,
SLO idle flags, and the live 2-replica HTTP/SSE replay harness with
token-stream parity and engine-counter reconciliation."""

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability import fleetsim, loadgen
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability.loadgen import (
    SLOSpec, WorkloadRequest, WorkloadSpec, WorkloadTrace,
)
from paddle_tpu.observability.fleetsim import ServiceModel
from paddle_tpu.observability.server import TelemetryServer
from paddle_tpu.observability.slo import SLOTracker
from paddle_tpu.serving import (
    Engine, EngineConfig, SamplingParams, Scheduler,
)

TINY = GPTConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 max_position_embeddings=64)


def _model(seed=0):
    paddle.seed(seed)
    m = GPTForCausalLM(TINY)
    m.eval()
    return m


def _cfg(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("max_horizon", 4)
    return EngineConfig(**kw)


# ===================================================== trace determinism
def test_trace_same_seed_byte_identical():
    a = loadgen.generate(loadgen.chat_heavy(seed=7, n_requests=24))
    b = loadgen.generate(loadgen.chat_heavy(seed=7, n_requests=24))
    assert a.to_json() == b.to_json()
    assert a.digest() == b.digest()


def test_trace_different_seed_differs():
    a = loadgen.generate(loadgen.chat_heavy(seed=1, n_requests=24))
    b = loadgen.generate(loadgen.chat_heavy(seed=2, n_requests=24))
    assert a.digest() != b.digest()


def test_trace_byte_identical_across_processes():
    """Same seed => the SAME bytes from a fresh interpreter: the
    generator reads no wall clock and no process-dependent state."""
    here = loadgen.generate(
        loadgen.mixed_chat_batch(seed=11, n_requests=20)).digest()
    script = (
        "from paddle_tpu.observability import loadgen;"
        "print(loadgen.generate(loadgen.mixed_chat_batch("
        "seed=11, n_requests=20)).digest())")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=300,
                         env=env)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == here


def test_trace_roundtrip():
    trace = loadgen.generate(loadgen.mixed_chat_batch(seed=3,
                                                      n_requests=16))
    back = WorkloadTrace.from_json(trace.to_json())
    assert back.to_json() == trace.to_json()
    assert back.digest() == trace.digest()
    assert isinstance(back.spec.priority_levels, tuple)
    assert back.requests[0] == trace.requests[0]


def test_spec_validation():
    with pytest.raises(ValueError):
        loadgen.generate(WorkloadSpec(n_requests=0))
    with pytest.raises(ValueError):
        loadgen.generate(WorkloadSpec(priority_levels=(0, 1),
                                      priority_weights=(1.0,)))


def test_trace_moments():
    """Heavy tails and burstiness are the point of the generator —
    check the moments, not just the plumbing."""
    trace = loadgen.generate(loadgen.chat_heavy(seed=0,
                                                n_requests=256))
    gaps = np.diff([r.t_submit for r in trace.requests])
    cv = gaps.std() / gaps.mean()
    assert cv > 1.05          # MMPP arrivals are burstier than Poisson

    prompts = np.array([r.prompt_len for r in trace.requests])
    spec = trace.spec
    assert prompts.max() <= spec.prompt_len_max
    assert np.percentile(prompts, 99) >= 2 * np.median(prompts)

    outs = np.array([r.max_new_tokens for r in trace.requests])
    assert outs.max() <= spec.max_new_tokens_cap
    assert np.percentile(outs, 99) >= 2 * np.median(outs)

    # Zipf tenancy: the head tenant dominates
    tenants = [r.tenant for r in trace.requests]
    counts = sorted((tenants.count(t) for t in set(tenants)),
                    reverse=True)
    assert counts[0] >= 2 * counts[-1]

    mixed = loadgen.generate(loadgen.mixed_chat_batch(seed=0,
                                                      n_requests=256))
    frac = sum(1 for r in mixed.requests if r.priority < 0) / 256
    assert 0.2 < frac < 0.5   # batch_fraction=0.35 within noise
    assert all(not r.stream for r in mixed.requests if r.priority < 0)


# ==================================================== simulator timeline
def _micro_trace(requests):
    spec = WorkloadSpec(seed=0, n_requests=len(requests))
    return WorkloadTrace(spec, requests)


def _req(index, t, prompt_len, max_new, *, pop=0, prefix_len=0,
         priority=0, deadline_s=None, abort_after_s=None):
    return WorkloadRequest(
        index=index, t_submit=t, tenant="t0", priority=priority,
        prompt_ids=list(range(prompt_len)), prefix_len=prefix_len,
        prefix_pop=pop, max_new_tokens=max_new, deadline_s=deadline_s,
        abort_after_s=abort_after_s, stream=priority >= 0,
        arrived_in_burst=False)


def test_sim_hand_computed_timeline():
    """3-request micro-trace on one single-slot replica against the
    timeline computed by hand: queueing, prefix-cache hit, exact
    phase latencies."""
    model = ServiceModel(prefill_s_per_token=0.01,
                         decode_s_per_token=0.1, overhead_s=0.0)
    trace = _micro_trace([
        _req(0, 0.0, 10, 3, pop=7, prefix_len=4),
        _req(1, 0.1, 10, 2, pop=7, prefix_len=4),   # hits r0's prefix
        _req(2, 0.2, 5, 2, pop=9),
    ])
    rep = fleetsim.simulate(trace, 1, model, num_slots=1,
                            slo=SLOSpec(ttft_s=0.3, tpot_s=0.5))
    by = {r["index"]: r for r in rep["records"]}
    # r0: admitted at 0, prefill 10*0.01=0.1, decode 2*0.1 -> done 0.3
    assert by[0]["queue_s"] == pytest.approx(0.0, abs=1e-9)
    assert by[0]["ttft_s"] == pytest.approx(0.1, abs=1e-9)
    assert by[0]["tokens"] == 3
    assert by[0]["prefix_hit_tokens"] == 0
    # r1: waits for r0's slot until 0.3; 4-token prefix hit
    assert by[1]["queue_s"] == pytest.approx(0.2, abs=1e-9)
    assert by[1]["prefix_hit_tokens"] == 4
    assert by[1]["ttft_s"] == pytest.approx(0.26, abs=1e-9)
    # r2: waits until 0.46 = 0.3 + prefill .06 + decode .1
    assert by[2]["queue_s"] == pytest.approx(0.26, abs=1e-9)
    assert by[2]["ttft_s"] == pytest.approx(0.31, abs=1e-9)
    assert all(r["completed"] for r in rep["records"])
    # SLO ttft 0.3: r0 and r1 attain, r2 misses
    assert rep["attainment"] == pytest.approx(2 / 3, abs=1e-6)


def test_sim_abort_truncates_and_deadline_expires():
    model = ServiceModel(prefill_s_per_token=0.01,
                         decode_s_per_token=0.1, overhead_s=0.0)
    trace = _micro_trace([
        _req(0, 0.0, 10, 5, abort_after_s=0.15),
        _req(1, 0.0, 10, 5, pop=1, deadline_s=0.05),
    ])
    rep = fleetsim.simulate(trace, 1, model, num_slots=1)
    by = {r["index"]: r for r in rep["records"]}
    # abort at 0.15: first token at 0.1, one decode boundary crossed
    assert by[0]["aborted"] and not by[0]["completed"]
    assert by[0]["tokens"] == 1
    # r1 still queued when its 0.05 deadline passed
    assert by[1]["deadline_expired"] and by[1]["aborted"]
    assert rep["deadline_expired"] == 1


def test_sim_deterministic_and_curve_monotone():
    trace = loadgen.generate(loadgen.chat_heavy(seed=0, n_requests=48,
                                                rate_rps=24.0))
    model = ServiceModel(prefill_s_per_token=9e-3,
                         decode_s_per_token=7e-3, overhead_s=1e-3)
    slo = SLOSpec(ttft_s=0.35, tpot_s=0.25)
    a = fleetsim.simulate(trace, 2, model, speed=4.0, slo=slo)
    b = fleetsim.simulate(trace, 2, model, speed=4.0, slo=slo)
    assert json.dumps(a, sort_keys=True) == json.dumps(b,
                                                       sort_keys=True)
    curve = fleetsim.attainment_curve(trace, (1, 2, 4), model,
                                      speed=4.0, slo=slo)
    attains = [c["attainment"] for c in curve]
    assert attains == sorted(attains)      # more replicas never hurt
    assert attains[-1] > attains[0]        # and the curve separates


def test_sim_shed_when_fleet_full():
    model = ServiceModel(prefill_s_per_token=0.0,
                         decode_s_per_token=1.0, overhead_s=0.0)
    reqs = [_req(i, 0.0, 2, 8, pop=i) for i in range(6)]
    rep = fleetsim.simulate(_micro_trace(reqs), 1, model, num_slots=1,
                            max_queue=2)
    assert rep["shed"] == 3       # 1 running + 2 queued, rest shed
    assert rep["completed"] == 3


# ================================================= service model + calib
def test_service_model_from_replay_medians():
    records = [
        {"completed": True, "tpot_s": 0.01, "ttft_s": 0.3,
         "queue_s": 0.1, "prompt_tokens": 11, "prefix_hit_tokens": 1},
        {"completed": True, "tpot_s": 0.03, "ttft_s": 0.5,
         "queue_s": 0.1, "prompt_tokens": 5, "prefix_hit_tokens": 0},
        {"completed": False, "tpot_s": 9.9},     # ignored
    ]
    m = ServiceModel.from_replay({"records": records})
    assert m.decode_s_per_token == pytest.approx(0.03)
    # medians: (0.3-0.1)/10 = 0.02 and (0.5-0.1)/5 = 0.08 -> upper mid
    assert m.prefill_s_per_token == pytest.approx(0.08)


def test_service_model_from_program_cards_empty_registry():
    from paddle_tpu.observability.profiling import ProgramCardRegistry

    m = ServiceModel.from_program_cards(registry=ProgramCardRegistry())
    d = ServiceModel()
    assert m.prefill_s_per_token == d.prefill_s_per_token
    assert m.decode_s_per_token == d.decode_s_per_token


def test_calibration_report_tie_aware_ordering():
    model = ServiceModel(prefill_s_per_token=0.0,
                         decode_s_per_token=0.0, overhead_s=0.0)
    trace = _micro_trace([_req(0, 0.0, 2, 2)])
    # sim attains 1.0 at both counts; live ties within eps -> ok even
    # though the exact sorted orders disagree
    live = {1: {"attainment": 1.0}, 2: {"attainment": 0.97}}
    cal = fleetsim.calibration_report(trace, live, model, speed=1.0,
                                      tolerance=0.1, tie_eps=0.05)
    assert cal["ordering_consistent"] and not cal["ordering_exact"]
    assert cal["ok"]
    # a live separation beyond eps that the sim contradicts must fail
    live = {1: {"attainment": 0.5}, 2: {"attainment": 1.0}}
    cal = fleetsim.calibration_report(trace, live, model, speed=1.0,
                                      tolerance=0.6, tie_eps=0.05)
    assert cal["ordering_consistent"]      # sim ties: no strict flip
    live_rep = {1: {"attainment": 1.0}, 2: {"attainment": 0.5}}
    m2 = ServiceModel(prefill_s_per_token=0.0, decode_s_per_token=10.0,
                      overhead_s=0.0)
    # build a sim that strictly prefers MORE replicas while live says
    # strictly fewer: 2 slow requests, one slot each
    trace2 = _micro_trace([_req(0, 0.0, 2, 3, pop=0),
                           _req(1, 0.0, 2, 3, pop=4)])
    cal = fleetsim.calibration_report(
        trace2, live_rep, m2, speed=1.0, tolerance=1.0, tie_eps=0.05,
        num_slots=1, slo=SLOSpec(ttft_s=15.0, tpot_s=99.0))
    assert not cal["ordering_consistent"]
    assert not cal["ok"]


def test_fleet_report_sim_only():
    report = fleetsim.fleet_report(shapes=("chat", "mixed"),
                                   replica_counts=(1, 2),
                                   n_requests=16, seed=0, live=False)
    assert set(report["shapes"]) == {"chat", "mixed"}
    for shape in report["shapes"].values():
        assert [c["replicas"] for c in shape["curve"]] == [1, 2]
        for c in shape["curve"]:
            assert 0.0 <= c["attainment"] <= 1.0
    assert report["ok"] and report["calibration"] is None
    json.dumps(report)                     # JSON-serializable end-to-end


def test_summarize_batch_tier_attains_on_completion():
    slo = SLOSpec(ttft_s=0.001, tpot_s=0.001)   # impossible latencies
    records = [
        {"index": 0, "tenant": "a", "tier": "batch", "priority": -1,
         "prompt_tokens": 4, "tokens": 3, "prefix_hit_tokens": 0,
         "completed": True, "shed": False, "aborted": False,
         "deadline_expired": False, "queue_s": 5.0, "ttft_s": 9.0,
         "tpot_s": 1.0},
        {"index": 1, "tenant": "a", "tier": "p0", "priority": 0,
         "prompt_tokens": 4, "tokens": 3, "prefix_hit_tokens": 0,
         "completed": True, "shed": False, "aborted": False,
         "deadline_expired": False, "queue_s": 0.0, "ttft_s": 9.0,
         "tpot_s": 1.0},
    ]
    rep = loadgen.summarize(records, slo=slo)
    assert rep["per_tier"]["batch"]["attainment"] == 1.0
    assert rep["per_tier"]["p0"]["attainment"] == 0.0


# ======================================================= batch lane (sched)
def test_batch_lane_unbounded_overtake():
    s = Scheduler(num_slots=1, reorder_window=2)
    b = s.submit([1], SamplingParams(max_new_tokens=1), priority=-1)
    inter = [s.submit([1, 2], SamplingParams(max_new_tokens=1))
             for _ in range(12)]
    assert s.overtake_cap(b, inter[0]) == math.inf
    s.promote()
    order = [r.priority for r in s.queue]
    assert order[-1] == -1 and all(p == 0 for p in order[:-1])
    assert b.bypassed == 12
    # batch-vs-batch keeps the plain FIFO window
    y = s.submit([1], SamplingParams(max_new_tokens=1), priority=-1)
    assert s.overtake_cap(b, y) == 2
    # ...and batch never overtakes interactive without budget math
    assert s.overtake_cap(inter[0], y) == 2


def test_batch_lane_skips_dont_seal_scan():
    s = Scheduler(num_slots=4, reorder_window=2)
    head = s.submit([1], SamplingParams(max_new_tokens=1))
    for _ in range(6):
        s.submit([9] * 5, SamplingParams(max_new_tokens=1), priority=-1)
    tail = [s.submit([1], SamplingParams(max_new_tokens=1))
            for _ in range(3)]
    batch = s.pop_batch(4, bucket_of=lambda r: r.prompt_len)
    assert [r.request_id for r in batch] == \
        [head.request_id] + [t.request_id for t in tail]


def test_engine_accepts_batch_priority_and_ledger():
    e = Engine(_model(), _cfg(), register_profiler=False)
    try:
        r_int = e.submit([1, 2, 3], SamplingParams(max_new_tokens=2),
                         tenant="acme")
        r_bat = e.submit([4, 5], SamplingParams(max_new_tokens=2),
                         priority=-1, tenant="bulk")
        e.run()
        assert len(r_int.output_ids) == 2
        assert len(r_bat.output_ids) == 2
        led = e.tenant_ledger()
        assert led["acme"]["tokens_generated"] == 2
        assert led["bulk"]["tokens_generated"] == 2
        assert led["acme"]["finished"] == 1
    finally:
        e.close()
    assert e.pool.blocks_in_use == 0


# ======================================================= gateway batch lane
def test_gateway_batch_lane_parse_rules():
    from paddle_tpu.serving.gateway import GatewayConfig
    from paddle_tpu.serving.gateway.protocol import Gateway, _Reject

    gw = Gateway.__new__(Gateway)           # parse only, no engines
    gw.config = GatewayConfig(model_id="m")
    parsed = gw.parse_completion({"prompt": [1, 2], "priority": -7})
    assert parsed["priority"] == -1         # one batch tier
    assert parsed["stream"] is False        # batch => non-streaming
    with pytest.raises(_Reject) as exc:
        gw.parse_completion({"prompt": [1, 2], "priority": -1,
                             "stream": True})
    assert exc.value.status == 400
    assert exc.value.code == "batch_no_stream"
    with pytest.raises(_Reject):
        gw.parse_completion({"prompt": [1, 2], "priority": 99})


# ===================================================== slo idle + telemetry
def test_slo_idle_flags():
    t = SLOTracker("fleet-test", registry=obs_metrics.Registry())
    t.declare("ttft", 0.5)
    snap = t.snapshot()
    assert snap["idle"] is True
    obj = snap["objectives"]["ttft"]
    assert obj["idle"] is True and obj["fast"]["idle"] is True
    assert obj["fast"]["compliance"] == 1.0      # vacuous, but flagged
    t.observe("ttft", 0.1)
    snap = t.snapshot()
    assert snap["idle"] is False
    assert snap["objectives"]["ttft"]["fast"]["idle"] is False
    assert snap["objectives"]["ttft"]["slow"]["samples"] == 1


def test_debug_fleet_route():
    srv = TelemetryServer(fleet=lambda: {"ok": True, "shapes": {}})
    status, ctype, body = srv.handle("/debug/fleet")
    assert status == 200 and b'"ok": true' in body
    srv2 = TelemetryServer()
    status, _, body = srv2.handle("/debug/fleet")
    assert status == 200 and b"hint" in body
    assert "/debug/fleet" in json.loads(
        srv2.handle("/")[2].decode())["endpoints"]


# ========================================================== live replay
@pytest.mark.slow
def test_live_two_replica_replay_reconciles_and_matches():
    """The acceptance loop: replay a seeded trace against a live
    2-replica gateway over real HTTP/SSE; token counts reconstructed
    from the trace must equal the engines' own counters, streamed
    token ids must be bitwise-equal to an in-process generate on the
    same weights, tenant gauges must publish, and no blocks may leak
    after drain."""
    obs_metrics.reset()
    spec = loadgen.calibration_probe(seed=5, n_requests=12,
                                     batch_fraction=0.25)
    trace = loadgen.generate(spec)
    gw = fleetsim.build_cpu_proxy_gateway(2, seed=0)
    try:
        report = loadgen.replay(trace, gw, speed=10.0,
                                slo=SLOSpec(ttft_s=30.0, tpot_s=30.0))
        rec = loadgen.reconcile_tokens(gw, report)
        assert rec["client_tokens"] == rec["flight_tokens"]
        assert rec["client_tokens"] == rec["ledger_tokens"]
        assert report["completed"] == len(trace.requests)
        assert report["shed"] == 0

        # bitwise stream parity vs an in-process generate on the same
        # weights (greedy; the proxy engines all share seed 0)
        probe = max((r for r in report["records"]
                     if r.get("completed") and r["token_ids"]),
                    key=lambda r: r["tokens"])
        req = trace.requests[probe["index"]]
        ref = Engine(_model(0),
                     _cfg(max_horizon=1, ragged_attention=False),
                     register_profiler=False)
        try:
            want = ref.generate(
                list(req.prompt_ids),
                SamplingParams(max_new_tokens=req.max_new_tokens,
                               temperature=0.0))
        finally:
            ref.close()
        assert probe["token_ids"] == list(want)

        # the per-tenant ledger made it to real scrapeable gauges
        ledger = gw.tenant_ledger()
        assert sum(v["tokens_generated"] for v in ledger.values()) \
            == rec["ledger_tokens"]
        top = max(ledger, key=lambda t: ledger[t]["tokens_generated"])
        assert obs_metrics.value("gateway.tenant_tokens_served",
                                 tenant=top) \
            == ledger[top]["tokens_generated"]
        assert "gateway_tenant_tokens_served" in \
            obs_metrics.render_prometheus()

        # per-tier rollup covers the batch lane end to end
        assert report["per_tier"].get("batch", {}).get("completed", 0) \
            > 0
    finally:
        gw.shutdown()
    for w in gw.workers:
        assert w.engine.pool.blocks_in_use == 0


@pytest.mark.slow
def test_live_shed_billed_to_tenant_gauge():
    from paddle_tpu.serving.gateway import GatewayConfig
    from paddle_tpu.serving.gateway.protocol import Gateway

    obs_metrics.reset()
    e = Engine(_model(), _cfg(), register_profiler=False)
    gw = Gateway([e], GatewayConfig(model_id="m", quota_tokens=5.0,
                                    quota_refill_per_s=0.001)).start()
    try:
        import http.client

        sheds = 0
        for _ in range(4):
            conn = http.client.HTTPConnection("127.0.0.1", gw.port,
                                              timeout=30)
            conn.request("POST", "/v1/completions",
                         json.dumps({"model": "m", "prompt": [1, 2, 3],
                                     "max_tokens": 2,
                                     "tenant": "greedy"}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            if resp.status == 429:
                sheds += 1
            conn.close()
        assert sheds > 0
        assert obs_metrics.value("gateway.tenant_sheds",
                                 tenant="greedy") == sheds
        assert gw.tenant_ledger()["greedy"]["sheds"] == sheds
    finally:
        gw.shutdown()
    assert e.pool.blocks_in_use == 0
