"""Op tests through the OpTest harness (SURVEY.md §4 reference pattern):
NumPy-reference output check (eager + jit) and numeric-gradient check."""

import numpy as np
import scipy.special

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import OpTest


def _rand(*shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).randn(*shape) * scale).astype(np.float32)


class TestMatmulOp(OpTest):
    op = staticmethod(lambda x, y: paddle.matmul(x, y))
    ref = staticmethod(lambda x, y: x @ y)

    def setup_method(self, _):
        self.inputs = {"x": _rand(3, 4), "y": _rand(4, 5, seed=1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestSoftmaxOp(OpTest):
    op = staticmethod(lambda x: F.softmax(x, axis=-1))
    ref = staticmethod(lambda x: scipy.special.softmax(x, axis=-1))

    def setup_method(self, _):
        self.inputs = {"x": _rand(4, 6)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestGeluOp(OpTest):
    op = staticmethod(lambda x: F.gelu(x))
    ref = staticmethod(
        lambda x: 0.5 * x * (1 + scipy.special.erf(x / np.sqrt(2))))

    def setup_method(self, _):
        self.inputs = {"x": _rand(3, 5)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestLayerNormOp(OpTest):
    op = staticmethod(lambda x, w, b: F.layer_norm(x, (8,), weight=w, bias=b))

    @staticmethod
    def _np_ln(x, w, b):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + 1e-5) * w + b

    ref = staticmethod(lambda x, w, b: TestLayerNormOp._np_ln(x, w, b))

    def setup_method(self, _):
        self.inputs = {"x": _rand(4, 8), "w": _rand(8, seed=2, scale=0.5) + 1.0,
                       "b": _rand(8, seed=3, scale=0.1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(max_relative_error=1e-2)


class TestLogSumExpOp(OpTest):
    op = staticmethod(lambda x: paddle.logsumexp(x, axis=-1))
    ref = staticmethod(lambda x: scipy.special.logsumexp(x, axis=-1))

    def setup_method(self, _):
        self.inputs = {"x": _rand(5, 7)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestSigmoidOp(OpTest):
    op = staticmethod(lambda x: F.sigmoid(x))
    ref = staticmethod(lambda x: scipy.special.expit(x))

    def setup_method(self, _):
        self.inputs = {"x": _rand(4, 4)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestConv2dOp(OpTest):
    op = staticmethod(lambda x, w: F.conv2d(x, w, stride=1, padding=1))

    @staticmethod
    def _np_conv(x, w):
        n, cin, h, wd = x.shape
        cout, _, kh, kw = w.shape
        xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
        out = np.zeros((n, cout, h, wd), x.dtype)
        for i in range(h):
            for j in range(wd):
                patch = xp[:, :, i:i + kh, j:j + kw]
                out[:, :, i, j] = np.einsum("ncij,ocij->no", patch, w)
        return out

    ref = staticmethod(lambda x, w: TestConv2dOp._np_conv(x, w))

    def setup_method(self, _):
        self.inputs = {"x": _rand(2, 3, 5, 5), "w": _rand(4, 3, 3, 3, seed=1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(max_relative_error=1e-2)


class TestLogSoftmaxOp(OpTest):
    op = staticmethod(lambda x: F.log_softmax(x, axis=-1))
    ref = staticmethod(
        lambda x: x - scipy.special.logsumexp(x, axis=-1, keepdims=True))

    def setup_method(self, _):
        self.inputs = {"x": _rand(4, 9)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestTanhOp(OpTest):
    op = staticmethod(lambda x: paddle.tanh(x))
    ref = staticmethod(lambda x: np.tanh(x))  # ufunc arg isn't named 'x'

    def setup_method(self, _):
        self.inputs = {"x": _rand(3, 7)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()
