"""Fault-tolerance tests: deterministic fault plans and replay,
mid-stream replica failover with bitwise-seamless resume (greedy AND
seeded), the worker watchdog, typed dead-worker errors, retry/backoff
determinism, the graceful-degradation ladder, and a chaos run that
reconciles every injected fault against the recovery it caused."""

import queue
import time

import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (
    Engine, EngineConfig, SamplingParams,
)
from paddle_tpu.serving.faults import (
    DEGRADE_LEVELS, FAULT_CRASH, FAULT_EXCEPTION, FAULT_POOL_EXHAUSTED,
    FAULT_STALL, FAULT_SUBMIT_FAIL, SITE_ENGINE_ADMIT,
    SITE_WORKER_DISPATCH, SITE_WORKER_SUBMIT, DispatchFault,
    FaultInjector, FaultPlan, FaultSpec, RetryPolicy,
    TransientSubmitError, WorkerCrash, WorkerDeadError,
)
from paddle_tpu.serving.gateway import (
    EngineWorker, FleetSupervisor, PrefixAffinityRouter,
)

TINY = GPTConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 max_position_embeddings=64)

PROMPT = list(range(1, 9))
GREEDY = SamplingParams(max_new_tokens=24)
SEEDED = SamplingParams(temperature=0.8, top_k=20, seed=11,
                        max_new_tokens=24)


def _model(seed=0):
    paddle.seed(seed)
    m = GPTForCausalLM(TINY)
    m.eval()
    return m


def _cfg(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("max_horizon", 4)
    return EngineConfig(**kw)


def _reference(prompt, samp):
    """The uninterrupted single-engine stream every failover run must
    reproduce bitwise."""
    eng = Engine(_model(0), _cfg(), register_profiler=False)
    req = eng.submit(prompt, samp)
    while eng.scheduler.has_work:
        eng.step()
    eng.close()
    return list(req.output_ids)


def _fleet(n, **cfg_kw):
    workers = [
        EngineWorker(Engine(_model(0), _cfg(**cfg_kw),
                            register_profiler=False), name=f"r{i}")
        for i in range(n)]
    return workers, PrefixAffinityRouter(workers, retry=RetryPolicy())


def _warm(workers, seeded=False):
    """Run a request through each replica so compile caches are hot
    before a test arms a tight watchdog (a cold XLA compile would be
    indistinguishable from a hung dispatch).  ``seeded`` additionally
    compiles the seeded-sampling decode program and the prefill bucket
    failover resumes land in."""
    for w in workers:
        h = w.submit(list(range(30, 36)),
                     sampling=SamplingParams(max_new_tokens=3))
        _drain(h)
        if seeded:
            h = w.submit(list(range(50, 62)),
                         sampling=SamplingParams(max_new_tokens=3,
                                                 temperature=0.7,
                                                 top_k=16, seed=1))
            _drain(h)


def _drain(h, timeout=180.0):
    """Consume a StreamHandle to its terminal event; returns
    (tokens, finish_reason)."""
    got, deadline = [], time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            kind, val = h.events.get(timeout=0.5)
        except queue.Empty:
            continue
        if kind == "tokens":
            got.extend(val)
        else:
            return got, val
    raise TimeoutError(f"stream {h.request_id} did not finish")


def _shutdown(workers, sup=None):
    if sup is not None:
        sup.stop()
    for w in workers:
        if w.alive:
            w.stop()


# ---------------------------------------------------------------- fault plans
class TestFaultPlan:
    def test_spec_validation(self):
        with pytest.raises(ValueError):
            FaultSpec("no.such.site", FAULT_CRASH, at=0)
        with pytest.raises(ValueError):
            FaultSpec(SITE_WORKER_SUBMIT, FAULT_CRASH, at=0)  # wrong site
        with pytest.raises(ValueError):
            FaultSpec(SITE_WORKER_DISPATCH, FAULT_CRASH, at=-1)
        with pytest.raises(ValueError):
            FaultSpec(SITE_WORKER_DISPATCH, FAULT_CRASH, at=0, times=0)

    def test_spec_matching_window_and_scope(self):
        s = FaultSpec(SITE_WORKER_DISPATCH, FAULT_EXCEPTION, at=2,
                      scope="r1", times=3)
        assert not s.matches("r1", SITE_WORKER_DISPATCH, 1)
        assert all(s.matches("r1", SITE_WORKER_DISPATCH, n)
                   for n in (2, 3, 4))
        assert not s.matches("r1", SITE_WORKER_DISPATCH, 5)
        assert not s.matches("r0", SITE_WORKER_DISPATCH, 2)
        wild = FaultSpec(SITE_WORKER_DISPATCH, FAULT_EXCEPTION, at=0)
        assert wild.matches("anything", SITE_WORKER_DISPATCH, 0)

    def test_injector_raises_by_kind_and_records(self):
        inj = FaultInjector(FaultPlan([
            FaultSpec(SITE_WORKER_DISPATCH, FAULT_EXCEPTION, at=1),
            FaultSpec(SITE_WORKER_DISPATCH, FAULT_STALL, at=2),
            FaultSpec(SITE_WORKER_SUBMIT, FAULT_SUBMIT_FAIL, at=0),
            FaultSpec(SITE_ENGINE_ADMIT, FAULT_POOL_EXHAUSTED, at=0),
        ]))
        assert inj.fire(SITE_WORKER_DISPATCH, scope="a") is None
        with pytest.raises(DispatchFault):
            inj.fire(SITE_WORKER_DISPATCH, scope="a")
        spec = inj.fire(SITE_WORKER_DISPATCH, scope="a")
        assert spec.kind == FAULT_STALL      # returned, not raised
        with pytest.raises(TransientSubmitError):
            inj.fire(SITE_WORKER_SUBMIT, scope="a")
        assert inj.fire(SITE_ENGINE_ADMIT,
                        scope="a").kind == FAULT_POOL_EXHAUSTED
        assert inj.counts() == {FAULT_EXCEPTION: 1, FAULT_STALL: 1,
                                FAULT_SUBMIT_FAIL: 1,
                                FAULT_POOL_EXHAUSTED: 1}

    def test_ordinals_are_scope_independent(self):
        inj = FaultInjector(FaultPlan([
            FaultSpec(SITE_WORKER_DISPATCH, FAULT_CRASH, at=1,
                      scope="r0")]))
        # r1's visits never advance r0's ordinal
        for _ in range(5):
            assert inj.fire(SITE_WORKER_DISPATCH, scope="r1") is None
        assert inj.fire(SITE_WORKER_DISPATCH, scope="r0") is None
        with pytest.raises(WorkerCrash):
            inj.fire(SITE_WORKER_DISPATCH, scope="r0")

    def test_replay_is_bitwise(self):
        plan = FaultPlan.chaos(seed=42, scopes=("r0", "r1", "r2"))

        def run():
            inj = FaultInjector(plan)
            for scope in ("r0", "r1", "r2"):
                for site in (SITE_WORKER_DISPATCH, SITE_WORKER_SUBMIT,
                             SITE_ENGINE_ADMIT):
                    for _ in range(30):
                        try:
                            inj.fire(site, scope=scope)
                        except Exception:
                            pass
            return list(inj.fired)

        assert run() == run()

    def test_chaos_schedule_determinism_and_safety(self):
        a = FaultPlan.chaos(seed=7, scopes=("r0", "r1"))
        b = FaultPlan.chaos(seed=7, scopes=("r0", "r1"))
        assert a.specs == b.specs
        assert a.specs != FaultPlan.chaos(seed=8,
                                          scopes=("r0", "r1")).specs
        # at most one fatal fault per scope: a plan that kills every
        # replica proves nothing about recovery
        fatal = {}
        for s in a.specs:
            if s.kind in (FAULT_CRASH, FAULT_STALL):
                fatal[s.scope] = fatal.get(s.scope, 0) + 1
        assert all(n <= 1 for n in fatal.values())
        doc = a.to_json()
        assert doc["seed"] == 7
        assert len(doc["specs"]) == len(a.specs)


class TestRetryPolicy:
    def test_deterministic_jitter(self):
        p = RetryPolicy(seed=3)
        assert p.delay(5, 1) == RetryPolicy(seed=3).delay(5, 1)
        assert p.delay(5, 1) != p.delay(6, 1)      # no thundering herd
        assert p.delay(5, 1) != RetryPolicy(seed=4).delay(5, 1)

    def test_capped_exponential_bounds(self):
        p = RetryPolicy(backoff_base_s=0.1, backoff_cap_s=0.4)
        for attempt in range(6):
            want = min(0.4, 0.1 * 2 ** attempt)
            d = p.delay(0, attempt)
            assert want * 0.5 <= d < want


# ----------------------------------------------------------- engine-level
class TestEngineFaults:
    def test_pool_exhausted_defers_admission_bitwise(self):
        ref = _reference(PROMPT, GREEDY)
        eng = Engine(_model(0), _cfg(), register_profiler=False)
        eng.install_faults(FaultInjector(FaultPlan([
            FaultSpec(SITE_ENGINE_ADMIT, FAULT_POOL_EXHAUSTED, at=0)])),
            scope="e0")
        req = eng.submit(PROMPT, GREEDY)
        eng.step()
        # the injected dry pool deferred the whole admission pass
        assert eng._admit_deferred
        assert not req.output_ids
        while eng.scheduler.has_work:
            eng.step()
        assert list(req.output_ids) == ref
        assert eng.pool.blocks_in_use == 0
        eng.close()


class TestDegradationLadder:
    def _burning(self, eng, burn=True):
        for _ in range(8):
            eng.slo.observe("ttft", 1.0 if burn else 0.0)

    def test_escalation_recovery_and_hysteresis(self):
        eng = Engine(_model(0),
                     _cfg(slo_ttft_s=0.01, slo_fast_window=4,
                          slo_slow_window=4, degrade_patience=2,
                          degrade_recover_patience=3),
                     register_profiler=False)
        self._burning(eng)
        assert not eng.slo.healthy
        for want in (1, 2, 3):
            eng._update_degradation()
            assert eng._degrade_level == want - 1   # patience not met
            eng._update_degradation()
            assert eng._degrade_level == want
        assert DEGRADE_LEVELS[eng._degrade_level] == "shed"
        # the ladder tops out
        for _ in range(4):
            eng._update_degradation()
        assert eng._degrade_level == 3
        # level >= 1 turns speculation off, level >= 2 pins horizon 1
        assert eng._resolve_spec_k() == 0
        assert eng._resolve_horizon() == 1
        # recovery is slower than escalation (hysteresis) ...
        self._burning(eng, burn=False)
        assert eng.slo.healthy
        eng._update_degradation()
        eng._update_degradation()
        assert eng._degrade_level == 3
        eng._update_degradation()
        assert eng._degrade_level == 2
        # ... and one burning step resets the calm streak entirely
        eng._update_degradation()
        eng._update_degradation()
        self._burning(eng)
        eng._update_degradation()
        self._burning(eng, burn=False)
        eng._update_degradation()
        eng._update_degradation()
        assert eng._degrade_level == 2
        eng._update_degradation()
        assert eng._degrade_level == 1
        hist = eng._degrade_history
        assert [h["reason"] for h in hist[:3]] == ["slo_burn"] * 3
        assert hist[-1]["reason"] == "recovered"
        assert eng.counters()["degradation_level"] == 1
        eng.close()

    def test_level3_sheds_lowest_priority_never_resumed(self):
        r_prompt = list(range(40, 48))
        r_samp = SamplingParams(max_new_tokens=8)
        # the true first token of the resumed stream — the resume path
        # asserts the re-sampled boundary token matches it bitwise
        first = _reference(r_prompt, r_samp)[0]
        eng = Engine(_model(0), _cfg(num_slots=2),
                     register_profiler=False)
        samp = SamplingParams(max_new_tokens=4)
        keep = eng.submit(list(range(10, 18)), samp, priority=2)
        low = eng.submit(list(range(20, 28)), samp, priority=0)
        resumed = eng.submit(r_prompt, r_samp, priority=0,
                             resume_ids=[first])
        eng._set_degrade_level(3, "test")
        eng.admit()
        # queue shed down to num_slots: the lowest-priority fresh
        # request goes first; the resumed one is immune (its tokens
        # are already on the wire)
        assert low.finish_reason == "abort"
        assert keep.finish_reason is None
        assert resumed.finish_reason is None
        assert eng.counters()["degradation_sheds"] == 1
        while eng.scheduler.has_work:
            eng.step()
        assert len(keep.output_ids) == 4
        assert len(resumed.output_ids) == 8
        assert eng.pool.blocks_in_use == 0
        eng.close()


# ------------------------------------------------------------- dead workers
class TestWorkerDeath:
    def test_crashed_worker_typed_errors_and_closed_books(self):
        w = EngineWorker(Engine(_model(0), _cfg(),
                                register_profiler=False), name="rd")
        w.set_faults(FaultInjector(FaultPlan([
            FaultSpec(SITE_WORKER_DISPATCH, FAULT_CRASH, at=0)])))
        h = w.submit(PROMPT, sampling=SamplingParams(max_new_tokens=8))
        w._thread.join(60)
        assert not w._thread.is_alive()
        assert w.crashed and isinstance(w._crash_error, WorkerCrash)
        assert not w.healthy
        # typed, prompt errors instead of hangs (the old behaviour)
        with pytest.raises(WorkerDeadError):
            w.drain()
        with pytest.raises(WorkerDeadError):
            w.submit(PROMPT)
        t0 = time.monotonic()
        w.stop()                                    # no-op, returns now
        assert time.monotonic() - t0 < 1.0
        # the dying thread closed its engine's books: the in-flight
        # request was aborted (trace closure) and every block released
        assert h.request.finish_reason == "abort"
        assert w.engine.pool.blocks_in_use == 0
        assert w.stats()["worker"]["crashed"]


# ----------------------------------------------------------------- failover
class TestFailover:
    def _crash_run(self, samp):
        ref = _reference(PROMPT, samp)
        workers, router = _fleet(2)
        sup = FleetSupervisor(router, watchdog_timeout_s=None,
                              interval_s=0.05)
        try:
            _warm(workers)
            target, _ = router.route(PROMPT)
            inj = FaultInjector(FaultPlan([
                FaultSpec(SITE_WORKER_DISPATCH, FAULT_CRASH, at=2)]))
            target.set_faults(inj)
            sup.start()
            h, w0, _ = router.submit(PROMPT, sampling=samp)
            assert w0 is target
            got, fin = _drain(h)
            assert fin == "length"
            assert got == ref                    # bitwise-seamless
            assert h.failovers == 1
            assert h.worker is not target
            assert sup.failovers == 1 and sup.failover_failures == 0
            assert sup.condemned == [(target.name, "crash")]
            assert inj.counts() == {FAULT_CRASH: 1}
            # the adopting engine's flight record shows the seam
            c = h.request.trace.counts()
            assert c["failovers"] == 1
            assert 0 < c["resumed_tokens"] < len(ref)
            # the resumed tokens are NOT double-counted as emitted
            assert c["resumed_tokens"] + c["tokens_emitted"] == len(ref)
            # survivors leak nothing
            h.worker.drain()
            assert h.worker.engine.pool.blocks_in_use == 0
        finally:
            _shutdown(workers, sup)

    def test_mid_stream_crash_failover_greedy_bitwise(self):
        self._crash_run(GREEDY)

    def test_mid_stream_crash_failover_seeded_bitwise(self):
        self._crash_run(SEEDED)

    def test_watchdog_condemns_stalled_worker_and_fails_over(self):
        ref = _reference(PROMPT, GREEDY)
        workers, router = _fleet(2)
        sup = FleetSupervisor(router, watchdog_timeout_s=None,
                              interval_s=0.05)
        try:
            _warm(workers)
            target, _ = router.route(PROMPT)
            target.set_faults(FaultInjector(FaultPlan([
                FaultSpec(SITE_WORKER_DISPATCH, FAULT_STALL, at=2)])))
            # tight leash on the stall target only — survivors may
            # still be compiling the resume bucket
            target.watchdog_timeout_s = 0.3
            sup.start()
            h, w0, _ = router.submit(PROMPT, sampling=GREEDY)
            assert w0 is target
            got, fin = _drain(h)
            assert (got, fin) == (ref, "length")
            assert sup.condemned == [(target.name, "watchdog_stall")]
            assert sup.failovers == 1
            # the condemned stall raised out: the thread is dead and
            # closed its engine's books (serving.* provider included)
            target._thread.join(30)
            assert target.crashed
            assert target.engine.pool.blocks_in_use == 0
        finally:
            _shutdown(workers, sup)

    def test_abort_during_failover_cancels_redispatch(self):
        workers, router = _fleet(2)
        sup = FleetSupervisor(router, watchdog_timeout_s=None)
        try:
            _warm(workers)
            target, _ = router.route(PROMPT)
            inj = FaultInjector(FaultPlan([
                FaultSpec(SITE_WORKER_DISPATCH, FAULT_STALL, at=2)]))
            target.set_faults(inj)
            h, w0, _ = router.submit(PROMPT, sampling=GREEDY)
            deadline = time.monotonic() + 60
            while not inj.fired and time.monotonic() < deadline:
                time.sleep(0.01)
            assert inj.fired[0][2] == FAULT_STALL
            # drive the condemnation by hand so the client abort can
            # land exactly between claim and re-dispatch
            target._condemned = True
            pending = target.take_pending()
            assert h.failing_over and h.request_id in pending
            h.abort()                      # client hangs up mid-swap
            assert h.abort_requested
            sup._failover(h, target, "watchdog_stall")
            got, fin = _drain(h)
            assert fin == "abort"
            assert sup.failovers == 0      # re-dispatch was cancelled
        finally:
            _shutdown(workers, sup)

    def test_abort_after_failover_routes_to_adopting_replica(self):
        workers, router = _fleet(2)
        sup = FleetSupervisor(router, watchdog_timeout_s=None,
                              interval_s=0.05)
        try:
            _warm(workers)
            target, _ = router.route(PROMPT)
            target.set_faults(FaultInjector(FaultPlan([
                FaultSpec(SITE_WORKER_DISPATCH, FAULT_CRASH, at=1)])))
            sup.start()
            h, w0, _ = router.submit(
                PROMPT, sampling=SamplingParams(max_new_tokens=48))
            deadline = time.monotonic() + 120
            while h.failovers == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert h.failovers == 1
            # the abort API of the DEAD original worker still lands on
            # whichever replica holds the request now
            w0.abort(h)
            got, fin = _drain(h)
            assert fin in ("abort", "length")
        finally:
            _shutdown(workers, sup)

    def test_finished_resume_history_is_finished_directly(self):
        workers, router = _fleet(2)
        sup = FleetSupervisor(router, watchdog_timeout_s=None)
        try:
            samp = SamplingParams(max_new_tokens=4)
            h = workers[0].submit(PROMPT, sampling=samp)
            got, fin = _drain(h)
            assert fin == "length" and len(got) == 4
            # reconstruct the race: the worker died after flushing the
            # last token but before the finish event reached the client
            h.failing_over = True
            sup._failover(h, workers[0], "crash")
            assert h.events.get(timeout=5) == ("finish", "length")
            assert sup.failovers == 1      # counted, but no re-decode
        finally:
            _shutdown(workers, sup)


# ------------------------------------------------------------- router retry
class TestRouterRetry:
    def test_transient_submit_retried_to_success(self):
        workers, router = _fleet(2)
        try:
            _warm(workers)
            inj = FaultInjector(FaultPlan([
                FaultSpec(SITE_WORKER_SUBMIT, FAULT_SUBMIT_FAIL, at=0)]))
            for w in workers:
                w.set_faults(inj)
            h, w, _ = router.submit(PROMPT, sampling=GREEDY)
            got, fin = _drain(h)
            assert fin == "length" and len(got) == 24
            assert inj.counts()[FAULT_SUBMIT_FAIL] >= 1
        finally:
            _shutdown(workers)

    def test_spent_budget_propagates_typed_error(self):
        workers, router = _fleet(2)
        router.retry = RetryPolicy(max_retries=1, backoff_base_s=0.001)
        try:
            _warm(workers)
            inj = FaultInjector(FaultPlan([
                FaultSpec(SITE_WORKER_SUBMIT, FAULT_SUBMIT_FAIL, at=0,
                          times=100)]))
            for w in workers:
                w.set_faults(inj)
            with pytest.raises(TransientSubmitError):
                router.submit(PROMPT, sampling=GREEDY)
            # budget: initial attempt + max_retries
            assert inj.counts()[FAULT_SUBMIT_FAIL] == 2
        finally:
            _shutdown(workers)


# -------------------------------------------------------------------- chaos
@pytest.mark.slow
class TestChaos:
    def test_chaos_run_reconciles_and_leaks_nothing(self):
        """Crash + stall + transient submits + a dry-pool admission
        over 16 concurrent requests on 3 replicas: every stream
        finishes bitwise-correct, every injected fault reconciles
        against the recovery it caused, and survivors leak zero
        blocks."""
        n_req = 16
        prompts = [[(7 * i + j) % 96 + 1 for j in range(8)]
                   for i in range(n_req)]
        samps = [SamplingParams(max_new_tokens=8 + (i % 3) * 4,
                                **({} if i % 2 == 0 else
                                   dict(temperature=0.7, top_k=16,
                                        seed=100 + i)))
                 for i in range(n_req)]
        refs = {}
        ref_eng = Engine(_model(0), _cfg(num_slots=4),
                         register_profiler=False)
        for p, s in zip(prompts, samps):
            req = ref_eng.submit(p, s)
            while ref_eng.scheduler.has_work:
                ref_eng.step()
            refs[tuple(p)] = list(req.output_ids)
        ref_eng.close()

        workers, router = _fleet(3)
        inj = FaultInjector(FaultPlan([
            FaultSpec(SITE_WORKER_DISPATCH, FAULT_CRASH, at=3,
                      scope="r0"),
            FaultSpec(SITE_WORKER_DISPATCH, FAULT_STALL, at=4,
                      scope="r1"),
            FaultSpec(SITE_WORKER_DISPATCH, FAULT_EXCEPTION, at=2,
                      scope="r2"),
            FaultSpec(SITE_WORKER_SUBMIT, FAULT_SUBMIT_FAIL, at=4,
                      scope="r2", times=2),
            FaultSpec(SITE_ENGINE_ADMIT, FAULT_POOL_EXHAUSTED, at=1,
                      scope="r2"),
        ]))
        sup = FleetSupervisor(router, watchdog_timeout_s=None,
                              interval_s=0.05)
        try:
            _warm(workers, seeded=True)
            # leash on the stall target: comfortably above any residual
            # compile (the seeded warm-up covered the big programs) but
            # short enough that the frozen-heartbeat stall is caught
            workers[1].watchdog_timeout_s = 5.0
            for w in workers:
                w.set_faults(inj)
            sup.start()
            handles = []
            # pin the first six 2-per-replica so every replica holds
            # in-flight work when its fault fires; route the rest
            for i in range(6):
                handles.append(workers[i % 3].submit(
                    prompts[i], sampling=samps[i]))
            for i in range(6, n_req):
                h, _, _ = router.submit(prompts[i], sampling=samps[i])
                handles.append(h)
            for p, s, h in zip(prompts, samps, handles):
                got, fin = _drain(h)
                assert fin in ("length", "eos")
                assert got == refs[tuple(p)], (
                    f"stream diverged for prompt {p}")
            # reconciliation: the injected fatal faults each condemned
            # exactly one replica, every adopted stream is counted, and
            # the transient faults were absorbed (retried), not fatal
            fired = inj.counts()
            assert fired[FAULT_CRASH] == 1 and fired[FAULT_STALL] == 1
            assert fired.get(FAULT_SUBMIT_FAIL, 0) >= 1
            assert sorted(r for _, r in sup.condemned) == [
                "crash", "watchdog_stall"]
            assert sup.failovers == sum(h.failovers for h in handles)
            assert sup.failovers >= 2       # both fatals held work
            assert sup.failover_failures == 0
            assert workers[2]._dispatch_faults == 1
            # survivors: drain clean, zero leaked blocks, and their
            # flight records reconcile with their engine counters
            for w in workers:
                if not w.alive:
                    continue
                w.drain()
                assert w.engine.pool.blocks_in_use == 0
                eng = w.engine
                emitted = sum(
                    t.counts()["tokens_emitted"]
                    for t in (eng.recorder.recent() + eng.recorder.live())
                    if t.engine == eng._profiler_name)
                assert emitted == eng.counters()["tokens_generated"]
        finally:
            _shutdown(workers, sup)
