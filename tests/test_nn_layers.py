"""Layer tests vs NumPy references (SURVEY.md §4 API/layer-test pattern)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestLayerBase:
    def test_parameter_registration(self):
        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 3)
                self.w = paddle.Parameter(np.zeros((2, 2), np.float32))

            def forward(self, x):
                return self.fc(x) + self.w.sum()

        m = M()
        names = [n for n, _ in m.named_parameters()]
        assert set(names) == {"fc.weight", "fc.bias", "w"}
        assert len(m.parameters()) == 3

    def test_state_dict_roundtrip(self):
        m = nn.Linear(4, 3)
        sd = m.state_dict()
        m2 = nn.Linear(4, 3)
        missing, unexpected = m2.set_state_dict(sd)
        assert not missing and not unexpected
        np.testing.assert_array_equal(m2.weight.numpy(), m.weight.numpy())

    def test_train_eval_propagates(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training

    def test_buffers(self):
        bn = nn.BatchNorm1D(3)
        assert "_mean" in dict(bn.named_buffers())
        assert "_mean" in bn.state_dict()

    def test_hooks(self):
        m = nn.Linear(2, 2)
        calls = []
        h = m.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
        m(paddle.randn([1, 2]))
        assert calls
        h.remove()

    def test_sublayers_apply(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Sequential(nn.Linear(2, 2)))
        assert len(m.sublayers()) == 3
        seen = []
        m.apply(lambda l: seen.append(type(l).__name__))
        assert "Linear" in seen


class TestCoreLayers:
    def test_linear_matches_numpy(self):
        fc = nn.Linear(4, 3)
        x = paddle.randn([5, 4])
        ref = x.numpy() @ fc.weight.numpy() + fc.bias.numpy()
        np.testing.assert_allclose(fc(x).numpy(), ref, rtol=1e-5, atol=1e-6)

    def test_embedding(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        out = emb(paddle.to_tensor([[1, 0], [2, 3]]))
        assert out.shape == [2, 2, 4]
        np.testing.assert_array_equal(out.numpy()[0, 1], np.zeros(4))

    def test_conv2d_shape_and_value(self):
        conv = nn.Conv2D(2, 4, 3, padding=1, stride=2)
        x = paddle.randn([1, 2, 8, 8])
        assert conv(x).shape == [1, 4, 4, 4]
        # value check vs explicit correlation for a tiny case
        c = nn.Conv2D(1, 1, 2, bias_attr=False)
        k = c.weight.numpy()[0, 0]
        a = np.random.rand(1, 1, 3, 3).astype(np.float32)
        out = c(paddle.to_tensor(a)).numpy()[0, 0]
        ref = np.array([[ (a[0,0,i:i+2,j:j+2]*k).sum() for j in range(2)] for i in range(2)])
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_conv_transpose_inverts_stride(self):
        ct = nn.Conv2DTranspose(3, 2, 2, stride=2)
        x = paddle.randn([1, 3, 4, 4])
        assert ct(x).shape == [1, 2, 8, 8]

    def test_groupnorm_layernorm_rmsnorm(self):
        x = paddle.randn([2, 4, 3, 3])
        gn = nn.GroupNorm(2, 4)
        out = gn(x)
        grouped = out.numpy().reshape(2, 2, 2 * 9)
        np.testing.assert_allclose(grouped.mean(-1), 0, atol=1e-5)

        rms = nn.RMSNorm(6)
        y = paddle.randn([2, 6])
        o = rms(y)
        ref = y.numpy() / np.sqrt((y.numpy() ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(o.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_batchnorm_running_stats(self):
        bn = nn.BatchNorm1D(3, momentum=0.5)
        x = paddle.to_tensor(np.random.rand(8, 3).astype(np.float32) + 5)
        bn(x)
        assert bn._mean.numpy().mean() > 1.0  # moved toward batch mean
        bn.eval()
        y = bn(x)
        ref = (x.numpy() - bn._mean.numpy()) / np.sqrt(bn._variance.numpy() + 1e-5)
        np.testing.assert_allclose(y.numpy(), ref * bn.weight.numpy() + bn.bias.numpy(), rtol=1e-4, atol=1e-4)

    def test_dropout_modes(self):
        x = paddle.ones([1000])
        d = nn.Dropout(0.5)
        y = d(x)
        kept = (y.numpy() != 0).mean()
        assert 0.3 < kept < 0.7
        np.testing.assert_allclose(y.numpy()[y.numpy() != 0], 2.0, rtol=1e-6)
        d.eval()
        np.testing.assert_array_equal(d(x).numpy(), x.numpy())

    def test_activations(self):
        a = np.random.randn(4, 4).astype(np.float32)
        x = paddle.to_tensor(a)
        np.testing.assert_allclose(F.relu(x).numpy(), np.maximum(a, 0))
        np.testing.assert_allclose(F.sigmoid(x).numpy(), 1 / (1 + np.exp(-a)), rtol=1e-5)
        from scipy.special import erf

        np.testing.assert_allclose(
            F.gelu(x).numpy(), 0.5 * a * (1 + erf(a / np.sqrt(2))), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(F.silu(x).numpy(), a / (1 + np.exp(-a)), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(F.softmax(x).numpy().sum(-1), np.ones(4), rtol=1e-5)

    def test_pools(self):
        x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        mp = F.max_pool2d(x, 2, 2)
        np.testing.assert_array_equal(mp.numpy()[0, 0], [[5, 7], [13, 15]])
        ap = F.avg_pool2d(x, 2, 2)
        np.testing.assert_allclose(ap.numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])
        aap = F.adaptive_avg_pool2d(x, 2)
        np.testing.assert_allclose(aap.numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])

    def test_interpolate(self):
        x = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
        up = F.interpolate(x, scale_factor=2, mode="nearest")
        assert up.shape == [1, 1, 4, 4]
        assert up.numpy()[0, 0, 0, 0] == 0 and up.numpy()[0, 0, 3, 3] == 3

    def test_pad(self):
        x = paddle.ones([1, 1, 2, 2])
        y = F.pad(x, [1, 1, 0, 0])
        assert y.shape == [1, 1, 2, 4]


class TestRecurrent:
    def test_lstm_shapes_and_grad(self):
        lstm = nn.LSTM(4, 8, num_layers=2)
        x = paddle.randn([2, 5, 4])
        out, (h, c) = lstm(x)
        assert out.shape == [2, 5, 8]
        assert h.shape == [2, 2, 8] and c.shape == [2, 2, 8]
        out.mean().backward()
        assert lstm._parameters["weight_ih_l0"].grad is not None

    def test_bidirectional(self):
        gru = nn.GRU(4, 8, direction="bidirectional")
        out, h = gru(paddle.randn([2, 5, 4]))
        assert out.shape == [2, 5, 16]

    def test_cell_matches_rnn(self):
        cell = nn.LSTMCell(4, 8)
        x = paddle.randn([2, 4])
        h, (h2, c2) = cell(x)
        assert h.shape == [2, 8]


class TestTransformer:
    def test_encoder_decoder(self):
        model = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=2,
                               num_decoder_layers=2, dim_feedforward=32)
        src = paddle.randn([2, 6, 16])
        tgt = paddle.randn([2, 4, 16])
        out = model(src, tgt)
        assert out.shape == [2, 4, 16]

    def test_causal_mask_blocks_future(self):
        mha = nn.MultiHeadAttention(8, 2)
        mha.eval()
        x = paddle.randn([1, 4, 8])
        mask = nn.Transformer.generate_square_subsequent_mask(4)
        out1 = mha(x, x, x, attn_mask=mask)
        x2_np = x.numpy().copy()
        x2_np[0, 3] = 999.0  # future token change must not affect position 0
        x2 = paddle.to_tensor(x2_np)
        out2 = mha(x2, x2, x2, attn_mask=mask)
        np.testing.assert_allclose(out1.numpy()[0, 0], out2.numpy()[0, 0], rtol=1e-4, atol=1e-5)

    def test_incremental_cache_matches_full(self):
        mha = nn.MultiHeadAttention(8, 2)
        mha.eval()
        x = paddle.randn([1, 3, 8])
        mask = nn.Transformer.generate_square_subsequent_mask(3)
        full = mha(x, x, x, attn_mask=mask)
        cache = mha.gen_cache(x[:, :0, :])
        outs = []
        for i in range(3):
            step = x[:, i : i + 1, :]
            o, cache = mha(step, step, step, None, cache)
            outs.append(o.numpy())
        inc = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(full.numpy(), inc, rtol=1e-4, atol=1e-5)


class TestLosses:
    def test_cross_entropy_ignore_index(self):
        logits = paddle.randn([4, 5])
        labels = paddle.to_tensor([1, 2, -100, 3])
        loss = F.cross_entropy(logits, labels, ignore_index=-100)
        l = logits.numpy() - logits.numpy().max(-1, keepdims=True)
        p = np.exp(l) / np.exp(l).sum(-1, keepdims=True)
        ref = -np.log(p[[0, 1, 3], [1, 2, 3]]).mean()
        np.testing.assert_allclose(float(loss), ref, rtol=1e-5)

    def test_soft_label_and_smoothing(self):
        logits = paddle.randn([2, 3])
        soft = paddle.to_tensor(np.array([[0.2, 0.3, 0.5], [1.0, 0, 0]], np.float32))
        loss = F.cross_entropy(logits, soft, soft_label=True)
        assert float(loss) > 0
        loss2 = F.cross_entropy(logits, paddle.to_tensor([1, 0]), label_smoothing=0.1)
        assert float(loss2) > 0

    def test_mse_bce(self):
        a, b = paddle.randn([3, 3]), paddle.randn([3, 3])
        np.testing.assert_allclose(float(F.mse_loss(a, b)), ((a.numpy() - b.numpy()) ** 2).mean(), rtol=1e-5)
        p = paddle.uniform([4], min=0.1, max=0.9)
        y = paddle.to_tensor([1.0, 0, 1, 0])
        ref = -(y.numpy() * np.log(p.numpy()) + (1 - y.numpy()) * np.log(1 - p.numpy())).mean()
        np.testing.assert_allclose(float(F.binary_cross_entropy(p, y)), ref, rtol=1e-4)
        logits = paddle.randn([4])
        l1 = F.binary_cross_entropy_with_logits(logits, y)
        l2 = F.binary_cross_entropy(F.sigmoid(logits), y)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-4)

    def test_kl_nll(self):
        logp = F.log_softmax(paddle.randn([3, 4]))
        tgt = F.softmax(paddle.randn([3, 4]))
        assert float(F.kl_div(logp, tgt, reduction="batchmean")) is not None
        lbl = paddle.to_tensor([0, 1, 2])
        np.testing.assert_allclose(
            float(F.nll_loss(logp, lbl)),
            -logp.numpy()[[0, 1, 2], [0, 1, 2]].mean(),
            rtol=1e-5,
        )

    def test_ctc_loss_runs(self):
        T, B, C, S = 6, 2, 5, 3
        log_probs = paddle.randn([T, B, C])
        labels = paddle.to_tensor(np.random.randint(1, C, (B, S)))
        loss = F.ctc_loss(log_probs, labels,
                          paddle.to_tensor([T, T]), paddle.to_tensor([S, 2]))
        assert np.isfinite(float(loss))


class TestClip:
    def test_global_norm_clip(self):
        g1 = paddle.to_tensor(np.ones(4, np.float32) * 3)
        g2 = paddle.to_tensor(np.ones(4, np.float32) * 4)
        p1, p2 = paddle.Parameter(np.zeros(4)), paddle.Parameter(np.zeros(4))
        clip = nn.ClipGradByGlobalNorm(1.0)
        out = clip([(p1, g1), (p2, g2)])
        total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in out))
        np.testing.assert_allclose(total, 1.0, rtol=1e-5)

    def test_value_clip(self):
        clip = nn.ClipGradByValue(0.5)
        (_, g), = clip([(None, paddle.to_tensor([1.0, -2.0]))])
        np.testing.assert_allclose(g.numpy(), [0.5, -0.5])
