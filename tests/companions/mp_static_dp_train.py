"""Companion: STATIC-GRAPH data-parallel training across two real
processes (the reference's fleet static path, SURVEY.md §3.3/§3.5):
each trainer builds the same recorded-DAG program (seeded identically),
feeds ITS OWN batch shard to Executor.run, and the executor assembles
the global sharded feed — GSPMD's grad allreduce keeps the replicated
parameters identical across processes. MP_SERIAL=1 runs the identical
program single-process on the full batch."""

import os

SERIAL = os.environ.get("MP_SERIAL") == "1"
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                           + ("8" if SERIAL else "4"))
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.static as static
from paddle_tpu.distributed import fleet


def main():
    if not SERIAL:
        dist.init_parallel_env()
        assert len(jax.local_devices()) == 4
    assert jax.device_count() == 8, jax.device_count()
    dist.create_hybrid_communicate_group(dp=8)

    rng = np.random.RandomState(0)
    X = rng.randn(32, 8).astype(np.float32)
    Y = X.sum(-1, keepdims=True).astype(np.float32)
    rank = 0 if SERIAL else dist.get_rank()
    n_proc = 1 if SERIAL else int(os.environ["PADDLE_TRAINERS_NUM"])
    share = 32 // n_proc
    lo, hi = rank * share, (rank + 1) * share

    paddle.enable_static()
    with static.program_guard(static.Program()):
        paddle.seed(0)          # same init on every process
        x = static.data("x", [None, 8], "float32")
        y = static.data("y", [None, 1], "float32")
        h = paddle.nn.functional.relu(static.nn.fc(x, 16))
        loss = paddle.mean((static.nn.fc(h, 1) - y) ** 2)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.Adam(learning_rate=0.05),
            strategy=fleet.DistributedStrategy())
        opt.minimize(loss)
        assert opt._static_dp_mesh is not None
        exe = static.Executor()
        losses = []
        for _ in range(4):
            (lv,) = exe.run(feed={"x": X[lo:hi], "y": Y[lo:hi]},
                            fetch_list=[loss])
            losses.append(round(float(lv), 6))
    paddle.disable_static()
    print("MP_LOSSES", rank, losses, flush=True)


if __name__ == "__main__":
    main()
