"""Companion for the failure-path test: 2-process DP training where rank 1
dies HARD (os._exit, no shutdown handshake — a segfault/preemption stand-in)
mid-run. The surviving rank keeps issuing cross-process collectives; the
coordination service must surface the peer loss as an error (taking the pod
down) instead of hanging, and each launcher must propagate its child's exit
status."""

import os

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn


def main():
    dist.init_parallel_env()
    rank = dist.get_rank()
    hcg = dist.create_hybrid_communicate_group(sharding=4)
    from paddle_tpu.distributed.sharding.group_sharded import (
        GroupShardedTrainStep,
    )
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters())

    def loss_fn(net, x, y):
        return nn.functional.mse_loss(net(x), y)

    step = GroupShardedTrainStep(model, loss_fn, opt, level="os",
                                 mesh=hcg.mesh)
    rng = np.random.RandomState(0)
    X = rng.randn(32, 8).astype(np.float32)
    Y = X.sum(-1, keepdims=True).astype(np.float32)
    share = 16
    lo, hi = rank * share, (rank + 1) * share
    gx = multihost_utils.host_local_array_to_global_array(
        X[lo:hi], hcg.mesh, P("sharding"))
    gy = multihost_utils.host_local_array_to_global_array(
        Y[lo:hi], hcg.mesh, P("sharding"))

    for i in range(2000):
        loss = step(paddle.Tensor(gx), paddle.Tensor(gy))
        float(loss)  # sync every step — the survivor must touch the wire
        print(f"KILLSTEP {rank} {i}", flush=True)
        if rank == 1 and i == 3:
            os._exit(7)  # hard death, no coordination-service goodbye


if __name__ == "__main__":
    main()
