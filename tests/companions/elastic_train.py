"""Elastic end-to-end companion (SURVEY.md §5 failure detection/elastic):
data-parallel training over PADDLE_TRAINERS_NUM virtual CPU devices with
periodic SHARDED checkpoints. When the elastic supervisor relaunches this
script at a new world size, it resumes from the latest checkpoint — params
written under the old mesh reshard onto the new one (reshard-on-load,
distributed/checkpoint). Each step appends {world, step, loss} to
ELASTIC_LOG so the driving test can watch progress across restarts.
"""

import json
import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import paddle_tpu.distributed.checkpoint as dckpt  # noqa: E402

WORLD = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
CKPT = os.environ["ELASTIC_CKPT_DIR"]
LOG = os.environ["ELASTIC_LOG"]
POINTER = os.path.join(CKPT, "LATEST")


def _log(step, loss):
    with open(LOG, "a") as f:
        f.write(json.dumps({"world": WORLD, "step": step,
                            "loss": float(loss)}) + "\n")


def _latest_ckpt():
    if not os.path.exists(POINTER):
        return None
    with open(POINTER) as f:
        path = f.read().strip()
    return path if path and os.path.isdir(path) else None


def _save(state, step):
    # write to a fresh dir, then atomically swing the LATEST pointer — a
    # SIGTERM mid-save must never corrupt the resume point
    path = os.path.join(CKPT, f"step_{step}")
    dckpt.save_state_dict(state, path)
    tmp = POINTER + ".tmp"
    with open(tmp, "w") as f:
        f.write(path)
    os.replace(tmp, POINTER)


def main():
    os.makedirs(CKPT, exist_ok=True)
    mesh = Mesh(np.array(jax.devices()[:WORLD]), ("dp",))
    paddle.seed(0)
    model = nn.Linear(8, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    sd = model.state_dict()
    # params sharded over dp where the leading axis divides (the weight's
    # 8 rows) — a world-size change makes resume a REAL reshard
    for n, t in sd.items():
        spec = P("dp") if (t._data.ndim >= 1
                           and t._data.shape[0] % WORLD == 0) else P()
        t._data = jax.device_put(t._data, NamedSharding(mesh, spec))

    state = dict(sd)
    state["__step__"] = np.zeros((), np.int32)
    step0 = 0
    latest = _latest_ckpt()
    if latest is not None:
        dckpt.load_state_dict(state, latest)
        step0 = int(np.asarray(state["__step__"])) + 1
        for n in sd:
            sd[n]._data = state[n]._data if hasattr(state[n], "_data") \
                else sd[n]._data

    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype(np.float32)
    Y = (X @ rng.randn(8, 1).astype(np.float32))

    for step in range(step0, step0 + 5000):
        xb = paddle.to_tensor(X)
        yb = paddle.to_tensor(Y)
        loss = nn.functional.mse_loss(model(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        _log(step, float(loss))
        if step % 5 == 4:
            state["__step__"] = np.asarray(step, np.int32)
            _save(state, step)
        time.sleep(0.05)


if __name__ == "__main__":
    main()
