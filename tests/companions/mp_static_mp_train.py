"""Companion: STATIC-GRAPH dp x TENSOR-PARALLEL training across two real
processes (r5, VERDICT r4 item 6 — the static analog of the reference's
tensor_parallel_optimizer, fleet/meta_optimizers/ (U)): each trainer
builds the same recorded-DAG program, feeds its own dp batch shard, and
the executor compiles with params SHARDED over the mp axis spanning both
processes — GSPMD's tensor-parallel collectives cross the process
boundary. MP_SERIAL=1 runs the identical program single-process."""

import os

SERIAL = os.environ.get("MP_SERIAL") == "1"
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                           + ("8" if SERIAL else "4"))
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.static as static
from paddle_tpu.distributed import fleet


def main():
    if not SERIAL:
        dist.init_parallel_env()
        assert len(jax.local_devices()) == 4
    assert jax.device_count() == 8, jax.device_count()
    # mp axis of 4 spans the process boundary (2 local devices each side)
    dist.create_hybrid_communicate_group(dp=2, mp=4)

    rng = np.random.RandomState(0)
    X = rng.randn(32, 8).astype(np.float32)
    Y = X.sum(-1, keepdims=True).astype(np.float32)
    rank = 0 if SERIAL else dist.get_rank()
    n_proc = 1 if SERIAL else int(os.environ["PADDLE_TRAINERS_NUM"])
    share = 32 // n_proc
    lo, hi = rank * share, (rank + 1) * share

    paddle.enable_static()
    with static.program_guard(static.Program()):
        paddle.seed(0)          # same init on every process
        x = static.data("x", [None, 8], "float32")
        y = static.data("y", [None, 1], "float32")
        h = paddle.nn.functional.relu(static.nn.fc(x, 16))
        loss = paddle.mean((static.nn.fc(h, 1) - y) ** 2)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.Adam(learning_rate=0.05),
            strategy=fleet.DistributedStrategy())
        _, pairs = opt.minimize(loss)
        assert opt._static_dp_mesh is not None
        exe = static.Executor()
        losses = []
        for _ in range(4):
            (lv,) = exe.run(feed={"x": X[lo:hi], "y": Y[lo:hi]},
                            fetch_list=[loss])
            losses.append(round(float(lv), 6))
        # the wide fc weight really is sharded over mp
        specs = [str(getattr(p._data.sharding, "spec", None))
                 for p, _ in pairs]
        assert any("mp" in s for s in specs), specs
    paddle.disable_static()
    print("MP_SMP_LOSSES", rank, losses, flush=True)


if __name__ == "__main__":
    main()
