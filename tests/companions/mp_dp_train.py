"""Companion for the multi-process SPMD test (reference test strategy
pattern A, SURVEY.md §4): launched once per 'host' by
paddle.distributed.launch; initializes the coordination service through
init_parallel_env's env contract, then trains data-parallel over the GLOBAL
8-device mesh (2 processes x 4 virtual CPU devices) and prints the losses.

MP_SERIAL=1 runs the IDENTICAL program single-process on 8 local devices —
the serial reference the driver test compares against."""

import os

SERIAL = os.environ.get("MP_SERIAL") == "1"
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                           + ("8" if SERIAL else "4"))
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn


def main():
    if not SERIAL:
        dist.init_parallel_env()  # coordination service via env contract
        assert len(jax.local_devices()) == 4
    assert jax.device_count() == 8, jax.device_count()

    hcg = dist.create_hybrid_communicate_group(sharding=8)
    from paddle_tpu.distributed.sharding.group_sharded import (
        GroupShardedTrainStep,
    )

    paddle.seed(0)  # same init on every process (replicated params)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters())

    def loss_fn(net, x, y):
        return nn.functional.mse_loss(net(x), y)

    step = GroupShardedTrainStep(model, loss_fn, opt, level="os",
                                 mesh=hcg.mesh)

    # deterministic GLOBAL batch; each process feeds its host-local slice
    # and jax assembles the global sharded array (serial: the whole batch)
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    rng = np.random.RandomState(0)
    X = rng.randn(32, 8).astype(np.float32)
    Y = X.sum(-1, keepdims=True).astype(np.float32)
    rank = 0 if SERIAL else dist.get_rank()
    n_proc = 1 if SERIAL else int(os.environ["PADDLE_TRAINERS_NUM"])
    share = 32 // n_proc
    lo, hi = rank * share, (rank + 1) * share
    gx = multihost_utils.host_local_array_to_global_array(
        X[lo:hi], hcg.mesh, P("sharding"))
    gy = multihost_utils.host_local_array_to_global_array(
        Y[lo:hi], hcg.mesh, P("sharding"))

    losses = []
    for _ in range(4):
        loss = step(paddle.Tensor(gx), paddle.Tensor(gy))
        losses.append(round(float(loss), 6))
    print("MP_LOSSES", rank, losses, flush=True)


if __name__ == "__main__":
    main()
