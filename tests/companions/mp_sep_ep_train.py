"""Companion: the LONG-CONTEXT and MoE axes across real processes — ring
(context-parallel) flash attention over a sep=8 axis spanning two
rendezvoused processes (k/v blocks ppermute THROUGH the process boundary)
and an ep=8 MoE all_to_all dispatch crossing it likewise. MP_SERIAL=1 runs
the identical program single-process on 8 local devices."""

import os

SERIAL = os.environ.get("MP_SERIAL") == "1"
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                           + ("8" if SERIAL else "4"))
import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist

from paddle_tpu.distributed.shard_map_compat import (
    NO_CHECK as _SM_NO_CHECK, shard_map)


def _feed_global(arr, mesh, spec, axis_len_local, rank):
    """Global sharded array from per-process slices (serial: whole array)."""
    if SERIAL:
        return jnp.asarray(arr)
    from jax.experimental import multihost_utils

    lo = rank * axis_len_local
    local = arr[:, lo:lo + axis_len_local]
    return multihost_utils.host_local_array_to_global_array(
        local, mesh, spec)


def main():
    if not SERIAL:
        dist.init_parallel_env()
    assert jax.device_count() == 8
    rank = 0 if SERIAL else dist.get_rank()
    rng = np.random.RandomState(0)

    # ---- ring attention over sep=8: ring hops between devices 3<->4
    # cross the process boundary in the 2-process run
    from paddle_tpu.distributed.ring_attention import (
        ring_flash_attention_arrays,
    )

    dist.set_hybrid_communicate_group(None)
    hcg = dist.create_hybrid_communicate_group(sep=8)
    qkv = rng.randn(2, 16 * 8, 4, 16).astype(np.float32)
    gq = _feed_global(qkv, hcg.mesh, P(None, "sep"), 16 * 4, rank)
    ring = shard_map(
        lambda a, b, c: ring_flash_attention_arrays(a, b, c, causal=True),
        mesh=hcg.mesh, in_specs=(P(None, "sep"),) * 3,
        out_specs=P(None, "sep"), **_SM_NO_CHECK)
    out = ring(gq, gq, gq)
    ring_norm = round(float(jax.jit(
        lambda o: jnp.linalg.norm(o.astype(jnp.float32)))(out)), 4)

    # ---- MoE ep=8 (expert axis = 'dp', as the reference's moe_group):
    # all_to_all expert dispatch crosses the process boundary
    from paddle_tpu.incubate.distributed.models.moe import MoELayer

    dist.set_hybrid_communicate_group(None)
    hcg2 = dist.create_hybrid_communicate_group(dp=8)
    paddle.seed(2)
    moe = MoELayer(16, 32, 8, gate="gshard", capacity_factor=8.0,
                   axis_name="dp")
    mnames = list(moe.state_dict())
    mparams = [moe.state_dict()[k]._data for k in mnames]
    tokens = rng.randn(4 * 8, 16).astype(np.float32)
    if SERIAL:
        gt = jnp.asarray(tokens)
    else:
        from jax.experimental import multihost_utils

        gt = multihost_utils.host_local_array_to_global_array(
            tokens[rank * 16:(rank + 1) * 16], hcg2.mesh, P("dp"))

    def moe_body(xa, *ps):
        with dist.axis_scope("dp"):
            with moe.use_state(dict(zip(mnames, ps))):
                return moe(paddle.Tensor(xa))._data

    moe_f = shard_map(moe_body, mesh=hcg2.mesh,
                      in_specs=(P("dp"),) + tuple(P() for _ in mparams),
                      out_specs=P("dp"), **_SM_NO_CHECK)
    mout = moe_f(gt, *mparams)
    moe_norm = round(float(jax.jit(
        lambda o: jnp.linalg.norm(o.astype(jnp.float32)))(mout)), 4)

    print(f"SEP_EP_RESULT {rank} [{ring_norm}, {moe_norm}]", flush=True)


if __name__ == "__main__":
    main()
