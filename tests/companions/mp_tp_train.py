"""Companion: cross-process TENSOR parallelism — Column/RowParallelLinear
over an mp=8 axis spanning both processes, so the row-parallel psum and the
column layer's backward all-reduce cross the process boundary. Trains by
jax.grad inside shard_map over the global mesh; prints per-rank losses.
MP_SERIAL=1 runs the identical program single-process on 8 local devices."""

import os

SERIAL = os.environ.get("MP_SERIAL") == "1"
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                           + ("8" if SERIAL else "4"))
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp

from paddle_tpu.distributed.shard_map_compat import (
    NO_CHECK as _SM_NO_CHECK, shard_map)
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
)


def spec(p):
    axes = getattr(p, "_sharding_axes", None)
    return P(*axes) if axes else P()


def main():
    if not SERIAL:
        dist.init_parallel_env()
    assert jax.device_count() == 8
    hcg = dist.create_hybrid_communicate_group(mp=8)

    paddle.seed(0)
    col = ColumnParallelLinear(8, 16, gather_output=False)
    row = RowParallelLinear(16, 4, input_is_parallel=True)
    tensors = ([col.state_dict()[k] for k in col.state_dict()]
               + [row.state_dict()[k] for k in row.state_dict()])
    params = [t._data for t in tensors]
    specs = [spec(t) for t in tensors]
    nc = len(col.state_dict())

    def loss_of(x, y, *ps):
        with dist.axis_scope("mp"):
            with col.use_state(dict(zip(list(col.state_dict()), ps[:nc]))):
                with row.use_state(dict(zip(list(row.state_dict()),
                                            ps[nc:]))):
                    h = col(paddle.Tensor(x))
                    h = paddle.tanh(h)
                    o = row(h)
        return jnp.mean((o._data - y) ** 2)

    def step_body(x, y, *ps):
        loss, grads = jax.value_and_grad(
            loss_of, argnums=tuple(range(2, 2 + len(ps))))(x, y, *ps)
        return (loss,) + grads

    f = shard_map(step_body, mesh=hcg.mesh,
                  in_specs=(P(), P()) + tuple(specs),
                  out_specs=(P(),) + tuple(specs), **_SM_NO_CHECK)
    jf = jax.jit(f)

    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randn(16, 4).astype(np.float32)

    lr = 0.2
    losses = []
    for _ in range(4):
        out = jf(X, Y, *params)
        loss, grads = out[0], out[1:]
        losses.append(round(float(loss), 6))
        params = [p - lr * g for p, g in zip(params, grads)]
    print("MP_TP_LOSSES", 0 if SERIAL else dist.get_rank(), losses,
          flush=True)


if __name__ == "__main__":
    main()
