"""Companion: cross-process literal 1F1B schedule WITH tied embeddings and
virtual pipeline stages (r4) — pp=4 x v=2 over a 2-process global mesh, so
both the activation/cotangent ring hops AND the tied-weight gradient psum
cross the process boundary. Prints per-rank losses. MP_SERIAL=1 runs the
identical program single-process on 8 local devices."""

import os

SERIAL = os.environ.get("MP_SERIAL") == "1"
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                           + ("8" if SERIAL else "4"))
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
    SharedLayerDesc,
)

H = 16


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(H, H)

    def forward(self, x):
        return paddle.tanh(self.fc(x))


def main():
    if not SERIAL:
        dist.init_parallel_env()
    assert jax.device_count() == 8
    hcg = dist.create_hybrid_communicate_group(dp=2, pp=4)

    paddle.seed(0)
    pp, v = 4, 2
    pl = PipelineLayer(
        [SharedLayerDesc("emb", nn.Linear, 8, H)]
        + [LayerDesc(Block) for _ in range(2 * pp * v - 2)]
        + [SharedLayerDesc(
            "emb", nn.Linear, 8, H,
            forward_func=lambda lyr, x: paddle.matmul(
                x, lyr.weight, transpose_y=True))],
        loss_fn=lambda o, y: nn.functional.mse_loss(o, y),
        num_virtual_pipeline_stages=v)
    runner = PipelineParallel(pl, hcg, {"accumulate_steps": 4,
                                        "schedule": "1f1b"})
    opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                    parameters=pl.parameters())

    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randn(16, 8).astype(np.float32)

    losses = []
    for _ in range(3):
        loss = runner.train_batch(
            (paddle.to_tensor(X), paddle.to_tensor(Y)), opt)
        losses.append(round(float(loss), 6))
    print("MP_1F1B_TIED_LOSSES", 0 if SERIAL else dist.get_rank(), losses,
          flush=True)


if __name__ == "__main__":
    main()
