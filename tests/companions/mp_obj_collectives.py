"""Companion: object collectives ACROSS processes (ADVICE r2 item 5) —
broadcast_object_list ships rank 0's Python objects to rank 1 through the
coordination service, and scatter_object_list delivers per-rank slots with
in_object_list=None on non-src ranks (the reference contract)."""

import os

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")

import paddle_tpu.distributed as dist


def main():
    dist.init_parallel_env()
    rank = dist.get_rank()

    objs = [{"vocab": 32000, "rank_tag": "from-rank-0"}, [1, 2, 3]] \
        if rank == 0 else [None, None]
    dist.broadcast_object_list(objs, src=0)

    out = []
    dist.scatter_object_list(
        out, in_object_list=["slot-a", "slot-b"] if rank == 0 else None,
        src=0)

    print(f"OBJ_RESULT {rank} "
          f"{objs[0]['rank_tag']}|{objs[1]}|{out[0]}", flush=True)


if __name__ == "__main__":
    main()
