"""Companion: FOUR-process rendezvous (SURVEY.md §4 pattern A at nnodes=4,
VERDICT r2 item 8) — dp=2 x pp=2 over a 4-device global mesh with ONE
device per process, so every edge (the dp gradient psum AND the pipeline
ppermute handoffs) crosses a process boundary. MP_SERIAL=1 runs the
identical program single-process on 4 local devices."""

import os

SERIAL = os.environ.get("MP_SERIAL") == "1"
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                           + ("4" if SERIAL else "1"))
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
)

H = 16


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(H, H)

    def forward(self, x):
        return paddle.tanh(self.fc(x))


def main():
    if not SERIAL:
        dist.init_parallel_env()
        assert len(jax.local_devices()) == 1
    assert jax.device_count() == 4, jax.device_count()
    hcg = dist.create_hybrid_communicate_group(dp=2, pp=2)

    paddle.seed(0)
    pl = PipelineLayer(
        [LayerDesc(nn.Linear, 8, H)] + [LayerDesc(Block) for _ in range(2)]
        + [LayerDesc(nn.Linear, H, 4)],
        loss_fn=lambda o, y: nn.functional.mse_loss(o, y))
    runner = PipelineParallel(pl, hcg, {"accumulate_steps": 4})
    opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                    parameters=pl.parameters())

    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randn(16, 4).astype(np.float32)

    losses = []
    for _ in range(3):
        loss = runner.train_batch(
            (paddle.to_tensor(X), paddle.to_tensor(Y)), opt)
        losses.append(round(float(loss), 6))
    print("MP4_LOSSES", 0 if SERIAL else dist.get_rank(), losses,
          flush=True)


if __name__ == "__main__":
    main()
