"""DataLoader worker semantics (VERDICT r2 item 6): get_worker_info in both
worker modes, IterableDataset sharded across workers via the WorkerInfo
contract, and no silent degradation."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, IterableDataset, get_worker_info


class _InfoDS:
    """Map-style dataset recording the WorkerInfo seen per sample."""

    def __len__(self):
        return 16

    def __getitem__(self, i):
        info = get_worker_info()
        assert info is not None, "worker info missing inside worker"
        return np.asarray([i, info.id, info.num_workers], np.int64)


class _ShardedIterable(IterableDataset):
    """The reference contract: __iter__ shards itself via get_worker_info."""

    def __init__(self, n=32):
        self.n = n

    def __iter__(self):
        info = get_worker_info()
        if info is None:
            lo, hi, step = 0, self.n, 1
        else:
            lo, hi, step = info.id, self.n, info.num_workers
        for i in range(lo, hi, step):
            yield np.asarray([i, os.getpid()], np.int64)


class TestWorkerInfo:
    def test_main_process_is_none(self):
        assert get_worker_info() is None

    def test_thread_workers_see_info(self):
        dl = DataLoader(_InfoDS(), batch_size=4, num_workers=2)
        rows = np.concatenate([b.numpy() for b in dl])
        np.testing.assert_array_equal(np.sort(rows[:, 0]), np.arange(16))
        assert set(rows[:, 1]) <= {0, 1}
        assert set(rows[:, 2]) == {2}
        # and the main process is clean again afterwards
        assert get_worker_info() is None

    def test_process_workers_see_info(self):
        dl = DataLoader(_InfoDS(), batch_size=4, num_workers=2,
                        use_process_workers=True, timeout=120)
        rows = np.concatenate([b.numpy() for b in dl])
        np.testing.assert_array_equal(np.sort(rows[:, 0]), np.arange(16))
        assert set(rows[:, 2]) == {2}


class TestIterableSharding:
    @pytest.mark.parametrize("procs", [False, True])
    def test_workers_cover_disjoint_shards(self, procs):
        dl = DataLoader(_ShardedIterable(32), batch_size=4, num_workers=2,
                        use_process_workers=procs, timeout=120)
        rows = np.concatenate([b.numpy() for b in dl])
        ids = np.sort(rows[:, 0])
        # no duplicates, full coverage: the loader really ran the sharded
        # iterators instead of silently degrading to synchronous iteration
        np.testing.assert_array_equal(ids, np.arange(32))
        if procs:
            assert os.getpid() not in set(rows[:, 1].tolist())

    def test_partial_tail_batch_per_worker(self):
        dl = DataLoader(_ShardedIterable(30), batch_size=4, num_workers=2,
                        drop_last=False)
        sizes = sorted(b.shape[0] for b in dl)
        assert sum(sizes) == 30
        # 15 samples per worker -> 3 full batches + one 3-sample tail each
        assert sizes[:2] == [3, 3]

    def test_drop_last_drops_worker_tails(self):
        dl = DataLoader(_ShardedIterable(30), batch_size=4, num_workers=2,
                        drop_last=True)
        sizes = [b.shape[0] for b in dl]
        assert all(sz == 4 for sz in sizes)
        assert sum(sizes) == 24

    def test_iterable_error_propagates(self):
        class Bad(IterableDataset):
            def __iter__(self):
                yield np.zeros(2, np.float32)
                raise ValueError("boom")

        dl = DataLoader(Bad(), batch_size=1, num_workers=2)
        with pytest.raises(ValueError, match="boom"):
            list(dl)


def _bad_init(wid):
    raise ValueError("init boom")


class _UnevenSlowIterable(IterableDataset):
    """Worker 0 gets nothing; worker 1 produces slowly — the early-finisher
    must not be misread as a dead worker."""

    def __iter__(self):
        import time

        info = get_worker_info()
        if info is not None and info.id == 0:
            return
        for i in range(4):
            time.sleep(0.6)
            yield np.asarray([i], np.int64)


class TestWorkerRobustness:
    def test_early_finisher_not_flagged_dead(self):
        dl = DataLoader(_UnevenSlowIterable(), batch_size=2, num_workers=2,
                        use_process_workers=True, timeout=120)
        rows = np.concatenate([b.numpy() for b in dl])
        np.testing.assert_array_equal(np.sort(rows[:, 0]), np.arange(4))

    @pytest.mark.parametrize("procs", [False, True])
    def test_worker_init_fn_failure_raises_not_hangs(self, procs):
        dl = DataLoader(_ShardedIterable(8), batch_size=2, num_workers=2,
                        worker_init_fn=_bad_init, use_process_workers=procs,
                        timeout=60)
        with pytest.raises((ValueError, RuntimeError)):
            list(dl)

    def test_map_style_worker_init_fn_failure_raises(self):
        dl = DataLoader(_InfoDS(), batch_size=4, num_workers=2,
                        worker_init_fn=_bad_init, timeout=60)
        with pytest.raises(ValueError, match="init boom"):
            list(dl)

    def test_consumer_break_then_fresh_epoch(self):
        # breaking mid-epoch must not strand workers or poison the next
        # epoch's iterator
        dl = DataLoader(_ShardedIterable(64), batch_size=4, num_workers=2)
        it = iter(dl)
        next(it)
        it.close()  # generator early-exit (the `break` path)
        rows = np.concatenate([b.numpy() for b in dl])
        np.testing.assert_array_equal(np.sort(rows[:, 0]), np.arange(64))

    def test_threaded_iterable_timeout_honored(self):
        class Hang(IterableDataset):
            def __iter__(self):
                import time

                time.sleep(600)
                yield np.zeros(1, np.float32)

        dl = DataLoader(Hang(), batch_size=1, num_workers=1, timeout=2)
        with pytest.raises(RuntimeError, match="timed out"):
            list(dl)

    def test_thread_workers_can_mutate_their_dataset_copy(self):
        class MutShard(IterableDataset):
            def __init__(self, n):
                self.n = n
                self.lo = 0
                self.step = 1

            def __iter__(self):
                info = get_worker_info()
                if info is not None:
                    # the reference's mutate-winfo.dataset idiom
                    ds = info.dataset
                    ds.lo = info.id
                    ds.step = info.num_workers
                    it = range(ds.lo, ds.n, ds.step)
                else:
                    it = range(self.n)
                for i in it:
                    yield np.asarray([i], np.int64)

        dl = DataLoader(MutShard(24), batch_size=3, num_workers=2)
        rows = np.concatenate([b.numpy() for b in dl])
        np.testing.assert_array_equal(np.sort(rows[:, 0]), np.arange(24))
