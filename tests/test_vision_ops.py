"""paddle.vision.ops tests: nms/box_iou/roi_align vs NumPy references."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def _np_nms(boxes, scores, thr):
    order = np.argsort(-scores)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if suppressed[j] or j == i:
                continue
            xx1 = max(boxes[i, 0], boxes[j, 0]); yy1 = max(boxes[i, 1], boxes[j, 1])
            xx2 = min(boxes[i, 2], boxes[j, 2]); yy2 = min(boxes[i, 3], boxes[j, 3])
            inter = max(xx2 - xx1, 0) * max(yy2 - yy1, 0)
            a1 = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            a2 = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            if inter / max(a1 + a2 - inter, 1e-10) > thr:
                suppressed[j] = True
    return keep


class TestNms:
    def test_matches_reference(self):
        rng = np.random.RandomState(0)
        xy = rng.rand(40, 2) * 10
        wh = rng.rand(40, 2) * 4 + 0.5
        boxes = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
        scores = rng.rand(40).astype(np.float32)
        ref = _np_nms(boxes, scores, 0.4)
        out = V.nms(paddle.to_tensor(boxes), 0.4,
                    scores=paddle.to_tensor(scores)).numpy()
        assert out.tolist() == ref

    def test_top_k(self):
        boxes = np.array([[0, 0, 1, 1], [5, 5, 6, 6], [10, 10, 11, 11]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        out = V.nms(paddle.to_tensor(boxes), 0.5,
                    scores=paddle.to_tensor(scores), top_k=2).numpy()
        assert out.tolist() == [0, 1]

    def test_categories(self):
        # identical overlapping boxes in different categories both survive
        boxes = np.array([[0, 0, 2, 2], [0, 0, 2, 2]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1], np.int32)
        out = V.nms(paddle.to_tensor(boxes), 0.5,
                    scores=paddle.to_tensor(scores),
                    category_idxs=paddle.to_tensor(cats),
                    categories=[0, 1]).numpy()
        assert sorted(out.tolist()) == [0, 1]


class TestBoxIou:
    def test_known_values(self):
        a = paddle.to_tensor(np.array([[0, 0, 2, 2]], np.float32))
        b = paddle.to_tensor(np.array([[1, 1, 3, 3], [0, 0, 2, 2],
                                       [4, 4, 5, 5]], np.float32))
        iou = V.box_iou(a, b).numpy()
        np.testing.assert_allclose(iou[0], [1 / 7, 1.0, 0.0], atol=1e-6)


class TestRoiAlign:
    def test_constant_map(self):
        # constant feature map -> every roi bin averages to the constant
        x = np.full((1, 3, 16, 16), 2.5, np.float32)
        boxes = np.array([[2, 2, 10, 10], [0, 0, 15, 15]], np.float32)
        out = V.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                          paddle.to_tensor(np.array([2], np.int32)),
                          output_size=4).numpy()
        assert out.shape == (2, 3, 4, 4)
        np.testing.assert_allclose(out, 2.5, atol=1e-5)

    def test_linear_ramp(self):
        # f(x,y) = x: averaging a bin gives the bin's center x coordinate
        w = 16
        ramp = np.tile(np.arange(w, dtype=np.float32), (w, 1))[None, None]
        boxes = np.array([[4, 4, 12, 12]], np.float32)
        out = V.roi_align(paddle.to_tensor(ramp), paddle.to_tensor(boxes),
                          paddle.to_tensor(np.array([1], np.int32)),
                          output_size=2, aligned=True).numpy()
        # aligned: roi [3.5, 11.5), bins of width 4 -> centers 5.5, 9.5
        np.testing.assert_allclose(out[0, 0, 0], [5.5, 9.5], atol=1e-4)

    def test_roi_pool_max(self):
        x = np.zeros((1, 1, 8, 8), np.float32)
        x[0, 0, 2, 3] = 7.0
        x[0, 0, 6, 6] = 9.0
        boxes = np.array([[0, 0, 7, 7]], np.float32)
        out = V.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([1], np.int32)),
                         output_size=2).numpy()
        assert out[0, 0, 0, 0] == 7.0  # top-left quadrant max
        assert out[0, 0, 1, 1] == 9.0  # bottom-right quadrant max


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.RandomState(1)
        priors = np.array([[0, 0, 4, 4], [2, 2, 8, 8]], np.float32)
        targets = np.array([[1, 1, 5, 5], [3, 3, 6, 7]], np.float32)
        enc = V.box_coder(paddle.to_tensor(priors), None,
                          paddle.to_tensor(targets)).numpy()
        dec = V.box_coder(paddle.to_tensor(priors), None,
                          paddle.to_tensor(enc),
                          code_type="decode_center_size").numpy()
        # decoding each target's own code against its prior reproduces it
        for i in range(2):
            np.testing.assert_allclose(dec[i, i], targets[i], atol=1e-4)


class TestQuantization:
    def test_fake_quant_roundtrip_and_ste(self):
        import jax.numpy as jnp
        from paddle_tpu.quantization import AbsmaxObserver

        obs = AbsmaxObserver(quant_bits=8)
        x = jnp.asarray(np.linspace(-1, 1, 11, dtype=np.float32))
        q = obs.fake_quant(x)
        # max error bounded by half a quantization step
        step = 1.0 / 127
        assert float(jnp.abs(q - x).max()) <= step / 2 + 1e-6

    def test_qat_quantize_and_train(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import QAT, QuantConfig

        paddle.seed(3)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        q = QAT(QuantConfig())
        qmodel = q.quantize(model)
        names = [type(l).__name__ for l in qmodel.sublayers()]
        assert names.count("QuantedLayer") == 2
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32))
        out = qmodel(x)
        loss = paddle.mean(out * out)
        loss.backward()
        # STE: quantized weights still receive gradients
        g = qmodel[0].inner.weight.grad
        assert g is not None and np.abs(g.numpy()).max() > 0

    def test_convert_bakes_weights(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import QAT

        paddle.seed(4)
        model = nn.Sequential(nn.Linear(4, 4))
        q = QAT()
        qmodel = q.quantize(model)
        final = q.convert(qmodel)
        assert type(final[0]).__name__ == "Linear"
        w = final[0].weight.numpy()
        scale = np.abs(w).max() / 127
        # every weight is an integer multiple of the scale
        np.testing.assert_allclose(w / scale, np.round(w / scale), atol=1e-3)


class TestReviewRegressions:
    def test_roi_pool_overlapping_bins(self):
        # roi height 5 pooled to 2 bins: boundaries floor/ceil overlap at
        # pixel 2, so a max there must appear in BOTH bins
        x = np.zeros((1, 1, 5, 1), np.float32)
        x[0, 0] = np.array([[0], [1], [9], [2], [3]], np.float32)
        boxes = np.array([[0, 0, 0, 4]], np.float32)
        out = V.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([1], np.int32)),
                         output_size=(2, 1)).numpy()
        assert out[0, 0, :, 0].tolist() == [9.0, 9.0]

    def test_box_coder_list_variance(self):
        priors = np.array([[0, 0, 4, 4]], np.float32)
        targets = np.array([[1, 1, 5, 5]], np.float32)
        var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
        enc = V.box_coder(paddle.to_tensor(priors), paddle.to_tensor(var),
                          paddle.to_tensor(targets)).numpy()
        enc_novar = V.box_coder(paddle.to_tensor(priors), None,
                                paddle.to_tensor(targets)).numpy()
        np.testing.assert_allclose(enc[0, 0], enc_novar[0, 0] / var,
                                   rtol=1e-5)

    def test_ptq_calibration_updates_ema(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import PTQ, QuantConfig, EMAObserver

        model = nn.Sequential(nn.Linear(4, 4))
        ptq = PTQ(QuantConfig(activation=EMAObserver()))
        qmodel = ptq.quantize(model)
        x = paddle.to_tensor(np.ones((2, 4), np.float32) * 3.0)
        qmodel(x)
        assert qmodel[0]._act_obs._ema is not None
        assert abs(qmodel[0]._act_obs._ema - 3.0) < 1e-5
