"""Seeded PTA702 violation (jaxpr level): a collective inside a
lax.while_loop body runs a data-dependent number of times — per-rank
predicate divergence deadlocks.

Traced by tests via ``check_balance(fn, x, axis_sizes={"dp": 2})``.
"""

from jax import lax


def chatty_loop(x):
    # TRIPS: psum inside the data-dependent loop body.
    return lax.while_loop(lambda v: v.sum() < 10.0, lambda v: lax.psum(v, "dp"), x)


def chatty_loop_suppressed(x):
    return lax.while_loop(lambda v: v.sum() < 10.0, lambda v: lax.psum(v, "dp"), x)  # noqa: PTA702


def quiet_loop(x):
    return lax.while_loop(lambda v: v.sum() < 10.0, lambda v: v * 2.0, x)  # clean
