"""Seeded PTA704 violation (jaxpr level): collective census drift —
the program issues more collectives than its registered expected-census
formula allows.

Traced by tests via ``check_census(fn, (x,), expected={("psum", "dp"):
1}, axis_sizes={"dp": 2})``.  The diagnostic anchors at the function's
``def`` line, so the suppressed counterpart carries its noqa there.
"""

from jax import lax


def census_drifter(x):
    # TRIPS: two psums against an expected census of one.
    return lax.psum(x, "dp") + lax.psum(x * 2.0, "dp")


def census_drifter_suppressed(x):  # noqa: PTA704 — fixture counterpart
    return lax.psum(x, "dp") + lax.psum(x * 2.0, "dp")


def census_exact(x):
    return lax.psum(x, "dp")  # clean: matches {("psum","dp"): 1}
