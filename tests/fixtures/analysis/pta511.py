"""Seeded PTA511 violation: StreamHandle guarded state mutated outside
`with handle.lock`."""


class RacyRouter:
    def mark_failing(self, handle):
        # TRIPS: guarded attr written lock-free — races the worker's
        # failover read.
        handle.failing_over = True

    def mark_failing_suppressed(self, handle):
        handle.failing_over = True  # noqa: PTA511 — fixture counterpart

    def mark_failing_locked(self, handle):
        with handle.lock:
            handle.failing_over = True  # clean: under the handle lock
