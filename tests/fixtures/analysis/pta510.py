"""Seeded PTA510 violation: engine mutation outside the owning worker
thread (the PR 14 thread-owned teardown doctrine)."""


class RogueSupervisor:
    def kill(self, worker):
        # TRIPS: close() on another object's engine, from a supervisor
        # method — exactly the segfault-through-donated-buffers class.
        worker.engine.close()

    def kill_after_handoff(self, worker):
        worker.drain()
        worker.stop()
        worker.engine.close()  # noqa: PTA510 — ownership transferred post drain+stop
