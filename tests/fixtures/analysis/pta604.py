"""Seeded PTA604 violation (jaxpr level): a donated input whose shape
and dtype match no output — the donation can never be fulfilled and the
buffer is silently copied instead of reused.

Imported and traced by tests via ``diagnose_donation(fn, a, b,
donate_argnums=(0,))``.
"""


def unfulfillable(a, b):
    # TRIPS: donating a (4,4) input into a scalar-output program.
    return (a + b).sum()


def unfulfillable_suppressed(a, b):  # noqa: PTA604 — fixture counterpart
    return (a + b).sum()


def fulfillable(a, b):
    return a + b  # clean: output aliases the donated shape/dtype
