"""Seeded PTA602 violation: the same buffer donated through two argnums
of one dispatch — double free on real hardware."""

from paddle_tpu.serving.engine import CompiledFn


class DoubleDonor:
    def dispatch(self, step):
        fn = CompiledFn(step, donate_argnums=(0, 1))
        # TRIPS: self.buf fills two donated positions.
        out = fn(self.buf, self.buf)
        self.buf = out
        return out

    def dispatch_suppressed(self, step):
        fn = CompiledFn(step, donate_argnums=(0, 1))
        out = fn(self.buf, self.buf)  # noqa: PTA602 — fixture counterpart
        self.buf = out
        return out

    def dispatch_distinct(self, step):
        fn = CompiledFn(step, donate_argnums=(0, 1))
        out = fn(self.k, self.v)  # clean: distinct buffers
        self.k, self.v = out
        return out
