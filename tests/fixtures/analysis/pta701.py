"""Seeded PTA701 violation (jaxpr level): lax.cond branches issuing
different collective censuses — ranks taking different branches
deadlock on a real mesh.

Traced by tests via ``check_balance(fn, x, p, axis_sizes={"dp": 2})``.
"""

from jax import lax


def lopsided(x, p):
    # TRIPS: true branch psums over "dp", false branch is collective-free.
    return lax.cond(p, lambda v: lax.psum(v, "dp"), lambda v: v * 2.0, x)


def lopsided_suppressed(x, p):
    return lax.cond(p, lambda v: lax.psum(v, "dp"), lambda v: v * 2.0, x)  # noqa: PTA701


def balanced(x, p):
    return lax.cond(p, lambda v: lax.psum(v, "dp"),
                    lambda v: lax.psum(v * 2.0, "dp"), x)  # clean
