"""Seeded PTA514 violation: non-daemon thread with no join/stop in
sight — leaks past interpreter shutdown."""

import threading


class LeakySpawner:
    def start(self):
        # TRIPS: non-daemon, and nothing in this class ever joins it.
        self.t = threading.Thread(target=self._run)
        self.t.start()

    def start_suppressed(self):
        self.t = threading.Thread(target=self._run)  # noqa: PTA514 — fixture counterpart
        self.t.start()

    def _run(self):
        pass


class DisciplinedSpawner:
    def start(self):
        self.t = threading.Thread(target=self._run, daemon=True)  # clean
        self.t.start()

    def _run(self):
        pass
