"""Seeded PTA512 violation: blocking operation performed while holding
a lock."""


class StallingWorker:
    def pump(self):
        with self.lock:
            # TRIPS: unbounded queue.get() under the lock — every
            # other thread contending on self.lock stalls with it.
            item = self.q.get()
        return item

    def pump_suppressed(self):
        with self.lock:
            item = self.q.get()  # noqa: PTA512 — fixture counterpart
        return item

    def pump_outside(self):
        item = self.q.get()  # clean: no lock held
        return item
