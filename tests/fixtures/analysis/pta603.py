"""Seeded PTA603 violation: donated engine-state buffer never rebound —
live engine state now points at donated (freed) memory."""

from paddle_tpu.serving.engine import CompiledFn


class LeakyRebind:
    def dispatch(self, step):
        fn = CompiledFn(step, donate_argnums=(0,))
        # TRIPS: self.pool.k donated but no rebind of self.pool
        # follows in this method.
        out = fn(self.pool.k)
        return out

    def dispatch_suppressed(self, step):
        fn = CompiledFn(step, donate_argnums=(0,))
        out = fn(self.pool.k)  # noqa: PTA603 — fixture counterpart
        return out

    def dispatch_rebound(self, step):
        fn = CompiledFn(step, donate_argnums=(0,))
        out = fn(self.pool.k)
        self.pool.rebind(out)  # clean: owner call re-establishes state
        return out
