"""Seeded PTA601 violation: host read of a buffer after it was donated
to a dispatch — the buffer's device memory now belongs to the output."""

from paddle_tpu.serving.engine import CompiledFn


class UseAfterDonate:
    def dispatch(self, step):
        fn = CompiledFn(step, donate_argnums=(0,))
        out = fn(self.buf)
        # TRIPS: self.buf was donated on the line above; reading it
        # now dereferences freed device memory.
        return self.buf.sum()

    def dispatch_suppressed(self, step):
        fn = CompiledFn(step, donate_argnums=(0,))
        out = fn(self.buf)
        return self.buf.sum()  # noqa: PTA601 — fixture counterpart

    def dispatch_rebound(self, step):
        fn = CompiledFn(step, donate_argnums=(0,))
        out = fn(self.buf)
        self.buf = out  # clean: rebound before any read
        return self.buf.sum()
