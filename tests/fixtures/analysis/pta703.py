"""Seeded PTA703 violation (jaxpr level): a collective over an axis
name bound by no enclosing shard_map mesh nor declared axis
environment.

Traced by tests via ``check_balance(fn, x, axis_env=[("mystery", 2)])``
— the axis env makes the trace legal, but the balance checker's bound
set is empty, so the axis is unbound from the mesh's point of view.
"""

from jax import lax


def stray_axis(x):
    # TRIPS: "mystery" is bound by no shard_map in this program.
    return lax.psum(x, "mystery")


def stray_axis_suppressed(x):
    return lax.psum(x, "mystery")  # noqa: PTA703 — fixture counterpart
