"""Seeded PTA513 violation: wall-clock call inside a fault-scheduling
path (the dispatch-ordinal doctrine: fault schedules must be
deterministic in dispatch ordinals, never in wall time)."""

import time


class FaultSchedule:
    def next_fire(self):
        # TRIPS: wall clock inside a fault-scoped class.
        return time.time()

    def next_fire_suppressed(self):
        return time.time()  # noqa: PTA513 — fixture counterpart

    def next_ordinal(self, ordinals, scope):
        return ordinals.get(scope, 0) + 1  # clean: ordinal arithmetic
