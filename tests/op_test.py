"""OpTest harness (ref: test/legacy_test/op_test.py (U), SURVEY.md §4).

The reference's op tests subclass OpTest and call check_output (compare
against a NumPy reference) and check_grad (numeric finite-difference
gradient comparison), swept over dtypes with per-dtype tolerances. Same
pattern here: subclasses define

    def setUp(self):
        self.op = paddle-callable (Tensors in, Tensor/tuple out)
        self.inputs = {"x": np.ndarray, ...}      # op kwargs or positional
        self.ref = numpy reference callable (same signature, ndarrays)

and get check_output() / check_grad() with eager-vs-jit parity included
(dygraph/static parity analog — the reference runs every op test in both
executors)."""

from __future__ import annotations

import numpy as np

import paddle_tpu as paddle

_TOL = {
    np.dtype(np.float32): dict(rtol=1e-5, atol=1e-6),
    np.dtype(np.float64): dict(rtol=1e-7, atol=1e-9),
    np.dtype(np.float16): dict(rtol=1e-2, atol=1e-3),
}


class OpTest:
    op = None
    ref = None
    inputs: dict = {}
    grad_inputs: tuple = None  # names to check grads for; default all floats

    # ------------------------------------------------------------ helpers
    def _tensors(self):
        return {k: paddle.to_tensor(v) for k, v in self.inputs.items()}

    def _run_op(self, tensors):
        out = type(self).op(**tensors)
        return out[0] if isinstance(out, (tuple, list)) else out

    def _tol(self, dtype):
        return _TOL.get(np.dtype(dtype), dict(rtol=1e-4, atol=1e-5))

    # ------------------------------------------------------------- checks
    def check_output(self):
        """Op output == NumPy reference, in eager AND under jit tracing."""
        tensors = self._tensors()
        got = self._run_op(tensors).numpy()
        want = np.asarray(type(self).ref(**self.inputs))
        # tolerance keyed by the OP's compute dtype (NumPy references often
        # upcast to f64, which must not tighten the comparison)
        tol = self._tol(got.dtype)
        np.testing.assert_allclose(got, want, **tol)

        # jit parity (to_static analog): trace the op, same result
        import jax

        names = list(tensors)

        def traced(*arrays):
            ts = {n: paddle.Tensor(a) for n, a in zip(names, arrays)}
            return self._run_op(ts)._data

        got_jit = np.asarray(jax.jit(traced)(
            *[tensors[n]._data for n in names]))
        np.testing.assert_allclose(got_jit, want, **tol)

    def check_grad(self, eps=1e-3, max_relative_error=5e-3):
        """Autodiff gradient vs central finite differences on a scalar
        projection sum(op(x) * r) with fixed random r (the reference uses
        the same scalarization)."""
        tensors = self._tensors()
        grad_names = self.grad_inputs or [
            k for k, v in self.inputs.items()
            if np.issubdtype(np.asarray(v).dtype, np.floating)]

        rng = np.random.RandomState(7)
        out0 = self._run_op(tensors)
        r = rng.randn(*out0.shape).astype(np.asarray(out0.numpy()).dtype) \
            if out0.shape else np.asarray(1.0, np.float32)
        r_t = paddle.to_tensor(r)

        # analytic grads
        for k in grad_names:
            tensors[k].stop_gradient = False
        loss = paddle.sum(self._run_op(tensors) * r_t)
        loss.backward()

        for k in grad_names:
            analytic = tensors[k].grad.numpy().astype(np.float64)
            x = np.asarray(self.inputs[k], np.float64)
            numeric = np.zeros_like(x)
            flat_x = x.reshape(-1)
            flat_num = numeric.reshape(-1)

            def scalar_at(xv):
                ins = dict(self.inputs)
                ins[k] = xv.astype(self.inputs[k].dtype)
                out = np.asarray(type(self).ref(**ins), np.float64)
                return float((out * r.astype(np.float64)).sum())

            for i in range(flat_x.size):
                orig = flat_x[i]
                flat_x[i] = orig + eps
                fp = scalar_at(x)
                flat_x[i] = orig - eps
                fm = scalar_at(x)
                flat_x[i] = orig
                flat_num[i] = (fp - fm) / (2 * eps)

            denom = np.maximum(np.abs(numeric), 1.0)
            rel = np.abs(analytic - numeric) / denom
            assert rel.max() <= max_relative_error, (
                f"grad wrt {k!r}: max rel err {rel.max():.2e} > "
                f"{max_relative_error:.2e}\nanalytic={analytic}\n"
                f"numeric={numeric}")
