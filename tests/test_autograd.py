"""Autograd engine tests — numeric-gradient checks in the style of the
reference's OpTest.check_grad (SURVEY.md §4: NumPy reference + finite
differences)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def numeric_grad(fn, x, eps=1e-3):
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp = x.copy()
        xp[idx] += eps
        xm = x.copy()
        xm[idx] -= eps
        g[idx] = (fn(xp) - fn(xm)) / (2 * eps)
        it.iternext()
    return g


def check_grad(paddle_fn, np_fn, x_np, rtol=1e-2, atol=1e-3):
    x = paddle.to_tensor(x_np.astype(np.float32), stop_gradient=False)
    y = paddle_fn(x)
    y.backward()
    ref = numeric_grad(np_fn, x_np)
    np.testing.assert_allclose(x.grad.numpy(), ref, rtol=rtol, atol=atol)


class TestBackward:
    def test_chain(self):
        x = paddle.to_tensor(3.0, stop_gradient=False)
        y = x * x + 2 * x + 1
        y.backward()
        assert abs(float(x.grad) - 8.0) < 1e-6

    def test_matmul_grad(self):
        a = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32), stop_gradient=False)
        b = paddle.to_tensor(np.random.rand(4, 5).astype(np.float32), stop_gradient=False)
        (a @ b).sum().backward()
        np.testing.assert_allclose(a.grad.numpy(), np.ones((3, 5)) @ b.numpy().T, rtol=1e-5)
        np.testing.assert_allclose(b.grad.numpy(), a.numpy().T @ np.ones((3, 5)), rtol=1e-5)

    def test_grad_accumulation(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        (x * x).backward()
        (x * 3).backward()
        assert abs(float(x.grad) - 7.0) < 1e-6

    def test_stop_gradient_blocks(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = (x * 2).detach()
        z = y * 3
        assert z.stop_gradient

    def test_no_grad_ctx(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        with paddle.no_grad():
            y = x * x
        assert y.stop_gradient

    def test_backward_twice_needs_retain(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        assert abs(float(x.grad) - 8.0) < 1e-6

    def test_multi_output_op(self):
        x = paddle.to_tensor(np.array([3.0, 1.0, 2.0], np.float32), stop_gradient=False)
        vals, idx = paddle.topk(x, 2)
        vals.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0, 1.0])

    def test_numeric_softmax(self):
        x_np = np.random.rand(4, 7)

        def np_softmax_sq_sum(a):
            e = np.exp(a - a.max(-1, keepdims=True))
            p = e / e.sum(-1, keepdims=True)
            return float((p**2).sum())

        check_grad(
            lambda t: (paddle.nn.functional.softmax(t) ** 2).sum(),
            np_softmax_sq_sum,
            x_np,
        )

    def test_numeric_tanh_chain(self):
        x_np = np.random.rand(3, 3)
        check_grad(
            lambda t: (paddle.tanh(t) * paddle.exp(t)).sum(),
            lambda a: float((np.tanh(a) * np.exp(a)).sum()),
            x_np,
        )

    def test_paddle_grad_fn(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = paddle.to_tensor(3.0, stop_gradient=False)
        (gx, gy) = paddle.grad(x * x * y, [x, y])
        assert abs(float(gx) - 12.0) < 1e-6
        assert abs(float(gy) - 4.0) < 1e-6

    def test_grad_of_intermediate(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 2
        z = (y * y).sum()
        (gy,) = paddle.grad(z, [y])
        np.testing.assert_allclose(gy.numpy(), 2 * y.numpy())

    def test_set_grad_enabled_restores(self):
        from paddle_tpu.core import tape

        assert tape.is_grad_enabled()
        with paddle.set_grad_enabled(False):
            assert not tape.is_grad_enabled()
        assert tape.is_grad_enabled()

    def test_split_non_divisible_raises(self):
        with pytest.raises(ValueError):
            paddle.split(paddle.ones([5, 2]), 2, axis=0)

    def test_multiplex(self):
        a = paddle.to_tensor(np.array([[1.0, 2], [3, 4]], np.float32))
        b = paddle.to_tensor(np.array([[5.0, 6], [7, 8]], np.float32))
        out = paddle.multiplex([a, b], paddle.to_tensor([[0], [1]]))
        np.testing.assert_array_equal(out.numpy(), [[1, 2], [7, 8]])

    def test_register_hook(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        h = x.register_hook(lambda g: g * 2)
        (x * 3).backward()
        assert abs(float(x.grad) - 6.0) < 1e-6
        h.remove()
        x.clear_grad()
        (x * 3).backward()
        assert abs(float(x.grad) - 3.0) < 1e-6


class TestBackwardInJit:
    def test_tape_traces_under_jit(self):
        """The whole fwd+bwd tape must be traceable: one jit'd train step."""
        import jax

        w = paddle.to_tensor(np.ones((4, 4), np.float32), stop_gradient=False)

        def step(w_arr, x_arr):
            wt = paddle.Tensor(w_arr, stop_gradient=False)
            xt = paddle.Tensor(x_arr)
            loss = ((xt @ wt) ** 2).sum()
            loss.backward()
            return wt.grad._data, loss._data

        jitted = jax.jit(step)
        x = np.random.rand(2, 4).astype(np.float32)
        g, l = jitted(w.numpy(), x)
        ref_g = 2 * x.T @ (x @ w.numpy())
        np.testing.assert_allclose(np.asarray(g), ref_g, rtol=1e-4)


class TestFunctionalAutograd:
    def test_jacobian(self):
        import numpy as np
        import paddle_tpu.autograd as AG

        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        jac = AG.jacobian(lambda t: t * t, x).numpy()
        np.testing.assert_allclose(jac, np.diag([2.0, 4.0, 6.0]), atol=1e-6)

    def test_hessian(self):
        import numpy as np
        import paddle_tpu.autograd as AG

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        h = AG.hessian(lambda t: (t * t * t).sum(), x).numpy()
        np.testing.assert_allclose(h, np.diag([6.0, 12.0]), atol=1e-5)

    def test_jvp_vjp_agree_for_symmetric_jacobian(self):
        import numpy as np
        import paddle_tpu.autograd as AG

        x = paddle.to_tensor(np.array([0.5, -1.5], np.float32))
        v = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
        _, tang = AG.jvp(lambda t: t * t, x, v)
        _, cot = AG.vjp(lambda t: t * t, x, v)
        np.testing.assert_allclose(tang.numpy(), cot.numpy(), atol=1e-6)


class TestPyLayerUnderRemat:
    def test_custom_backward_honored_inside_recompute(self):
        """Inside a rematted body (tape off, outer jax.vjp) a PyLayer's
        custom backward must be used — previously AD-of-forward silently
        replaced it (round-2 staging fix)."""
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.autograd import PyLayer
        from paddle_tpu.distributed.recompute import recompute

        class TripleGrad(PyLayer):
            # forward is identity, but custom grad multiplies by 3 — AD of
            # the forward would give 1, so the factor proves the custom
            # backward ran
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 1.0

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 3.0

        def body(x):
            return TripleGrad.apply(x) * 2.0

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        x.stop_gradient = False
        out = recompute(body, x)
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])

    def test_eager_path_unchanged(self):
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.autograd import PyLayer

        class TripleGrad(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return x * 1.0

            @staticmethod
            def backward(ctx, dy):
                return dy * 3.0

        x = paddle.to_tensor(np.array([1.0], np.float32))
        x.stop_gradient = False
        TripleGrad.apply(x).backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0])


class TestHigherOrderGrad:
    """create_graph=True (SURVEY.md §2.1 N8): the backward walk records
    itself — each node's vjp re-derived as a taped op of (inputs,
    cotangents) — so grads of grads work to any order."""

    def test_second_and_third_derivative(self):
        import numpy as np

        import paddle_tpu as paddle

        x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
        y = x * x * x
        (g,) = paddle.grad([y], [x], create_graph=True)
        (g2,) = paddle.grad([g], [x], create_graph=True)
        (g3,) = paddle.grad([g2], [x])
        assert float(g) == 12.0 and float(g2) == 12.0 and float(g3) == 6.0

    def test_gradient_penalty_backward(self):
        import numpy as np

        import paddle_tpu as paddle

        w = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        z = (w * w).sum()
        (gw,) = paddle.grad([z], [w], create_graph=True)
        assert not gw.stop_gradient          # grads carry a graph
        gp = (gw * gw).sum()                 # ||2w||^2 -> d/dw = 8w
        gp.backward()
        np.testing.assert_allclose(w.grad.numpy(), [8.0, 16.0])

    def test_elementwise_hessian_diag_matches_jax(self):
        import numpy as np

        import paddle_tpu as paddle

        xv = np.array([0.3, 1.7, -2.1], np.float32)
        t = paddle.to_tensor(xv, stop_gradient=False)
        out = (paddle.sin(t) * paddle.exp(t)).sum()
        (g1,) = paddle.grad([out], [t], create_graph=True)
        (g2,) = paddle.grad([g1.sum()], [t])
        expect = 2 * np.cos(xv) * np.exp(xv)   # (sin·exp)'' = 2cos·exp
        np.testing.assert_allclose(g2.numpy(), expect, rtol=1e-5)

    def test_replay_linearizes_at_forward_time_values(self):
        """create_graph replay must linearize at the FORWARD-time arrays:
        rebinding an input's ._data between forward and backward (in-place
        style) must not shift the derivative (advisor r4)."""
        import numpy as np

        import paddle_tpu as paddle

        x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
        y = x * x                         # dy/dx at x=2 -> 4
        x.set_value(np.array(100.0, np.float32))
        (g,) = paddle.grad([y], [x], create_graph=True)
        assert float(g) == 4.0            # matches the create_graph=False path

    def test_pylayer_raises_informatively(self):
        import numpy as np
        import pytest

        import paddle_tpu as paddle
        from paddle_tpu.autograd import PyLayer

        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return x * 2.0

            @staticmethod
            def backward(ctx, dy):
                return dy * 2.0

        x = paddle.to_tensor(np.array(1.0, np.float32), stop_gradient=False)
        y = Double.apply(x)
        with pytest.raises(NotImplementedError, match="PyLayer"):
            paddle.grad([y], [x], create_graph=True)


class TestDlpack:
    def test_roundtrip_and_torch_interop(self):
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.utils.dlpack import from_dlpack, to_dlpack

        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        back = from_dlpack(to_dlpack(x))
        np.testing.assert_array_equal(back.numpy(), x.numpy())
        try:
            import torch
        except ImportError:
            return
        tt = torch.utils.dlpack.from_dlpack(to_dlpack(x))
        np.testing.assert_array_equal(tt.numpy(), x.numpy())
        ours = from_dlpack(torch.arange(4, dtype=torch.float32))
        np.testing.assert_array_equal(ours.numpy(), [0, 1, 2, 3])
        legacy = from_dlpack(torch.utils.dlpack.to_dlpack(
            torch.ones(3, dtype=torch.float32)))
        np.testing.assert_array_equal(legacy.numpy(), [1, 1, 1])


class TestIncubateAutograd:
    def test_jacobian_hessian_objects_and_functionals(self):
        ia = paddle.incubate.autograd
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        J = ia.Jacobian(lambda t: t * t, x)
        np.testing.assert_allclose(J[:].numpy(), np.diag([2.0, 4.0]))
        np.testing.assert_allclose(J[0].numpy(), [2.0, 0.0])
        np.testing.assert_allclose(J[0:2, 1].numpy(), [0.0, 4.0])
        assert tuple(J.shape) == (2, 2)
        H = ia.Hessian(lambda t: (t ** 3).sum(), x)
        np.testing.assert_allclose(H[:].numpy(), np.diag([6.0, 12.0]))
        _, jv = ia.jvp(lambda t: t * t, x)
        np.testing.assert_allclose(jv.numpy(), [2.0, 4.0])
        _, vj = ia.vjp(lambda t: t * t, x)
        np.testing.assert_allclose(vj.numpy(), [2.0, 4.0])

    def test_lite_scope_edges_raise(self):
        ia = paddle.incubate.autograd
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        with pytest.raises(NotImplementedError, match="is_batched"):
            ia.Jacobian(lambda t: t * t, x, is_batched=True)
        with pytest.raises(NotImplementedError, match="multiple xs"):
            ia.Jacobian(lambda a, b: a * b, [x, x])
        with pytest.raises(NotImplementedError, match="multi-output"):
            ia.Jacobian(lambda t: (t * t, t * 3), x)
        with pytest.raises(NotImplementedError, match="multiple xs"):
            ia.Hessian(lambda a, b: (a * b).sum(), [x, x])

    def test_multi_output_jacobian_functional(self):
        # paddle.autograd.jacobian no longer silently drops outputs
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        j1, j2 = paddle.autograd.jacobian(lambda t: (t * t, 3 * t), x)
        np.testing.assert_allclose(j1.numpy(), np.diag([2.0, 4.0]))
        np.testing.assert_allclose(j2.numpy(), np.diag([3.0, 3.0]))
