"""Distributed core: topology grid arithmetic + functional collectives inside
shard_map on the 8-device virtual CPU mesh (SURVEY.md §4 — single-process SPMD
replaces the reference's multi-GPU subprocess pattern)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from paddle_tpu.distributed.shard_map_compat import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


class TestTopology:
    def test_grid_arithmetic(self):
        topo = dist.CommunicateTopology(
            ["data", "pipe", "sharding", "sep", "model"], [2, 2, 1, 1, 2]
        )
        assert topo.world_size == 8
        assert topo.get_dim("model") == 2
        # rank 0 is coordinate (0,0,0,0,0); last rank is all-max
        assert topo.get_rank(data=0, pipe=0, sharding=0, sep=0, model=0) == 0
        assert topo.get_rank(data=1, pipe=1, sharding=0, sep=0, model=1) == 7
        c = topo.get_coord(5)
        assert topo.get_rank(**c._asdict()) == 5
        # comm lists partition the world
        comms = topo.get_comm_list("model")
        flat = sorted(r for comm in comms for r in comm)
        assert flat == list(range(8))
        assert all(len(c) == 2 for c in comms)

    def test_hcg_groups(self):
        hcg = dist.create_hybrid_communicate_group(dp=2, mp=2, pp=2)
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_pipe_parallel_world_size() == 2
        assert hcg.get_sharding_parallel_world_size() == 1
        g = hcg.get_model_parallel_group()
        assert g.axis_name == "mp" and g.nranks == 2
        assert set(hcg.mesh.axis_names) == {"dp", "pp", "sharding", "sep", "mp"}
        assert dist.get_hybrid_communicate_group() is hcg

    def test_rank_from_stage(self):
        hcg = dist.create_hybrid_communicate_group(dp=2, pp=4)
        assert hcg.get_rank_from_stage(0) == 0
        assert hcg.get_stage_id() == 0 and hcg.is_first_stage


class TestShardMapCompat:
    def test_kwarg_detected_by_signature_not_import_location(self):
        """ADVICE r5: there is a jax window where top-level jax.shard_map
        exists but still takes check_rep — the kwarg spelling must come
        from the resolved function's signature, never from which import
        succeeded."""
        from paddle_tpu.distributed.shard_map_compat import (
            NO_CHECK, _takes_check_vma, shard_map as resolved,
        )

        def modern(f, mesh, in_specs, out_specs, check_vma=True):
            pass

        def legacy(f, mesh, in_specs, out_specs, check_rep=True):
            pass

        def legacy_kw(f, mesh, in_specs, out_specs, check_rep=True, **kw):
            pass

        def opaque(*args, **kwargs):
            pass

        assert _takes_check_vma(modern)
        assert not _takes_check_vma(legacy)
        assert not _takes_check_vma(legacy_kw)
        assert _takes_check_vma(opaque)      # unsignaturable → modern
        # NO_CHECK's spelling agrees with whatever was resolved, and the
        # resolved shard_map accepts it (the legacy wrapper translates)
        assert len(NO_CHECK) == 1
        assert set(NO_CHECK) <= {"check_vma", "check_rep"}
        import inspect as _inspect

        params = _inspect.signature(resolved).parameters
        has_kw = any(p.kind is _inspect.Parameter.VAR_KEYWORD
                     for p in params.values())
        assert has_kw or all(k in params for k in NO_CHECK)


class TestCollectives:
    @pytest.fixture()
    def dp8(self):
        hcg = dist.create_hybrid_communicate_group(dp=8)
        return hcg, hcg.get_data_parallel_group()

    def test_all_reduce_sum_max(self, dp8):
        hcg, g = dp8

        def body(x):
            with dist.axis_scope("dp"):
                t = paddle.Tensor(x)
                dist.all_reduce(t, group=g)
                m = paddle.Tensor(x)
                dist.all_reduce(m, op=dist.ReduceOp.MAX, group=g)
            return t._data, m._data

        f = shard_map(body, mesh=hcg.mesh, in_specs=P("dp"),
                      out_specs=(P("dp"), P("dp")))
        x = np.arange(16.0, dtype=np.float32).reshape(8, 2)
        s, m = f(x)
        np.testing.assert_allclose(np.asarray(s), np.tile(x.sum(0), (8, 1)))
        np.testing.assert_allclose(np.asarray(m), np.tile(x.max(0), (8, 1)))

    def test_all_gather(self, dp8):
        hcg, g = dp8

        def body(x):
            with dist.axis_scope("dp"):
                out = dist.all_gather(None, paddle.Tensor(x), group=g)
            return out._data.reshape(-1, x.shape[-1])

        f = shard_map(body, mesh=hcg.mesh, in_specs=P("dp"), out_specs=P(None),
                      check_vma=False)
        x = np.arange(16.0, dtype=np.float32).reshape(8, 2)
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, x)

    def test_reduce_scatter(self, dp8):
        hcg, g = dp8

        def body(x):
            with dist.axis_scope("dp"):
                out = paddle.Tensor(jnp.zeros((1,), jnp.float32))
                dist.reduce_scatter(out, paddle.Tensor(x), group=g)
            return out._data

        f = shard_map(body, mesh=hcg.mesh, in_specs=P(None), out_specs=P("dp"))
        x = np.arange(8.0, dtype=np.float32)
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, 8.0 * x)

    def test_broadcast(self, dp8):
        hcg, g = dp8

        def body(x):
            with dist.axis_scope("dp"):
                t = paddle.Tensor(x)
                dist.broadcast(t, src=3, group=g)
            return t._data

        f = shard_map(body, mesh=hcg.mesh, in_specs=P("dp"), out_specs=P("dp"))
        x = np.arange(8.0, dtype=np.float32).reshape(8, 1)
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, np.full((8, 1), 3.0))

    def test_alltoall_single(self, dp8):
        hcg, g = dp8

        def body(x):
            with dist.axis_scope("dp"):
                out = paddle.Tensor(jnp.zeros_like(x))
                dist.alltoall_single(out, paddle.Tensor(x), group=g)
            return out._data

        f = shard_map(body, mesh=hcg.mesh, in_specs=P("dp"), out_specs=P("dp"))
        x = np.arange(64.0, dtype=np.float32).reshape(64, 1)
        out = np.asarray(f(x)).reshape(8, 8)
        np.testing.assert_allclose(out, x.reshape(8, 8).T)

    def test_shift_ring(self, dp8):
        hcg, g = dp8

        def body(x):
            with dist.axis_scope("dp"):
                out = dist.shift(paddle.Tensor(x), offset=1, group=g)
            return out._data

        f = shard_map(body, mesh=hcg.mesh, in_specs=P("dp"), out_specs=P("dp"))
        x = np.arange(8.0, dtype=np.float32).reshape(8, 1)
        out = np.asarray(f(x)).ravel()
        np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))

    def test_send_recv_pipeline_pair(self, dp8):
        hcg, g = dp8

        def body(x):
            with dist.axis_scope("dp"):
                t = paddle.Tensor(x)
                dist.send(t, dst=(g.rank + 1) % g.nranks, group=g)
                out = paddle.Tensor(jnp.zeros_like(x))
                dist.recv(out, src=(g.rank - 1) % g.nranks, group=g)
            return out._data

        f = shard_map(body, mesh=hcg.mesh, in_specs=P("dp"), out_specs=P("dp"))
        x = np.arange(8.0, dtype=np.float32).reshape(8, 1)
        out = np.asarray(f(x)).ravel()
        np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))

    def test_collective_gradients(self, dp8):
        """psum has a correct vjp through the tape (grad of allreduce-sum is
        allreduce-sum of the upstream grad)."""
        hcg, g = dp8

        def body(x):
            with dist.axis_scope("dp"):
                t = paddle.Tensor(x, stop_gradient=False)
                y = t * t
                dist.all_reduce(y, group=g)
                loss = y.sum()
                loss.backward()
            return t.grad._data

        f = shard_map(body, mesh=hcg.mesh, in_specs=P("dp"), out_specs=P("dp"))
        x = np.arange(8.0, dtype=np.float32).reshape(8, 1)
        grad = np.asarray(f(x)).ravel()
        np.testing.assert_allclose(grad, 2.0 * np.arange(8.0))

    def test_eager_world1_identity(self):
        g = dist.new_group([0])
        t = paddle.to_tensor([1.0, 2.0])
        assert dist.all_reduce(t, group=g) is None
        np.testing.assert_allclose(t.numpy(), [1.0, 2.0])
        out = []
        dist.all_gather(out, t, group=g)
        assert len(out) == 1
        dist.barrier()

    def test_eager_multirank_raises(self):
        g = dist.Group(axis_name="mp", nranks=4)
        t = paddle.to_tensor([1.0])
        with pytest.raises(RuntimeError, match="shard_map"):
            dist.all_reduce(t, group=g)


class TestParallelEnv:
    def test_init_parallel_env_single(self):
        dist.set_hybrid_communicate_group(None)
        g = dist.init_parallel_env()
        assert g.nranks == jax.device_count()
        assert dist.get_world_size() == jax.device_count()
        assert dist.get_rank() == 0

    def test_data_parallel_wrapper(self):
        import paddle_tpu.nn as nn

        dist.set_hybrid_communicate_group(None)
        dist.create_hybrid_communicate_group(dp=8)
        m = nn.Linear(4, 2)
        dp = dist.DataParallel(m)
        x = paddle.randn([8, 4])
        out = dp(x)
        ref = m(x)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)
        with dp.no_sync():
            pass
        assert len(dp.state_dict()) == len(m.state_dict())
