"""paddle.linalg vs NumPy references + incubate fused functional parity."""

import numpy as np
import pytest

import paddle_tpu as paddle


def _t(x):
    return paddle.to_tensor(np.asarray(x))


def _spd(n, seed=0):
    rng = np.random.RandomState(seed)
    a = rng.randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


class TestLinalg:
    def test_svd_reconstruction(self):
        rng = np.random.RandomState(0)
        a = rng.randn(5, 3).astype(np.float32)
        u, s, vh = paddle.linalg.svd(_t(a), full_matrices=False)
        rec = u.numpy() @ np.diag(s.numpy()) @ vh.numpy()
        np.testing.assert_allclose(rec, a, atol=1e-5)

    def test_qr(self):
        rng = np.random.RandomState(1)
        a = rng.randn(4, 4).astype(np.float32)
        q, r = paddle.linalg.qr(_t(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-5)
        np.testing.assert_allclose(q.numpy().T @ q.numpy(), np.eye(4),
                                   atol=1e-5)

    def test_eigh(self):
        a = _spd(4)
        w, v = paddle.linalg.eigh(_t(a))
        np.testing.assert_allclose(
            v.numpy() @ np.diag(w.numpy()) @ v.numpy().T, a, atol=1e-3)

    def test_solve_and_det(self):
        a = _spd(3, seed=2)
        b = np.array([[1.0], [2.0], [3.0]], np.float32)
        x = paddle.linalg.solve(_t(a), _t(b))
        np.testing.assert_allclose(a @ x.numpy(), b, atol=1e-4)
        np.testing.assert_allclose(paddle.linalg.det(_t(a)).numpy(),
                                   np.linalg.det(a), rtol=1e-4)

    def test_cholesky_and_inv(self):
        a = _spd(3, seed=3)
        l = paddle.linalg.cholesky(_t(a))
        np.testing.assert_allclose(l.numpy() @ l.numpy().T, a, atol=1e-4)
        inv = paddle.linalg.inv(_t(a))
        np.testing.assert_allclose(a @ inv.numpy(), np.eye(3), atol=1e-4)

    def test_lstsq(self):
        rng = np.random.RandomState(4)
        a = rng.randn(6, 3).astype(np.float32)
        b = rng.randn(6, 1).astype(np.float32)
        sol = paddle.linalg.lstsq(_t(a), _t(b))
        x = sol[0] if isinstance(sol, (tuple, list)) else sol
        ref = np.linalg.lstsq(a, b, rcond=None)[0]
        np.testing.assert_allclose(x.numpy(), ref, atol=1e-4)

    def test_norms(self):
        rng = np.random.RandomState(5)
        a = rng.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(
            paddle.linalg.norm(_t(a)).numpy(), np.linalg.norm(a), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.linalg.matrix_rank(_t(a)).numpy(), 3)


class TestIncubateFused:
    def test_fused_rms_norm_matches_ref(self):
        from paddle_tpu.incubate.nn.functional import fused_rms_norm

        rng = np.random.RandomState(0)
        x = rng.randn(4, 16).astype(np.float32)
        w = (rng.rand(16).astype(np.float32) + 0.5)
        out = fused_rms_norm(_t(x), _t(w), None, epsilon=1e-6)
        if isinstance(out, (tuple, list)):
            out = out[0]
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_fused_rotary_position_embedding(self):
        from paddle_tpu.incubate.nn.functional import (
            fused_rotary_position_embedding,
        )
        from paddle_tpu.ops.rope import apply_rotary_emb

        rng = np.random.RandomState(1)
        q = rng.randn(2, 8, 4, 16).astype(np.float32)
        k = rng.randn(2, 8, 4, 16).astype(np.float32)
        out = fused_rotary_position_embedding(_t(q), _t(k))
        oq = out[0] if isinstance(out, (tuple, list)) else out
        ref_q = apply_rotary_emb(_t(q))
        np.testing.assert_allclose(oq.numpy(), ref_q.numpy(), rtol=1e-5,
                                   atol=1e-5)

    def test_fused_linear_activation(self):
        from paddle_tpu.incubate.nn.functional import fused_linear_activation
        import scipy.special as sp

        rng = np.random.RandomState(2)
        x = rng.randn(3, 8).astype(np.float32)
        w = rng.randn(8, 4).astype(np.float32)
        b = rng.randn(4).astype(np.float32)
        out = fused_linear_activation(_t(x), _t(w), _t(b), activation="gelu")
        z = x @ w + b
        ref = 0.5 * z * (1 + sp.erf(z / np.sqrt(2)))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


class TestLinalgRound2:
    def test_lu_unpack_roundtrip(self):
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.linalg as L

        a = np.random.RandomState(0).randn(4, 4).astype(np.float32)
        lu_m, piv = L.lu(paddle.to_tensor(a))
        P, Lw, U = L.lu_unpack(lu_m, piv)
        np.testing.assert_allclose(P.numpy() @ Lw.numpy() @ U.numpy(), a,
                                   atol=1e-5)

    def test_matrix_exp_vs_scipy(self):
        import numpy as np
        from scipy.linalg import expm

        import paddle_tpu as paddle
        import paddle_tpu.linalg as L

        a = np.random.RandomState(1).randn(4, 4).astype(np.float32) * 0.5
        np.testing.assert_allclose(
            L.matrix_exp(paddle.to_tensor(a)).numpy(), expm(a),
            rtol=1e-4, atol=1e-5)

    def test_ormqr_vs_lapack(self):
        import numpy as np
        import scipy.linalg as sla

        import paddle_tpu as paddle
        import paddle_tpu.linalg as L

        rng = np.random.RandomState(2)
        a = rng.randn(4, 4).astype(np.float32)
        h, tau = sla.lapack.sgeqrf(a)[:2]
        y = rng.randn(4, 3).astype(np.float32)
        out = L.ormqr(paddle.to_tensor(h), paddle.to_tensor(tau),
                      paddle.to_tensor(y))
        qfull = sla.lapack.sorgqr(h, tau)[0]
        np.testing.assert_allclose(out.numpy(), qfull @ y, atol=1e-4)

    def test_svd_lowrank_reconstructs(self):
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.linalg as L

        rng = np.random.RandomState(3)
        b = (rng.randn(8, 3) @ rng.randn(3, 6)).astype(np.float32)
        U_, S_, V_ = L.svd_lowrank(paddle.to_tensor(b), q=3, niter=4)
        rec = U_.numpy() @ np.diag(S_.numpy()) @ V_.numpy().T
        # randomized f32 subspace iteration: loose tolerance
        np.testing.assert_allclose(rec, b, atol=1e-2)
