"""Elastic END-TO-END (VERDICT r2 item 5): the composed flow the reference
pairs together — training with periodic checkpoints, a scale event injected
through the membership store, the elastic supervisor relaunching at the new
world size, and training RESUMING from the resharded checkpoint with loss
still descending — exercised as one pytest on the virtual CPU mesh."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMPANION = os.path.join(REPO, "tests", "companions", "elastic_train.py")


def _read_log(path):
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
    return out


def _wait_for(cond, timeout, interval=0.5, desc=""):
    t0 = time.time()
    while time.time() - t0 < timeout:
        got = cond()
        if got:
            return got
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {desc}")


def test_scale_up_relaunch_resume(tmp_path):
    membership = tmp_path / "membership"
    ckpt = tmp_path / "ckpt"
    log = tmp_path / "train.jsonl"
    membership.mkdir()
    env = dict(
        os.environ,
        PADDLE_ELASTIC_DIR=str(membership),
        ELASTIC_CKPT_DIR=str(ckpt),
        ELASTIC_LOG=str(log),
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    sup = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--elastic_np", "1:4", "--rank", "0", "--max_restarts", "3",
         COMPANION],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    stop_beat = threading.Event()
    try:
        # phase 1: world=1 training underway, at least one checkpoint cut
        _wait_for(lambda: len([e for e in _read_log(str(log))
                               if e["world"] == 1]) >= 8,
                  timeout=180, desc="world=1 progress")

        # phase 2: inject a scale event — node '1' joins the membership
        # store and keeps heartbeating (the test plays the second host)
        from paddle_tpu.distributed.fleet.elastic.manager import (
            FileMembershipStore,
        )

        store = FileMembershipStore(str(membership))
        store.register("1", {})

        def beat():
            while not stop_beat.wait(0.4):
                store.heartbeat("1")

        beater = threading.Thread(target=beat, daemon=True)
        beater.start()

        # phase 3: supervisor relaunches at world=2; trainer resumes
        w2 = _wait_for(lambda: [e for e in _read_log(str(log))
                                if e["world"] == 2][:1],
                       timeout=180, desc="world=2 relaunch")[0]
        # resumed from checkpoint, not from scratch
        assert w2["step"] > 0, w2
        world1 = [e for e in _read_log(str(log)) if e["world"] == 1]
        assert w2["step"] >= max(5, world1[-1]["step"] - 10)

        # phase 4: loss continues descending across the restart
        entries = _wait_for(
            lambda: (lambda es: es if len(es) >= 10 else None)(
                [e for e in _read_log(str(log)) if e["world"] == 2]),
            timeout=120, desc="world=2 progress")
        first_ever = _read_log(str(log))[0]["loss"]
        resumed_first = entries[0]["loss"]
        pre_kill = world1[-1]["loss"]
        # resume point is near where world=1 left off, far below the start
        assert resumed_first < 0.7 * first_ever, (resumed_first, first_ever)
        assert resumed_first < 4 * max(pre_kill, 1e-6) + 1e-3
        # and still descending
        assert entries[-1]["loss"] <= resumed_first * 1.05 + 1e-9
    finally:
        stop_beat.set()
        sup.terminate()
        try:
            sup.wait(timeout=30)
        except subprocess.TimeoutExpired:
            sup.kill()
            sup.wait()
