"""paddle.distribution tests: log_prob vs scipy, sample moments, transforms."""

import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
import paddle_tpu.distribution as D


def _t(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


class TestLogProbVsScipy:
    CASES = [
        (lambda: D.Normal(_t(0.5), _t(2.0)), st.norm(0.5, 2.0), [-1.0, 0.5, 3.0]),
        (lambda: D.Laplace(_t(0.0), _t(1.5)), st.laplace(0.0, 1.5), [-2.0, 0.1, 1.0]),
        (lambda: D.Gumbel(_t(1.0), _t(2.0)), st.gumbel_r(1.0, 2.0), [0.0, 1.0, 4.0]),
        (lambda: D.Exponential(_t(2.0)), st.expon(scale=0.5), [0.1, 0.5, 2.0]),
        (lambda: D.LogNormal(_t(0.2), _t(0.7)), st.lognorm(0.7, scale=np.exp(0.2)), [0.5, 1.0, 3.0]),
        (lambda: D.Cauchy(_t(0.0), _t(1.0)), st.cauchy(0.0, 1.0), [-1.0, 0.0, 2.0]),
        (lambda: D.StudentT(_t(5.0)), st.t(5.0), [-1.0, 0.0, 2.0]),
        (lambda: D.Poisson(_t(3.0)), st.poisson(3.0), [0.0, 2.0, 5.0]),
        (lambda: D.Geometric(_t(0.3)), st.geom(0.3, loc=-1), [0.0, 1.0, 4.0]),
    ]

    @pytest.mark.parametrize("mk,ref,vals", CASES,
                             ids=[c[1].dist.name for c in CASES])
    def test_log_prob(self, mk, ref, vals):
        d = mk()
        ours = d.log_prob(_t(vals)).numpy()
        if hasattr(ref, "logpdf") and ref.dist.name not in ("poisson", "geom"):
            expect = ref.logpdf(vals)
        else:
            expect = ref.logpmf(vals)
        np.testing.assert_allclose(ours, expect, rtol=1e-4, atol=1e-5)


class TestSampleMoments:
    def test_laplace_moments(self):
        paddle.seed(0)
        s = D.Laplace(_t(1.0), _t(2.0)).sample((20000,)).numpy()
        assert abs(s.mean() - 1.0) < 0.1
        assert abs(s.var() - 8.0) < 0.6

    def test_dirichlet_sums_to_one(self):
        paddle.seed(0)
        d = D.Dirichlet(_t([2.0, 3.0, 5.0]))
        s = d.sample((512,)).numpy()
        np.testing.assert_allclose(s.sum(-1), 1.0, atol=1e-5)
        np.testing.assert_allclose(s.mean(0), [0.2, 0.3, 0.5], atol=0.03)
        lp = d.log_prob(_t([0.2, 0.3, 0.5])).numpy()
        np.testing.assert_allclose(lp, st.dirichlet([2.0, 3.0, 5.0]).logpdf([0.2, 0.3, 0.5]), rtol=1e-4)

    def test_poisson_mean(self):
        paddle.seed(0)
        s = D.Poisson(_t(4.0)).sample((20000,)).numpy()
        assert abs(s.mean() - 4.0) < 0.1


class TestKL:
    def test_normal_kl_sanity(self):
        kl = D.kl_divergence(D.Normal(_t(0.0), _t(1.0)),
                             D.Normal(_t(0.0), _t(1.0))).numpy()
        np.testing.assert_allclose(kl, 0.0, atol=1e-6)

    def test_exponential_kl_montecarlo(self):
        paddle.seed(0)
        p, q = D.Exponential(_t(2.0)), D.Exponential(_t(0.7))
        kl = float(D.kl_divergence(p, q).numpy())
        s = p.sample((40000,))
        mc = float((p.log_prob(s).numpy() - q.log_prob(s).numpy()).mean())
        assert abs(kl - mc) < 0.05

    def test_laplace_kl_montecarlo(self):
        paddle.seed(0)
        p, q = D.Laplace(_t(0.0), _t(1.0)), D.Laplace(_t(1.0), _t(2.0))
        kl = float(D.kl_divergence(p, q).numpy())
        s = p.sample((40000,))
        mc = float((p.log_prob(s).numpy() - q.log_prob(s).numpy()).mean())
        assert abs(kl - mc) < 0.05


class TestTransforms:
    def test_lognormal_via_transform(self):
        base = D.Normal(_t(0.2), _t(0.7))
        td = D.TransformedDistribution(base, [D.ExpTransform()])
        direct = D.LogNormal(_t(0.2), _t(0.7))
        for v in (0.5, 1.0, 2.5):
            np.testing.assert_allclose(td.log_prob(_t(v)).numpy(),
                                       direct.log_prob(_t(v)).numpy(),
                                       rtol=1e-5)

    def test_affine_roundtrip(self):
        t = D.AffineTransform(_t(1.0), _t(3.0))
        x = _t([0.5, -1.0])
        np.testing.assert_allclose(t.inverse(t.forward(x)).numpy(), x.numpy(),
                                   rtol=1e-6)

    def test_sigmoid_logdet(self):
        t = D.SigmoidTransform()
        x = _t([0.0])
        # d sigmoid/dx at 0 = 0.25 -> log det = log(0.25)
        np.testing.assert_allclose(t.forward_log_det_jacobian(x).numpy(),
                                   np.log(0.25), rtol=1e-5)


class TestBatchedDirichlet:
    def test_batched_concentration_sample(self):
        paddle.seed(0)
        d = D.Dirichlet(_t(np.ones((2, 3), np.float32)))
        s = d.sample().numpy()
        assert s.shape == (2, 3)
        np.testing.assert_allclose(s.sum(-1), 1.0, atol=1e-5)
        s2 = d.sample((5,)).numpy()
        assert s2.shape == (5, 2, 3)


class TestMultivariateNormal:
    def _params(self):
        rng = np.random.RandomState(3)
        loc = rng.randn(3).astype(np.float32)
        a = rng.randn(3, 3).astype(np.float32)
        cov = a @ a.T + 3.0 * np.eye(3, dtype=np.float32)
        return loc, cov

    def test_log_prob_vs_scipy(self):
        loc, cov = self._params()
        d = D.MultivariateNormal(_t(loc), covariance_matrix=_t(cov))
        vals = np.random.RandomState(4).randn(5, 3).astype(np.float32)
        ours = d.log_prob(_t(vals)).numpy()
        expect = st.multivariate_normal(loc, cov).logpdf(vals)
        np.testing.assert_allclose(ours, expect, rtol=1e-4, atol=1e-4)

    def test_three_parameterizations_agree(self):
        loc, cov = self._params()
        v = _t(np.zeros(3, np.float32))
        lp_cov = D.MultivariateNormal(_t(loc), covariance_matrix=_t(cov)
                                      ).log_prob(v).numpy()
        lp_tril = D.MultivariateNormal(
            _t(loc), scale_tril=_t(np.linalg.cholesky(cov))
        ).log_prob(v).numpy()
        lp_prec = D.MultivariateNormal(
            _t(loc), precision_matrix=_t(np.linalg.inv(cov))
        ).log_prob(v).numpy()
        np.testing.assert_allclose(lp_cov, lp_tril, rtol=1e-5)
        np.testing.assert_allclose(lp_cov, lp_prec, rtol=1e-3, atol=1e-4)
        with pytest.raises(ValueError):
            D.MultivariateNormal(_t(loc))

    def test_entropy_and_moments(self):
        loc, cov = self._params()
        d = D.MultivariateNormal(_t(loc), covariance_matrix=_t(cov))
        np.testing.assert_allclose(d.entropy().numpy(),
                                   st.multivariate_normal(loc, cov).entropy(),
                                   rtol=1e-5)
        paddle.seed(0)
        s = d.sample((40000,)).numpy()
        np.testing.assert_allclose(s.mean(0), loc, atol=0.05)
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.2)
        np.testing.assert_allclose(d.variance.numpy(), np.diag(cov),
                                   rtol=1e-5)

    def test_rsample_pathwise_gradients(self):
        # rsample must backprop into loc and the covariance parameter
        loc, cov = self._params()
        tl = paddle.to_tensor(loc)
        tc = paddle.to_tensor(cov)
        tl.stop_gradient = False
        tc.stop_gradient = False
        d = D.MultivariateNormal(tl, covariance_matrix=tc)
        paddle.seed(7)
        s = d.rsample((16,))
        (s.sum()).backward()
        assert tl.grad is not None and tc.grad is not None
        # d(sum)/d(loc_j) = n_samples exactly
        np.testing.assert_allclose(tl.grad.numpy(),
                                   np.full(3, 16.0, np.float32), rtol=1e-5)
        assert np.any(np.abs(tc.grad.numpy()) > 0)

    def test_kl_closed_form_vs_montecarlo(self):
        loc, cov = self._params()
        p = D.MultivariateNormal(_t(loc), covariance_matrix=_t(cov))
        q = D.MultivariateNormal(_t(loc * 0.5),
                                 covariance_matrix=_t(cov * 1.5))
        kl = float(D.kl_divergence(p, q).numpy())
        paddle.seed(1)
        s = p.sample((60000,))
        mc = float((p.log_prob(s).numpy() - q.log_prob(s).numpy()).mean())
        assert abs(kl - mc) < 0.05 * max(1.0, abs(kl))
        # self-KL is zero
        np.testing.assert_allclose(float(D.kl_divergence(p, p).numpy()),
                                   0.0, atol=1e-5)
