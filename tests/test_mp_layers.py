"""Tensor-parallel layer parity tests (SURVEY.md §4 distributed pattern:
single-process SPMD on the 8-device CPU mesh, correctness = numerical parity
with the serial model)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from paddle_tpu.distributed.shard_map_compat import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from paddle_tpu.distributed.fleet.utils import sequence_parallel_utils as spu

MP = 4


@pytest.fixture()
def hcg():
    dist.set_hybrid_communicate_group(None)
    return dist.create_hybrid_communicate_group(dp=2, mp=MP)


def _spec(param):
    axes = getattr(param, "_sharding_axes", None)
    return P(*axes) if axes else P()


def _run_sharded(hcg, layer, x_np, n_out=1, extra=None, extra_spec=P()):
    """shard_map the layer's forward over 'mp' with params sliced per rank
    according to their _sharding_axes hints."""
    names = list(layer.state_dict())
    params = [layer.state_dict()[k]._data for k in names]
    specs = [_spec(layer.state_dict()[k]) for k in names]

    def body(x, *args):
        if extra is not None:
            ps, ex = args[:-1], args[-1]
        else:
            ps, ex = args, None
        with dist.axis_scope("mp"):
            with layer.use_state(dict(zip(names, ps))):
                out = (layer(paddle.Tensor(x), paddle.Tensor(ex))
                       if ex is not None else layer(paddle.Tensor(x)))
        return out._data

    in_specs = [P()] + specs + ([extra_spec] if extra is not None else [])
    f = shard_map(body, mesh=hcg.mesh, in_specs=tuple(in_specs),
                  out_specs=P(), check_vma=False)
    args = [x_np] + params + ([extra] if extra is not None else [])
    return np.asarray(f(*args))


class TestColumnParallelLinear:
    def test_parity_and_grad(self, hcg):
        layer = ColumnParallelLinear(16, 24, gather_output=True)
        x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        ref = layer(paddle.Tensor(x)).numpy()  # serial path (mp not live)
        out = _run_sharded(hcg, layer, x)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_no_gather_keeps_local(self, hcg):
        layer = ColumnParallelLinear(8, 16, gather_output=False)
        x = np.random.RandomState(1).randn(2, 8).astype(np.float32)
        names = list(layer.state_dict())
        params = [layer.state_dict()[k]._data for k in names]
        specs = [_spec(layer.state_dict()[k]) for k in names]

        def body(x, *ps):
            with dist.axis_scope("mp"):
                with layer.use_state(dict(zip(names, ps))):
                    out = layer(paddle.Tensor(x))
            return out._data

        f = shard_map(body, mesh=hcg.mesh, in_specs=tuple([P()] + specs),
                      out_specs=P(None, "mp"), check_vma=False)
        out = np.asarray(f(x, *params))
        ref = layer(paddle.Tensor(x)).numpy()
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


class TestRowParallelLinear:
    def test_parity(self, hcg):
        layer = RowParallelLinear(16, 12, input_is_parallel=False)
        x = np.random.RandomState(2).randn(4, 16).astype(np.float32)
        ref = layer(paddle.Tensor(x)).numpy()
        out = _run_sharded(hcg, layer, x)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


class TestVocabParallelEmbedding:
    def test_parity(self, hcg):
        layer = VocabParallelEmbedding(32, 8)
        ids = np.array([[0, 5, 31, 17], [8, 9, 15, 16]], np.int32)
        ref = layer(paddle.Tensor(ids)).numpy()
        out = _run_sharded(hcg, layer, ids)
        np.testing.assert_allclose(out, ref, rtol=1e-6)


class TestParallelCrossEntropy:
    def test_parity(self, hcg):
        B, V = 6, 32
        rng = np.random.RandomState(3)
        logits = rng.randn(B, V).astype(np.float32)
        labels = rng.randint(0, V, size=(B,)).astype(np.int32)
        ce = ParallelCrossEntropy()
        ref = ce(paddle.Tensor(logits), paddle.Tensor(labels)).numpy().reshape(B)

        def body(lg, lb):
            with dist.axis_scope("mp"):
                out = ce(paddle.Tensor(lg), paddle.Tensor(lb))
            return out._data

        f = shard_map(body, mesh=hcg.mesh, in_specs=(P(None, "mp"), P()),
                      out_specs=P(), check_vma=False)
        out = np.asarray(f(logits, labels)).reshape(B)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_grad_parity(self, hcg):
        """End-to-end: grad of the sharded vocab-parallel CE wrt logits
        matches softmax(p)-onehot computed serially."""
        B, V = 4, 16
        rng = np.random.RandomState(4)
        logits = rng.randn(B, V).astype(np.float32)
        labels = rng.randint(0, V, size=(B,)).astype(np.int32)

        def sharded_loss(lg, lb):
            # loss from vocab_parallel_cross_entropy is already replicated
            # (inner psums); psum transpose is identity so plain sum/B gives
            # per-rank grads matching the serial slice
            from paddle_tpu.distributed.fleet.layers.mpu import mp_ops
            loss = mp_ops.vocab_parallel_cross_entropy(lg, lb, "mp")
            return jnp.sum(loss) / B

        def body(lg, lb):
            with dist.axis_scope("mp"):
                g = jax.grad(sharded_loss)(lg, lb)
            return g

        f = shard_map(body, mesh=hcg.mesh, in_specs=(P(None, "mp"), P()),
                      out_specs=P(None, "mp"), check_vma=False)
        g = np.asarray(f(logits, labels))

        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = p.copy()
        ref[np.arange(B), labels] -= 1.0
        ref /= B
        np.testing.assert_allclose(g, ref, rtol=1e-5, atol=1e-5)


class TestSequenceParallel:
    def test_column_row_sp_roundtrip(self, hcg):
        """seq-sharded x → ColumnSP(gather seq) → RowSP(reduce-scatter seq)
        matches the serial two-matmul reference."""
        B, S, H = 2, 8, 16
        col = spu.ColumnSequenceParallelLinear(H, 2 * H, gather_output=False)
        row = spu.RowSequenceParallelLinear(2 * H, H, input_is_parallel=True)
        x = np.random.RandomState(5).randn(B, S, H).astype(np.float32)
        ref = row(col(paddle.Tensor(x))).numpy()

        all_names, all_params, all_specs = [], [], []
        for layer in (col, row):
            for k, v in layer.state_dict().items():
                all_names.append((layer, k))
                all_params.append(v._data)
                all_specs.append(_spec(v))

        def body(x, *ps):
            with dist.axis_scope("mp"):
                cd = {k: p for (ly, k), p in zip(all_names, ps) if ly is col}
                rd = {k: p for (ly, k), p in zip(all_names, ps) if ly is row}
                with col.use_state(cd), row.use_state(rd):
                    out = row(col(paddle.Tensor(x)))
            return out._data

        f = shard_map(body, mesh=hcg.mesh,
                      in_specs=tuple([P(None, "mp")] + all_specs),
                      out_specs=P(None, "mp"), check_vma=False)
        out = np.asarray(f(x, *all_params))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_scatter_gather_inverse(self, hcg):
        x = np.arange(2 * 8 * 4, dtype=np.float32).reshape(2, 8, 4)

        def body(x):
            with dist.axis_scope("mp"):
                s = spu.scatter(paddle.Tensor(x))
                g = spu.all_gather(s)
            return g._data

        f = shard_map(body, mesh=hcg.mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
        np.testing.assert_allclose(np.asarray(f(x)), x)


class TestRNGTracker:
    def test_local_stream_differs_per_rank(self, hcg):
        from paddle_tpu.distributed.fleet.layers.mpu.random import (
            model_parallel_random_seed, model_parallel_rng)

        model_parallel_random_seed(7)

        def body(_):
            with dist.axis_scope("mp"):
                with model_parallel_rng():
                    from paddle_tpu.core.random import next_key
                    k = next_key()
            return jax.random.uniform(k, (1,))

        f = shard_map(body, mesh=hcg.mesh, in_specs=P("mp"), out_specs=P("mp"),
                      check_vma=False)
        out = np.asarray(f(np.zeros((MP, 1), np.float32))).ravel()
        assert len(np.unique(out)) == MP  # distinct stream per mp rank
