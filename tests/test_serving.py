"""paddle_tpu.serving tests: slotted-cache decode parity with the legacy
concat cache, continuous batching vs sequential generation, bucketed
prefill compilation counters, sampling determinism."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core import tape as _tape
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.models.llama import LlamaForCausalLM
from paddle_tpu.serving import (
    Engine, EngineConfig, HostKVTier, PagedKVCache, PagedKVPool,
    PrefixCache, SamplingParams, Scheduler, SlotKV, SlottedKVCache,
)
from paddle_tpu.quantization import (
    PerChannelAbsmaxObserver, channelwise_scales, dequantize_weight,
    quantize_for_serving, quantize_weight,
)
from paddle_tpu.serving.kv_cache import (
    paged_write, paged_write_quant, visible_mask, write_slots,
)
from paddle_tpu.serving.paged_attention import (
    _pallas_paged_attention, _xla_paged_attention,
)

TINY = GPTConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 max_position_embeddings=64)
TINY_GQA = GPTConfig(vocab_size=96, hidden_size=64, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=8,
                     num_key_value_heads=2, max_position_embeddings=64)


def _model(cfg=TINY, seed=0):
    paddle.seed(seed)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _fresh_views(cfg, b, max_seq, n_layers):
    shape = (b, max_seq, cfg.kv_heads, cfg.head_dim)
    pos = jnp.zeros((b,), jnp.int32)
    return [SlotKV(jnp.zeros(shape, jnp.float32),
                   jnp.zeros(shape, jnp.float32), pos)
            for _ in range(n_layers)]


class TestSlottedCacheParity:
    """The slotted static-shape cache must reproduce the legacy
    concat-per-step cache decode."""

    def test_prefill_logits_bit_identical(self):
        m = _model()
        ids = paddle.randint(0, TINY.vocab_size, [2, 6])
        with _tape.no_grad():
            h1, _ = m.model(ids, caches=[(None, None)] * 2)
            h2, _ = m.model(ids, caches=_fresh_views(TINY, 2, 24, 2))
            l1 = m._logits(h1).numpy()
            l2 = m._logits(h2).numpy()
        # same shapes, same math, cache-write side effects only: the
        # prompt pass is bitwise identical
        np.testing.assert_array_equal(l1, l2)

    @pytest.mark.parametrize("cfg", [TINY, TINY_GQA], ids=["mha", "gqa"])
    def test_decode_matches_concat_cache(self, cfg):
        """Greedy decode over both cache kinds: token streams identical,
        per-step logits equal to reduction-order rounding (the slotted
        path sums exp(-inf)=0 terms over the padded tail, which may
        re-associate the reduction — observed <=2 ulp on CPU)."""
        m = _model(cfg)
        b, s, steps, max_seq = 2, 6, 8, 24
        ids = paddle.randint(0, cfg.vocab_size, [b, s])
        with _tape.no_grad():
            h1, concat = m.model(ids, caches=[(None, None)] * 2)
            h2, slotted = m.model(ids, caches=_fresh_views(cfg, b, max_seq, 2))
            t1 = paddle.argmax(m._logits(h1)[:, -1], axis=-1)
            t2 = paddle.argmax(m._logits(h2)[:, -1], axis=-1)
            np.testing.assert_array_equal(t1.numpy(), t2.numpy())
            for step in range(steps):
                h1, concat = m.model(t1.unsqueeze(-1), caches=concat,
                                     position_offset=s + step)
                h2, slotted = m.model(t2.unsqueeze(-1), caches=slotted)
                l1 = m._logits(h1)[:, -1]
                l2 = m._logits(h2)[:, -1]
                np.testing.assert_allclose(l1.numpy(), l2.numpy(),
                                           rtol=0, atol=1e-5)
                t1 = paddle.argmax(l1, axis=-1)
                t2 = paddle.argmax(l2, axis=-1)
                np.testing.assert_array_equal(t1.numpy(), t2.numpy())

    def test_slot_positions_advance(self):
        m = _model()
        views = _fresh_views(TINY, 2, 24, 2)
        ids = paddle.randint(0, TINY.vocab_size, [2, 5])
        with _tape.no_grad():
            _, views = m.model(ids, caches=views)
        assert np.asarray(views[0].pos).tolist() == [5, 5]
        with _tape.no_grad():
            _, views = m.model(paddle.randint(0, 128, [2, 1]), caches=views)
        assert np.asarray(views[0].pos).tolist() == [6, 6]


class TestKVCacheHelpers:
    def test_write_slots_per_row_positions(self):
        cache = jnp.zeros((2, 8, 1, 4))
        new = jnp.ones((2, 1, 1, 4))
        out = write_slots(cache, new, jnp.asarray([0, 5], jnp.int32))
        out = np.asarray(out)
        assert out[0, 0].sum() == 4 and out[0, 1:].sum() == 0
        assert out[1, 5].sum() == 4 and out[1, :5].sum() == 0

    def test_visible_mask_is_causal_per_row(self):
        mask = np.asarray(visible_mask(jnp.asarray([0, 3], jnp.int32), 2, 8))
        assert mask.shape == (2, 1, 2, 8)
        # row 0: queries at absolute positions 0,1
        assert mask[0, 0, 0].tolist() == [True] + [False] * 7
        assert mask[0, 0, 1].tolist() == [True, True] + [False] * 6
        # row 1: queries at absolute positions 3,4
        assert mask[1, 0, 0].tolist() == [True] * 4 + [False] * 4
        assert mask[1, 0, 1].tolist() == [True] * 5 + [False] * 3

    def test_slot_alloc_free(self):
        c = SlottedKVCache(1, 2, 8, 1, 4)
        a, b = c.alloc(), c.alloc()
        assert {a, b} == {0, 1} and c.alloc() is None
        c.free(a)
        assert c.free_slots == 1 and c.used_slots == 1
        with pytest.raises(ValueError):
            c.free(a)


class TestEngine:
    def test_greedy_matches_legacy_generate(self):
        m = _model()
        prompt = [1, 5, 9, 2, 7]
        eng = Engine(m, EngineConfig(num_slots=2, max_seq_len=32),
                     register_profiler=False)
        out = eng.generate(prompt, SamplingParams(max_new_tokens=6))
        gen = m.generate(paddle.to_tensor(np.asarray([prompt], np.int64)),
                         max_new_tokens=6, temperature=0)
        assert out == gen.numpy()[0, len(prompt):].tolist()

    @pytest.mark.slow
    def test_continuous_batching_matches_sequential(self):
        """Staggered submits/EOS with mixed sampling params produce the
        SAME tokens as one-request-at-a-time generation: a request's
        stream depends only on (its prompt, its params, its seed), never
        on batch composition."""
        m = _model()
        prompts = [[1, 5, 9], [2, 7, 4, 11], [3, 3, 8, 1, 2, 9],
                   [10, 20, 30, 40, 50]]
        samp = [SamplingParams(max_new_tokens=5),
                SamplingParams(temperature=0.8, top_k=20, seed=7,
                               max_new_tokens=6),
                SamplingParams(temperature=1.0, top_p=0.9, seed=123,
                               max_new_tokens=4),
                SamplingParams(temperature=0.5, top_k=5, top_p=0.8,
                               seed=42, max_new_tokens=7)]
        sequential = []
        for p, s in zip(prompts, samp):
            e = Engine(m, EngineConfig(num_slots=2, max_seq_len=32),
                       register_profiler=False)
            sequential.append(e.generate(p, s))

        eng = Engine(m, EngineConfig(num_slots=2, max_seq_len=32),
                     register_profiler=False)
        reqs = [eng.submit(prompts[0], samp[0])]
        eng.step()
        eng.step()
        reqs.append(eng.submit(prompts[1], samp[1]))
        eng.step()
        reqs.append(eng.submit(prompts[2], samp[2]))
        reqs.append(eng.submit(prompts[3], samp[3]))   # queued: slots full
        eng.run()
        assert [r.output_ids for r in reqs] == sequential
        # 4 requests through 2 slots: slots were reused
        assert eng.counters()["requests_finished"] == 4

    def test_single_decode_compilation_heterogeneous_prompts(self):
        """The acceptance criterion: a multi-request run with
        heterogeneous prompt lengths compiles the fused decode program
        exactly ONCE PER HORIZON BUCKET, and prefill once per
        (lane-bucket, length-bucket) pair — with same-bucket requests
        co-admitted into a single batched dispatch."""
        m = _model()
        eng = Engine(m, EngineConfig(num_slots=3, max_seq_len=48,
                                     min_prefill_bucket=4),
                     register_profiler=False)
        # length buckets: 3->4, 4->4, 6->8, 5->8, 9->16
        for p in ([1, 2, 3], [1, 2, 3, 4], [5, 6, 7, 8, 9, 1],
                  [9, 8, 7, 6, 5], [1] * 9):
            eng.submit(p, SamplingParams(max_new_tokens=4))
        eng.run()
        s = eng.stats()
        assert s["decode_compiles"] == len(s["decode_buckets"])
        # dispatch shapes: (2 lanes, 4), (1, 8) twice, (1, 16)
        assert s["prefill_compiles"] == 3
        assert s["prefill_calls"] == 4       # first two share ONE dispatch
        assert s["prefill_requests"] == 5    # ...but all 5 were prefilled
        assert s["decode_cache_hits"] == \
            s["decode_horizons"] - s["decode_compiles"]
        assert s["tokens_generated"] == 5 * 4

    def test_eos_frees_slot_early(self):
        m = _model()
        prompt = [4, 8, 15, 16, 23, 42]
        eng = Engine(m, EngineConfig(num_slots=1, max_seq_len=32),
                     register_profiler=False)
        ref = eng.generate(prompt, SamplingParams(max_new_tokens=8))
        eos = ref[3]
        stop = ref.index(eos)  # greedy streams can cycle: truncate at
        # the FIRST occurrence, which is where the engine must stop
        eng2 = Engine(m, EngineConfig(num_slots=1, max_seq_len=32),
                      register_profiler=False)
        req = eng2.submit(prompt, SamplingParams(max_new_tokens=8,
                                                 eos_token_id=eos))
        eng2.run()
        assert req.output_ids == ref[:stop + 1]
        assert req.finish_reason == "eos"
        assert eng2.cache.free_slots == 1

    def test_sampling_determinism_under_fixed_seeds(self):
        m = _model()
        prompt = [3, 1, 4, 1, 5]
        sp = dict(temperature=0.9, top_k=30, top_p=0.95, max_new_tokens=8)

        def run(seed):
            e = Engine(m, EngineConfig(num_slots=2, max_seq_len=32),
                       register_profiler=False)
            return e.generate(prompt, SamplingParams(seed=seed, **sp))

        a, b, c = run(11), run(11), run(99)
        assert a == b                      # same seed: bitwise replay
        assert a != c                      # different seed: new stream

    def test_submit_validates_budget(self):
        m = _model()
        eng = Engine(m, EngineConfig(num_slots=1, max_seq_len=16),
                     register_profiler=False)
        with pytest.raises(ValueError):
            eng.submit(list(range(10)), SamplingParams(max_new_tokens=10))
        with pytest.raises(ValueError):
            eng.submit([], SamplingParams())

    def test_llama_alias_serves(self):
        paddle.seed(2)
        m = LlamaForCausalLM(TINY)
        m.eval()
        eng = Engine(m, EngineConfig(num_slots=1, max_seq_len=32),
                     register_profiler=False)
        out = eng.generate([7, 7, 7], SamplingParams(max_new_tokens=3))
        assert len(out) == 3

    def test_inference_bridge_and_lazy_submodule(self):
        import paddle_tpu
        import paddle_tpu.inference as inference

        assert paddle_tpu.serving.Engine is Engine  # lazy attr resolves
        m = _model()
        eng = inference.create_llm_engine(m, num_slots=1, max_seq_len=32)
        try:
            direct = Engine(m, EngineConfig(num_slots=1, max_seq_len=32),
                            register_profiler=False)
            sp = SamplingParams(max_new_tokens=3)
            assert eng.generate([5, 6, 7], sp) == \
                direct.generate([5, 6, 7], sp)
        finally:
            eng.close()

    def test_counters_exposed_via_profiler(self):
        import paddle_tpu.profiler as profiler

        m = _model()
        eng = Engine(m, EngineConfig(num_slots=1, max_seq_len=32))
        try:
            eng.generate([1, 2, 3], SamplingParams(max_new_tokens=2))
            snap = profiler.counters()
            assert eng._profiler_name in snap
            got = snap[eng._profiler_name]
            assert got["decode_compiles"] == 1
            assert got["tokens_generated"] == 2
            assert "tokens_per_s" in got and got["tokens_per_s"] > 0
            assert "ttft_avg_s" in got
        finally:
            eng.close()
        assert eng._profiler_name not in profiler.counters()


class TestHorizonDecode:
    """Horizon-scanned fused decode: one compiled dispatch and one host
    sync advance every slot by H steps, with in-scan EOS/limit masking.
    Every horizon partition of a request's stream must be bitwise-equal
    to horizon=1 and to sequential generation."""

    MIXED_PROMPTS = [[1, 5, 9], [2, 7, 4, 11], [3, 3, 8, 1, 2, 9]]
    MIXED_SAMP = [
        SamplingParams(max_new_tokens=9),
        SamplingParams(temperature=0.8, top_k=20, seed=7,
                       max_new_tokens=12),
        SamplingParams(temperature=1.0, top_p=0.9, seed=123,
                       max_new_tokens=10),
    ]

    @staticmethod
    def _sequential(m, prompts, samp):
        outs = []
        for p, s in zip(prompts, samp):
            e = Engine(m, EngineConfig(num_slots=2, max_seq_len=32,
                                       max_horizon=1),
                       register_profiler=False)
            outs.append(e.generate(p, s))
        return outs

    @pytest.mark.slow
    def test_horizon8_bitwise_equals_horizon1_and_sequential(self):
        m = _model()
        seq = self._sequential(m, self.MIXED_PROMPTS, self.MIXED_SAMP)
        e1 = Engine(m, EngineConfig(num_slots=3, max_seq_len=32,
                                    max_horizon=1),
                    register_profiler=False)
        e8 = Engine(m, EngineConfig(num_slots=3, max_seq_len=32,
                                    max_horizon=8),
                    register_profiler=False)
        out1 = e1.generate(self.MIXED_PROMPTS, self.MIXED_SAMP)
        out8 = e8.generate(self.MIXED_PROMPTS, self.MIXED_SAMP)
        assert out8 == out1 == seq
        s1, s8 = e1.stats(), e8.stats()
        assert s1["horizon_buckets"] == [1]
        assert max(s8["horizon_buckets"]) > 1       # adaptive growth ran
        # the horizon engine did the same work in fewer dispatches/syncs
        assert s8["decode_horizons"] < s1["decode_horizons"]
        assert s8["decode_host_syncs"] < s1["decode_host_syncs"]

    def test_one_dispatch_and_one_sync_per_horizon(self):
        """The dispatch-count probe: compiled decode calls == horizon
        dispatches == blocking host syncs (the per-step np.asarray sync
        is gone from the decode path)."""
        m = _model()
        eng = Engine(m, EngineConfig(num_slots=1, max_seq_len=64,
                                     max_horizon=8),
                     register_profiler=False)
        eng.submit([2, 4, 6], SamplingParams(max_new_tokens=17))
        while eng.scheduler.has_work:
            eng.step(horizon=8)
        c = eng.counters()
        # 16 decode tokens through horizon-8 dispatches: exactly 2
        assert c["decode_horizons"] == 2
        assert c["decode_calls"] == 2
        assert c["decode_host_syncs"] == 2
        assert c["decode_steps"] == 16
        assert c["tokens_generated"] == 17

    def test_mid_horizon_eos_masks_lane(self):
        """A lane hitting EOS inside the scan freezes: its tokens stop
        at the EOS, the rest of the horizon is discarded (-1 harvest),
        and the co-resident request is unaffected bitwise."""
        m = _model()
        prompt = [4, 8, 15, 16, 23, 42]
        other_prompt = [9, 1, 7, 3]
        ref_engine = Engine(m, EngineConfig(num_slots=1, max_seq_len=32,
                                            max_horizon=1),
                            register_profiler=False)
        ref = ref_engine.generate(prompt, SamplingParams(max_new_tokens=12))
        other_ref = Engine(
            m, EngineConfig(num_slots=1, max_seq_len=32, max_horizon=1),
            register_profiler=False).generate(
                other_prompt, SamplingParams(max_new_tokens=14))
        # pick an EOS whose FIRST occurrence lands mid-horizon (decode
        # scan step 0..6 of the first horizon-8 dispatch)
        eos = stop = None
        for k in range(1, 8):
            if 1 <= ref.index(ref[k]) <= 7:
                eos, stop = ref[k], ref.index(ref[k])
                break
        assert eos is not None, "greedy stream had no usable EOS token"
        eng = Engine(m, EngineConfig(num_slots=2, max_seq_len=32,
                                     max_horizon=8),
                     register_profiler=False)
        victim = eng.submit(prompt, SamplingParams(max_new_tokens=12,
                                                   eos_token_id=eos))
        other = eng.submit(other_prompt, SamplingParams(max_new_tokens=14))
        while eng.scheduler.has_work:
            eng.step(horizon=8)
        assert victim.output_ids == ref[:stop + 1]
        assert victim.finish_reason == "eos"
        assert other.output_ids == other_ref
        s = eng.stats()
        assert s["wasted_lane_tokens"] > 0          # masked EOS tail
        assert 0.0 < s["wasted_lane_fraction"] < 1.0

    def test_slot_free_and_reuse_across_horizon_boundary(self):
        """One slot, two queued requests: the second is admitted at a
        horizon boundary into the slot the first freed mid-horizon, and
        both streams match their sequential references."""
        m = _model()
        prompts = [[5, 3, 1], [8, 8, 2, 6]]
        samp = [SamplingParams(max_new_tokens=6),
                SamplingParams(temperature=0.7, top_k=16, seed=31,
                               max_new_tokens=7)]
        seq = self._sequential(m, prompts, samp)
        eng = Engine(m, EngineConfig(num_slots=1, max_seq_len=32,
                                     max_horizon=4),
                     register_profiler=False)
        reqs = [eng.submit(p, s) for p, s in zip(prompts, samp)]
        while eng.scheduler.has_work:
            eng.step(horizon=4)
        assert [r.output_ids for r in reqs] == seq
        assert reqs[0].slot == reqs[1].slot         # the slot was reused
        c = eng.counters()
        assert c["requests_finished"] == 2
        assert eng.cache.free_slots == 1

    @pytest.mark.slow
    def test_staggered_admission_with_horizons(self):
        """Requests joining at horizon boundaries mid-stream reproduce
        sequential generation bitwise (continuous batching preserved)."""
        m = _model()
        seq = self._sequential(m, self.MIXED_PROMPTS, self.MIXED_SAMP)
        eng = Engine(m, EngineConfig(num_slots=2, max_seq_len=32,
                                     max_horizon=8),
                     register_profiler=False)
        reqs = [eng.submit(self.MIXED_PROMPTS[0], self.MIXED_SAMP[0])]
        eng.step(horizon=2)
        reqs.append(eng.submit(self.MIXED_PROMPTS[1], self.MIXED_SAMP[1]))
        eng.step(horizon=4)
        reqs.append(eng.submit(self.MIXED_PROMPTS[2], self.MIXED_SAMP[2]))
        eng.run()
        assert [r.output_ids for r in reqs] == seq

    def test_one_compile_per_horizon_bucket(self):
        """Forced horizon sequence 1,8,8,4,2,8: exactly one compile per
        distinct (horizon, table-width, spec-K) bucket, cache hits for
        every repeat.  Ragged paged attention re-buckets the static
        table width nb as the sequence grows (block_size 16, so nb steps
        1 -> 2 -> 4 here), so the compile key is the TRIPLE — the
        repeated 8s land on different nb and are real compiles, not
        hits (K stays 0 with speculative decoding off)."""
        m = _model()
        eng = Engine(m, EngineConfig(num_slots=1, max_seq_len=64,
                                     max_horizon=8),
                     register_profiler=False)
        eng.submit([3, 1, 4], SamplingParams(max_new_tokens=26))
        for h in (1, 8, 8, 4, 2, 8):
            assert eng.scheduler.has_work
            eng.step(horizon=h)
        assert not eng.scheduler.has_work
        s = eng.stats()
        assert s["horizon_buckets"] == [1, 2, 4, 8]
        assert s["decode_buckets"] == [(1, 1, 0), (2, 2, 0), (4, 2, 0),
                                       (8, 1, 0), (8, 2, 0), (8, 4, 0)]
        assert s["decode_compiles"] == len(s["decode_buckets"])
        assert s["decode_horizons"] == 6
        assert s["decode_cache_hits"] == \
            s["decode_horizons"] - s["decode_compiles"]
        assert s["decode_host_syncs"] == 6
        # 25 decode tokens out of 1+8+8+4+2+8=31 scanned lane steps
        assert s["tokens_generated"] == 26
        assert s["wasted_lane_tokens"] == 6

    def test_adaptive_horizon_growth_and_budget_cap(self):
        """Stable single-request decode grows 1->2->4->8 and the budget
        cap retires the lane exactly at a horizon edge: zero waste,
        4 dispatches for 15 decode tokens."""
        m = _model()
        eng = Engine(m, EngineConfig(num_slots=1, max_seq_len=64,
                                     max_horizon=8),
                     register_profiler=False)
        ref = Engine(m, EngineConfig(num_slots=1, max_seq_len=64,
                                     max_horizon=1),
                     register_profiler=False).generate(
            [11, 7, 5], SamplingParams(max_new_tokens=16))
        out = eng.generate([11, 7, 5], SamplingParams(max_new_tokens=16))
        assert out == ref
        s = eng.stats()
        assert s["horizon_buckets"] == [1, 2, 4, 8]
        assert s["decode_horizons"] == 4
        assert s["decode_steps"] == 15
        assert s["wasted_lane_tokens"] == 0
        assert s["wasted_lane_fraction"] == 0.0
        assert s["decode_host_syncs"] == 4

    def test_device_state_not_rebuilt_between_horizons(self):
        """Steady-state decode never re-uploads per-slot state: the
        dirty flag is set by admission only, and the device arrays the
        scan returns are fed straight back in."""
        m = _model()
        eng = Engine(m, EngineConfig(num_slots=1, max_seq_len=64,
                                     max_horizon=4),
                     register_profiler=False)
        eng.submit([1, 2, 3], SamplingParams(max_new_tokens=12))
        eng.step(horizon=2)          # admission dirtied, then uploaded
        assert eng._state_dirty is False
        first = eng._d_tokens
        eng.step(horizon=2)
        assert eng._state_dirty is False
        assert eng._d_tokens is not first    # advanced by the scan...
        eng.run()                            # ...never rebuilt from host
        assert eng._state_dirty is False


class TestSamplingPrimitives:
    def test_greedy_ignores_key(self):
        from paddle_tpu.serving.sampling import request_key, sample_token

        logits = jnp.asarray(np.random.RandomState(0).randn(64), jnp.float32)
        t0 = sample_token(logits, request_key(1, 0), 0.0, 0, 1.0)
        t1 = sample_token(logits, request_key(2, 5), 0.0, 0, 1.0)
        assert int(t0) == int(t1) == int(np.argmax(np.asarray(logits)))

    def test_top_k_restricts_support(self):
        from paddle_tpu.serving.sampling import request_key, sample_token

        rng = np.random.RandomState(3)
        logits = jnp.asarray(rng.randn(64) * 3, jnp.float32)
        top2 = set(np.argsort(np.asarray(logits))[-2:].tolist())
        draws = {int(sample_token(logits, request_key(0, i), 1.0, 2, 1.0))
                 for i in range(20)}
        assert draws <= top2

    def test_top_p_restricts_support(self):
        from paddle_tpu.serving.sampling import request_key, sample_token

        # one dominant token: tiny top_p must always return it
        logits = jnp.asarray([10.0] + [0.0] * 31, jnp.float32)
        draws = {int(sample_token(logits, request_key(0, i), 1.0, 0, 0.5))
                 for i in range(10)}
        assert draws == {0}

    def test_validate(self):
        with pytest.raises(ValueError):
            SamplingParams(max_new_tokens=0).validate()
        with pytest.raises(ValueError):
            SamplingParams(top_p=0.0).validate()


class TestPrefixCacheUnit:
    """Host-side radix-store bookkeeping: byte-budget capacity, LRU
    eviction of unpinned leaves, refcount pinning while leased."""

    @staticmethod
    def _cache(blocks, bs=4):
        # bytes_per_block = 2 (k+v) * 1 layer * bs * 1 head * 2 * 4B
        c = PrefixCache(num_layers=1, block_size=bs, kv_heads=1,
                        head_dim=2, budget_bytes=blocks * 2 * bs * 2 * 4)
        assert c.capacity == blocks
        return c

    def test_insert_then_match_is_block_granular(self):
        c = self._cache(4)
        p = [7, 3, 9, 1, 4, 4, 2, 8, 5]           # 9 tokens -> 2 blocks
        lease = c.acquire(p)
        assert lease.matched_tokens == 0           # cold cache
        assert [i for i, _ in c.insert(p, lease)] == [0, 1]
        c.release(lease)
        assert c.lookup(p + [1]) == 8              # both blocks reusable
        assert c.lookup(p) == 8                    # cap: len-1 = 8 -> 2
        assert c.lookup(p[:8]) == 4                # cap: len-1 = 7 -> 1
        assert c.lookup([1] + p) == 0              # no shared prefix

    def test_eviction_under_byte_budget(self):
        c = self._cache(2)
        a, b = [1] * 8, [2] * 8
        la = c.acquire(a)
        c.insert(a, la)
        c.release(la)
        lb = c.acquire(b)
        c.insert(b, lb)
        c.release(lb)
        s = c.stats()
        assert s["used_blocks"] <= s["capacity_blocks"] == 2
        assert s["evictions"] == 2                 # A aged out, leaf first
        assert c.lookup(a + [0]) == 0
        assert c.lookup(b + [0]) == 8

    def test_refcount_pins_leased_blocks(self):
        c = self._cache(2)
        a, b = [1] * 8, [2] * 8
        la = c.acquire(a)
        c.insert(a, la)                            # NOT released: pinned
        lb = c.acquire(b)
        assert c.insert(b, lb) == []               # nothing evictable
        assert c.stats()["evictions"] == 0
        assert c.lookup(a + [0]) == 8              # A untouched
        c.release(la)
        c.release(la)                              # idempotent unpin
        lb2 = c.acquire(b)
        assert len(c.insert(b, lb2)) == 2          # now A ages out
        assert c.lookup(b + [0]) == 8
        assert c.stats()["evictions"] == 2


class TestPrefixReuse:
    """The tentpole acceptance gates: cached-prefix + suffix-only
    prefill is bitwise-equal to full uncached prefill and to sequential
    generation; same-bucket admission is ONE compiled dispatch."""

    SHARED = [7, 3, 9, 1, 4, 4, 2, 8]              # 2 blocks of 4

    @staticmethod
    def _cfg(**kw):
        kw.setdefault("num_slots", 4)
        kw.setdefault("max_seq_len", 48)
        kw.setdefault("min_prefill_bucket", 4)
        kw.setdefault("prefix_block_size", 4)
        return EngineConfig(**kw)

    @classmethod
    def _sequential(cls, m, prompts, samp):
        outs = []
        for p, s in zip(prompts, samp):
            e = Engine(m, cls._cfg(num_slots=1, prefix_block_size=0),
                       register_profiler=False)
            outs.append(e.generate(p, s))
        return outs

    @pytest.mark.slow
    def test_shared_prefix_parity_on_off_sequential(self):
        """Warm-cache suffix prefill == cache-off prefill == one-at-a-
        time generation, bitwise, with hit/miss lanes co-batched."""
        m = _model()
        prompts = [self.SHARED + [5, 6, 7],
                   self.SHARED + [1, 2],
                   [2, 2, 1],                      # unrelated: cold miss
                   self.SHARED + [9, 9, 9, 9, 2]]
        samp = [SamplingParams(max_new_tokens=5),
                SamplingParams(temperature=0.8, top_k=20, seed=7,
                               max_new_tokens=6),
                SamplingParams(max_new_tokens=4),
                SamplingParams(temperature=0.6, top_p=0.9, seed=3,
                               max_new_tokens=5)]
        seq = self._sequential(m, prompts, samp)
        on = Engine(m, self._cfg(), register_profiler=False)
        warm = on.submit(prompts[0], samp[0])
        on.run()                                   # caches SHARED blocks
        reqs = [on.submit(p, s) for p, s in zip(prompts[1:], samp[1:])]
        on.run()
        assert warm.output_ids == seq[0]
        assert [r.output_ids for r in reqs] == seq[1:]
        assert warm.prefix_hit_tokens == 0         # cold cache
        assert reqs[0].prefix_hit_tokens == 8
        assert reqs[1].prefix_hit_tokens == 0
        assert reqs[2].prefix_hit_tokens == 8
        s = on.stats()
        assert s["prefix"]["hit_tokens"] >= 16
        assert 0.0 < s["prefix_hit_ratio"] < 1.0

        off = Engine(m, self._cfg(prefix_block_size=0),
                     register_profiler=False)
        offs = [off.submit(p, sp) for p, sp in zip(prompts, samp)]
        off.run()
        assert [r.output_ids for r in offs] == seq
        assert off.stats()["prefix"]["capacity_blocks"] == 0

    def test_exact_resubmit_and_mid_block_extension(self):
        m = _model()
        a = self.SHARED + [5, 6, 7, 1]             # 12 tokens: 3 blocks
        b = self.SHARED + [5, 6, 9, 9, 3]          # diverges IN block 3
        sp = SamplingParams(max_new_tokens=5)
        seq = self._sequential(m, [a, b], [sp, sp])
        eng = Engine(m, self._cfg(), register_profiler=False)
        assert eng.generate(a, sp) == seq[0]       # warm: caches 3 blocks
        again = eng.submit(a, sp)
        eng.run()
        assert again.output_ids == seq[0]          # exact-hit resubmit
        # 2 full-block leases (8) + a 3-token copy-on-write tail match
        # against cached block 3, capped at len(a) - 1 = 11
        assert again.prefix_hit_tokens == 11
        mid = eng.submit(b, sp)
        eng.run()
        assert mid.output_ids == seq[1]
        # 8 leased + COW tail: [5, 6] of cached [5, 6, 7, 1] matches
        assert mid.prefix_hit_tokens == 10

    @pytest.mark.slow
    def test_same_bucket_batch_is_one_dispatch(self):
        """The dispatch-count probe: N co-bucketed admissions prefill in
        ONE compiled call (plus at most one block-insert scatter)."""
        m = _model()
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5],
                   [8, 9, 7, 9, 1], [2, 3, 8, 4, 6, 2, 6]]  # buckets: 8
        samp = [SamplingParams(max_new_tokens=4, seed=i,
                               temperature=0.7 if i % 2 else 0.0)
                for i in range(4)]
        seq = self._sequential(m, prompts, samp)
        eng = Engine(m, self._cfg(), register_profiler=False)
        reqs = [eng.submit(p, s) for p, s in zip(prompts, samp)]
        eng.run()
        c = eng.counters()
        assert c["prefill_calls"] == 1             # ONE prefill dispatch
        assert c["prefill_requests"] == 4
        assert c["prefix_insert_calls"] <= 1       # plus <= one scatter
        assert eng.stats()["prefill_compiles"] == 1
        assert [r.output_ids for r in reqs] == seq

    def test_leases_released_on_retirement(self):
        m = _model()
        eng = Engine(m, self._cfg(num_slots=2), register_profiler=False)
        for p in (self.SHARED + [1], self.SHARED + [2], [4, 4, 1]):
            eng.submit(p, SamplingParams(max_new_tokens=3))
        eng.run()
        assert eng._leases == {}                   # every lease released
        stack = [eng.prefix._root]
        while stack:                               # ...and nothing pinned
            n = stack.pop()
            stack.extend(n.children.values())
            assert n.refcount == 0


class TestTTFT:
    def test_ttft_includes_queue_and_prefill(self):
        """TTFT clock starts at submit(): a request that waited for a
        slot carries its queue time inside its TTFT."""
        m = _model()
        eng = Engine(m, EngineConfig(num_slots=1, max_seq_len=32),
                     register_profiler=False)
        first = eng.submit([1, 2, 3], SamplingParams(max_new_tokens=6))
        queued = eng.submit([4, 5, 6], SamplingParams(max_new_tokens=4))
        eng.run()
        assert first.ttft is not None and queued.ttft is not None
        assert queued.queue_seconds > 0            # waited for the slot
        assert queued.ttft >= queued.queue_seconds
        s = eng.stats()
        assert s["ttft_p50_s"] >= 0.0
        assert s["ttft_p95_s"] >= s["ttft_p50_s"]


class TestPopBatch:
    """Bounded-reorder co-bucketed admission: the head always anchors,
    and no request is overtaken more than ``reorder_window`` times."""

    @staticmethod
    def _sched(window, lens):
        s = Scheduler(4, reorder_window=window)
        return s, [s.submit([0] * n, SamplingParams(max_new_tokens=2))
                   for n in lens]

    @staticmethod
    def _bucket(r):
        return r.prompt_len

    def test_contiguous_same_bucket_batches_fully(self):
        s, reqs = self._sched(2, [3, 3, 3, 3])
        assert s.pop_batch(8, bucket_of=self._bucket) == reqs
        assert s.queue_depth == 0

    def test_head_always_anchors(self):
        s, reqs = self._sched(2, [5, 3, 3, 3])
        assert s.pop_batch(8, bucket_of=self._bucket)[0] is reqs[0]

    def test_no_request_starved_past_window(self):
        w = 3
        s, reqs = self._sched(w, [3, 5, 3, 3, 3, 3, 3, 3])
        odd = reqs[1]                              # the lone bucket-5
        pops = []
        while s.queue_depth:
            pops.append(s.pop_batch(8, bucket_of=self._bucket))
            assert all(r.bypassed <= w for r in reqs)
        flat = [r for b in pops for r in b]
        assert sorted(r.request_id for r in flat) == \
            [r.request_id for r in reqs]           # nobody dropped
        # overtaken at most w times => admitted by the second batch
        k = next(i for i, b in enumerate(pops) if odd in b)
        assert k <= 1 and odd.bypassed <= w

    def test_window_zero_is_strict_fifo(self):
        s, reqs = self._sched(0, [3, 5, 3])
        assert s.pop_batch(8, bucket_of=self._bucket) == [reqs[0]]
        assert s.pop_batch(8, bucket_of=self._bucket) == [reqs[1]]
        assert s.pop_batch(8, bucket_of=self._bucket) == [reqs[2]]

    def test_free_slot_cap_and_fifo_fallback(self):
        s, reqs = self._sched(4, [3, 3, 3])
        assert s.pop_batch(2, bucket_of=self._bucket) == reqs[:2]
        assert s.pop_batch(0, bucket_of=self._bucket) == []
        assert s.pop_batch(4) == [reqs[2]]         # bucket_of=None: FIFO

class TestPagedPool:
    """Unified-pool host bookkeeping: refcounted blocks, the reserved
    scratch block 0, lazy table growth, and write routing."""

    @staticmethod
    def _pool(num_blocks=6, bs=4):
        return PagedKVPool(num_layers=1, num_blocks=num_blocks,
                           block_size=bs, kv_heads=1, head_dim=2)

    def test_refcounts_and_scratch_block(self):
        p = self._pool()
        assert p.capacity == 5 and p.free_blocks == 5
        a = p.alloc()
        assert a != 0                              # scratch never handed out
        assert p.refcount(a) == 1 and p.blocks_in_use == 1
        p.share(a)
        assert p.refcount(a) == 2
        p.release(a)
        assert p.blocks_in_use == 1                # still one ref held
        p.release(a)
        assert p.blocks_in_use == 0 and p.free_blocks == 5
        with pytest.raises(ValueError):
            p.release(a)                           # over-release is a bug
        p.release(0)                               # scratch release: no-op
        assert p.refcount(0) == 1

    def test_pool_exhaustion_returns_none(self):
        p = self._pool(num_blocks=3)
        assert p.alloc() is not None and p.alloc() is not None
        assert p.alloc() is None                   # dry, not an exception

    def test_cache_lazy_growth_and_release(self):
        c = PagedKVCache(num_layers=1, num_slots=2, max_seq_len=16,
                         block_size=4, kv_heads=1, head_dim=2)
        s = c.alloc()
        assert c.ensure_blocks(s, 5)               # 5 tokens -> 2 blocks
        row = c.tables[s]
        assert (row[:2] > 0).all() and (row[2:] == 0).all()
        assert c.pool.blocks_in_use == 2
        assert c.ensure_blocks(s, 6)               # same need: no growth
        assert c.pool.blocks_in_use == 2
        c.release_slot_blocks(s)
        assert (c.tables[s] == 0).all()
        assert c.pool.blocks_in_use == 0
        c.free(s)

    def test_lease_block_shares_refcount(self):
        c = PagedKVCache(num_layers=1, num_slots=2, max_seq_len=16,
                         block_size=4, kv_heads=1, head_dim=2)
        donor = c.pool.alloc()                     # e.g. a prefix block
        s = c.alloc()
        c.lease_block(s, 0, donor)
        assert c.pool.refcount(donor) == 2 and c.leased_blocks == 1
        c.release_slot_blocks(s)
        assert c.pool.refcount(donor) == 1         # table ref dropped...
        c.pool.release(donor)                      # ...owner ref remains

    def test_paged_write_roundtrip_and_scratch_clip(self):
        bs, kh, d = 4, 1, 2
        pool = jnp.zeros((4, bs, kh, d), jnp.float32)
        tables = jnp.array([[1, 2]], jnp.int32)    # one lane, two blocks
        new = jnp.arange(2 * kh * d, dtype=jnp.float32).reshape(1, 2, kh, d)
        # write 2 tokens straddling the block boundary (pos 3, 4)
        out = np.asarray(paged_write(pool, new, tables, jnp.array([3])))
        assert (out[1, 3] == new[0, 0]).all()      # block 1, offset 3
        assert (out[2, 0] == new[0, 1]).all()      # block 2, offset 0
        # out-of-table positions route to scratch block 0, real blocks
        # untouched (this is what makes bench overflow writes harmless)
        far = np.asarray(paged_write(pool, new, tables, jnp.array([8])))
        assert (far[1:] == 0).all()


class TestPagedAttention:
    """The XLA fallback is the parity reference: bitwise-invariant to
    the static table width and equal to dense softmax attention."""

    @staticmethod
    def _case(b=2, s=1, qh=4, kh=2, d=8, bs=4, nb=3, seed=0):
        r = np.random.RandomState(seed)
        q = jnp.asarray(r.randn(b, s, qh, d).astype(np.float32))
        num_blocks = 1 + b * nb
        k = jnp.asarray(r.randn(num_blocks, bs, kh, d).astype(np.float32))
        v = jnp.asarray(r.randn(num_blocks, bs, kh, d).astype(np.float32))
        tables = jnp.asarray(
            1 + np.arange(b * nb, dtype=np.int32).reshape(b, nb))
        pos = jnp.asarray(np.array([5, 9], np.int32)[:b])
        return q, k, v, tables, pos

    def test_bitwise_invariant_to_table_width(self):
        """Padding the table with scratch columns must not change ONE
        bit of the output — this is what lets the engine re-bucket nb
        as sequences grow without breaking decode determinism."""
        q, k, v, tables, pos = self._case()
        out = np.asarray(_xla_paged_attention(q, k, v, tables, pos))
        for pad in (1, 3, 8):
            wide = jnp.concatenate(
                [tables, jnp.zeros((tables.shape[0], pad), jnp.int32)],
                axis=1)
            out_w = np.asarray(_xla_paged_attention(q, k, v, wide, pos))
            np.testing.assert_array_equal(out, out_w)

    def test_matches_dense_attention(self):
        q, k, v, tables, pos = self._case(s=1)
        b, s, qh, d = q.shape
        bs, kh = k.shape[1], k.shape[2]
        g = qh // kh
        out = np.asarray(_xla_paged_attention(q, k, v, tables, pos))
        kn, vn, tn, pn = (np.asarray(x) for x in (k, v, tables, pos))
        for i in range(b):
            keys = kn[tn[i]].reshape(-1, kh, d)[:pn[i] + 1]   # [T, KH, D]
            vals = vn[tn[i]].reshape(-1, kh, d)[:pn[i] + 1]
            for h in range(qh):
                qv = np.asarray(q)[i, 0, h] / np.sqrt(d)
                sc = keys[:, h // g] @ qv
                w = np.exp(sc - sc.max())
                w /= w.sum()
                ref = w @ vals[:, h // g]
                np.testing.assert_allclose(out[i, 0, h], ref, atol=1e-5)

    def test_multi_token_prefill_is_causal(self):
        """s > 1 (prefill): each query row attends to keys <= its own
        position only; row s-1 must equal a fresh s=1 decode query."""
        q, k, v, tables, pos = self._case(s=3)
        pos0 = pos - 2                             # 3 queries end at pos
        out = np.asarray(_xla_paged_attention(q, k, v, tables, pos0))
        last = np.asarray(_xla_paged_attention(
            q[:, 2:], k, v, tables, pos0 + 2))
        np.testing.assert_array_equal(out[:, 2:], last)


class TestPreemptionSwap:
    """Preempt-and-resume: an idle lane's blocks are released, the
    request requeues at the FRONT, and re-admission (re-prefill of
    prompt + generated-so-far) reproduces its stream bitwise."""

    @staticmethod
    def _cfg(**kw):
        kw.setdefault("num_slots", 2)
        kw.setdefault("max_seq_len", 32)
        kw.setdefault("max_horizon", 4)
        kw.setdefault("prefix_block_size", 4)
        kw.setdefault("prefix_cache_bytes", 0)     # isolate pool effects
        return EngineConfig(**kw)

    @classmethod
    def _sequential(cls, m, prompts, samp):
        return [Engine(m, cls._cfg(num_slots=1), register_profiler=False)
                .generate(p, s) for p, s in zip(prompts, samp)]

    @pytest.mark.slow
    def test_explicit_preempt_resume_parity(self):
        m = _model()
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
        samp = [SamplingParams(max_new_tokens=10),
                SamplingParams(temperature=0.8, top_k=20, seed=11,
                               max_new_tokens=10)]
        seq = self._sequential(m, prompts, samp)
        eng = Engine(m, self._cfg(), register_profiler=False)
        reqs = [eng.submit(p, s) for p, s in zip(prompts, samp)]
        eng.step(horizon=2)                        # both lanes decoding
        victim = reqs[1]
        held = int(np.count_nonzero(eng.cache.tables[victim.slot]))
        assert held > 0
        before = eng.pool.blocks_in_use
        eng.preempt(victim)
        assert victim.status == "waiting" and victim.slot is None
        assert eng.scheduler.queue[0] is victim    # front of the queue
        assert eng.pool.blocks_in_use == before - held
        eng.run()                                  # re-admit + finish
        assert [r.output_ids for r in reqs] == seq
        assert eng.counters()["preemptions"] == 1
        assert eng.pool.blocks_in_use == 0         # nothing leaked

    @pytest.mark.slow
    def test_auto_preempt_under_block_pressure(self):
        """An explicitly undersized pool: decode growth runs the pool
        dry, the engine preempts the youngest lane, and every request
        still finishes with sequential parity."""
        m = _model()
        prompts = [[7, 3, 9, 1, 4, 4, 2, 8], [5, 6, 7, 8, 9, 1, 2, 3]]
        samp = [SamplingParams(max_new_tokens=12) for _ in prompts]
        seq = self._sequential(m, prompts, samp)
        # capacity 7 blocks of 4: both admit (2+2) but cannot both grow
        # to 20 tokens (5+5)
        eng = Engine(m, self._cfg(kv_pool_blocks=8),
                     register_profiler=False)
        reqs = [eng.submit(p, s) for p, s in zip(prompts, samp)]
        eng.run()
        assert [r.output_ids for r in reqs] == seq
        assert eng.counters()["preemptions"] >= 1
        assert eng.pool.blocks_in_use == 0

    def test_block_leak_invariant(self):
        """After every request retires: zero leased table entries, and
        the only live blocks are the prefix cache's (none when it's
        off).  This is the CI smoke invariant."""
        m = _model()
        prompts = [[1, 2, 3, 4, 5], [1, 2, 3, 4, 5, 6, 7], [9, 9]]
        for bs, budget in ((4, 0), (4, 1 << 20)):
            eng = Engine(m, self._cfg(num_slots=2, prefix_block_size=bs,
                                      prefix_cache_bytes=budget),
                         register_profiler=False)
            for p in prompts:
                eng.submit(p, SamplingParams(max_new_tokens=4))
            eng.run()
            s = eng.stats()["kv_pool"]
            assert s["leased_blocks"] == 0
            assert s["blocks_in_use"] == s["cached_blocks"]
            if budget == 0:
                assert s["blocks_in_use"] == 0


class TestPopBatchResume:
    """The ``resumed`` head-anchor exemption: re-admitting a preempted
    request restores FIFO order rather than violating it, so it must
    neither spend the reorder window nor charge bypassed counters —
    even from behind requests that are at their overtake cap."""

    @staticmethod
    def _sched(window, lens):
        s = Scheduler(4, reorder_window=window)
        return s, [s.submit([0] * n, SamplingParams(max_new_tokens=2))
                   for n in lens]

    @staticmethod
    def _bucket(r):
        return r.prompt_len

    def test_resumed_admitted_from_behind_capped_skips(self):
        # window 1: normally nothing same-bucket can be admitted from
        # behind a skipped request at index >= 1
        s, reqs = self._sched(1, [3, 5, 3])
        reqs[2].resumed = True
        batch = s.pop_batch(8, bucket_of=self._bucket)
        assert batch == [reqs[0], reqs[2]]
        assert reqs[1].bypassed == 0       # exemption: no overtake charged

    def test_resumed_does_not_consume_window_for_others(self):
        # [A(3), B(5), C(5), D(3,resumed), E(3)] with window 2: D rides
        # the exemption, but E is a genuine overtake past the window cap
        s, reqs = self._sched(2, [3, 5, 5, 3, 3])
        reqs[3].resumed = True
        batch = s.pop_batch(8, bucket_of=self._bucket)
        assert batch == [reqs[0], reqs[3]]
        assert reqs[1].bypassed == 0 and reqs[2].bypassed == 0

    def test_non_resumed_same_shape_is_still_bounded(self):
        # identical queue WITHOUT the resumed flag: the bucket-3 request
        # behind the skip is not admitted (control for the test above)
        s, reqs = self._sched(1, [3, 5, 3])
        batch = s.pop_batch(8, bucket_of=self._bucket)
        assert batch == [reqs[0]]

    def test_requeue_front_marks_and_start_clears(self):
        s = Scheduler(2)
        r = s.submit([1, 2, 3], SamplingParams(max_new_tokens=2))
        assert r.resumed is False
        s.start(r, 0)
        s.requeue_front(r)
        assert r.resumed is True and s.queue[0] is r
        s.start(r, 1)
        assert r.resumed is False

    def test_resume_ordering_under_load(self):
        """Preempt under a full queue: the resumed request re-admits
        FIRST (front of queue, head anchor) and co-buckets with same-
        bucket resumes; queued newcomers never jump it."""
        s, reqs = self._sched(2, [3, 3, 5, 3])
        s.start(reqs[0], 0)
        s.start(reqs[1], 1)
        s.queue = __import__("collections").deque(reqs[2:])
        s.requeue_front(reqs[1])
        s.requeue_front(reqs[0])
        batch = s.pop_batch(2, bucket_of=self._bucket)
        assert batch == [reqs[0], reqs[1]]  # both resumes, before all
        assert reqs[2].bypassed == 0 and reqs[3].bypassed == 0


class TestDrafter:
    """draft_tokens unit behavior: the -1 sentinel contract and the
    runway-then-recency match ranking."""

    @staticmethod
    def _draft(row, length, k=3, ngram=2, width=16):
        from paddle_tpu.serving import draft_tokens

        hist = np.zeros((1, width), np.int32)
        hist[0, :len(row)] = row
        out = draft_tokens(jnp.asarray(hist),
                           jnp.asarray([length], jnp.int32), k, ngram)
        return np.asarray(out)[0].tolist()

    def test_history_shorter_than_ngram_plus_one_is_sentinel(self):
        assert self._draft([7, 7], 2) == [-1, -1, -1]
        from paddle_tpu.serving import draft_tokens
        out = draft_tokens(jnp.zeros((2, 2), jnp.int32),
                           jnp.asarray([2, 2], jnp.int32), 4)
        assert np.asarray(out).tolist() == [[-1] * 4] * 2

    def test_no_earlier_match_is_sentinel(self):
        assert self._draft([1, 2, 3, 4, 5, 6], 6) == [-1, -1, -1]

    def test_match_with_full_runway_drafts_continuation(self):
        # suffix [1,2] matched at start 0; continuation 3, 9, 1
        assert self._draft([1, 2, 3, 9, 1, 2], 6) == [3, 9, 1]

    def test_runway_beats_recency(self):
        # suffix [1,2] occurs at 0 (runway 5) and 3 (runway 2): the
        # early match drafts k=3 tokens, the late one only 2
        assert self._draft([1, 2, 3, 1, 2, 1, 2], 7) == [3, 1, 2]

    def test_recency_breaks_runway_ties(self):
        # both matches have >= k runway; the later one wins
        assert self._draft([1, 2, 5, 5, 5, 1, 2, 8, 8, 8, 1, 2], 12) \
            == [8, 8, 8]

    def test_drafts_clamped_to_known_history(self):
        # the only match sits 2 tokens from the end: the third draft
        # would read past known history and must be the sentinel
        assert self._draft([7, 1, 2, 1, 2], 5) == [1, 2, -1]

    def test_tail_never_matches_itself(self):
        # the trailing window is the only occurrence: no proposal
        assert self._draft([5, 1, 2], 3) == [-1, -1, -1]

    def test_lanes_are_independent(self):
        from paddle_tpu.serving import draft_tokens

        hist = np.zeros((2, 16), np.int32)
        hist[0, :6] = [1, 2, 3, 9, 1, 2]
        hist[1, :6] = [4, 5, 6, 7, 8, 9]
        out = draft_tokens(jnp.asarray(hist),
                           jnp.asarray([6, 6], jnp.int32), 3)
        assert np.asarray(out).tolist() == [[3, 9, 1], [-1, -1, -1]]

    def test_validates_static_args(self):
        from paddle_tpu.serving import draft_tokens

        h = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError):
            draft_tokens(h, jnp.asarray([4], jnp.int32), 0)
        with pytest.raises(ValueError):
            draft_tokens(h, jnp.asarray([4], jnp.int32), 2, ngram=0)


class TestSpeculativeDecode:
    """Self-drafting speculative decoding: every K and every workload
    must reproduce the spec_k=0 stream bitwise — drafting is a pure
    perf lever, invisible in outputs, PRNG, EOS, and budgets."""

    REP_PROMPT = [3, 17, 42, 9] * 4          # repeated pattern
    RND_PROMPT = [11, 62, 97, 23, 5, 81, 40, 108]
    #: cached sequential K=0 greedy stream for REP_PROMPT (computed
    #: once; greedy decode of a prefix is a prefix of the stream, so
    #: every shorter-budget reference is a slice of this one)
    _REP_STREAM = None

    @classmethod
    def _rep_stream(cls, m, n):
        if cls._REP_STREAM is None:
            sp = SamplingParams(max_new_tokens=16)
            ref, _ = cls._run(m, cls.REP_PROMPT, sp, 0)
            cls._REP_STREAM = list(ref.output_ids)
        assert n <= len(cls._REP_STREAM)
        return cls._REP_STREAM[:n]

    @staticmethod
    def _engine(m, k, adaptive=False, **kw):
        kw.setdefault("num_slots", 2)
        kw.setdefault("max_seq_len", 48)
        kw.setdefault("max_horizon", 4)
        return Engine(m, EngineConfig(spec_k=k, spec_adaptive=adaptive,
                                      **kw), register_profiler=False)

    @classmethod
    def _run(cls, m, prompt, sp, k, adaptive=False, **kw):
        eng = cls._engine(m, k, adaptive, **kw)
        req = eng.submit(list(prompt), sp)
        while eng.scheduler.has_work:
            eng.step()
        stats = eng.stats()
        eng.close()
        return req, stats

    def test_greedy_parity_repetitive_prompt(self):
        m = _model()
        sp = SamplingParams(max_new_tokens=16)
        ref = self._rep_stream(m, 16)
        out, stats = self._run(m, self.REP_PROMPT, sp, 4)
        assert out.output_ids == ref
        assert stats["spec"]["draft_tokens"] > 0

    def test_greedy_parity_random_prompt(self):
        m = _model()
        sp = SamplingParams(max_new_tokens=16)
        ref, _ = self._run(m, self.RND_PROMPT, sp, 0)
        out, _ = self._run(m, self.RND_PROMPT, sp, 4)
        assert out.output_ids == ref.output_ids

    def test_parity_across_draft_widths(self):
        m = _model()
        sp = SamplingParams(max_new_tokens=12)
        ref = self._rep_stream(m, 12)
        # extreme widths; K=4 is exercised by every other test here
        for k in (1, 8):
            out, _ = self._run(m, self.REP_PROMPT, sp, k)
            assert out.output_ids == ref, f"K={k} diverged"

    def test_seeded_sampling_parity(self):
        m = _model()
        sp = SamplingParams(temperature=0.9, top_k=20, top_p=0.9,
                            seed=7, max_new_tokens=10)
        ref, _ = self._run(m, self.REP_PROMPT, sp, 0)
        out, _ = self._run(m, self.REP_PROMPT, sp, 4)
        assert out.output_ids == ref.output_ids

    def test_mid_window_eos(self):
        """EOS landing inside a verify window must truncate the emitted
        run exactly where sequential decode stops."""
        m = _model()
        sp = SamplingParams(max_new_tokens=12)
        stream = self._rep_stream(m, 12)
        # an EOS whose FIRST occurrence is interior (not window-aligned)
        idx = next(i for i in range(2, 9) if stream.index(stream[i]) == i)
        eos = stream[idx]
        sp_eos = SamplingParams(max_new_tokens=12, eos_token_id=eos)
        out, _ = self._run(m, self.REP_PROMPT, sp_eos, 4)
        assert out.output_ids == stream[:idx + 1]
        assert out.finish_reason == "eos"

    def test_budget_truncation_mid_window(self):
        """max_new_tokens that is no multiple of any window size: the
        lane must stop at EXACTLY the budget even when the accepted
        window would overshoot it."""
        m = _model()
        for budget in (1, 7):
            sp = SamplingParams(max_new_tokens=budget)
            ref = self._rep_stream(m, budget)
            out, _ = self._run(m, self.REP_PROMPT, sp, 4)
            assert out.output_ids == ref
            assert len(out.output_ids) == budget
            assert out.finish_reason == "length"

    @pytest.mark.slow
    def test_staggered_admission_parity(self):
        """Requests joining at horizon boundaries mid-flight see the
        same streams as sequential runs, drafting included."""
        m = _model()
        prompts = [self.REP_PROMPT, [2, 7, 4, 11], [9, 9, 9, 9, 9, 9]]
        samp = [SamplingParams(max_new_tokens=10),
                SamplingParams(max_new_tokens=8),
                SamplingParams(temperature=0.9, top_k=16, seed=3,
                               max_new_tokens=9)]
        seq = []
        for p, s in zip(prompts, samp):
            r, _ = self._run(m, p, s, 0)
            seq.append(r.output_ids)
        eng = self._engine(m, 4)
        reqs = [eng.submit(prompts[0], samp[0])]
        eng.step()
        reqs.append(eng.submit(prompts[1], samp[1]))
        eng.step()
        reqs.append(eng.submit(prompts[2], samp[2]))
        while eng.scheduler.has_work:
            eng.step()
        eng.close()
        assert [r.output_ids for r in reqs] == seq

    @pytest.mark.slow
    def test_preempt_resume_parity_with_spec(self):
        """Preemption mid-draft: blocks released, request re-admitted
        (resumed exemption), stream still bitwise-sequential."""
        m = _model()
        prompts = [self.REP_PROMPT, [9, 2, 6, 1]]
        samp = [SamplingParams(max_new_tokens=10),
                SamplingParams(max_new_tokens=10)]
        seq = []
        for p, s in zip(prompts, samp):
            r, _ = self._run(m, p, s, 0, num_slots=1)
            seq.append(r.output_ids)
        eng = self._engine(m, 4)
        reqs = [eng.submit(p, s) for p, s in zip(prompts, samp)]
        eng.step(horizon=2)
        eng.preempt(reqs[1])
        assert reqs[1].resumed is True
        while eng.scheduler.has_work:
            eng.step()
        eng.close()
        assert [r.output_ids for r in reqs] == seq

    def test_one_compile_per_horizon_width_k_bucket(self):
        """Decode programs are keyed by (horizon, table-width, K): one
        compile per distinct triple, cache hits for every repeat."""
        m = _model()
        eng = self._engine(m, 4, num_slots=1)
        sp = SamplingParams(max_new_tokens=8)
        for _ in range(2):
            eng.submit(self.REP_PROMPT, sp)
            while eng.scheduler.has_work:
                eng.step(horizon=4)
        s = eng.stats()
        eng.close()
        assert all(b[2] == 4 for b in s["decode_buckets"])
        assert s["decode_compiles"] == len(s["decode_buckets"])
        assert s["decode_cache_hits"] == \
            s["decode_horizons"] - s["decode_compiles"]

    def test_accept_stats_exported(self):
        m = _model()
        sp = SamplingParams(max_new_tokens=16)
        _, s = self._run(m, self.REP_PROMPT, sp, 4)
        spec = s["spec"]
        assert spec["k"] == 4 and spec["adaptive"] is False
        assert spec["draft_tokens"] > 0
        assert 0.0 <= spec["accept_rate"] <= 1.0
        hist = spec["accept_len_hist"]
        windows = sum(hist.values())
        assert windows > 0
        assert all(1 <= n <= 5 for n in hist)      # emits 1..K+1
        got = sum(n * c for n, c in hist.items())
        assert abs(spec["mean_accept_len"] - got / windows) < 1e-9
        # counters() mirrors the totals
        eng = self._engine(m, 4)
        req = eng.submit(self.REP_PROMPT, sp)
        while eng.scheduler.has_work:
            eng.step()
        c = eng.counters()
        eng.close()
        assert c["spec_draft_tokens"] == eng.stats()["spec"]["draft_tokens"]
        assert "spec_accept_rate" in c
        assert req.output_ids  # the run actually decoded

    def test_adaptive_gate_shrinks_dispatch_to_k0(self):
        """A lane whose drafts never land falls below the acceptance
        floor, flips its gate off, and — when no gated lane remains —
        the next dispatch compiles/reuses the plain K=0 program."""
        m = _model()
        sp = SamplingParams(max_new_tokens=16)
        ref, _ = self._run(m, self.RND_PROMPT, sp, 0)
        eng = self._engine(m, 4, adaptive=True, num_slots=1)
        eng.config.spec_accept_floor = 1.1         # unreachable: always off
        req = eng.submit(self.RND_PROMPT, sp)
        while eng.scheduler.has_work:
            eng.step()
        s = eng.stats()
        eng.close()
        assert req.output_ids == ref.output_ids    # parity through the flip
        ks = {b[2] for b in s["decode_buckets"]}
        assert 0 in ks and 4 in ks                 # shrank mid-request
        assert all(e < 1.0 for e in s["spec"]["lane_accept_ema"][:1])

    def test_k0_engine_reports_no_spec_activity(self):
        m = _model()
        sp = SamplingParams(max_new_tokens=8)
        _, s = self._run(m, self.REP_PROMPT, sp, 0)
        assert s["spec"]["draft_tokens"] == 0
        assert s["spec"]["accepted_tokens"] == 0
        assert s["spec"]["accept_len_hist"] == {}
        assert all(b[2] == 0 for b in s["decode_buckets"])

    def test_spec_with_prefix_cache_and_gqa(self):
        """Drafting composes with prefix-cache hits and GQA models."""
        paddle.seed(3)
        m = GPTForCausalLM(TINY_GQA)
        m.eval()
        sp = SamplingParams(max_new_tokens=10)
        prompt = [5, 9, 5, 9, 5, 9, 5, 9]
        ref, _ = self._run(m, prompt, sp, 0,
                           prefix_cache_bytes=1 << 20)
        out, _ = self._run(m, prompt, sp, 4,
                           prefix_cache_bytes=1 << 20)
        assert out.output_ids == ref.output_ids


class TestPagedAttentionVerify:
    """Multi-position (verify-window) queries through the paged kernel:
    each row must equal the single-token decode at that position, and
    the whole window must match a dense causal reference."""

    @staticmethod
    def _case(b=2, s=1, qh=4, kh=2, d=8, bs=4, nb=4, seed=0,
              pos_vals=(9, 13)):
        r = np.random.RandomState(seed)
        q = jnp.asarray(r.randn(b, s, qh, d).astype(np.float32))
        num_blocks = 1 + b * nb
        k = jnp.asarray(r.randn(num_blocks, bs, kh, d).astype(np.float32))
        v = jnp.asarray(r.randn(num_blocks, bs, kh, d).astype(np.float32))
        tables = jnp.asarray(
            1 + np.arange(b * nb, dtype=np.int32).reshape(b, nb))
        pos = jnp.asarray(np.array(pos_vals, np.int32)[:b])
        return q, k, v, tables, pos

    @pytest.mark.parametrize("w", [1, 2, 4, 8])
    def test_window_rows_bitwise_match_single_queries(self, w):
        """Row j of an s=w window at base position p equals an s=1 call
        at position p+j — the property that makes verify-as-prefill
        bitwise-safe, across block boundaries (bs=4, windows straddle
        them for w >= 2)."""
        q, k, v, tables, pos = self._case(s=w)
        base = pos - (w - 1)
        out = np.asarray(_xla_paged_attention(q, k, v, tables, base))
        for j in range(w):
            one = np.asarray(_xla_paged_attention(
                q[:, j:j + 1], k, v, tables, base + j))
            np.testing.assert_array_equal(out[:, j:j + 1], one)

    def test_window_matches_dense_causal_reference(self):
        w = 4
        q, k, v, tables, pos = self._case(s=w)
        base = pos - (w - 1)
        out = np.asarray(_xla_paged_attention(q, k, v, tables, base))
        kn, vn, tn, bn = (np.asarray(x) for x in (k, v, tables, base))
        b, _, qh, d = q.shape
        kh = kn.shape[2]
        g = qh // kh
        for i in range(b):
            keys = kn[tn[i]].reshape(-1, kh, d)
            vals = vn[tn[i]].reshape(-1, kh, d)
            for j in range(w):
                t = int(bn[i]) + j + 1             # visible prefix length
                for h in range(qh):
                    qv = np.asarray(q)[i, j, h] / np.sqrt(d)
                    sc = keys[:t, h // g] @ qv
                    ww = np.exp(sc - sc.max())
                    ww /= ww.sum()
                    ref = ww @ vals[:t, h // g]
                    np.testing.assert_allclose(out[i, j, h], ref,
                                               atol=1e-5)

    def test_shared_prefix_cow_tail_blocks(self):
        """Two lanes share a prefix block (COW-style table aliasing);
        their divergent tails must not bleed into each other, and each
        lane's window must equal a private-copy run."""
        r = np.random.RandomState(1)
        bs, kh, d, qh, w = 4, 2, 8, 4, 2
        k = jnp.asarray(r.randn(6, bs, kh, d).astype(np.float32))
        v = jnp.asarray(r.randn(6, bs, kh, d).astype(np.float32))
        q = jnp.asarray(r.randn(2, w, qh, d).astype(np.float32))
        # lanes alias block 1 as their shared prefix, own tails 2/3
        shared = jnp.asarray([[1, 2], [1, 3]], jnp.int32)
        base = jnp.asarray([4, 4], jnp.int32)      # window rows 4,5
        out_shared = np.asarray(
            _xla_paged_attention(q, k, v, shared, base))
        # private copies of the prefix (blocks 4/5 = copies of block 1)
        k2 = k.at[4].set(k[1]).at[5].set(k[1])
        v2 = v.at[4].set(v[1]).at[5].set(v[1])
        private = jnp.asarray([[4, 2], [5, 3]], jnp.int32)
        out_private = np.asarray(
            _xla_paged_attention(q, k2, v2, private, base))
        np.testing.assert_array_equal(out_shared, out_private)


class TestPallasMultiToken:
    """The generalized Pallas ragged kernel (interpret mode on CPU) vs
    the XLA fallback for every query window size s >= 1, on fp32 and
    int8-quantized pools, including COW-aliased tables.  The kernel
    runs the fallback's exact per-block recurrence, but the interpret
    grid loop and the fallback's scan compile separately, so XLA:CPU
    may reassociate the tiny per-block reductions — raw outputs match
    to ~1 ulp (exact at most shapes), asserted here with a tight
    tolerance; the BITWISE gate is stream equality of whole-engine runs
    under ``PADDLE_TPU_PAGED_ATTN=pallas`` (see
    ``test_tp2_chunked_prefill_pallas_kernel_parity``).  Kernel-vs-
    kernel comparisons (same program, different tables) stay exact."""

    ATOL = 1e-5

    @staticmethod
    def _case(b=2, s=1, qh=4, kh=2, d=8, bs=4, nb=4, seed=0,
              pos_vals=(9, 13)):
        return TestPagedAttentionVerify._case(b, s, qh, kh, d, bs, nb,
                                              seed, pos_vals)

    @pytest.mark.parametrize("w", [1, 2, 4, 8])
    def test_kernel_matches_fallback(self, w):
        q, k, v, tables, pos = self._case(s=w)
        base = pos - (w - 1)
        ref = np.asarray(_xla_paged_attention(q, k, v, tables, base))
        out = np.asarray(_pallas_paged_attention(q, k, v, tables, base,
                                                 interpret=True))
        np.testing.assert_allclose(out, ref, rtol=0, atol=self.ATOL)

    @pytest.mark.parametrize("w", [1, 4])
    def test_kernel_matches_fallback_quantized(self, w):
        q, kf, vf, tables, pos = self._case(s=w)
        r = np.random.RandomState(3)
        k = jnp.asarray(r.randint(-127, 128, kf.shape).astype(np.int8))
        v = jnp.asarray(r.randint(-127, 128, vf.shape).astype(np.int8))
        ks = jnp.asarray(
            r.uniform(0.01, 0.1, kf.shape[:2]).astype(np.float32))
        vs = jnp.asarray(
            r.uniform(0.01, 0.1, vf.shape[:2]).astype(np.float32))
        base = pos - (w - 1)
        ref = np.asarray(
            _xla_paged_attention(q, k, v, tables, base, ks, vs))
        out = np.asarray(_pallas_paged_attention(q, k, v, tables, base,
                                                 ks, vs, interpret=True))
        np.testing.assert_allclose(out, ref, rtol=0, atol=self.ATOL)

    def test_kernel_cow_aliased_tail_blocks(self):
        """Two lanes alias a shared prefix block through their tables;
        the kernel must read it once per lane without bleed, matching
        both the fallback and a private-copy run bitwise."""
        r = np.random.RandomState(1)
        bs, kh, d, qh, w = 4, 2, 8, 4, 2
        k = jnp.asarray(r.randn(6, bs, kh, d).astype(np.float32))
        v = jnp.asarray(r.randn(6, bs, kh, d).astype(np.float32))
        q = jnp.asarray(r.randn(2, w, qh, d).astype(np.float32))
        shared = jnp.asarray([[1, 2], [1, 3]], jnp.int32)
        base = jnp.asarray([4, 4], jnp.int32)
        out = np.asarray(_pallas_paged_attention(q, k, v, shared, base,
                                                 interpret=True))
        ref = np.asarray(_xla_paged_attention(q, k, v, shared, base))
        np.testing.assert_allclose(out, ref, rtol=0, atol=self.ATOL)
        k2 = k.at[4].set(k[1]).at[5].set(k[1])
        v2 = v.at[4].set(v[1]).at[5].set(v[1])
        private = jnp.asarray([[4, 2], [5, 3]], jnp.int32)
        out_p = np.asarray(_pallas_paged_attention(q, k2, v2, private,
                                                   base, interpret=True))
        # same compiled kernel, different tables: aliasing itself is
        # BITWISE-neutral
        np.testing.assert_array_equal(out, out_p)

    def test_router_env_override_runs_kernel_on_cpu(self, monkeypatch):
        """``PADDLE_TPU_PAGED_ATTN=pallas`` off-TPU routes to the kernel
        in interpret mode — the switch the whole-engine and shard_map
        kernel tests ride — and stays bitwise with the fallback."""
        from paddle_tpu.serving.paged_attention import paged_attention

        q, k, v, tables, pos = self._case(s=2)
        base = pos - 1
        monkeypatch.setenv("PADDLE_TPU_PAGED_ATTN", "pallas")
        out = np.asarray(paged_attention(q, k, v, tables, base))
        monkeypatch.setenv("PADDLE_TPU_PAGED_ATTN", "xla")
        ref = np.asarray(paged_attention(q, k, v, tables, base))
        np.testing.assert_allclose(out, ref, rtol=0, atol=self.ATOL)


class TestQuantServing:
    """``EngineConfig(weight_dtype="int8", kv_cache_dtype="int8")``:
    int8 weight-only decode + int8 paged KV.

    Knobs OFF is asserted structurally here (fp state arrays, fp pool,
    no scale planes — the engine threads ``None`` where the quant path
    threads scale pytrees, so the traced programs are the pre-quant
    ones) and behaviorally by every other class in this file running
    the same engine code.  Quantized-vs-fp parity is tolerance-based BY
    DESIGN: PTQ rounds weights, logits move ~1e-3, and a greedy argmax
    near a tie can legitimately flip — after which the streams diverge.
    What must stay BITWISE is everything within one quant config:
    batched-vs-sequential scheduling, preemption/resume replay, and
    spec-decode K>0 vs K=0 (the verify-window guarantee)."""

    # ---- pure-function paths (fast: no engine compile) ----

    def test_zero_channel_scale_floor(self):
        """Satellite regression: an all-zero output channel quantizes
        without NaN because the 1e-8 floor is applied PER CHANNEL before
        the divide — not to the post-max per-tensor scale."""
        w = jnp.zeros((8, 4), jnp.float32).at[:, 1:].set(3.0)
        scale = np.asarray(channelwise_scales(w)).ravel()
        assert np.isfinite(scale).all() and (scale > 0).all()
        assert scale[0] == pytest.approx(1e-8 / 127.0)  # floored channel
        assert scale[1] == pytest.approx(3.0 / 127.0)   # untouched by it
        q, s = quantize_weight(w)
        dq = np.asarray(dequantize_weight(q, s))
        assert np.isfinite(dq).all()
        np.testing.assert_array_equal(dq[:, 0], 0.0)    # exact zeros
        np.testing.assert_allclose(dq[:, 1:], 3.0, atol=3.0 / 254.0)
        # the observer the serving path is built on: same per-channel
        # floor inside fake_quant
        fq = np.asarray(PerChannelAbsmaxObserver().fake_quant(w))
        assert np.isfinite(fq).all()
        np.testing.assert_array_equal(fq[:, 0], 0.0)

    def test_paged_write_quant_roundtrip(self):
        """Quantize-at-append: dequantized blocks are within a half
        quantization step of the written vectors, zero vectors store
        exact zeros (matching the fp pool's zero init), and untouched
        blocks stay untouched."""
        r = np.random.RandomState(0)
        pool = jnp.zeros((4, 4, 2, 8), jnp.int8)
        scales = jnp.zeros((4, 4), jnp.float32)
        new = jnp.asarray(r.randn(1, 5, 2, 8).astype(np.float32))
        new = new.at[0, 2].set(0.0)                     # a zero token
        tables = jnp.asarray([[1, 2]], jnp.int32)
        pos = jnp.asarray([0], jnp.int32)
        pool2, scales2 = paged_write_quant(pool, scales, new, tables, pos)
        deq = (np.asarray(pool2, np.float32)
               * np.asarray(scales2)[:, :, None, None])
        ref = np.asarray(new[0])
        for t in range(5):
            got = deq[tables[0, t // 4], t % 4]
            bound = np.abs(ref[t]).max() / 254.0 + 1e-12
            np.testing.assert_allclose(got, ref[t], atol=bound)
        np.testing.assert_array_equal(deq[0, :, :, :], 0.0)  # scratch
        np.testing.assert_array_equal(deq[1, 2], 0.0)   # zero token exact
        np.testing.assert_array_equal(np.asarray(pool2[3]), 0)

    def test_kv8_xla_fallback_nb_invariant_and_matches_fp(self):
        """The int8 XLA fallback keeps the fp fallback's load-bearing
        property — bitwise invariance to table width — AND equals the
        fp path run on the dequantized pool bitwise (the scale multiply
        commutes with the gather)."""
        r = np.random.RandomState(3)
        b, qh, kh, d, bs, nb = 2, 4, 2, 8, 4, 3
        q = jnp.asarray(r.randn(b, 1, qh, d).astype(np.float32))
        num_blocks = 1 + b * nb
        k = jnp.asarray(r.randint(-127, 128, (num_blocks, bs, kh, d)),
                        jnp.int8)
        v = jnp.asarray(r.randint(-127, 128, (num_blocks, bs, kh, d)),
                        jnp.int8)
        ks = jnp.asarray((r.rand(num_blocks, bs) * 0.05 + 1e-3)
                         .astype(np.float32))
        vs = jnp.asarray((r.rand(num_blocks, bs) * 0.05 + 1e-3)
                         .astype(np.float32))
        tables = jnp.asarray(
            1 + np.arange(b * nb, dtype=np.int32).reshape(b, nb))
        pos = jnp.asarray(np.array([5, 9], np.int32))
        out = np.asarray(_xla_paged_attention(q, k, v, tables, pos,
                                              ks, vs))
        assert np.isfinite(out).all()
        for pad in (1, 4):
            wide = jnp.concatenate(
                [tables, jnp.zeros((b, pad), jnp.int32)], axis=1)
            out_w = np.asarray(_xla_paged_attention(q, k, v, wide, pos,
                                                    ks, vs))
            np.testing.assert_array_equal(out, out_w)
        kf = k.astype(jnp.float32) * ks[:, :, None, None]
        vf = v.astype(jnp.float32) * vs[:, :, None, None]
        out_fp = np.asarray(_xla_paged_attention(q, kf, vf, tables, pos))
        np.testing.assert_array_equal(out, out_fp)

    def test_pool_bytes_per_block_accounting(self):
        """bytes_per_block is the telemetry, prefix-budget, and bench
        unit: int8 storage charges the int8 payload plus the 4-byte
        per-token scale reads — about 3.8x under the f32 pool, the
        capacity headroom the quant bench's capacity row measures."""
        mk = dict(num_layers=2, num_blocks=4, block_size=4, kv_heads=2,
                  head_dim=8)
        fp = PagedKVPool(**mk)
        q8 = PagedKVPool(**mk, quant_dtype="int8")
        assert fp.bytes_per_block == 2 * 2 * 4 * (2 * 8 * 4)
        assert q8.bytes_per_block == 2 * 2 * 4 * (2 * 8 * 1 + 4)
        assert fp.bytes_per_block / q8.bytes_per_block > 3
        assert str(jnp.dtype(q8.k[0].dtype)) == "int8"
        assert q8.k_scale[0].shape == (4, 4)
        # zero scales dequantize zero-init blocks to the fp pool's 0.0
        np.testing.assert_array_equal(np.asarray(q8.k_scale[0]), 0.0)

    def test_w8_weight_and_logit_error_bounds(self):
        """The documented PTQ bounds behind the tolerance thresholds:
        per-channel symmetric rounding keeps |W - deq(W)| <= scale/2
        elementwise (exact), and the end-to-end greedy logit error on
        the tiny model stays ~1e-2 — small against typical logit gaps,
        which is why the slow parity test can demand a high greedy
        token-match fraction."""
        m = _model()
        ids = paddle.randint(0, TINY.vocab_size, [1, 8])
        with _tape.no_grad():
            h, _ = m.model(ids, caches=[(None, None)] * 2)
            ref = m._logits(h).numpy()
        qmap = quantize_for_serving(m)
        # every matmul projection (q/k/v/o + SwiGLU gate/up/down) plus
        # the LM head got calibrated
        assert len(qmap) == 7 * TINY.num_hidden_layers + 1, sorted(qmap)
        sd = m.state_dict()
        orig = {}
        for name, qw in qmap.items():
            orig[name] = sd[name]._data
            err = np.abs(np.asarray(orig[name])
                         - np.asarray(qw.dequantize()))
            assert err.max() <= float(np.asarray(qw.scale).max()) / 2 + 1e-9
            sd[name]._data = qw.dequantize()
        try:
            with _tape.no_grad():
                h, _ = m.model(ids, caches=[(None, None)] * 2)
                got = m._logits(h).numpy()
        finally:
            for name, a in orig.items():
                sd[name]._data = a
        lerr = np.abs(got - ref).max()
        assert lerr < 0.05, lerr

    def test_quant_knob_normalization(self):
        norm = Engine._norm_quant_knob
        for off in (None, "", "none", "NONE"):
            assert norm(off, "weight_dtype") is None
        for on in ("int8", "INT8", "i8"):
            assert norm(on, "weight_dtype") == "int8"
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            norm("fp8", "kv_cache_dtype")

    def test_knobs_off_engine_structure_is_fp(self):
        """Knobs off: no (q, scale) tuples in the threaded state, fp
        pool, no scale planes — the decode/prefill traces are the
        pre-quant programs.  Knobs on: int8 where promised, and the
        resident weight bytes actually shrink."""
        m = _model()
        cfg = dict(num_slots=2, max_seq_len=32)
        fp = Engine(m, EngineConfig(**cfg), register_profiler=False)
        w8 = Engine(m, EngineConfig(**cfg, weight_dtype="int8"),
                    register_profiler=False)
        kv8 = Engine(m, EngineConfig(**cfg, kv_cache_dtype="int8"),
                     register_profiler=False)
        try:
            assert all(type(a) is not tuple for a in fp._state_arrays)
            assert fp.pool.quant_dtype is None
            assert fp.pool.k_scale is None
            assert str(jnp.dtype(fp.pool.store_dtype)) == "float32"
            assert fp.stats()["quant"]["quantized_weights"] == 0

            sq = w8.stats()["quant"]
            assert sq["quantized_weights"] > 0
            assert sq["weight_bytes"] < fp.stats()["quant"]["weight_bytes"]
            assert any(type(a) is tuple for a in w8._state_arrays)
            assert w8.pool.quant_dtype is None   # KV untouched by w8

            assert str(jnp.dtype(kv8.pool.store_dtype)) == "int8"
            assert kv8.pool.k_scale is not None
            assert kv8.stats()["kv_pool"]["dtype"] == "int8"
            assert (kv8.pool.bytes_per_block
                    < fp.pool.bytes_per_block)
        finally:
            fp.close()
            w8.close()
            kv8.close()

    # ---- engine end-to-end (slow: several compiled engines) ----

    @pytest.mark.slow
    def test_w8kv8_greedy_parity_under_batching(self):
        """The satellite workload: continuous batching + prefix hits +
        forced preemption/resume, fp vs int8.  Within the quant config
        the batched/preempted run must equal per-request sequential runs
        BITWISE (scheduling never changes tokens); across configs the
        greedy streams must agree on a documented fraction of tokens
        (mean longest-common-prefix; PTQ can flip a near-tie argmax,
        after which greedy divergence is permanent, so this is a
        tolerance threshold, not a bug budget)."""
        m = _model()
        system = list(range(1, 13))              # 3 shared prefix blocks
        prompts = [system + [20 + i, 40 + i] for i in range(4)]
        sp = SamplingParams(max_new_tokens=12)

        def run(wq, kq):
            eng = Engine(m, EngineConfig(
                num_slots=2, max_seq_len=48, max_horizon=4,
                prefix_block_size=4, kv_pool_blocks=12,
                weight_dtype=wq, kv_cache_dtype=kq),
                register_profiler=False)
            reqs = [eng.submit(list(p), sp) for p in prompts]
            eng.run()
            c = eng.stats()
            eng.close()
            return [r.output_ids for r in reqs], c

        fp_out, fp_c = run(None, None)
        off_out, _ = run("none", "")             # spelled-out "off" knobs
        assert off_out == fp_out                 # bitwise: same programs
        q_out, q_c = run("int8", "int8")

        for c in (fp_c, q_c):
            assert c["preemptions"] >= 1         # pool pressure was real
            assert c["prefix_hit_tokens"] > 0    # prefix cache was live

        # within-config determinism: sequential singles, same knobs
        eng = Engine(m, EngineConfig(
            num_slots=1, max_seq_len=48, max_horizon=4,
            prefix_block_size=0, weight_dtype="int8",
            kv_cache_dtype="int8"), register_profiler=False)
        seq_out = [eng.generate(list(p), sp) for p in prompts]
        eng.close()
        assert q_out == seq_out

        # cross-config tolerance: mean LCP fraction of the fp stream
        def lcp(a, b):
            n = 0
            for x, y in zip(a, b):
                if x != y:
                    break
                n += 1
            return n / max(1, len(a))

        match = sum(lcp(a, b) for a, b in zip(fp_out, q_out)) / len(fp_out)
        assert match >= 0.75, f"greedy token match {match:.3f} < 0.75"

        # the tentpole byte claim: int8 KV + scales cut per-step KV
        # traffic to <= 0.55x fp (measured ~0.27x at f32)
        fp_per = fp_c["kv_bytes_read"] / max(1, fp_c["decode_steps"])
        q_per = q_c["kv_bytes_read"] / max(1, q_c["decode_steps"])
        assert q_per <= 0.55 * fp_per, (q_per, fp_per)

    @pytest.mark.slow
    @pytest.mark.parametrize("kq", [None, "int8"], ids=["w8", "w8kv8"])
    def test_spec_decode_bitwise_under_quant(self, kq):
        """Draft-verify must stay EXACT under quantization: the verify
        window scores drafted tokens with the same quantized weights and
        same stored KV bytes the sequential path would produce, so K=4
        output equals K=0 output bitwise — not within tolerance."""
        m = _model()
        prompt = [5, 6, 7, 8] * 4
        sp = SamplingParams(max_new_tokens=16)
        outs = []
        for k in (0, 4):
            eng = Engine(m, EngineConfig(
                num_slots=1, max_seq_len=64, max_horizon=4,
                spec_k=k, spec_adaptive=False,
                weight_dtype="int8", kv_cache_dtype=kq),
                register_profiler=False)
            outs.append(eng.generate(list(prompt), sp))
            eng.close()
        assert outs[0] == outs[1]


class TestRequestTracing:
    """Flight records vs engine ground truth: every request's trace must
    reconstruct the engine's own counters — under continuous batching
    with preemption AND speculative decoding enabled — and abort must
    tear down cleanly from both the queued and the running state."""

    @staticmethod
    def _cfg(**kw):
        kw.setdefault("num_slots", 2)
        kw.setdefault("max_seq_len", 32)
        kw.setdefault("max_horizon", 4)
        kw.setdefault("prefix_block_size", 4)
        kw.setdefault("prefix_cache_bytes", 0)
        return EngineConfig(**kw)

    @pytest.mark.slow
    def test_trace_matches_counters_under_preempt_and_spec(self):
        m = _model()
        # the auto-preempt recipe (undersized pool forces at least one
        # swap round-trip) with self-drafting speculation on top
        prompts = [[7, 3, 9, 1, 4, 4, 2, 8], [5, 6, 7, 8, 9, 1, 2, 3]]
        samp = [SamplingParams(max_new_tokens=12) for _ in prompts]
        eng = Engine(m, self._cfg(kv_pool_blocks=8, spec_k=2),
                     register_profiler=False)
        reqs = [eng.submit(p, s) for p, s in zip(prompts, samp)]
        eng.run()
        c = eng.counters()
        assert c["preemptions"] >= 1
        for r in reqs:
            assert r.trace is not None and r.trace.finished
            tc = r.trace.counts()
            assert tc["tokens_emitted"] == r.n_generated == 12
            assert tc["prefix_hit_tokens"] == r.prefix_hit_tokens
            kinds = [k for k, _, _ in r.trace.events]
            assert kinds[0] == "queued" and kinds[-1] == "finish"
            assert kinds.count("first_token") == 1
            # every preempt pairs with a resume; FIRST_TOKEN only once
            assert (kinds.count("preempt") == kinds.count("resume")
                    == tc["preemptions"])
            ts = [t for _, t, _ in r.trace.events]
            assert ts == sorted(ts)
        # trace sums ARE the engine counters restated per request
        tcs = [r.trace.counts() for r in reqs]
        assert (sum(t["tokens_emitted"] for t in tcs)
                == c["tokens_generated"])
        assert (sum(t["preemptions"] for t in tcs) == c["preemptions"])
        assert (sum(t["spec_accepted_tokens"] for t in tcs)
                == c["spec_accepted_tokens"])
        # recorder retained both finished flight records
        assert ({t.request_id for t in eng.recorder.recent()}
                == {r.request_id for r in reqs})
        assert not eng.recorder.live()

    def test_counts_reconcile_deadline_aborts(self):
        """The counts() reconciliation must also hold when requests die
        to the admission deadline: per-trace ``aborted`` tallies sum to
        the engine's requests_aborted, and aborted requests contribute
        zero emitted tokens."""
        import time as _time

        m = _model()
        eng = Engine(m, self._cfg(num_slots=1), register_profiler=False)
        runner = eng.submit([1, 2, 3, 4],
                            SamplingParams(max_new_tokens=6))
        doomed = eng.submit([5, 6, 7, 8],
                            SamplingParams(max_new_tokens=6),
                            deadline_s=0.01)
        _time.sleep(0.03)                # deadline passes while queued
        eng.run()
        c = eng.counters()
        assert c["deadline_expired"] == 1 == c["requests_aborted"]
        tcs = [r.trace.counts() for r in (runner, doomed)]
        assert sum(t["aborted"] for t in tcs) == c["requests_aborted"]
        assert (sum(t["tokens_emitted"] for t in tcs)
                == c["tokens_generated"] == 6)
        assert doomed.trace.counts()["tokens_emitted"] == 0
        eng.close()

    def test_prefix_hit_tokens_in_trace(self):
        m = _model()
        eng = Engine(m, self._cfg(num_slots=1,
                                  prefix_cache_bytes=1 << 20),
                     register_profiler=False)
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        r1 = eng.submit(prompt, SamplingParams(max_new_tokens=4))
        eng.run()
        r2 = eng.submit(prompt, SamplingParams(max_new_tokens=4))
        eng.run()
        assert r2.prefix_hit_tokens > 0          # served from the cache
        for r in (r1, r2):
            assert (r.trace.counts()["prefix_hit_tokens"]
                    == r.prefix_hit_tokens)
        assert (r1.prefix_hit_tokens + r2.prefix_hit_tokens
                == eng.counters()["prefix_hit_tokens"])

    def test_abort_queued_and_running(self):
        m = _model()
        eng = Engine(m, self._cfg(num_slots=1), register_profiler=False)
        running = eng.submit([1, 2, 3, 4], SamplingParams(max_new_tokens=8))
        queued = eng.submit([5, 6, 7], SamplingParams(max_new_tokens=8))
        eng.step(horizon=2)
        assert running.status == "running" and queued.status == "waiting"

        eng.abort(queued)
        assert queued.status == "finished"
        assert queued.finish_reason == "abort"
        # never admitted: the flight record is queued -> abort, nothing else
        assert [k for k, _, _ in queued.trace.events] == ["queued", "abort"]

        had = running.n_generated
        assert had >= 1
        eng.abort(running)
        assert running.finish_reason == "abort"
        assert running.n_generated == had        # keeps its tokens
        kinds = [k for k, _, _ in running.trace.events]
        assert kinds[-1] == "abort" and "prefill" in kinds
        # full teardown: no queue, no running lane, no leaked blocks
        assert eng.scheduler.queue_depth == 0
        assert not eng.scheduler.running
        assert eng.pool.blocks_in_use == 0
        c = eng.counters()
        assert c["requests_aborted"] == 2
        assert ({t.request_id for t in eng.recorder.recent()}
                == {queued.request_id, running.request_id})
        with pytest.raises(ValueError):
            eng.abort(running)                   # already finished
        # the engine keeps serving after aborts
        r3 = eng.submit([9, 9], SamplingParams(max_new_tokens=3))
        eng.run()
        assert r3.n_generated == 3 and r3.finish_reason == "length"

    def test_tracing_disabled(self):
        m = _model()
        eng = Engine(m, self._cfg(num_slots=1, request_tracing=False),
                     register_profiler=False)
        r = eng.submit([1, 2, 3], SamplingParams(max_new_tokens=2))
        eng.run()
        assert r.trace is None and eng.recorder is None
        assert "tracing" not in eng.stats()
        eng.abort is not None                    # abort path still works
        r2 = eng.submit([4, 5], SamplingParams(max_new_tokens=4))
        eng.abort(r2)
        assert r2.finish_reason == "abort"


class TestPerformanceObservatory:
    """Observability phase 3 at the engine level: every compiled
    program has a cost card, per-request attribution reconstructs the
    engine's dispatch totals, the memory ledger reconciles, and the
    queue-wait histogram feeds stats()."""

    @staticmethod
    def _cfg(**kw):
        kw.setdefault("num_slots", 2)
        kw.setdefault("max_seq_len", 32)
        kw.setdefault("max_horizon", 4)
        kw.setdefault("prefix_block_size", 4)
        kw.setdefault("prefix_cache_bytes", 0)
        return EngineConfig(**kw)

    def test_every_compiled_program_has_a_card(self):
        m = _model()
        eng = Engine(m, self._cfg(), register_profiler=False)
        reqs = [eng.submit([1 + i, 2, 3, 4, 5][:3 + i % 3],
                           SamplingParams(max_new_tokens=6, seed=i))
                for i in range(4)]
        eng.run()
        assert all(r.finish_reason is not None for r in reqs)
        # one card per distinct compiled program, on both fns
        assert len(eng._decode.cards) == eng._decode.misses > 0
        assert len(eng._prefill.cards) == eng._prefill.misses > 0
        for fn in (eng._decode, eng._prefill):
            for card in fn.cards.values():
                assert card.flops and card.flops > 0
                assert card.bytes_accessed and card.bytes_accessed > 0
                assert card.compile_seconds > 0
                assert card.dispatches >= 1
        # decode cards carry the bucket semantics in meta
        metas = [c.meta for c in eng._decode.cards.values()]
        assert all({"horizon", "nb", "k_draft"} <= set(mt)
                   for mt in metas)
        assert ({(mt["horizon"], mt["nb"], mt["k_draft"])
                 for mt in metas}
                == set(eng.stats()["decode_buckets"]))
        # ...and prefill cards the (lanes, bucket) pair
        assert all({"lanes", "bucket"} <= set(mt.keys())
                   for mt in (c.meta for c in eng._prefill.cards.values()))
        # the dispatch ledger: every call rode a card (cards are
        # process-wide, so other engines may have bumped them too)
        assert (sum(c.dispatches for c in eng._decode.cards.values())
                >= eng._decode.calls)
        st = eng.stats()
        assert st["cost"]["decode_cards"] == len(
            {id(c) for c in eng._decode.cards.values()})
        eng.close()

    @pytest.mark.slow
    def test_attribution_reconciles_under_preempt_and_spec(self):
        """Sum of per-request flops/bytes estimates == the engine's own
        dispatch-weighted card totals, within 1%, under continuous
        batching with preemption and speculative decoding."""
        m = _model()
        prompts = [[7, 3, 9, 1, 4, 4, 2, 8], [5, 6, 7, 8, 9, 1, 2, 3],
                   [2, 4, 6, 8], [1, 3, 5, 7, 9, 2]]
        eng = Engine(m, self._cfg(kv_pool_blocks=8, spec_k=2),
                     register_profiler=False)
        reqs = [eng.submit(p, SamplingParams(max_new_tokens=10, seed=i))
                for i, p in enumerate(prompts)]
        eng.run()
        assert eng.counters()["preemptions"] >= 1
        assert eng.counters()["spec_accepted_tokens"] >= 0
        st = eng.stats()
        assert st["cost"]["program_flops_total"] > 0
        assert st["cost"]["program_bytes_total"] > 0
        got_f = sum(r.trace.counts()["flops_est"] for r in reqs)
        got_b = sum(r.trace.counts()["bytes_est"] for r in reqs)
        assert got_f == pytest.approx(st["cost"]["program_flops_total"],
                                      rel=0.01)
        assert got_b == pytest.approx(st["cost"]["program_bytes_total"],
                                      rel=0.01)
        # attribution is per-request meaningful, not all-on-one
        assert all(r.trace.counts()["flops_est"] > 0 for r in reqs)
        # /debug/requests carries the same numbers
        doc = eng.recorder.to_json()
        assert (sum(t["counts"]["flops_est"] for t in doc["recent"])
                == pytest.approx(got_f))
        eng.close()

    def test_memory_ledger_reconciles_in_stats(self):
        import gc

        gc.collect()                 # settle foreign arrays first
        m = _model()
        eng = Engine(m, self._cfg(), register_profiler=False)
        eng.submit([1, 2, 3], SamplingParams(max_new_tokens=4))
        eng.run()
        mem = eng.stats()["memory"]
        assert set(mem["components"]) == {"kv_pool", "weights",
                                          "engine_state"}
        assert all(v > 0 for v in mem["components"].values())
        assert (mem["accounted_total_bytes"]
                == sum(mem["components"].values()))
        # live_arrays is process-wide (other tests' arrays included),
        # but it must at least cover what this engine accounts for
        assert mem["live_bytes"] >= mem["accounted_total_bytes"]
        # steady state: the unaccounted residue does not grow between
        # snapshots of the same engine (the leak-detector contract)
        eng.submit([4, 5, 6], SamplingParams(max_new_tokens=4))
        eng.run()
        gc.collect()
        assert eng.stats()["memory"]["leak_delta_bytes"] <= 1 << 16
        eng.close()

    def test_queue_wait_histogram_in_stats(self):
        m = _model()
        eng = Engine(m, self._cfg(num_slots=1), register_profiler=False)
        # second request queues behind the first -> nonzero wait
        eng.submit([1, 2, 3], SamplingParams(max_new_tokens=6))
        eng.submit([4, 5, 6], SamplingParams(max_new_tokens=2))
        eng.run()
        st = eng.stats()
        assert "queue_wait_p50_s" in st and "queue_wait_p95_s" in st
        assert st["queue_wait_p95_s"] >= st["queue_wait_p50_s"] >= 0.0
        eng.close()

    def test_program_cards_disabled(self):
        m = _model()
        eng = Engine(m, self._cfg(num_slots=1, program_cards=False),
                     register_profiler=False)
        r = eng.submit([1, 2, 3], SamplingParams(max_new_tokens=3))
        eng.run()
        assert r.finish_reason == "length"
        assert eng._decode.cards == {} and eng._prefill.cards == {}
        st = eng.stats()
        assert st["cost"]["program_flops_total"] == 0.0
        assert st["cost"]["decode_cards"] == 0
        # tracing still works, just without cost estimates
        assert r.trace.counts()["flops_est"] == 0.0
        eng.close()

    def test_abort_storm_flight_recorder_retention(self):
        """Satellite: N submits then abort everything — the recorder's
        ring retains only the last `capacity` finished traces, counts
        the drops, and pins zero live traces afterwards."""
        m = _model()
        eng = Engine(m, self._cfg(num_slots=2,
                                  flight_recorder_capacity=3),
                     register_profiler=False)
        reqs = [eng.submit([1 + i, 2, 3], SamplingParams(
            max_new_tokens=8, seed=i)) for i in range(8)]
        eng.step(horizon=2)          # two admitted + decoding, six queued
        for r in reqs:
            if r.finish_reason is None:
                eng.abort(r)
        assert all(r.finish_reason is not None for r in reqs)
        aborted = [r for r in reqs if r.finish_reason == "abort"]
        assert len(aborted) >= 6
        for r in aborted:
            assert [k for k, _, _ in r.trace.events][-1] == "abort"
        rec = eng.recorder
        assert rec.live() == []                  # nothing pinned
        doc = rec.to_json()
        assert doc["live_count"] == 0
        assert doc["finished_total"] == len(reqs)
        assert doc["finished_retained"] == 3
        assert rec.dropped == len(reqs) - 3
        assert ([t.request_id for t in rec.recent()]
                == [r.request_id for r in reqs[-3:]])
        # the engine is fully torn down and still serviceable
        assert eng.scheduler.queue_depth == 0
        assert not eng.scheduler.running
        assert eng.pool.blocks_in_use == 0
        r9 = eng.submit([7, 7], SamplingParams(max_new_tokens=2))
        eng.run()
        assert r9.finish_reason == "length"
        eng.close()


# ----------------------------------------------------------- sharded serving
class TestServingSpecLayout:
    """The sharded layout's placement rules: every decode-model
    parameter gets a spec, projections are column-parallel, and
    unshardable shapes are rejected EAGERLY (before any device work)."""

    def test_every_param_gets_a_spec(self):
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.serving import ServingSpecLayout

        layout = ServingSpecLayout()
        m = _model()
        names = list(m.state_dict().keys())
        specs = layout.state_specs(names)
        assert len(specs) == len(names)
        for n, sp in zip(names, specs):
            if layout.is_tp_sharded(n):
                # column-parallel: LAST axis sharded, never the first
                # (sharding the contraction dim would break bitwise)
                assert sp == P(None, "tp"), n
            else:
                assert sp == P(), n
        # the decode-model projections really are in the sharded set
        sharded = [n for n in names if layout.is_tp_sharded(n)]
        for proj in ("q_proj", "k_proj", "v_proj", "o_proj", "gate_proj",
                     "up_proj", "down_proj", "lm_head"):
            assert any(proj in n for n in sharded), proj
        # engine scan state and the KV pool have placements too
        assert layout.engine_state() == P()
        assert layout.kv_pool() == P(None, None, "tp", None)
        assert layout.kv_scales() == P()

    def test_divisibility_errors_are_eager_and_name_offenders(self):
        from paddle_tpu.serving import ServingSpecLayout

        layout = ServingSpecLayout()
        # TINY: 4 heads / hidden 64 / vocab 128 — tp=3 divides nothing
        with pytest.raises(ValueError, match="num_attention_heads=4"):
            layout.validate(TINY, 3)
        # TINY_GQA: 8 q-heads divide by 4 but the 2 kv_heads do not
        with pytest.raises(ValueError, match="kv_heads"):
            layout.validate(TINY_GQA, 4)
        layout.validate(TINY_GQA, 2)            # and tp=2 is fine

    def test_tied_embeddings_rejected(self):
        from paddle_tpu.serving import ServingSpecLayout

        tied = GPTConfig(vocab_size=128, hidden_size=64,
                         intermediate_size=128, num_hidden_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=64,
                         tie_word_embeddings=True)
        with pytest.raises(ValueError, match="tie_word_embeddings"):
            ServingSpecLayout().validate(tied, 2)

    def test_mesh_engine_rejects_bad_shapes_before_compiling(self):
        from paddle_tpu.serving import MeshEngine

        m = _model()
        with pytest.raises(ValueError, match="not divisible"):
            MeshEngine(m, EngineConfig(num_slots=2, max_seq_len=32),
                       tp=3, register_profiler=False)
        with pytest.raises(ValueError, match="mesh_shape"):
            MeshEngine._norm_mesh_knob(None, None)
        with pytest.raises(ValueError, match="contradicts"):
            MeshEngine._norm_mesh_knob((1, 2), 4)
        with pytest.raises(ValueError, match="disaggregated"):
            MeshEngine._norm_mesh_knob((2, 2), None)
        with pytest.raises(ValueError, match="tp must be"):
            MeshEngine._norm_mesh_knob(None, 0)
        assert MeshEngine._norm_mesh_knob(None, 2) == (1, 2)
        assert MeshEngine._norm_mesh_knob((1, 4), None) == (1, 4)


class TestChunkedPrefill:
    """Chunked prefill (``prefill_chunk_tokens``) vs whole-prompt
    prefill: the token streams must be BITWISE equal — greedy and
    seeded — under continuous batching, prefix hits at and across chunk
    boundaries, preemption (mid-prefill and mid-decode), speculative
    decoding, and int8 KV.  Chunking is pure scheduling: each chunk is
    an iterated prefix-extension of the same lane, so the streams can
    only diverge if the interleave machinery breaks."""

    _rng = np.random.default_rng(11)
    BASE = list(map(int, _rng.integers(1, 127, 26)))
    # phase-2 prompts: shared prefix ending exactly AT a chunk boundary
    # (16 = 2 chunks of 8) and ACROSS one (20 straddles chunk 3)
    PROMPTS1 = [BASE,
                list(map(int, _rng.integers(1, 127, 9))),
                list(map(int, _rng.integers(1, 127, 23)))]
    PROMPTS2 = [BASE[:16] + list(map(int, _rng.integers(1, 127, 7))),
                BASE[:20] + list(map(int, _rng.integers(1, 127, 5)))]
    SAMP1 = [SamplingParams(max_new_tokens=8),
             SamplingParams(max_new_tokens=7, temperature=0.9, seed=5),
             SamplingParams(max_new_tokens=8, temperature=1.2, top_k=13,
                            seed=2)]
    SAMP2 = [SamplingParams(max_new_tokens=6),
             SamplingParams(max_new_tokens=6, temperature=0.8, seed=9)]

    @staticmethod
    def _engine(m, chunk, **kw):
        kw.setdefault("num_slots", 4)
        kw.setdefault("max_seq_len", 64)
        kw.setdefault("kv_pool_blocks", 96)
        return Engine(m, EngineConfig(prefill_chunk_tokens=chunk, **kw),
                      register_profiler=False)

    @classmethod
    def _run(cls, chunk, **kw):
        eng = cls._engine(_model(), chunk, **kw)
        out = [eng.generate(cls.PROMPTS1, cls.SAMP1),
               eng.generate(cls.PROMPTS2, cls.SAMP2)]
        return eng, out

    _whole1 = None

    @classmethod
    def _whole_phase1(cls):
        """Phase-1 whole-prompt reference, computed once per session."""
        if cls._whole1 is None:
            eng = cls._engine(_model(), 0)
            cls._whole1 = eng.generate(cls.PROMPTS1, cls.SAMP1)
            eng.close()
        return cls._whole1

    def test_parity_greedy_seeded_prefix_hits(self):
        """The core gate: chunk=8 streams bitwise-equal whole-prompt
        across two phases, where phase 2's prompts hit the radix cache
        at and across chunk boundaries; the compiled prefill programs
        never exceed the chunk bucket, yet a 26-token prompt (> any
        single 8-wide dispatch) completes — the context cap the chunking
        lifts."""
        e0, whole = self._run(0)
        e1, chunked = self._run(8)
        assert chunked == whole
        st = e1.stats()["prefill"]
        assert st["chunked_requests"] >= 3
        assert st["chunks_in_flight"] == 0
        assert st["context_high_water"] == len(self.BASE)
        assert all(b <= st["chunk_tokens"] for _, b in st["buckets"])
        # whole-prompt compiled a 32-wide program for the same work
        assert max(b for _, b in e0.stats()["prefill"]["buckets"]) == 32
        assert e1.stats()["prefix"]["hit_tokens"] > 0
        e1.drain()                   # radix store may still hold blocks
        assert e1.pool.blocks_in_use == 0
        e0.close()
        e1.close()

    @pytest.mark.slow
    def test_interleave_schedule_is_deterministic(self):
        """Identical workload -> identical chunk/dispatch counters; the
        same fields DECODE_BENCH.json gates exact so the interleave
        schedule can't silently drift."""
        e1, out1 = self._run(8)
        e2, out2 = self._run(8)
        keys = ("prefill_calls", "prefill_chunk_dispatches",
                "prefill_chunked_requests")
        c1, c2 = e1.counters(), e2.counters()
        assert out1 == out2
        assert {k: c1[k] for k in keys} == {k: c2[k] for k in keys}
        s1, s2 = e1.stats()["prefill"], e2.stats()["prefill"]
        assert s1["chunk_count_total"] == s2["chunk_count_total"]
        assert s1["buckets"] == s2["buckets"]
        e1.close()
        e2.close()

    def test_mid_prefill_preempt_resumes_at_chunk_boundary(self):
        """Preempting a lane mid-chunked-prefill drops its ledger; the
        blocks its finished chunks adopted survive in the radix store,
        so re-admission resumes from the chunk boundary as an ordinary
        prefix hit — and the stream stays bitwise."""
        whole = self._whole_phase1()
        eng = self._engine(_model(), 8)
        reqs = [eng.submit(p, s)
                for p, s in zip(self.PROMPTS1, self.SAMP1)]
        eng.admit()                  # first chunks dispatched
        eng.step()                   # chunk 2: 16 tokens = 1 full block
        victim = reqs[0]             # 26-token prompt, mid-prefill
        assert victim.request_id in eng._chunking
        eng.preempt(victim)
        assert victim.request_id not in eng._chunking
        eng.run()
        assert [r.output_ids for r in reqs] == whole
        assert victim.prefix_hit_tokens >= 16
        assert eng.counters()["preemptions"] == 1
        eng.close()

    @pytest.mark.slow
    def test_decode_preempt_reprefills_through_chunks(self):
        """A lane preempted mid-DECODE re-prefills prompt + generated
        tokens through chunked dispatches; the final chunk re-samples
        the in-flight token and the PR 6 bitwise consistency check runs
        against it."""
        whole = self._whole_phase1()
        eng = self._engine(_model(), 8)
        reqs = [eng.submit(p, s)
                for p, s in zip(self.PROMPTS1, self.SAMP1)]
        while not all(r.output_ids for r in reqs):
            eng.step()
        eng.preempt(reqs[0])
        eng.run()
        assert [r.output_ids for r in reqs] == whole
        eng.close()

    @pytest.mark.slow
    def test_spec_k4_parity(self):
        m = _model()
        e0 = self._engine(m, 0, spec_k=4)
        whole = e0.generate(self.PROMPTS1, self.SAMP1)
        e0.close()
        e1 = self._engine(m, 8, spec_k=4)
        assert e1.generate(self.PROMPTS1, self.SAMP1) == whole
        assert e1.stats()["prefill"]["chunked_requests"] >= 1
        e1.close()

    @pytest.mark.slow
    def test_int8_kv_parity(self):
        m = _model()
        e0 = self._engine(m, 0, kv_cache_dtype="int8")
        whole = e0.generate(self.PROMPTS1, self.SAMP1)
        e0.close()
        e1 = self._engine(m, 8, kv_cache_dtype="int8")
        assert e1.generate(self.PROMPTS1, self.SAMP1) == whole
        e1.close()

    def test_chunk_size_normalization(self):
        """The knob normalizes to a power of two in
        [min_prefill_bucket, max_seq_len] (compile-cache discipline);
        negative rejects."""
        m = _model()
        eng = self._engine(m, 10)
        assert eng._chunk_tokens == 16
        eng.close()
        eng = self._engine(m, 2)     # below min_prefill_bucket (8)
        assert eng._chunk_tokens == 8
        eng.close()
        with pytest.raises(ValueError):
            self._engine(m, -4)

    def test_abort_mid_chunked_prefill_releases_blocks(self):
        eng = self._engine(_model(), 8)
        reqs = [eng.submit(p, s)
                for p, s in zip(self.PROMPTS1, self.SAMP1)]
        eng.admit()
        victim = reqs[0]
        assert victim.request_id in eng._chunking
        eng.abort(victim)
        assert victim.request_id not in eng._chunking
        assert victim.finish_reason == "abort"
        eng.run()
        assert all(r.output_ids for r in reqs[1:])
        eng.drain()
        assert eng.pool.blocks_in_use == 0
        eng.close()


class TestShardedServing:
    """MeshEngine vs single-chip Engine: greedy AND seeded streams must
    be bitwise-equal under continuous batching, prefix hits, preemption
    and speculative decoding (8 virtual CPU devices, tp=2)."""

    PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6]]
    SAMP = [SamplingParams(max_new_tokens=10),
            SamplingParams(temperature=0.8, top_k=20, seed=11,
                           max_new_tokens=10)]

    @staticmethod
    def _cfg(**kw):
        kw.setdefault("num_slots", 2)
        kw.setdefault("max_seq_len", 32)
        kw.setdefault("max_horizon", 4)
        kw.setdefault("prefix_block_size", 4)
        kw.setdefault("prefix_cache_bytes", 0)
        return EngineConfig(**kw)

    @classmethod
    def _ref(cls, m, prompts, samp, **kw):
        eng = Engine(m, cls._cfg(**kw), register_profiler=False)
        out = eng.generate(prompts, samp)
        eng.close()
        return out

    @classmethod
    def _mesh(cls, m, tp=2, **kw):
        from paddle_tpu.serving import MeshEngine

        return MeshEngine(m, cls._cfg(**kw), tp=tp,
                          register_profiler=False)

    def test_tp2_bitwise_parity_greedy_and_seeded(self):
        """The core acceptance test: continuous batching over a greedy
        and a seeded lane, tp=2 vs single chip, bitwise."""
        m = _model()
        ref = self._ref(m, self.PROMPTS, self.SAMP)
        eng = self._mesh(m)
        assert eng.generate(self.PROMPTS, self.SAMP) == ref
        assert eng.pool.blocks_in_use == 0
        s = eng.stats()["mesh"]
        assert s["mesh_shape"] == {"dp": 1, "tp": 2}
        assert len(s["devices"]) == 2
        eng.close()

    def test_tp1_is_the_degenerate_mesh(self):
        m = _model()
        ref = self._ref(m, self.PROMPTS, self.SAMP)
        eng = self._mesh(m, tp=1)
        assert eng.generate(self.PROMPTS, self.SAMP) == ref
        eng.close()

    def test_decode_census_matches_hand_formula(self):
        """The comms walker's census of the REAL compiled decode
        program equals the hand-derived per-layer count — the same
        contract MULTICHIP_BENCH.json gates exact in CI."""
        m = _model()
        eng = self._mesh(m)
        rep = eng.decode_comms_report(horizon=4)   # asserts internally
        L, h = 2, 4
        assert rep.counts() == {("psum", "tp"): L * h,
                                ("all_gather", "tp"): (3 * L + 1) * h}
        eng.close()

    @pytest.mark.slow
    def test_tp2_chunked_prefill_pallas_kernel_parity(self, monkeypatch):
        """Chunked prefill over the mesh WITH the Pallas ragged kernel
        running inside shard_map on each shard's head slice (interpret
        mode on CPU): streams bitwise vs the single-chip whole-prompt
        engine, and the decode collective census stays EXACT — the
        kernel adds no collectives."""
        m = _model()
        prompts = [[3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3, 2,
                    3, 8, 4, 6, 2], [9, 2, 6]]
        samp = [SamplingParams(max_new_tokens=8),
                SamplingParams(temperature=0.8, top_k=20, seed=11,
                               max_new_tokens=8)]
        ref = self._ref(m, prompts, samp)
        monkeypatch.setenv("PADDLE_TPU_PAGED_ATTN", "pallas")
        eng = self._mesh(m, prefill_chunk_tokens=8)
        assert eng.generate(prompts, samp) == ref
        st = eng.stats()["prefill"]
        assert st["chunked_requests"] >= 1
        assert all(b <= st["chunk_tokens"] for _, b in st["buckets"])
        rep = eng.decode_comms_report(horizon=4)   # asserts internally
        L, h = 2, 4
        assert rep.counts() == {("psum", "tp"): L * h,
                                ("all_gather", "tp"): (3 * L + 1) * h}
        assert eng.pool.blocks_in_use == 0
        eng.close()

    @pytest.mark.slow
    def test_tp2_parity_gqa(self):
        m = _model(TINY_GQA)
        ref = self._ref(m, self.PROMPTS, self.SAMP)
        eng = self._mesh(m)
        assert eng.generate(self.PROMPTS, self.SAMP) == ref
        eng.close()

    @pytest.mark.slow
    def test_tp2_prefix_hit_parity(self):
        """A shared-prefix workload over the mesh-sharded pool: leases,
        COW and the radix store run host-side and unchanged; the leased
        blocks hold sharded KV.  Streams stay bitwise and the second
        submission actually hits the cache."""
        m = _model()
        shared = [5, 5, 7, 7, 1, 2, 3, 4]
        prompts = [shared + [9], shared + [8]]
        samp = [SamplingParams(max_new_tokens=8),
                SamplingParams(max_new_tokens=8)]
        kw = dict(prefix_cache_bytes=1 << 20)
        # sequential submissions so the second prompt can actually hit
        # the blocks the first one's retirement adopted
        refeng = Engine(m, self._cfg(**kw), register_profiler=False)
        ref = [refeng.generate(p, s) for p, s in zip(prompts, samp)]
        refeng.close()
        eng = self._mesh(m, **kw)
        out = [eng.generate(p, s) for p, s in zip(prompts, samp)]
        assert out == ref
        assert eng.stats()["prefix"]["hit_tokens"] > 0
        eng.drain()
        assert eng.pool.blocks_in_use == 0
        eng.close()

    @pytest.mark.slow
    def test_tp2_preempt_resume_parity(self):
        """Explicit preemption of a seeded lane mid-decode: blocks
        released, request re-admitted at the queue front, stream still
        bitwise vs the single-chip run of the same scenario."""
        m = _model()
        ref = self._ref(m, self.PROMPTS, self.SAMP)
        eng = self._mesh(m)
        reqs = [eng.submit(p, s)
                for p, s in zip(self.PROMPTS, self.SAMP)]
        eng.step(horizon=2)
        victim = reqs[1]
        eng.preempt(victim)
        assert victim.status == "waiting"
        eng.run()
        assert [r.output_ids for r in reqs] == ref
        assert eng.counters()["preemptions"] == 1
        assert eng.pool.blocks_in_use == 0
        eng.close()

    @pytest.mark.slow
    def test_tp2_spec_decode_parity(self):
        """Speculative decoding (K=4) over the mesh: drafts verified
        through the sharded forward, output bitwise vs the single-chip
        engine with the same knob — greedy and seeded."""
        m = _model()
        rep = TestSpeculativeDecode.REP_PROMPT
        samp = [SamplingParams(max_new_tokens=10),
                SamplingParams(temperature=0.9, top_k=20, top_p=0.9,
                               seed=7, max_new_tokens=10)]
        prompts = [rep, rep]
        kw = dict(max_seq_len=48, spec_k=4)
        ref = self._ref(m, prompts, samp, **kw)
        eng = self._mesh(m, **kw)
        assert eng.generate(prompts, samp) == ref
        assert eng.stats()["spec"]["draft_tokens"] > 0
        eng.close()

    @pytest.mark.slow
    def test_tp2_kv_quant_parity(self):
        """int8 paged KV over the mesh: the pmax'ed absmax gives every
        shard the full-head scale, so streams match the single-chip
        int8 engine bitwise (and census grows the 2L pmaxes)."""
        m = _model()
        kw = dict(kv_cache_dtype="int8")
        ref = self._ref(m, self.PROMPTS, self.SAMP, **kw)
        eng = self._mesh(m, **kw)
        assert eng.generate(self.PROMPTS, self.SAMP) == ref
        assert eng.decode_comms_report(horizon=4).counts()[
            ("pmax", "tp")] == 2 * 2 * 4
        eng.close()

    def test_create_llm_engine_knobs(self):
        """The predictor-style entry point: tp picks the engine class,
        knob contradictions raise like _norm_quant_knob does."""
        from paddle_tpu.inference import create_llm_engine
        from paddle_tpu.serving import MeshEngine

        m = _model()
        eng = create_llm_engine(m, num_slots=2, max_seq_len=32)
        assert type(eng) is Engine
        eng.close()
        eng = create_llm_engine(m, tp=1, num_slots=2, max_seq_len=32)
        assert type(eng) is Engine
        eng.close()
        eng = create_llm_engine(m, tp=2, num_slots=2, max_seq_len=32)
        assert isinstance(eng, MeshEngine)
        assert eng.mesh_shape == (1, 2)
        eng.close()
        with pytest.raises(ValueError, match="contradicts"):
            create_llm_engine(m, mesh_shape=(1, 2), tp=4)
        with pytest.raises(ValueError, match="disaggregated"):
            create_llm_engine(m, mesh_shape=(2, 2))


class TestHostKVTier:
    """Tiered KV: the host-RAM spill arena (kv_host_tier.py).
    Preempted lanes swap back in with one batched upload instead of
    re-prefilling, LRU-evicted prefix blocks demote to host and
    re-match later — and every path must be bitwise-equal to the
    recompute it replaces (the engine's resume-divergence check is the
    standing parity gate)."""

    PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6]]
    SAMP = [SamplingParams(max_new_tokens=10),
            SamplingParams(temperature=0.8, top_k=20, seed=11,
                           max_new_tokens=10)]

    @staticmethod
    def _cfg(**kw):
        kw.setdefault("num_slots", 2)
        kw.setdefault("max_seq_len", 48)
        kw.setdefault("max_horizon", 4)
        kw.setdefault("prefix_block_size", 4)
        kw.setdefault("prefix_cache_bytes", 1 << 20)
        kw.setdefault("kv_host_bytes", 1 << 20)
        kw.setdefault("kv_swap_policy", "always")
        return EngineConfig(**kw)

    @classmethod
    def _preempt_run(cls, eng):
        """Both lanes decode, both get preempted mid-stream, the run
        finishes through re-admission (swap-in when a tier is on,
        re-prefill otherwise)."""
        reqs = [eng.submit(list(p), s)
                for p, s in zip(cls.PROMPTS, cls.SAMP)]
        eng.step(horizon=2)
        eng.preempt(reqs[0])
        eng.preempt(reqs[1])
        eng.run()
        return reqs

    def test_preempt_swap_in_resume_bitwise(self):
        """The core acceptance: a greedy AND a seeded lane preempted
        mid-decode finish bitwise-equal whether their KV came back via
        host-arena swap-in or recompute, per-request traces restate the
        engine's swap counters exactly, and drain leaves zero host
        blocks."""
        m = _model()
        ref = Engine(m, self._cfg(kv_host_bytes=0),
                     register_profiler=False)
        r0 = self._preempt_run(ref)
        ref.close()
        eng = Engine(m, self._cfg(), register_profiler=False)
        r1 = self._preempt_run(eng)
        assert [r.output_ids for r in r1] == [r.output_ids for r in r0]
        c = eng.counters()
        assert c["kv_swap_outs"] >= 1 and c["kv_swap_ins"] >= 1
        tcs = [r.trace.counts() for r in r1]
        assert sum(t["swap_outs"] for t in tcs) == c["kv_swap_outs"]
        assert sum(t["swap_ins"] for t in tcs) == c["kv_swap_ins"]
        assert (sum(t["swap_out_bytes"] for t in tcs)
                == c["kv_swap_out_bytes"])
        assert (sum(t["swap_in_bytes"] for t in tcs)
                == c["kv_swap_in_bytes"])
        eng.drain()
        s = eng.stats()["kv_pool"]
        assert s["host_blocks_in_use"] == 0
        assert s["kv_swaps_averted_tokens"] > 0
        eng.close()

    def test_demoted_prefix_rematch_beats_drop(self):
        """A tight device radix budget plus churn evicts a warm
        prompt's chain; with the host tier the eviction is a demotion,
        so a later identical prompt re-matches at least as many tokens
        as a never-evicted control does under an ample budget (the
        budget is 8 blocks — enough to graft the promoted chain back,
        small enough that 12 blocks of churn still evicts it)."""
        m = _model()
        P = [5, 5, 7, 7, 1, 2, 3, 4, 9, 8, 7, 6,
             1, 3, 5, 7, 2, 4, 6, 8]
        churn = [[c] * 12 for c in (11, 22, 33)]
        samp = SamplingParams(max_new_tokens=4)

        def warm_probe(eng):
            eng.generate(list(P), samp)
            for q in churn:
                eng.generate(list(q), samp)
            r = eng.submit(list(P), samp)
            eng.run()
            return r

        ctrl = Engine(m, self._cfg(kv_host_bytes=0),
                      register_profiler=False)
        bpb = ctrl.pool.bytes_per_block
        ctrl_hit = warm_probe(ctrl).prefix_hit_tokens
        ctrl.close()
        eng = Engine(m, self._cfg(prefix_cache_bytes=8 * bpb),
                     register_profiler=False)
        probe = warm_probe(eng)
        st = eng.stats()
        assert st["prefix"]["evictions_demoted"] > 0
        assert st["kv_pool"]["host_tier"]["promotions"] > 0
        assert probe.prefix_hit_tokens >= ctrl_hit > 0
        eng.drain()
        assert eng.stats()["kv_pool"]["host_blocks_in_use"] == 0
        eng.close()

    @pytest.mark.slow
    def test_int8_roundtrip_stored_bytes_identical(self):
        """int8 KV swaps at quantized density: the device bytes of the
        re-bound blocks after a swap round-trip equal the pre-preempt
        pool bytes exactly — payloads AND scale planes — and the
        resumed stream matches the no-tier recompute engine."""
        m = _model()
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        samp = SamplingParams(temperature=0.8, top_k=20, seed=11,
                              max_new_tokens=16)
        ref = Engine(m, self._cfg(kv_host_bytes=0,
                                  kv_cache_dtype="int8"),
                     register_profiler=False)
        r0 = ref.submit(list(prompt), samp)
        ref.step(horizon=4)
        ref.preempt(r0)
        ref.run()
        ref.close()
        eng = Engine(m, self._cfg(kv_cache_dtype="int8"),
                     register_profiler=False)
        r = eng.submit(list(prompt), samp)
        eng.step(horizon=4)
        assert r.status == "running"
        slot, bs = r.slot, eng._block_size
        pos = int(eng._pos[slot])
        nb = -(-pos // bs)
        bids = [int(eng.cache.tables[slot][j]) for j in range(nb)]
        k0, v0, ks0, vs0 = eng._fetch_blocks(bids)
        eng.preempt(r)
        assert eng.host_tier.stats()["lane_images"] == 1
        assert eng._swap_in(r)
        toks = eng._admission_tokens(r)
        chain = eng.prefix._walk(toks, len(toks))
        fb = pos // bs
        assert len(chain) == fb
        k1, v1, ks1, vs1 = eng._fetch_blocks([n.block for n in chain])
        assert k1.dtype == np.int8              # quantized density
        assert np.array_equal(k1, k0[:fb])
        assert np.array_equal(v1, v0[:fb])
        assert np.array_equal(ks1, ks0[:fb])
        assert np.array_equal(vs1, vs0[:fb])
        eng.run()
        assert r.output_ids == r0.output_ids
        eng.drain()
        assert eng.stats()["kv_pool"]["host_blocks_in_use"] == 0
        eng.close()

    @pytest.mark.slow
    def test_tp2_swap_parity(self):
        """Swap-in over the mesh-sharded pool: device_get gathers the
        full block, the upload re-places through the layout, and the
        stream stays bitwise vs the single-chip NO-tier engine (swap ==
        recompute across both axes at once)."""
        from paddle_tpu.serving import MeshEngine

        m = _model()
        ref = Engine(m, self._cfg(kv_host_bytes=0),
                     register_profiler=False)
        r0 = self._preempt_run(ref)
        ref.close()
        eng = MeshEngine(m, self._cfg(), tp=2, register_profiler=False)
        r1 = self._preempt_run(eng)
        assert [r.output_ids for r in r1] == [r.output_ids for r in r0]
        assert eng.counters()["kv_swap_ins"] >= 1
        eng.drain()
        assert eng.stats()["kv_pool"]["host_blocks_in_use"] == 0
        eng.close()

    def test_arena_exhaustion_and_policy_never_fall_back(self):
        """A one-byte arena (capacity 0 blocks) and policy "never" both
        degrade to plain recompute — same bitwise output, zero swap
        counters, no errors.  Bad knob values raise at construction."""
        m = _model()
        ref = Engine(m, self._cfg(kv_host_bytes=0),
                     register_profiler=False)
        r0 = self._preempt_run(ref)
        ref.close()
        for kw in (dict(kv_host_bytes=1), dict(kv_swap_policy="never")):
            eng = Engine(m, self._cfg(**kw), register_profiler=False)
            rs = self._preempt_run(eng)
            assert ([r.output_ids for r in rs]
                    == [r.output_ids for r in r0])
            c = eng.counters()
            assert c["kv_swap_ins"] == 0 and c["kv_swap_outs"] == 0
            if "kv_host_bytes" in kw:
                assert eng.host_tier.capacity == 0
            eng.drain()
            assert eng.stats()["kv_pool"]["host_blocks_in_use"] == 0
            eng.close()
        with pytest.raises(ValueError, match="kv_swap_policy"):
            Engine(m, self._cfg(kv_swap_policy="sometimes"),
                   register_profiler=False)

    def test_host_block_leak_invariant(self):
        """After preempt + abort + drain: zero host blocks in use and
        zero retained lane images — aborting a swapped-out request must
        drop its pinned image (the host-side leak smoke invariant)."""
        m = _model()
        eng = Engine(m, self._cfg(), register_profiler=False)
        reqs = [eng.submit(list(p), s)
                for p, s in zip(self.PROMPTS, self.SAMP)]
        eng.step(horizon=2)
        eng.preempt(reqs[1])
        assert eng.host_tier.stats()["lane_images"] == 1
        eng.abort(reqs[1])
        eng.submit([7, 7, 7, 7, 2], SamplingParams(max_new_tokens=6))
        eng.run()
        eng.drain()
        s = eng.stats()["kv_pool"]
        assert s["host_blocks_in_use"] == 0
        assert s["host_tier"]["lane_images"] == 0
        assert s["host_tier"]["lane_drops"] >= 1
        eng.close()

    def test_host_tier_unit(self):
        """HostKVTier in isolation: refresh-in-place demotion,
        consecutive-run matching capped at len-1, all-or-nothing lane
        saves with LRU prefix eviction, refcount guards."""
        L, bs, kvh, hd = 2, 4, 2, 8
        bpb = 2 * L * bs * kvh * hd * 4
        tier = HostKVTier(L, bs, kvh, hd, np.float32,
                          budget_bytes=3 * bpb, bytes_per_block=bpb)
        assert tier.capacity == 3

        def blk(x):
            return np.full((L, bs, kvh, hd), x, np.float32)

        toks = list(range(12))
        assert tier.store_prefix(tuple(toks[:4]), blk(1), blk(-1))
        assert tier.store_prefix(tuple(toks[:8]), blk(2), blk(-2))
        # re-demotion of a held path refreshes in place — no new block
        in_use = tier.blocks_in_use
        assert tier.store_prefix(tuple(toks[:4]), blk(9), blk(-9))
        assert tier.blocks_in_use == in_use and tier.demotions == 3
        # consecutive-run match; a block covering exactly len(tokens)
        # is still promotable (served partially via COW after graft)
        assert (tier.match_prefix(toks[:8] + [99], 0)
                == [tuple(toks[:4]), tuple(toks[:8])])
        assert (tier.match_prefix(toks[:8], 0)
                == [tuple(toks[:4]), tuple(toks[:8])])
        assert tier.match_prefix(toks[:7], 0) == [tuple(toks[:4])]
        assert tier.match_prefix([99] + toks[1:8], 0) == []
        # promotion consumes the entry; roundtrip bytes identical
        hb = tier.pop_prefix(tuple(toks[:4]))
        k, v, ks, vs = tier.read_block(hb)
        assert np.array_equal(k, blk(9)) and ks is None
        tier.release(hb)
        # lane save fits by spending the free list
        payload = [(blk(7), blk(-7), None, None)] * 2
        assert tier.save_lane("r1", 8, payload)
        assert tier.blocks_in_use == 3
        # all-or-nothing: evicting every prefix entry still isn't
        # enough room, so nothing is kept
        assert not tier.save_lane("r2", 16, [payload[0]] * 4)
        assert tier.peek_lane("r2") is None
        assert tier.blocks_in_use == 2          # just r1's pinned image
        img = tier.take_lane("r1")
        assert img.n_tokens == 8 and tier.peek_lane("r1") is None
        for h in img.hbs:
            tier.release(h)
        with pytest.raises(ValueError, match="over-released"):
            tier.release(img.hbs[0])
        assert not tier.drop_lane("r1")         # idempotent
        assert tier.blocks_in_use == 0

    def test_pinned_match_survives_midswap_spill(self):
        """Regression: between match_prefix and pop_prefix the engine
        allocates device blocks, and that reclaim path can spill NEW
        victims into the arena — with the arena full, store_prefix
        making room must not LRU-evict the pinned match (that used to
        KeyError pop_prefix and crash the engine under exactly the
        device-dry + arena-full pressure the tier serves).  Unpinned
        entries stay fair victims, and a pop that lost the race
        returns None (degrade to recompute) instead of raising."""
        L, bs, kvh, hd = 2, 4, 2, 8
        bpb = 2 * L * bs * kvh * hd * 4
        tier = HostKVTier(L, bs, kvh, hd, np.float32,
                          budget_bytes=2 * bpb, bytes_per_block=bpb)

        def blk(x):
            return np.full((L, bs, kvh, hd), x, np.float32)

        toks = list(range(8))
        assert tier.store_prefix(tuple(toks[:4]), blk(1), blk(-1))
        assert tier.store_prefix(tuple(toks[:8]), blk(2), blk(-2))
        paths = tier.match_prefix(toks, 0)
        assert len(paths) == 2
        tier.pin_prefix(paths)
        # the mid-swap spill finds everything pinned: refused (counted
        # as a dropped demotion), the matched entries stay resident
        assert not tier.store_prefix((9, 9, 9, 9), blk(3), blk(-3))
        assert tier.demotions_dropped == 1
        for p in paths:
            hb = tier.pop_prefix(p)
            assert hb is not None
            tier.release(hb)
        tier.unpin_prefix(paths)                # no-op after the pops
        assert tier.blocks_in_use == 0
        # an UNPINNED matched entry can still lose the race to later
        # spills; the pop then reports None instead of raising
        assert tier.store_prefix(tuple(toks[:4]), blk(4), blk(-4))
        stale = tier.match_prefix(toks[:5], 0)
        assert stale == [tuple(toks[:4])]
        assert tier.store_prefix((7, 7, 7, 7), blk(5), blk(-5))
        assert tier.store_prefix((6, 6, 6, 6), blk(6), blk(-6))
        assert tier.prefix_evictions == 1       # the stale match
        assert tier.pop_prefix(stale[0]) is None
        # mixed arena: the pinned entry is skipped, the unpinned
        # sibling is the victim
        tier.pin_prefix([(7, 7, 7, 7)])
        assert tier.store_prefix((5, 5, 5, 5), blk(7), blk(-7))
        assert tier.pop_prefix((6, 6, 6, 6)) is None
        hb = tier.pop_prefix((7, 7, 7, 7))
        assert hb is not None
        tier.release(hb)
        tier.unpin_prefix([(7, 7, 7, 7)])

    def test_bulk_reclaim_batches_demotion_copies(self):
        """A bulk radix reclaim demotes ALL its victims through ONE
        spill_batch pass — one gather + device_get per reclaim pass,
        not one synchronous device round-trip per block on the
        admission hot path."""
        m = _model()
        eng = Engine(m, self._cfg(), register_profiler=False)
        assert eng.prefix.spill_batch == eng._demote_blocks
        eng.generate([5, 5, 7, 7, 1, 2, 3, 4, 9, 8, 7, 6],
                     SamplingParams(max_new_tokens=4))
        held = eng.prefix._held
        assert held > 1
        calls = []
        orig = eng._fetch_blocks
        eng._fetch_blocks = (
            lambda bids: calls.append(list(bids)) or orig(bids))
        try:
            assert eng.prefix.reclaim(held) == held
        finally:
            eng._fetch_blocks = orig
        assert len(calls) == 1 and len(calls[0]) > 1
        st = eng.stats()
        assert st["prefix"]["evictions_demoted"] >= len(calls[0])
        assert (st["kv_pool"]["host_tier"]["demotions"]
                >= len(calls[0]))
        eng.drain()
        assert eng.stats()["kv_pool"]["host_blocks_in_use"] == 0
        eng.close()
