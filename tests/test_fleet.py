"""Fleet facade tests: init → distributed_model → distributed_optimizer
drives an end-to-end hybrid step (SURVEY.md §3.3 call stack)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc, PipelineLayer, PipelineParallel,
)


class TestDistributedStrategy:
    def test_defaults_and_update_semantics(self):
        s = DistributedStrategy()
        assert s.hybrid_configs["mp_degree"] == 1
        s.hybrid_configs = {"mp_degree": 2, "pp_degree": 2}
        # update-in-place: unspecified keys keep defaults (reference behavior)
        assert s.hybrid_configs["mp_degree"] == 2
        assert s.hybrid_configs["sharding_degree"] == 1
        assert s.hybrid_degrees(8) == {"dp": 2, "mp": 2, "pp": 2,
                                       "sharding": 1, "sep": 1}

    def test_rejects_unknown_keys_and_bad_degrees(self):
        s = DistributedStrategy()
        with pytest.raises(ValueError, match="unknown"):
            s.hybrid_configs = {"dp_degreee": 2}
        s.hybrid_configs = {"mp_degree": 3}
        with pytest.raises(ValueError, match="not divisible"):
            s.hybrid_degrees(8)

    def test_amp_pipeline_configs(self):
        s = DistributedStrategy()
        s.amp = True
        s.amp_configs = {"init_loss_scaling": 1024.0}
        assert s.amp_configs["init_loss_scaling"] == 1024.0
        assert s.amp_configs["incr_ratio"] == 2.0
        s.pipeline_configs = {"accumulate_steps": 4}
        assert s.pipeline_configs["accumulate_steps"] == 4
        assert "amp" in repr(s)


class TestFleetInit:
    def test_init_builds_mesh(self):
        dist.set_hybrid_communicate_group(None)
        s = DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
        fleet.init(is_collective=True, strategy=s)
        hcg = fleet.fleet.get_hybrid_communicate_group()
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_pipe_parallel_world_size() == 2
        assert fleet.worker_num() == 8
        assert fleet.is_first_worker()

    def test_distributed_model_dispatch(self):
        dist.set_hybrid_communicate_group(None)
        s = DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
        fleet.init(strategy=s)
        m = nn.Linear(4, 4)
        dm = fleet.distributed_model(m)
        assert type(dm).__name__ == "TensorParallel"

        dist.set_hybrid_communicate_group(None)
        s2 = DistributedStrategy()
        fleet.init(strategy=s2)
        dm2 = fleet.distributed_model(nn.Linear(4, 4))
        assert type(dm2).__name__ == "DataParallel"

    def test_pipeline_model_end_to_end(self):
        dist.set_hybrid_communicate_group(None)
        s = DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 2, "pp_degree": 4}
        s.pipeline_configs = {"accumulate_steps": 4}
        fleet.init(strategy=s)
        paddle.seed(3)
        model = PipelineLayer(
            [LayerDesc(nn.Linear, 8, 16)] +
            [LayerDesc(nn.Linear, 16, 16) for _ in range(6)] +
            [LayerDesc(nn.Linear, 16, 4)],
            loss_fn=nn.functional.mse_loss)
        dm = fleet.distributed_model(model)
        assert isinstance(dm, PipelineParallel)
        assert dm.accumulate_steps == 4
        opt = fleet.distributed_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-3,
                                   parameters=model.parameters()))
        rng = np.random.RandomState(0)
        x = rng.randn(16, 8).astype(np.float32)
        y = rng.randn(16, 4).astype(np.float32)
        l0 = float(dm.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt))
        for _ in range(4):
            l = float(dm.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt))
        assert l < l0


class TestHybridParallelOptimizer:
    def test_wraps_and_steps(self):
        dist.set_hybrid_communicate_group(None)
        fleet.init(strategy=DistributedStrategy())
        m = nn.Linear(4, 2)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters()))
        x = paddle.randn([8, 4])
        loss = m(x).sum()
        w0 = np.asarray(m.weight._data).copy()
        loss.backward()
        opt.step()
        opt.clear_grad()
        assert not np.allclose(np.asarray(m.weight._data), w0)
        assert opt.get_lr() == 0.1  # __getattr__ passthrough
