"""Model-zoo tests: shapes, gradients, decode parity, recompute parity."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models import (
    GPTConfig, GPTForCausalLM, BertConfig, BertForMaskedLM, UNetConfig,
    UNet2DConditionModel,
)

TINY_GPT = GPTConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     max_position_embeddings=64)


class TestGPT:
    def test_loss_and_grads(self):
        m = GPTForCausalLM(TINY_GPT)
        ids = paddle.randint(0, 128, [2, 16])
        loss, logits = m(ids, labels=ids)
        assert logits.shape == [2, 16, 128]
        loss.backward()
        assert m.model.layers[0].self_attn.q_proj.weight.grad is not None
        assert m.model.embed_tokens.weight.grad is not None

    def test_causality(self):
        m = GPTForCausalLM(TINY_GPT)
        m.eval()
        ids = paddle.randint(0, 128, [1, 8])
        logits1 = m(ids)
        ids2 = ids.numpy().copy()
        ids2[0, -1] = (ids2[0, -1] + 1) % 128  # change last token
        logits2 = m(paddle.to_tensor(ids2))
        # positions < 7 unaffected
        np.testing.assert_allclose(logits1.numpy()[0, :7], logits2.numpy()[0, :7], atol=1e-4)

    def test_cached_decode_matches_full_forward(self):
        m = GPTForCausalLM(TINY_GPT)
        ids = paddle.randint(0, 128, [2, 6])
        gen = m.generate(ids, max_new_tokens=2, temperature=0)
        # last generated token must equal argmax of full forward on the prefix
        full = m(gen[:, :-1])
        nxt = paddle.argmax(full[:, -1], axis=-1)
        np.testing.assert_array_equal(gen.numpy()[:, -1], nxt.numpy())

    def test_gqa(self):
        cfg = GPTConfig(vocab_size=64, hidden_size=64, intermediate_size=128,
                        num_hidden_layers=1, num_attention_heads=8,
                        num_key_value_heads=2, max_position_embeddings=32)
        m = GPTForCausalLM(cfg)
        out = m(paddle.randint(0, 64, [1, 8]))
        assert out.shape == [1, 8, 64]

    def test_recompute_parity(self):
        paddle.seed(11)
        m1 = GPTForCausalLM(TINY_GPT)
        sd = m1.state_dict()
        cfg2 = GPTConfig(**{**TINY_GPT.__dict__, "use_recompute": True})
        m2 = GPTForCausalLM(cfg2)
        m2.set_state_dict(sd)
        ids = paddle.randint(0, 128, [2, 8])
        l1, _ = m1(ids, labels=ids)
        l2, _ = m2(ids, labels=ids)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
        l1.backward()
        l2.backward()
        g1 = m1.model.layers[0].mlp.gate_proj.weight.grad.numpy()
        g2 = m2.model.layers[0].mlp.gate_proj.weight.grad.numpy()
        np.testing.assert_allclose(g1, g2, rtol=1e-4, atol=1e-6)


class TestBert:
    def test_mlm_loss(self):
        cfg = BertConfig(vocab_size=100, hidden_size=32, num_hidden_layers=2,
                         num_attention_heads=2, intermediate_size=64,
                         max_position_embeddings=64)
        m = BertForMaskedLM(cfg)
        ids = paddle.randint(0, 100, [2, 10])
        labels = ids.numpy().copy()
        labels[:, ::2] = -100  # only score odd positions
        loss, logits = m(ids, labels=paddle.to_tensor(labels))
        assert logits.shape == [2, 10, 100]
        loss.backward()
        assert m.bert.embeddings.word_embeddings.weight.grad is not None

    def test_attention_mask(self):
        cfg = BertConfig(vocab_size=50, hidden_size=32, num_hidden_layers=1,
                         num_attention_heads=2, intermediate_size=64,
                         max_position_embeddings=32)
        m = BertForMaskedLM(cfg)
        m.eval()
        ids = paddle.randint(0, 50, [1, 6])
        mask = paddle.to_tensor(np.array([[1, 1, 1, 0, 0, 0]], np.float32))
        out1 = m(ids, attention_mask=mask)
        ids2 = ids.numpy().copy()
        ids2[0, 4] = (ids2[0, 4] + 7) % 50  # masked-out position changed
        out2 = m(paddle.to_tensor(ids2), attention_mask=mask)
        np.testing.assert_allclose(out1.numpy()[0, :3], out2.numpy()[0, :3], atol=1e-4)


class TestUNet:
    def test_shapes_and_grad(self):
        cfg = UNetConfig(block_out_channels=(16, 32), layers_per_block=1,
                         cross_attention_dim=16, attention_head_dim=2,
                         norm_num_groups=4, in_channels=4, out_channels=4)
        m = UNet2DConditionModel(cfg)
        lat = paddle.randn([2, 4, 8, 8])
        t = paddle.to_tensor([1, 2])
        ctx = paddle.randn([2, 3, 16])
        out = m(lat, t, ctx)
        assert out.shape == [2, 4, 8, 8]
        (out ** 2).mean().backward()
        assert m.conv_in.weight.grad is not None

    def test_conditioning_matters(self):
        cfg = UNetConfig(block_out_channels=(16, 32), layers_per_block=1,
                         cross_attention_dim=16, attention_head_dim=2,
                         norm_num_groups=4)
        m = UNet2DConditionModel(cfg)
        m.eval()
        lat = paddle.randn([1, 4, 8, 8])
        t = paddle.to_tensor([5])
        o1 = m(lat, t, paddle.randn([1, 3, 16]))
        o2 = m(lat, t, paddle.randn([1, 3, 16]))
        assert not np.allclose(o1.numpy(), o2.numpy())


class TestVision:
    def test_resnet50_shape(self):
        m = paddle.vision.models.resnet50(num_classes=10)
        out = m(paddle.randn([1, 3, 64, 64]))
        assert out.shape == [1, 10]

    def test_resnet18_trains(self):
        m = paddle.vision.models.resnet18(num_classes=4)
        opt = paddle.optimizer.Momentum(learning_rate=0.01, parameters=m.parameters())
        x = paddle.randn([2, 3, 32, 32])
        y = paddle.to_tensor([0, 1])
        loss = nn.functional.cross_entropy(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        assert np.isfinite(float(loss))

    def test_mobilenet_lenet(self):
        m = paddle.vision.models.mobilenet_v2(num_classes=7, scale=0.35)
        assert m(paddle.randn([1, 3, 32, 32])).shape == [1, 7]
        l = paddle.vision.models.LeNet()
        assert l(paddle.randn([1, 1, 28, 28])).shape == [1, 10]

    def test_transforms(self):
        from paddle_tpu.vision import transforms as T

        img = (np.random.rand(32, 32, 3) * 255).astype(np.uint8)
        pipeline = T.Compose([T.Resize(16), T.CenterCrop(8), T.ToTensor(),
                              T.Normalize(mean=[0.5] * 3, std=[0.5] * 3)])
        out = pipeline(img)
        assert out.shape == [3, 8, 8]
        assert float(out.numpy().max()) <= 1.0 + 1e-6
