"""Grammar-compiler unit tests: regex -> char DFA (corner syntax,
minimization, full-match semantics), JSON-schema lowering, the char-DFA
x vocab crossproduct (multi-char token walks, EOS-iff-accepting,
dense-vs-bitmask equivalence, REJECT unreachability), and the
GrammarSlab lifecycle.  Pure host-side compiler machinery — engine-level
structured-decoding acceptance lives in test_structured.py."""

import numpy as np
import pytest

from paddle_tpu.serving import (
    GrammarError, GrammarSlab, GrammarSpec, compile_grammar,
    compile_regex, schema_to_regex,
)
from paddle_tpu.serving.structured.grammar import REJECT, as_grammar_spec


def make_vocab(size=128, eos_id=95):
    """Printable-ASCII single chars (ids 0..94), <eos> at 95, then a
    handful of multi-char tokens exercising tokenizer boundaries."""
    vocab = [chr(32 + i) for i in range(95)]
    vocab.append("<eos>")
    vocab.extend(['{"', '":', '",', '"}', 'true', 'false', 'null',
                  '": "', '", "', 'ab', 'abc', '0', '12'])
    while len(vocab) < size:
        vocab.append(f"<unused{len(vocab)}>")
    return vocab


VOCAB = make_vocab()
EOS = 95
SCHEMA = {"type": "object",
          "properties": {"a": {"enum": ["x", "y"]},
                         "b": {"type": "boolean"}},
          "required": ["a", "b"]}


class TestRegexCompiler:
    """compile_regex corners: the char-DFA must implement full-match
    semantics over the supported dialect and 400 the rest by name."""

    def test_literal_and_alternation(self):
        d = compile_regex("ab|cd")
        assert d.matches("ab") and d.matches("cd")
        assert not d.matches("a") and not d.matches("abcd")
        assert not d.matches("")

    def test_bounded_repetition(self):
        d = compile_regex("a{2,4}")
        assert [d.matches("a" * n) for n in range(6)] == \
            [False, False, True, True, True, False]
        assert compile_regex("a{3}").matches("aaa")
        assert not compile_regex("a{3}").matches("aa")
        d = compile_regex("a{2,}")
        assert not d.matches("a") and d.matches("a" * 7)

    def test_char_classes_and_escapes(self):
        d = compile_regex(r"[a-c]+[0-9]?")
        assert d.matches("abc") and d.matches("cab7")
        assert not d.matches("7") and not d.matches("abd")
        assert compile_regex(r"[^x]").matches("y")
        assert not compile_regex(r"[^x]").matches("x")
        assert compile_regex(r"\d+").matches("42")
        assert not compile_regex(r"\d+").matches("4a")
        assert compile_regex(r"\w+\s\w+").matches("ab cd")

    def test_star_plus_optional_dot(self):
        assert compile_regex("(ab)*").matches("")
        assert compile_regex("(ab)*").matches("ababab")
        assert not compile_regex("(ab)*").matches("aba")
        assert compile_regex("a+").matches("aaa")
        assert not compile_regex("a+").matches("")
        assert compile_regex("a?b").matches("b")
        d = compile_regex("a.c")
        assert d.matches("abc") and d.matches("a.c")
        assert not d.matches("ac")

    def test_minimization_merges_equivalent_states(self):
        # "a|a" and "a" must land on the same minimized machine
        assert compile_regex("a|a").n_states == compile_regex("a").n_states

    def test_nullable_repetition(self):
        """Star/plus over a nullable body ("(a*)*", "()") must produce
        the one-state accept machine, not crash minimization."""
        for pat in ("(a*)*", "(a?)+", "(a|)*", "a**"):
            d = compile_regex(pat)
            assert d.n_states == 1
            assert d.matches("") and d.matches("aaa")
        d = compile_regex("()")
        assert d.matches("") and not d.matches("a")

    def test_unsupported_constructs_raise_by_name(self):
        for pat in ("(?=a)", "(a", "[a", "a{4,2}", "*a", "a{,3}"):
            with pytest.raises(GrammarError, match="regex"):
                compile_regex(pat)

    def test_hex_escapes_wellformed_and_truncated(self):
        assert compile_regex(r"\x41B").matches("AB")
        assert compile_regex(r"[\x41-\x43]").matches("B")
        # truncated/decorated escapes must raise, not silently parse as
        # a shorter codepoint (int('4', 16) and int('+4', 16) succeed)
        for pat in (r"a\x4", r"\u12", r"\x", r"\x4g", r"\u004g",
                    r"\x+4", r"[\x4]", r"[\u123]", r"[a-\x4]"):
            with pytest.raises(GrammarError, match="malformed"):
                compile_regex(pat)


class TestSchemaLowering:
    """JSON-schema subset -> regex: the lowered language must contain
    the valid instances and exclude the malformed ones."""

    def _dfa(self, schema):
        return compile_regex(schema_to_regex(schema))

    def test_object_required_and_types(self):
        d = self._dfa(SCHEMA)
        assert d.matches('{"a":"x","b":true}')
        assert d.matches('{"a":"y","b":false}')
        assert not d.matches('{"a":"z","b":true}')      # enum violation
        assert not d.matches('{"b":true}')              # missing required
        assert not d.matches('{"a":"x","b":true')       # unterminated

    def test_optional_property(self):
        schema = {"type": "object",
                  "properties": {"a": {"type": "boolean"},
                                 "b": {"type": "null"}},
                  "required": ["a"]}
        d = self._dfa(schema)
        assert d.matches('{"a":true}')
        assert d.matches('{"a":false,"b":null}')
        assert not d.matches('{"b":null}')

    def test_top_level_enum_and_const(self):
        d = self._dfa({"enum": [1, "x", True]})
        assert d.matches("1") and d.matches('"x"') and d.matches("true")
        assert not d.matches('"y"') and not d.matches("2")

    def test_nested_arrays(self):
        schema = {"type": "array",
                  "items": {"type": "array",
                            "items": {"type": "integer"}}}
        d = self._dfa(schema)
        assert d.matches("[]") and d.matches("[[1,2],[-3]]")
        assert not d.matches("[[1,]]") and not d.matches("[1]")

    def test_scalar_types(self):
        assert self._dfa({"type": "integer"}).matches("-12")
        assert not self._dfa({"type": "integer"}).matches("01")
        assert self._dfa({"type": "number"}).matches("3.5e-2")
        assert self._dfa({"type": "string"}).matches('"hi"')
        assert not self._dfa({"type": "string"}).matches('"a')
        assert self._dfa({"type": "boolean"}).matches("false")
        assert self._dfa({"type": "null"}).matches("null")

    def test_unsupported_features_named_in_error(self):
        for key in ("anyOf", "$ref", "patternProperties", "minimum"):
            with pytest.raises(GrammarError, match=key.replace("$", "\\$")):
                schema_to_regex({key: []})

    def test_grammar_spec_validates_eagerly(self):
        with pytest.raises(GrammarError):
            GrammarSpec.regex("(a")
        with pytest.raises(GrammarError):
            GrammarSpec.json_schema({"anyOf": []})
        spec = as_grammar_spec(SCHEMA)
        assert spec.kind == "json_schema"
        assert as_grammar_spec(spec) is spec
        assert as_grammar_spec("a+").kind == "regex"
        with pytest.raises(GrammarError):
            as_grammar_spec(17)


# --------------------------------------------------- vocab crossproduct
class TestTokenDFA:
    """char DFA x vocab: multi-char token walks, EOS-iff-accepting,
    dense-vs-bitmask equivalence, REJECT unreachability."""

    SMALL_VOCAB = ["a", "b", "c", "ab", "x", "", "<eos>"]
    SMALL_EOS = 6

    def _dfa(self, pattern="ab*c"):
        return compile_grammar(pattern, self.SMALL_VOCAB, self.SMALL_EOS)

    def test_multichar_token_boundaries(self):
        d = self._dfa()
        # from the start of "ab*c": 'a' and the multi-char 'ab' both
        # begin a match, 'b'/'c'/'x' do not
        assert d.allows(0, 0) and d.allows(0, 3)
        assert not d.allows(0, 1) and not d.allows(0, 2)
        assert not d.allows(0, 4)
        # after 'ab' the walk sits mid-repetition: 'b' and 'c' legal
        s = d.step(0, 3)
        assert d.allows(s, 1) and d.allows(s, 2)

    def test_empty_token_never_legal(self):
        d = self._dfa()
        assert not any(d.allows(s, 5) for s in range(d.n_states))

    def test_ids_beyond_vocab_illegal(self):
        d = compile_grammar("a+", self.SMALL_VOCAB, self.SMALL_EOS,
                            vocab_size=16)
        assert d.vocab_size == 16
        assert not any(d.allows(s, t) for s in range(d.n_states)
                       for t in range(len(self.SMALL_VOCAB), 16))

    def test_eos_legal_iff_accepting_and_self_loops(self):
        d = self._dfa()
        assert d.accepting.any() and not d.accepting.all()
        for s in range(d.n_states):
            assert d.allows(s, self.SMALL_EOS) == bool(d.accepting[s])
            if d.accepting[s]:
                assert d.step(s, self.SMALL_EOS) == s

    def test_dense_vs_bitmask_equivalence(self):
        for grammar in ("ab*c", SCHEMA):
            vocab = self.SMALL_VOCAB if grammar == "ab*c" else VOCAB
            eos = self.SMALL_EOS if grammar == "ab*c" else EOS
            d = compile_grammar(grammar, vocab, eos)
            unpacked = np.unpackbits(
                d.mask.view(np.uint8), bitorder="little",
            ).reshape(d.n_states, -1)[:, :d.vocab_size].astype(bool)
            np.testing.assert_array_equal(unpacked, d.next_state >= 0)
            np.testing.assert_array_equal(
                d.popcount, unpacked.sum(axis=1))

    def test_forced_iff_popcount_one(self):
        d = compile_grammar(SCHEMA, VOCAB, EOS)
        for s in range(d.n_states):
            if d.popcount[s] == 1:
                assert d.forced[s] >= 0 and d.allows(s, int(d.forced[s]))
            else:
                assert d.forced[s] == REJECT
        # a JSON-skeleton grammar has forced punctuation states
        assert (d.forced >= 0).any()

    def test_reject_states_unreachable_via_legal_tokens(self):
        d = compile_grammar(SCHEMA, VOCAB, EOS)
        seen, stack = {0}, [0]
        while stack:
            s = stack.pop()
            assert d.popcount[s] > 0          # no lane can strand
            for t in range(d.vocab_size):
                if d.allows(s, t):
                    nxt = d.step(s, t)
                    assert nxt != REJECT
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)

    def test_inexpressible_grammar_raises(self):
        with pytest.raises(GrammarError, match="cannot express"):
            compile_grammar("z+", self.SMALL_VOCAB, self.SMALL_EOS)
        with pytest.raises(GrammarError, match="eos_id"):
            compile_grammar("a", self.SMALL_VOCAB, 99)


class TestGrammarSlab:
    """Fixed-capacity device-table master: sentinel row 0, refcounted
    segments, exhaustion."""

    def _dfa(self, pattern="ab*c"):
        return compile_grammar(pattern, TestTokenDFA.SMALL_VOCAB,
                               TestTokenDFA.SMALL_EOS)

    def test_sentinel_row_accepts_everything(self):
        slab = GrammarSlab(16, 7)
        assert slab.popcount[0] == 7
        unpacked = np.unpackbits(slab.mask[0:1].view(np.uint8),
                                 bitorder="little")[:7]
        assert unpacked.all()
        assert (slab.next[0] == 0).all()      # self-loop on row 0
        with pytest.raises(ValueError, match=">= 2"):
            GrammarSlab(1, 7)

    def test_install_is_refcounted(self):
        slab = GrammarSlab(64, 7)
        dfa = self._dfa()
        off = slab.install("k", dfa)
        assert off >= 1 and slab.grammars_installed == 1
        used = slab.states_used
        assert slab.install("k", dfa) == off       # re-reference
        assert slab.states_used == used
        slab.release("k")
        assert slab.grammars_installed == 1        # one ref left
        slab.release("k")
        assert slab.grammars_installed == 0
        assert slab.states_used == 1
        slab.release("missing")                    # no-op

    def test_global_next_ids_and_reject_rows_point_at_sentinel(self):
        slab = GrammarSlab(64, 7)
        dfa = self._dfa()
        off = slab.install("k", dfa)
        rows = slab.next[off:off + dfa.n_states]
        assert rows.min() >= 0 and rows.max() < slab.capacity
        # REJECT entries store row 0: a rejected gather stays a valid
        # index; legality comes from the bitmask alone
        assert (rows[dfa.next_state == REJECT] == 0).all()
        legal = dfa.next_state >= 0
        np.testing.assert_array_equal(rows[legal],
                                      dfa.next_state[legal] + off)

    def test_two_grammars_disjoint_and_exhaustion(self):
        d1, d2 = self._dfa("ab*c"), self._dfa("(a|b)c{2}")
        slab = GrammarSlab(d1.n_states + d2.n_states + 1, 7)
        o1 = slab.install("g1", d1)
        o2 = slab.install("g2", d2)
        r1 = set(range(o1, o1 + d1.n_states))
        r2 = set(range(o2, o2 + d2.n_states))
        assert not (r1 & r2) and 0 not in (r1 | r2)
        with pytest.raises(RuntimeError, match="exhausted"):
            slab.install("g3", self._dfa("a{2,9}b"))
        # releasing one frees its rows for reuse
        slab.release("g1")
        assert slab.install("g3", d1) >= 1

