"""Cross-check the round-2 loss functionals against torch.nn.functional on
random inputs — an independent reference implementation (the in-repo OpTests
use hand-rolled NumPy formulas; torch catches formula-level mistakes both
might share)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")
TF = torch.nn.functional


def _t(x):
    return paddle.to_tensor(x)


def _pt(x):
    return torch.tensor(x)


@pytest.fixture()
def rng():
    # fresh seeded stream per test: inputs don't depend on test order, so a
    # failing case reproduces in isolation
    return np.random.RandomState(0)


class TestTorchCrossCheck:
    @pytest.mark.parametrize("reduction", ["mean", "sum"])
    def test_gaussian_nll(self, rng, reduction):
        mu = rng.randn(6, 3).astype(np.float32)
        y = rng.randn(6, 3).astype(np.float32)
        var = (rng.rand(6, 3).astype(np.float32) + 0.1)
        ours = float(F.gaussian_nll_loss(_t(mu), _t(y), _t(var),
                                         reduction=reduction))
        ref = float(TF.gaussian_nll_loss(_pt(mu), _pt(y), _pt(var),
                                         reduction=reduction, eps=1e-6))
        np.testing.assert_allclose(ours, ref, rtol=1e-5)

    @pytest.mark.parametrize("log_input,full", [(True, False), (False, False),
                                                (True, True)])
    def test_poisson_nll(self, rng, log_input, full):
        x = rng.rand(8).astype(np.float32) + 0.2
        y = rng.randint(0, 5, 8).astype(np.float32)
        ours = float(F.poisson_nll_loss(_t(x), _t(y), log_input=log_input,
                                        full=full))
        ref = float(TF.poisson_nll_loss(_pt(x), _pt(y), log_input=log_input,
                                        full=full))
        np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)

    def test_soft_margin(self, rng):
        x = rng.randn(10).astype(np.float32) * 3
        y = np.sign(rng.randn(10)).astype(np.float32)
        ours = float(F.soft_margin_loss(_t(x), _t(y)))
        ref = float(TF.soft_margin_loss(_pt(x), _pt(y)))
        np.testing.assert_allclose(ours, ref, rtol=1e-5)

    def test_multilabel_soft_margin(self, rng):
        x = rng.randn(4, 5).astype(np.float32)
        y = rng.randint(0, 2, (4, 5)).astype(np.float32)
        ours = float(F.multi_label_soft_margin_loss(_t(x), _t(y)))
        ref = float(TF.multilabel_soft_margin_loss(_pt(x), _pt(y)))
        np.testing.assert_allclose(ours, ref, rtol=1e-5)

    @pytest.mark.parametrize("p,margin", [(1, 1.0), (2, 0.5)])
    def test_multi_margin(self, rng, p, margin):
        x = rng.randn(6, 4).astype(np.float32)
        y = rng.randint(0, 4, 6).astype(np.int64)
        ours = float(F.multi_margin_loss(_t(x), _t(y), p=p, margin=margin))
        ref = float(TF.multi_margin_loss(_pt(x), _pt(y), p=p, margin=margin))
        np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)

    def test_triplet_with_distance(self, rng):
        a = rng.randn(5, 8).astype(np.float32)
        pos = rng.randn(5, 8).astype(np.float32)
        neg = rng.randn(5, 8).astype(np.float32)
        ours = float(F.triplet_margin_with_distance_loss(
            _t(a), _t(pos), _t(neg),
            distance_function=lambda u, v: F.pairwise_distance(u, v)))
        ref = float(TF.triplet_margin_with_distance_loss(
            _pt(a), _pt(pos), _pt(neg),
            distance_function=lambda u, v: TF.pairwise_distance(u, v)))
        np.testing.assert_allclose(ours, ref, rtol=1e-4)

    def test_pairwise_distance(self, rng):
        a = rng.randn(7, 5).astype(np.float32)
        b = rng.randn(7, 5).astype(np.float32)
        ours = F.pairwise_distance(_t(a), _t(b)).numpy()
        ref = TF.pairwise_distance(_pt(a), _pt(b)).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-5)

    def test_max_unpool2d_roundtrip_vs_torch(self, rng):
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        ours_out, ours_idx = F.max_pool2d(_t(x), 2, stride=2,
                                          return_mask=True)
        t_out, t_idx = TF.max_pool2d(_pt(x), 2, stride=2,
                                     return_indices=True)
        np.testing.assert_allclose(ours_out.numpy(), t_out.numpy())
        np.testing.assert_array_equal(ours_idx.numpy(), t_idx.numpy())
        ours_un = F.max_unpool2d(ours_out, ours_idx, 2, stride=2)
        t_un = TF.max_unpool2d(t_out, t_idx, 2, stride=2)
        np.testing.assert_allclose(ours_un.numpy(), t_un.numpy())

    def test_logit_and_polygamma(self, rng):
        p = rng.rand(9).astype(np.float32) * 0.98 + 0.01
        np.testing.assert_allclose(paddle.logit(_t(p)).numpy(),
                                   torch.logit(_pt(p)).numpy(), rtol=1e-5)
        x = rng.rand(5).astype(np.float32) * 3 + 0.5
        np.testing.assert_allclose(
            paddle.polygamma(_t(x), 1).numpy(),
            torch.polygamma(1, _pt(x)).numpy(), rtol=1e-4)

    def test_nadam_radam_trajectories_vs_torch(self, rng):
        """Full 20-step optimizer trajectory parity on a quadratic."""
        for ours_ctor, torch_ctor in [
            (lambda ps: paddle.optimizer.NAdam(learning_rate=0.05,
                                               parameters=ps),
             lambda ps: torch.optim.NAdam(ps, lr=0.05)),
            (lambda ps: paddle.optimizer.RAdam(learning_rate=0.05,
                                               parameters=ps),
             lambda ps: torch.optim.RAdam(ps, lr=0.05)),
        ]:
            p0 = np.array([3.0, -2.0, 0.5], np.float32)
            p_ours = paddle.Parameter(p0.copy())
            opt_ours = ours_ctor([p_ours])
            p_t = torch.tensor(p0.copy(), requires_grad=True)
            opt_t = torch_ctor([p_t])
            for _ in range(20):
                loss = (p_ours * p_ours).sum()
                loss.backward()
                opt_ours.step()
                opt_ours.clear_grad()
                opt_t.zero_grad()
                (p_t * p_t).sum().backward()
                opt_t.step()
            # per-step agreement is ~1e-5 (verified); 20 steps of f32
            # accumulation (incl. RAdam's rectification switch-on) compound
            np.testing.assert_allclose(p_ours.numpy(),
                                       p_t.detach().numpy(),
                                       rtol=2e-2, atol=1e-3)
