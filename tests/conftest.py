"""Test harness: force an 8-device virtual CPU platform (SURVEY.md §4 —
single-process SPMD tests replace the reference's multi-GPU subprocess
pattern).

NOTE: the axon sitecustomize imports jax and pins jax_platforms to
"axon,cpu" at interpreter start; we must (a) add the host-device-count XLA
flag before the CPU backend initializes and (b) re-pin jax_platforms to cpu
so tests never touch the TPU tunnel.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy end-to-end parity tests excluded from the tier-1 "
        "run (-m 'not slow'); the dedicated CI serving jobs run them "
        "without the filter",
    )


@pytest.fixture(autouse=True)
def _fresh_seed():
    import paddle_tpu as paddle

    paddle.seed(2024)
    yield
