"""Structured (grammar-constrained) decoding: engine-level
acceptance — constrained greedy output is ALWAYS grammar-valid,
batched-vs-sequential and K=0-vs-K=4 streams are bitwise-equal (greedy
AND seeded), forced-token drafting beats plain n-gram drafting on a
JSON workload, and the knobs-off engine threads ``None`` for every
grammar argument.  Compiler-level unit tests (regex -> char DFA ->
token DFA, schema lowering, GrammarSlab) live in test_grammar_dfa.py."""

import json
import types

import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (
    Engine, EngineConfig, GrammarError, SamplingParams, compile_regex,
)

TINY = GPTConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 max_position_embeddings=128)


def _model(seed=0):
    paddle.seed(seed)
    m = GPTForCausalLM(TINY)
    m.eval()
    return m


def make_vocab(size=128, eos_id=95):
    """Printable-ASCII single chars (ids 0..94), <eos> at 95, then a
    handful of multi-char tokens exercising tokenizer boundaries."""
    vocab = [chr(32 + i) for i in range(95)]
    vocab.append("<eos>")
    vocab.extend(['{"', '":', '",', '"}', 'true', 'false', 'null',
                  '": "', '", "', 'ab', 'abc', '0', '12'])
    while len(vocab) < size:
        vocab.append(f"<unused{len(vocab)}>")
    return vocab


VOCAB = make_vocab()
EOS = 95
SCHEMA = {"type": "object",
          "properties": {"a": {"enum": ["x", "y"]},
                         "b": {"type": "boolean"}},
          "required": ["a", "b"]}

GREEDY = SamplingParams(max_new_tokens=48, eos_token_id=EOS)
SEEDED = SamplingParams(temperature=0.9, top_k=20, seed=7,
                        max_new_tokens=48, eos_token_id=EOS)


def _cfg(**kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("max_horizon", 4)
    kw.setdefault("prefix_block_size", 4)
    kw.setdefault("prefix_cache_bytes", 0)
    kw.setdefault("grammar_max_states", 256)
    kw.setdefault("grammar_vocab", VOCAB)
    return EngineConfig(**kw)


def _drive(eng):
    while eng.scheduler.has_work:
        eng.step()


def _text(req):
    return "".join(VOCAB[t] for t in req.output_ids if t != EOS)


# ------------------------------------------------------------ engine
class TestStructuredEngine:
    """Constrained decode through the fused horizon scan: validity,
    bitwise parity across batching and spec_k, forced drafting,
    knobs-off structure."""

    def test_constrained_greedy_is_schema_valid(self):
        m = _model()
        eng = Engine(m, _cfg(), register_profiler=False)
        req = eng.submit([3, 1, 4], sampling=GREEDY, grammar=SCHEMA)
        free = eng.submit([9, 2, 6],
                          sampling=SamplingParams(max_new_tokens=8))
        _drive(eng)
        obj = json.loads(_text(req))
        assert set(obj) == {"a", "b"}
        assert obj["a"] in ("x", "y") and isinstance(obj["b"], bool)
        assert req.output_ids[-1] == EOS and req.finish_reason == "eos"
        st = eng.stats()["structured"]
        assert st["enabled"] and st["grammars_installed"] == 0
        assert st["compile_cache_misses"] == 1
        eng.close()
        # the free lane is untouched by its constrained neighbour:
        # bitwise-equal to a solo run on an unconstrained engine
        solo = Engine(m, _cfg(), register_profiler=False)
        ref = solo.submit([9, 2, 6],
                          sampling=SamplingParams(max_new_tokens=8))
        _drive(solo)
        solo.close()
        assert free.output_ids == ref.output_ids

    def test_seeded_constrained_valid_and_deterministic(self):
        m = _model()
        outs = []
        for _ in range(2):
            eng = Engine(m, _cfg(), register_profiler=False)
            r = eng.submit([3, 1, 4], sampling=SEEDED, grammar=SCHEMA)
            _drive(eng)
            eng.close()
            json.loads(_text(r))                  # always schema-valid
            outs.append(r.output_ids)
        assert outs[0] == outs[1]

    def test_k4_bitwise_equals_k0_and_forces_tokens(self):
        """Speculative decode with forced-token drafting must not change
        a single emitted token — greedy AND seeded — while the JSON
        skeleton's forced states land as draft accepts."""
        m = _model()
        ref = {}
        for name, sp in (("greedy", GREEDY), ("seeded", SEEDED)):
            eng = Engine(m, _cfg(), register_profiler=False)
            r = eng.submit([3, 1, 4], sampling=sp, grammar=SCHEMA)
            _drive(eng)
            eng.close()
            ref[name] = r.output_ids
        eng = Engine(m, _cfg(spec_k=4), register_profiler=False)
        reqs = {name: eng.submit([3, 1, 4], sampling=sp, grammar=SCHEMA)
                for name, sp in (("greedy", GREEDY), ("seeded", SEEDED))}
        _drive(eng)
        for name, r in reqs.items():
            assert r.output_ids == ref[name], name
        st = eng.stats()["structured"]
        assert st["forced_tokens"] > 0
        assert eng.counters()["spec_forced_tokens"] == st["forced_tokens"]
        # flight records restate the counter per request
        traced = sum(r.trace.counts()["spec_forced_tokens"]
                     for r in reqs.values())
        assert traced == st["forced_tokens"]
        eng.close()

    def test_batched_vs_sequential_bitwise(self):
        """Two constrained lanes (seeded schema + greedy regex) batched
        together equal their solo runs token-for-token."""
        m = _model()
        eng = Engine(m, _cfg(), register_profiler=False)
        ra = eng.submit([3, 1, 4], sampling=SEEDED, grammar=SCHEMA)
        rb = eng.submit([9, 2, 6], sampling=GREEDY,
                        grammar="(ab|abc)*c")
        _drive(eng)
        eng.close()
        solo = []
        for prompt, sp, g in ([3, 1, 4], SEEDED, SCHEMA), \
                             ([9, 2, 6], GREEDY, "(ab|abc)*c"):
            e = Engine(m, _cfg(), register_profiler=False)
            r = e.submit(prompt, sampling=sp, grammar=g)
            _drive(e)
            e.close()
            solo.append(r.output_ids)
        assert [ra.output_ids, rb.output_ids] == solo
        json.loads(_text(ra))
        assert compile_regex("(ab|abc)*c").matches(_text(rb))

    def test_forced_drafting_beats_plain_ngram_on_json(self):
        """The acceptance bar: on a JSON workload, grammar-forced
        drafting's mean accept length >= the plain n-gram drafter's."""
        m = _model()
        accept = {}
        for forced in (True, False):
            eng = Engine(m, _cfg(spec_k=4, num_slots=2,
                                 grammar_forced_drafting=forced),
                         register_profiler=False)
            for p in ([3, 1, 4], [9, 2, 6]):
                eng.submit(p, sampling=GREEDY, grammar=SCHEMA)
            _drive(eng)
            accept[forced] = eng.stats()["spec"]["mean_accept_len"]
            eng.close()
        assert accept[True] >= accept[False]

    def test_slab_released_on_retire_and_abort(self):
        m = _model()
        eng = Engine(m, _cfg(num_slots=1), register_profiler=False)
        done = eng.submit([3, 1, 4], sampling=GREEDY, grammar=SCHEMA)
        queued = eng.submit([9, 2, 6], sampling=GREEDY, grammar=SCHEMA)
        assert eng.stats()["structured"]["grammars_installed"] == 1
        eng.abort(queued)                    # released from WAITING
        _drive(eng)
        assert done.finish_reason == "eos"
        st = eng.stats()["structured"]
        assert st["grammars_installed"] == 0 and st["states_used"] == 1
        assert st["compile_cache_hits"] == 1
        running = eng.submit([3, 1, 4], sampling=GREEDY, grammar=SCHEMA)
        eng.step()
        eng.abort(running)                   # released from RUNNING
        assert eng.stats()["structured"]["grammars_installed"] == 0
        assert eng.pool.blocks_in_use == 0
        eng.close()

    def test_slab_exhaustion_refused_before_queueing(self):
        """An over-capacity grammar raises at submit() with NOTHING
        queued — the engine keeps serving.  (Regression: install() used
        to run after scheduler.submit(), stranding a request with
        ``grammar`` set but no slab segment, and the next admission
        pass crashed the step loop for every request.)"""
        m = _model()
        eng = Engine(m, _cfg(grammar_max_states=8),
                     register_profiler=False)
        with pytest.raises(RuntimeError, match="slab exhausted"):
            eng.submit([3, 1, 4], sampling=GREEDY, grammar=SCHEMA)
        assert eng.scheduler.queue_depth == 0
        assert eng.stats()["structured"]["grammars_installed"] == 0
        # still healthy: a small grammar and a free lane decode fine
        r = eng.submit([3, 1, 4], sampling=GREEDY, grammar="a{2}")
        free = eng.submit([9, 2, 6],
                          sampling=SamplingParams(max_new_tokens=4))
        _drive(eng)
        assert _text(r) == "aa" and r.finish_reason == "eos"
        assert len(free.output_ids) == 4
        assert eng.stats()["structured"]["grammars_installed"] == 0
        eng.close()

    def test_compile_cache_bounded_lru(self):
        """A stream of unique gateway grammars cannot grow the host DFA
        cache without bound: retired entries trim to
        ``grammar_cache_keep`` LRU, a repeat inside the window is still
        a hit, and an evicted grammar recompiles."""
        m = _model()
        eng = Engine(m, _cfg(grammar_cache_keep=2),
                     register_profiler=False)
        pats = ["a{%d}" % n for n in (1, 2, 3, 4)]
        for p in pats:
            eng.submit([3], sampling=GREEDY, grammar=p)
            _drive(eng)
        st = eng.stats()["structured"]
        assert st["compile_cache_entries"] == 2
        assert st["compile_cache_misses"] == 4
        eng.submit([3], sampling=GREEDY, grammar=pats[-1])  # kept: hit
        _drive(eng)
        assert eng.stats()["structured"]["compile_cache_hits"] == 1
        eng.submit([3], sampling=GREEDY, grammar=pats[0])   # evicted
        _drive(eng)
        st = eng.stats()["structured"]
        assert st["compile_cache_misses"] == 5
        assert st["compile_cache_entries"] == 2
        eng.close()
        # live grammars are PINNED even at keep=0 (the admission walk
        # reads the cached TokenDFA), and fully evict once retired
        eng = Engine(m, _cfg(grammar_cache_keep=0, num_slots=1),
                     register_profiler=False)
        eng.submit([3, 1, 4], sampling=GREEDY, grammar=SCHEMA)
        eng.submit([9, 2, 6], sampling=GREEDY, grammar="a{2}")
        assert eng.stats()["structured"]["compile_cache_entries"] == 2
        _drive(eng)
        assert eng.stats()["structured"]["compile_cache_entries"] == 0
        eng.close()

    def test_resume_ids_must_walk_grammar(self):
        """Cross-engine resume tokens that are illegal under the
        request grammar are refused at submit() — not silently
        un-constrained at admission (the slab stores REJECT as the
        accept-all sentinel row, so only the eager cache walk can see
        the divergence)."""
        m = _model()
        eng = Engine(m, _cfg(), register_profiler=False)
        for bad in ([90, 1],      # 'z' can't open the schema's object
                    [5000]):      # beyond the vocab entirely
            with pytest.raises(ValueError, match="illegal"):
                eng.submit([3, 1, 4], sampling=SEEDED, grammar=SCHEMA,
                           resume_ids=bad)
        assert eng.scheduler.queue_depth == 0
        assert eng.stats()["structured"]["grammars_installed"] == 0
        eng.close()

    def test_cross_engine_constrained_resume_bitwise(self):
        """A constrained seeded stream cut mid-generation resumes
        bitwise on a fresh engine via resume_ids (the failover path)."""
        m = _model()
        ref = Engine(m, _cfg(), register_profiler=False)
        want = ref.submit([3, 1, 4], sampling=SEEDED, grammar=SCHEMA)
        _drive(ref)
        ref.close()
        cut = 5
        assert len(want.output_ids) > cut
        eng = Engine(m, _cfg(), register_profiler=False)
        r = eng.submit([3, 1, 4], sampling=SEEDED, grammar=SCHEMA,
                       resume_ids=want.output_ids[:cut])
        _drive(eng)
        eng.close()
        assert r.output_ids == want.output_ids
        json.loads(_text(r))

    def test_submit_validation(self):
        m = _model()
        eng = Engine(m, _cfg(), register_profiler=False)
        with pytest.raises(ValueError, match="eos"):
            eng.submit([1, 2], sampling=SamplingParams(max_new_tokens=4),
                       grammar=SCHEMA)
        with pytest.raises(GrammarError):
            eng.submit([1, 2], sampling=GREEDY, grammar=17)
        eng.close()
        off = Engine(m, EngineConfig(num_slots=2, max_seq_len=96,
                                     prefix_block_size=4,
                                     prefix_cache_bytes=0),
                     register_profiler=False)
        with pytest.raises(ValueError, match="grammar_max_states"):
            off.submit([1, 2], sampling=GREEDY, grammar=SCHEMA)
        off.close()
        novocab = Engine(m, _cfg(grammar_vocab=None),
                         register_profiler=False)
        with pytest.raises(ValueError, match="grammar_vocab"):
            novocab.submit([1, 2], sampling=GREEDY, grammar=SCHEMA)
        novocab.close()
        with pytest.raises(ValueError, match="grammar_max_states"):
            Engine(m, EngineConfig(num_slots=2, max_seq_len=96,
                                   grammar_max_states=-1),
                   register_profiler=False)

    def test_knobs_off_engine_threads_none(self):
        """grammar_max_states=0 (the default): no slab, no device
        tables, and the compiled programs carry no grammar operands."""
        m = _model()
        eng = Engine(m, EngineConfig(num_slots=2, max_seq_len=96,
                                     max_horizon=4, prefix_block_size=4,
                                     prefix_cache_bytes=0),
                     register_profiler=False)
        r = eng.submit([3, 1, 4],
                       sampling=SamplingParams(max_new_tokens=8))
        _drive(eng)
        assert len(r.output_ids) == 8
        assert eng._grammar_slab is None
        assert eng._d_dfa_state is None and eng._d_dfa_next is None
        assert eng._d_dfa_mask is None and eng._d_dfa_forced is None
        assert eng.stats()["structured"]["enabled"] is False
        eng.close()

    @pytest.mark.slow
    def test_preempt_resume_parity(self):
        """A constrained seeded lane preempted mid-decode resumes
        bitwise: the DFA admission walk replays its emitted tokens."""
        m = _model()
        ref = Engine(m, _cfg(), register_profiler=False)
        want = ref.submit([3, 1, 4], sampling=SEEDED, grammar=SCHEMA)
        _drive(ref)
        ref.close()
        eng = Engine(m, _cfg(), register_profiler=False)
        r = eng.submit([3, 1, 4], sampling=SEEDED, grammar=SCHEMA)
        eng.step(horizon=2)
        eng.step(horizon=2)
        eng.preempt(r)
        assert r.resumed is True
        assert eng.stats()["structured"]["grammars_installed"] == 1
        _drive(eng)
        assert r.output_ids == want.output_ids
        json.loads(_text(r))
        assert eng.stats()["structured"]["grammars_installed"] == 0
        eng.close()

    @pytest.mark.slow
    def test_prefix_hit_parity(self):
        """Constrained decode over a prefix-cache hit: leased blocks
        change nothing about the stream."""
        m = _model()
        shared = [5, 5, 7, 7, 1, 2, 3, 4]
        outs = []
        for bytes_ in (0, 1 << 20):
            eng = Engine(m, _cfg(prefix_cache_bytes=bytes_),
                         register_profiler=False)
            # sequential so the second prompt can hit the blocks the
            # first one's retirement adopted
            pair = []
            for extra in (9, 8):
                pair.append(eng.submit(shared + [extra], sampling=GREEDY,
                                       grammar=SCHEMA))
                _drive(eng)
            if bytes_:
                assert eng.stats()["prefix"]["hit_tokens"] > 0
            outs.append([r.output_ids for r in pair])
            eng.close()
        assert outs[0] == outs[1]

    @pytest.mark.slow
    def test_int8_kv_constrained_still_valid(self):
        """Quantized KV changes logits, not legality: constrained
        greedy under int8 KV is still schema-valid and deterministic."""
        m = _model()
        outs = []
        for _ in range(2):
            eng = Engine(m, _cfg(kv_cache_dtype="int8"),
                         register_profiler=False)
            r = eng.submit([3, 1, 4], sampling=GREEDY, grammar=SCHEMA)
            _drive(eng)
            eng.close()
            json.loads(_text(r))
            assert r.finish_reason == "eos"
            outs.append(r.output_ids)
        assert outs[0] == outs[1]


# ------------------------------------------------------------- sharded
class TestStructuredSharded:
    """tp=2 MeshEngine under grammar constraints: bitwise parity with
    the single-chip engine, and the layout's placement rule."""

    def test_layout_dfa_tables_replicated(self):
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.serving import ServingSpecLayout

        layout = ServingSpecLayout()
        assert layout.dfa_tables() == P()
        assert layout.dfa_tables() == layout.engine_state()

    @pytest.mark.slow
    def test_tp2_constrained_bitwise_parity(self):
        from paddle_tpu.serving import MeshEngine

        m = _model()
        ref = Engine(m, _cfg(), register_profiler=False)
        wa = ref.submit([3, 1, 4], sampling=GREEDY, grammar=SCHEMA)
        wb = ref.submit([9, 2, 6], sampling=SEEDED, grammar=SCHEMA)
        _drive(ref)
        ref.close()
        eng = MeshEngine(m, _cfg(), tp=2, register_profiler=False)
        ra = eng.submit([3, 1, 4], sampling=GREEDY, grammar=SCHEMA)
        rb = eng.submit([9, 2, 6], sampling=SEEDED, grammar=SCHEMA)
        _drive(eng)
        assert ra.output_ids == wa.output_ids
        assert rb.output_ids == wb.output_ids
        json.loads(_text(ra))
        json.loads(_text(rb))
        assert eng.pool.blocks_in_use == 0
        eng.close()

    @pytest.mark.slow
    def test_tp2_constrained_spec_k4_parity(self):
        from paddle_tpu.serving import MeshEngine

        m = _model()
        ref = Engine(m, _cfg(), register_profiler=False)
        want = ref.submit([3, 1, 4], sampling=GREEDY, grammar=SCHEMA)
        _drive(ref)
        ref.close()
        eng = MeshEngine(m, _cfg(spec_k=4), tp=2,
                         register_profiler=False)
        r = eng.submit([3, 1, 4], sampling=GREEDY, grammar=SCHEMA)
        _drive(eng)
        assert r.output_ids == want.output_ids
        assert eng.stats()["structured"]["forced_tokens"] > 0
        eng.close()


# ------------------------------------------------------------- gateway
class TestGatewayProtocol:
    """/v1/completions structured fields: eager validation, typed
    invalid_grammar 400s naming the unsupported feature."""

    @staticmethod
    def _parse(payload):
        from paddle_tpu.serving.gateway import Gateway, GatewayConfig

        gw = types.SimpleNamespace(config=GatewayConfig())
        base = {"prompt": [1, 2, 3], "eos_token_id": EOS}
        return Gateway.parse_completion(gw, dict(base, **payload))

    def _reject(self, payload):
        from paddle_tpu.serving.gateway.protocol import _Reject

        with pytest.raises(_Reject) as e:
            self._parse(payload)
        return e.value

    def test_response_format_json_schema(self):
        parsed = self._parse({"response_format": {
            "type": "json_schema",
            "json_schema": {"schema": SCHEMA}}})
        assert parsed["grammar"].kind == "json_schema"
        # bare schema (no OpenAI "schema" nesting) accepted too
        parsed = self._parse({"response_format": {
            "type": "json_schema", "json_schema": SCHEMA}})
        assert parsed["grammar"].kind == "json_schema"
        assert self._parse({"response_format": {"type": "text"}})[
            "grammar"] is None
        assert self._parse({})["grammar"] is None

    def test_grammar_regex_forms(self):
        assert self._parse({"grammar": "a+b"})["grammar"].kind == "regex"
        parsed = self._parse(
            {"grammar": {"type": "regex", "pattern": "a+b"}})
        assert parsed["grammar"].pattern == "a+b"

    def test_invalid_grammar_400s_name_the_feature(self):
        e = self._reject({"response_format": {
            "type": "json_schema",
            "json_schema": {"schema": {"anyOf": []}}}})
        assert e.status == 400 and e.code == "invalid_grammar"
        assert "anyOf" in str(e)
        e = self._reject({"response_format": {"type": "json_object"}})
        assert e.code == "invalid_grammar" and "json_object" in str(e)
        e = self._reject({"grammar": "(a"})
        assert e.status == 400 and e.code == "invalid_grammar"
        e = self._reject({"grammar": {"type": "bnf", "rules": []}})
        assert e.code == "invalid_grammar"
        e = self._reject({"grammar": "a+", "response_format": {
            "type": "json_schema", "json_schema": SCHEMA}})
        assert e.code == "invalid_grammar" and "exclusive" in str(e)

    def test_constrained_requires_eos(self):
        from paddle_tpu.serving.gateway import Gateway, GatewayConfig
        from paddle_tpu.serving.gateway.protocol import _Reject

        gw = types.SimpleNamespace(config=GatewayConfig())
        with pytest.raises(_Reject) as e:
            Gateway.parse_completion(
                gw, {"prompt": [1, 2], "grammar": "a+"})
        assert e.value.code == "invalid_grammar"
        assert "eos_token_id" in str(e.value)

    @pytest.mark.slow
    def test_http_end_to_end_constrained(self):
        """POST a json_schema response_format through a live gateway:
        the streamed tokens are the engine's constrained stream."""
        import http.client

        from paddle_tpu.serving.gateway import Gateway, GatewayConfig

        m = _model()
        ref = Engine(m, _cfg(), register_profiler=False)
        want = ref.submit([3, 1, 4], sampling=GREEDY, grammar=SCHEMA)
        _drive(ref)
        ref.close()
        eng = Engine(m, _cfg(), register_profiler=False)
        gw = Gateway([eng], GatewayConfig(model_id="tiny")).start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", gw.port,
                                              timeout=60)
            body = json.dumps({
                "prompt": [3, 1, 4], "max_tokens": 48,
                "eos_token_id": EOS,
                "response_format": {"type": "json_schema",
                                    "json_schema": {"schema": SCHEMA}}})
            conn.request("POST", "/v1/completions", body,
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            doc = json.loads(r.read())
            assert r.status == 200, doc
            choice = doc["choices"][0]
            assert choice["token_ids"] == want.output_ids
            assert choice["finish_reason"] == "stop"   # OpenAI eos word
            # malformed grammar 400s before anything queues
            conn.request("POST", "/v1/completions",
                         json.dumps({"prompt": [1], "eos_token_id": EOS,
                                     "grammar": "(a"}),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            doc = json.loads(r.read())
            assert r.status == 400
            assert doc["error"]["code"] == "invalid_grammar"
        finally:
            gw.shutdown()
        assert eng.pool.blocks_in_use == 0
