"""Distributed checkpoint (reshard-on-load) + launcher tests."""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.checkpoint import load_state_dict, save_state_dict


class TestDistributedCheckpoint:
    def test_roundtrip_plain(self, tmp_path):
        paddle.seed(0)
        m = nn.Linear(8, 4)
        sd = m.state_dict()
        w0 = np.asarray(sd["weight"]._data).copy()
        save_state_dict(sd, str(tmp_path / "ck"))

        paddle.seed(1)
        m2 = nn.Linear(8, 4)
        assert not np.allclose(np.asarray(m2.weight._data), w0)
        load_state_dict(m2.state_dict(), str(tmp_path / "ck"))
        np.testing.assert_allclose(np.asarray(m2.weight._data), w0)

    def test_reshard_on_load(self, tmp_path):
        """Save replicated, load onto a sharded placement (and back)."""
        dist.set_hybrid_communicate_group(None)
        hcg = dist.create_hybrid_communicate_group(sharding=8)
        paddle.seed(2)
        m = nn.Linear(16, 8)
        w0 = np.asarray(m.weight._data).copy()
        save_state_dict(m.state_dict(), str(tmp_path / "ck"))

        paddle.seed(3)
        m2 = nn.Linear(16, 8)
        sharded = NamedSharding(hcg.mesh, P("sharding"))
        m2.weight._data = jax.device_put(m2.weight._data, sharded)
        load_state_dict(m2.state_dict(), str(tmp_path / "ck"))
        assert "sharding" in str(m2.weight._data.sharding.spec)
        np.testing.assert_allclose(np.asarray(m2.weight._data), w0)

    def test_missing_key_raises(self, tmp_path):
        m = nn.Linear(4, 4)
        save_state_dict(m.state_dict(), str(tmp_path / "ck"))
        m2 = nn.Linear(4, 8)
        with pytest.raises((KeyError, Exception)):
            load_state_dict(m2.state_dict(), str(tmp_path / "ck"))

    def test_async_save_overlaps_training(self, tmp_path):
        """r4 (VERDICT r3 item 6): async_save=True returns after the
        snapshot; training steps mutate params while the write is in
        flight; the committed checkpoint holds the SNAPSHOT values."""
        from paddle_tpu.distributed.checkpoint import wait_all_saves

        paddle.seed(4)
        m = nn.Linear(64, 64)
        opt = paddle.optimizer.SGD(learning_rate=0.5,
                                   parameters=m.parameters())
        w_snap = np.asarray(m.weight._data).copy()
        save_state_dict(m.state_dict(), str(tmp_path / "ck"),
                        async_save=True)
        # training proceeds while the save is in flight
        X = np.random.RandomState(0).randn(32, 64).astype(np.float32)
        for _ in range(3):
            loss = (m(paddle.to_tensor(X)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert not np.allclose(np.asarray(m.weight._data), w_snap)
        wait_all_saves()
        paddle.seed(5)
        m2 = nn.Linear(64, 64)
        load_state_dict(m2.state_dict(), str(tmp_path / "ck"))
        # the checkpoint is the SNAPSHOT, not the post-training weights
        np.testing.assert_allclose(np.asarray(m2.weight._data), w_snap)

    def test_async_save_successive_saves_serialize(self, tmp_path):
        m = nn.Linear(8, 8)
        for i in range(3):
            m.weight._data = m.weight._data * 0 + float(i)
            save_state_dict(m.state_dict(), str(tmp_path / "ck"),
                            async_save=True)
        # load drains the in-flight save; last write wins
        m2 = nn.Linear(8, 8)
        load_state_dict(m2.state_dict(), str(tmp_path / "ck"))
        np.testing.assert_allclose(np.asarray(m2.weight._data), 2.0)


class TestLauncher:
    def test_env_contract_and_run(self, tmp_path):
        script = tmp_path / "train.py"
        script.write_text(
            "import os\n"
            "assert os.environ['PADDLE_TRAINER_ID'] == '0'\n"
            "assert os.environ['PADDLE_TRAINERS_NUM'] == '1'\n"
            "print('TRAINED_OK')\n")
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             str(script)],
            capture_output=True, text=True, cwd="/root/repo",
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        assert "TRAINED_OK" in out.stdout

    def test_watcher_restarts(self, tmp_path):
        marker = tmp_path / "marker"
        script = tmp_path / "flaky.py"
        script.write_text(
            f"import os, sys\n"
            f"m = {str(marker)!r}\n"
            f"if not os.path.exists(m):\n"
            f"    open(m, 'w').close()\n"
            f"    sys.exit(1)\n"
            f"print('RECOVERED')\n")
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--max_restarts", "2", "--log_dir", str(tmp_path / "logs"),
             str(script)],
            capture_output=True, text=True, cwd="/root/repo",
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        logs = os.listdir(tmp_path / "logs")
        assert len(logs) == 2  # failed attempt + recovered attempt


class TestNativeContainer:
    def test_large_roundtrip_uses_container(self, tmp_path):
        import numpy as np

        p = str(tmp_path / "big.pdparams")
        obj = {"w": paddle.to_tensor(np.arange(400_000, dtype=np.float32)),
               "nested": {"b": paddle.to_tensor(np.ones((64, 64), np.float32)),
                          "step": 7, "name": "x"},
               "empty": paddle.to_tensor(np.zeros((0,), np.float32))}
        paddle.save(obj, p)
        with open(p, "rb") as f:
            assert f.read(8) == b"PTCKPT01"
        back = paddle.load(p)
        np.testing.assert_array_equal(back["w"].numpy(), obj["w"].numpy())
        np.testing.assert_array_equal(back["nested"]["b"].numpy(),
                                      obj["nested"]["b"].numpy())
        assert back["nested"]["step"] == 7
        assert back["nested"]["name"] == "x"
        assert back["empty"].numpy().shape == (0,)

    def test_small_stays_pickle(self, tmp_path):
        import numpy as np

        p = str(tmp_path / "small.pdparams")
        paddle.save({"w": paddle.to_tensor(np.ones(4, np.float32))}, p)
        with open(p, "rb") as f:
            assert f.read(1) == b"\x80"  # pickle protocol marker
        back = paddle.load(p)
        np.testing.assert_array_equal(back["w"].numpy(), np.ones(4))

    def test_bf16_roundtrip(self, tmp_path):
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import Tensor

        p = str(tmp_path / "bf16.pdparams")
        t = Tensor(jnp.ones((600, 600), jnp.bfloat16) * 1.5)
        paddle.save({"w": t}, p)
        back = paddle.load(p)
        assert back["w"].numpy().dtype == np.asarray(t._data).dtype
        assert float(np.asarray(back["w"]._data)[0, 0]) == 1.5
