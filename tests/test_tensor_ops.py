"""Op tests vs NumPy references (SURVEY.md §4 OpTest pattern)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def t(a, sg=True):
    return paddle.to_tensor(np.asarray(a, np.float32), stop_gradient=sg)


class TestCreation:
    def test_basic(self):
        assert paddle.zeros([2, 3]).shape == [2, 3]
        assert paddle.ones([2]).numpy().tolist() == [1.0, 1.0]
        assert paddle.full([2], 7).numpy().tolist() == [7, 7]
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5))
        assert paddle.eye(3).numpy()[1, 1] == 1.0

    def test_like(self):
        x = t([[1, 2], [3, 4]])
        assert paddle.zeros_like(x).shape == [2, 2]
        assert float(paddle.full_like(x, 5).numpy()[0, 0]) == 5.0

    def test_tril_triu_diag(self):
        x = t(np.arange(9).reshape(3, 3))
        np.testing.assert_array_equal(paddle.tril(x).numpy(), np.tril(x.numpy()))
        np.testing.assert_array_equal(paddle.triu(x).numpy(), np.triu(x.numpy()))
        np.testing.assert_array_equal(paddle.diag(t([1, 2, 3])).numpy(), np.diag([1, 2, 3]))

    def test_one_hot(self):
        oh = paddle.one_hot(paddle.to_tensor([0, 2]), 3)
        np.testing.assert_array_equal(oh.numpy(), [[1, 0, 0], [0, 0, 1]])


class TestMath:
    def test_elementwise(self):
        a, b = np.random.rand(3, 4), np.random.rand(3, 4)
        x, y = t(a), t(b)
        np.testing.assert_allclose((x + y).numpy(), a + b, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose((x - y).numpy(), a - b, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose((x * y).numpy(), a * b, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose((x / y).numpy(), a / b, rtol=1e-5)
        np.testing.assert_allclose(paddle.maximum(x, y).numpy(), np.maximum(a, b))
        np.testing.assert_allclose((x**2).numpy(), a**2, rtol=1e-6)
        np.testing.assert_allclose(paddle.sqrt(x).numpy(), np.sqrt(a), rtol=1e-6)
        np.testing.assert_allclose(paddle.exp(x).numpy(), np.exp(a), rtol=1e-6)
        np.testing.assert_allclose(paddle.log(x).numpy(), np.log(a), rtol=1e-5, atol=1e-6)

    def test_scalar_broadcast(self):
        x = t([1.0, 2.0])
        np.testing.assert_allclose((2 * x + 1).numpy(), [3.0, 5.0])
        np.testing.assert_allclose((1 / x).numpy(), [1.0, 0.5])
        np.testing.assert_allclose((x - 1).numpy(), [0.0, 1.0])
        np.testing.assert_allclose((3 - x).numpy(), [2.0, 1.0])

    def test_reductions(self):
        a = np.random.rand(2, 3, 4)
        x = t(a)
        np.testing.assert_allclose(float(x.sum()), a.sum(), rtol=1e-5)
        np.testing.assert_allclose(x.mean(axis=1).numpy(), a.mean(1), rtol=1e-5)
        np.testing.assert_allclose(x.max(axis=[0, 2]).numpy(), a.max((0, 2)), rtol=1e-6)
        np.testing.assert_allclose(x.prod(axis=0).numpy(), a.prod(0), rtol=1e-5)
        np.testing.assert_allclose(x.std(axis=-1, unbiased=True).numpy(), a.std(-1, ddof=1), rtol=1e-4)
        np.testing.assert_allclose(paddle.logsumexp(x, axis=1).numpy(),
                                   np.log(np.exp(a).sum(1)), rtol=1e-5)

    def test_cumsum_cummax(self):
        a = np.random.rand(3, 4)
        x = t(a)
        np.testing.assert_allclose(paddle.cumsum(x, axis=1).numpy(), np.cumsum(a, 1), rtol=1e-5)
        v, i = paddle.cummax(x, axis=1)
        np.testing.assert_allclose(v.numpy(), np.maximum.accumulate(a, 1), rtol=1e-6)

    def test_matmul_family(self):
        a, b = np.random.rand(2, 3, 4), np.random.rand(2, 4, 5)
        np.testing.assert_allclose(paddle.matmul(t(a), t(b)).numpy(), a @ b, rtol=1e-5)
        np.testing.assert_allclose(
            paddle.matmul(t(a), t(b.transpose(0, 2, 1)), transpose_y=True).numpy(), a @ b, rtol=1e-5
        )
        v1, v2 = np.random.rand(4), np.random.rand(4)
        np.testing.assert_allclose(float(paddle.dot(t(v1), t(v2))), v1 @ v2, rtol=1e-5)
        np.testing.assert_allclose(paddle.outer(t(v1), t(v2)).numpy(), np.outer(v1, v2), rtol=1e-5)

    def test_clip_trig(self):
        a = np.random.randn(3, 3)
        np.testing.assert_allclose(paddle.clip(t(a), -0.5, 0.5).numpy(), np.clip(a, -0.5, 0.5), rtol=1e-6)
        np.testing.assert_allclose(paddle.sin(t(a)).numpy(), np.sin(a), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(paddle.atan2(t(a), t(a + 1)).numpy(), np.arctan2(a, a + 1), rtol=1e-5, atol=1e-6)


class TestManipulation:
    def test_reshape_transpose(self):
        a = np.arange(24).reshape(2, 3, 4).astype(np.float32)
        x = t(a)
        assert x.reshape([4, 6]).shape == [4, 6]
        assert x.reshape([-1, 4]).shape == [6, 4]
        np.testing.assert_array_equal(
            paddle.transpose(x, [2, 0, 1]).numpy(), a.transpose(2, 0, 1)
        )
        assert paddle.flatten(x, 1).shape == [2, 12]

    def test_squeeze_unsqueeze(self):
        x = t(np.zeros((2, 1, 3)))
        assert paddle.squeeze(x, 1).shape == [2, 3]
        assert paddle.unsqueeze(x, 0).shape == [1, 2, 1, 3]
        assert paddle.unsqueeze(x, [0, 4]).shape == [1, 2, 1, 3, 1]

    def test_concat_stack_split(self):
        a = np.random.rand(2, 3)
        x = t(a)
        assert paddle.concat([x, x], axis=0).shape == [4, 3]
        assert paddle.stack([x, x], axis=1).shape == [2, 2, 3]
        parts = paddle.split(t(np.arange(12).reshape(2, 6)), 3, axis=1)
        assert len(parts) == 3 and parts[0].shape == [2, 2]
        parts = paddle.split(t(np.arange(12).reshape(2, 6)), [1, 2, -1], axis=1)
        assert parts[2].shape == [2, 3]

    def test_gather_scatter(self):
        a = np.arange(12).reshape(3, 4).astype(np.float32)
        idx = paddle.to_tensor([2, 0])
        np.testing.assert_array_equal(paddle.gather(t(a), idx, axis=0).numpy(), a[[2, 0]])
        np.testing.assert_array_equal(paddle.index_select(t(a), idx, axis=1).numpy(), a[:, [2, 0]])
        out = paddle.scatter(t(a), paddle.to_tensor([0]), t(np.full((1, 4), 9.0)))
        assert out.numpy()[0, 0] == 9.0

    def test_where_masked(self):
        a = np.random.randn(3, 4).astype(np.float32)
        x = t(a)
        np.testing.assert_array_equal(
            paddle.where(x > 0, x, paddle.zeros_like(x)).numpy(), np.where(a > 0, a, 0)
        )
        np.testing.assert_array_equal(paddle.masked_select(x, x > 0).numpy(), a[a > 0])

    def test_tile_expand_roll_flip(self):
        a = np.arange(6).reshape(2, 3).astype(np.float32)
        x = t(a)
        np.testing.assert_array_equal(paddle.tile(x, [2, 1]).numpy(), np.tile(a, (2, 1)))
        assert paddle.expand(t(np.ones((1, 3))), [4, 3]).shape == [4, 3]
        np.testing.assert_array_equal(paddle.roll(x, 1, axis=0).numpy(), np.roll(a, 1, 0))
        np.testing.assert_array_equal(paddle.flip(x, [1]).numpy(), a[:, ::-1])

    def test_take_along_put_along(self):
        a = np.random.rand(3, 4).astype(np.float32)
        idx = np.argsort(a, axis=1)
        out = paddle.take_along_axis(t(a), paddle.to_tensor(idx), axis=1)
        np.testing.assert_allclose(out.numpy(), np.take_along_axis(a, idx, 1))

    def test_unique_nonzero(self):
        x = paddle.to_tensor([1, 2, 2, 3, 1])
        np.testing.assert_array_equal(paddle.unique(x).numpy(), [1, 2, 3])
        nz = paddle.nonzero(paddle.to_tensor([0, 1, 0, 2]))
        np.testing.assert_array_equal(nz.numpy().reshape(-1), [1, 3])


class TestLogicSearch:
    def test_compare(self):
        x, y = t([1, 2, 3]), t([2, 2, 2])
        np.testing.assert_array_equal((x < y).numpy(), [True, False, False])
        np.testing.assert_array_equal(paddle.equal(x, y).numpy(), [False, True, False])
        assert bool(paddle.all(t([1, 1]).astype("bool")))
        assert bool(paddle.any((x > 2)))

    def test_argmax_sort_topk(self):
        a = np.random.rand(3, 5)
        x = t(a)
        np.testing.assert_array_equal(paddle.argmax(x, axis=1).numpy(), a.argmax(1))
        np.testing.assert_allclose(paddle.sort(x, axis=1).numpy(), np.sort(a, 1), rtol=1e-6)
        v, i = paddle.topk(x, 2, axis=1)
        np.testing.assert_allclose(v.numpy(), np.sort(a, 1)[:, ::-1][:, :2], rtol=1e-6)

    def test_searchsorted_median(self):
        s = t([1.0, 3.0, 5.0, 7.0])
        np.testing.assert_array_equal(
            paddle.searchsorted(s, t([2.0, 6.0])).numpy(), [1, 3]
        )
        assert float(paddle.median(t([1.0, 2.0, 3.0]))) == 2.0


class TestLinalg:
    def test_norm_det_inv(self):
        a = np.random.rand(3, 3) + np.eye(3)
        x = t(a)
        np.testing.assert_allclose(float(paddle.linalg.norm(x)), np.linalg.norm(a), rtol=1e-5)
        np.testing.assert_allclose(float(paddle.linalg.det(x)), np.linalg.det(a), rtol=1e-4)
        np.testing.assert_allclose(paddle.linalg.inv(x).numpy(), np.linalg.inv(a), rtol=1e-4, atol=1e-5)

    def test_svd_qr_cholesky(self):
        a = np.random.rand(4, 3)
        u, s, vh = paddle.linalg.svd(t(a))
        np.testing.assert_allclose(s.numpy(), np.linalg.svd(a, compute_uv=False), rtol=1e-4)
        q, r = paddle.linalg.qr(t(a))
        np.testing.assert_allclose((q @ r).numpy(), a, rtol=1e-4, atol=1e-5)
        spd = a.T @ a + np.eye(3)
        l = paddle.linalg.cholesky(t(spd))
        np.testing.assert_allclose((l @ l.T).numpy(), spd, rtol=1e-4, atol=1e-5)

    def test_solve_eigh(self):
        a = np.random.rand(3, 3) + 3 * np.eye(3)
        b = np.random.rand(3, 2)
        np.testing.assert_allclose(
            paddle.linalg.solve(t(a), t(b)).numpy(), np.linalg.solve(a, b), rtol=1e-4, atol=1e-5
        )
        sym = (a + a.T) / 2
        w, v = paddle.linalg.eigh(t(sym))
        np.testing.assert_allclose(w.numpy(), np.linalg.eigh(sym)[0], rtol=1e-4, atol=1e-5)


class TestEinsumRandom:
    def test_einsum(self):
        a, b = np.random.rand(2, 3), np.random.rand(3, 4)
        np.testing.assert_allclose(paddle.einsum("ij,jk->ik", t(a), t(b)).numpy(), a @ b, rtol=1e-5)

    def test_random_shapes_and_determinism(self):
        paddle.seed(7)
        a = paddle.randn([3, 3]).numpy()
        paddle.seed(7)
        b = paddle.randn([3, 3]).numpy()
        np.testing.assert_array_equal(a, b)
        assert paddle.randint(0, 10, [5]).numpy().max() < 10
        p = paddle.randperm(10).numpy()
        assert sorted(p.tolist()) == list(range(10))

    def test_bernoulli_multinomial(self):
        probs = paddle.full([100], 0.5)
        s = paddle.bernoulli(probs).numpy()
        assert 10 < s.sum() < 90
        m = paddle.multinomial(paddle.to_tensor([0.1, 0.2, 0.7]), 2)
        assert m.shape == [2]


class TestDtypeCast:
    def test_astype(self):
        x = paddle.to_tensor([1.5, 2.5])
        assert str(x.astype("int32").dtype) == "int32"
        assert str(x.astype(paddle.float16).dtype) == "float16"
        y = paddle.cast(x, "bool")
        assert y.numpy().tolist() == [True, True]

    def test_default_dtypes(self):
        assert str(paddle.to_tensor(1.0).dtype) == "float32"
        assert str(paddle.to_tensor(1).dtype) == "int32"
        assert str(paddle.to_tensor(True).dtype) == "bool"
