#!/usr/bin/env python
"""Benchmark: flagship GPT (ERNIE/LLaMA-style) jitted train step on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
value = tokens/sec/chip; vs_baseline = achieved MFU / 0.50 (BASELINE.md's
derived A100-parity anchor — no published reference numbers exist, see
BASELINE.md provenance).
"""

import json
import os
import sys
import time


def _kernel_checks(perturb=None):
    """Yield (name, max_abs_err, tol) for every Pallas kernel path, fwd AND
    bwd, computed on the CURRENT backend (real Mosaic on TPU, interpret on
    CPU — the same code is exercised by tests/test_kernel_smoke_gate.py).
    `perturb=name` injects a seeded offset into that check's kernel result
    so the gate's ability to fail loudly is itself testable."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    # single source of truth for interpret-vs-Mosaic: the kernels' own
    # backend predicate (the gate must test the mode the models use)
    from paddle_tpu.ops.pallas.norms import _interpret_default
    interp = _interpret_default()

    def bump(name, arr):
        # perturbation emulates a silent kernel regression; multiplicative
        # + additive so it exceeds both absolute and relative tolerances
        return arr * 1.5 + 2.0 if perturb == name else arr

    rng = np.random.RandomState(0)
    b, s, h, kv, d = 1, 256, 4, 2, 128
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, s, kv, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, s, kv, d), jnp.bfloat16)

    from paddle_tpu.ops.pallas.flash import flash_attention as pallas_flash
    from paddle_tpu.ops.flash_attention import _xla_flash
    for causal in (False, True):
        nm = f"flash_fwd_causal{int(causal)}"
        out = np.asarray(bump(nm, pallas_flash(q, k, v, causal=causal,
                                               interpret=interp)), np.float32)
        ref = np.asarray(_xla_flash(q, k, v, causal, None), np.float32)
        yield nm, np.abs(out - ref).max(), 0.1

    # flash BACKWARD (dq/dk/dv, GQA): the bwd kernels only ran inside full
    # benches before — a Mosaic regression there showed up as a silently
    # wrong loss (VERDICT r2 item 3)
    for causal in (False, True):
        def loss_pl(q, k, v):
            o = pallas_flash(q, k, v, causal=causal, interpret=interp)
            return (o.astype(jnp.float32) ** 2).sum()

        def loss_ref(q, k, v):
            return (_xla_flash(q, k, v, causal, None)
                    .astype(jnp.float32) ** 2).sum()

        gp = jax.grad(loss_pl, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name_c, a, r in zip(("dq", "dk", "dv"), gp, gr):
            nm = f"flash_bwd_{name_c}_causal{int(causal)}"
            a = np.asarray(bump(nm, a.astype(jnp.float32)))
            r = np.asarray(r.astype(jnp.float32))
            scale = max(1.0, np.abs(r).max())
            yield nm, np.abs(a - r).max() / scale, 0.05

    from paddle_tpu.ops.pallas.norms import layer_norm, rms_norm
    x = jnp.asarray(rng.randn(8, 512), jnp.float32)
    w = jnp.asarray(rng.randn(512), jnp.float32)
    bias = jnp.asarray(rng.randn(512), jnp.float32)
    ln = np.asarray(bump("layer_norm", layer_norm(x, w, bias,
                                                  interpret=interp)))
    mu = np.asarray(x, np.float64).mean(-1, keepdims=True)
    var = np.asarray(x, np.float64).var(-1, keepdims=True)
    ln_ref = (np.asarray(x) - mu) / np.sqrt(var + 1e-5) * np.asarray(w) + np.asarray(bias)
    yield "layer_norm", np.abs(ln - ln_ref).max(), 1e-3
    rn = np.asarray(bump("rms_norm", rms_norm(x, w, interpret=interp)))
    rn_ref = np.asarray(x) / np.sqrt((np.asarray(x, np.float64) ** 2).mean(-1, keepdims=True) + 1e-6) * np.asarray(w)
    yield "rms_norm", np.abs(rn - rn_ref).max(), 1e-3

    from paddle_tpu.ops.pallas.norms import group_norm
    xg = jnp.asarray(rng.randn(2, 32, 16, 16), jnp.float32)
    wg = jnp.asarray(rng.randn(32), jnp.float32)
    bg = jnp.asarray(rng.randn(32), jnp.float32)

    def gn_ref_fn(xv, wv, bv):
        g4 = xv.reshape(2, 8, 4, 16, 16).astype(jnp.float32)
        mu = g4.mean(axis=(2, 3, 4), keepdims=True)
        var = ((g4 - mu) ** 2).mean(axis=(2, 3, 4), keepdims=True)
        out = ((g4 - mu) * jax.lax.rsqrt(var + 1e-5)).reshape(xv.shape)
        return out * wv.reshape(1, 32, 1, 1) + bv.reshape(1, 32, 1, 1)

    gn = np.asarray(bump("group_norm", group_norm(xg, wg, bg, 8, 1e-5,
                                                  interpret=interp)))
    yield "group_norm", np.abs(gn - np.asarray(gn_ref_fn(xg, wg, bg))).max(), 1e-3

    gp = jax.grad(lambda *a: (group_norm(*a, 8, 1e-5, interp) ** 2).sum(),
                  argnums=(0, 1, 2))(xg, wg, bg)
    gr = jax.grad(lambda *a: (gn_ref_fn(*a) ** 2).sum(),
                  argnums=(0, 1, 2))(xg, wg, bg)
    for name_c, a, r in zip(("dx", "dw", "db"), gp, gr):
        nm = f"group_norm_bwd_{name_c}"
        a = np.asarray(bump(nm, a))
        r = np.asarray(r)
        scale = max(1.0, np.abs(r).max())
        yield nm, np.abs(a - r).max() / scale, 1e-3

    # one ring-attention step (sep axis of 1 on this chip: the ring bwd
    # kernel path — global-lse flash bwd with rotating accumulators — runs
    # on real silicon; multi-device parity is covered on the CPU mesh)
    from jax.sharding import Mesh
    from paddle_tpu.distributed.shard_map_compat import (
        NO_CHECK as sm_kw, shard_map)
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed.ring_attention import ring_flash_attention_arrays
    mesh = Mesh(np.array(jax.devices()[:1]), ("sep",))
    spec = P(None, "sep", None, None)

    def ring_loss(q, k, v):
        f = shard_map(
            lambda a, b, c: ring_flash_attention_arrays(
                a, b, c, causal=True, axis_name="sep", interpret=interp),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            **sm_kw)
        return (f(q, k, v).astype(jnp.float32) ** 2).sum()

    ring_val_and_grads = jax.value_and_grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    ref_val_and_grads = jax.value_and_grad(
        lambda a, b, c: (_xla_flash(a, b, c, True, None)
                         .astype(jnp.float32) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    rv, rg = ring_val_and_grads
    fv, fg = ref_val_and_grads
    yield ("ring_step_loss",
           abs(float(bump("ring_step_loss", rv)) - float(fv)) / max(1.0, abs(float(fv))),
           0.02)
    for name_c, a, r in zip(("dq", "dk", "dv"), rg, fg):
        nm = f"ring_bwd_{name_c}"
        a = np.asarray(bump(nm, a.astype(jnp.float32)))
        r = np.asarray(r.astype(jnp.float32))
        scale = max(1.0, np.abs(r).max())
        yield nm, np.abs(a - r).max() / scale, 0.05

    # fused chunked LM-head CE, fwd + grads, vs the unfused XLA logits path
    from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy
    nrow, hdim, vocab = 96, 64, 512
    hid = jnp.asarray(rng.randn(nrow, hdim) * 0.3, jnp.float32)
    wce = jnp.asarray(rng.randn(hdim, vocab) * 0.1, jnp.float32)
    lab = jnp.asarray(rng.randint(0, vocab, (nrow,)), jnp.int32)

    def ce_ref(hv, wv):
        logits = (hv @ wv).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, lab[:, None], -1)[:, 0]
        return (lse - picked).mean()

    fv, fg = jax.value_and_grad(
        lambda hv, wv: fused_linear_cross_entropy(hv, wv, lab, chunk_rows=32),
        argnums=(0, 1))(hid, wce)
    rv, rg = jax.value_and_grad(ce_ref, argnums=(0, 1))(hid, wce)
    yield ("fused_ce_loss",
           abs(float(bump("fused_ce_loss", fv)) - float(rv)) / max(1.0, abs(float(rv))),
           1e-4)
    for name_c, a, r in zip(("dhidden", "dweight"), fg, rg):
        nm = f"fused_ce_{name_c}"
        a = np.asarray(bump(nm, a))
        r = np.asarray(r)
        scale = max(1e-3, np.abs(r).max())
        yield nm, np.abs(a - r).max() / scale, 1e-3


def kernel_smoke(perturb=None):
    """Numerics check of every Pallas kernel path — forward AND backward —
    ON THE REAL CHIP before any timing: a Mosaic-lowering regression must
    fail loudly here rather than silently corrupt the perf numbers
    (SURVEY.md §4 tolerance discipline; VERDICT r2 item 3)."""
    for name, err, tol in _kernel_checks(perturb):
        assert err < tol, f"{name} kernel mismatch: {err} >= {tol}"


def main():
    import jax

    backend = jax.default_backend()
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    on_tpu = backend in ("tpu", "axon")
    if on_tpu:
        kernel_smoke()  # numerics gate before timing
        # ~0.5B-param config: big enough for meaningful MFU, fits 16G HBM;
        # fused chunked LM-head CE keeps the [B*S, 32k] f32 logits out of HBM
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536, intermediate_size=4096,
                        num_hidden_layers=12, num_attention_heads=12,
                        max_position_embeddings=2048, fused_lm_loss=True)
        batch, seq, steps, windows = 16, 1024, 10, 3
        batch = int(os.environ.get("BENCH_BATCH", batch))
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256, intermediate_size=688,
                        num_hidden_layers=4, num_attention_heads=8,
                        max_position_embeddings=512, fused_lm_loss=True)
        batch, seq, steps, windows = 2, 128, 3, 1

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")  # bf16 params + activations on the MXU
    n_params = sum(p.size for p in model.parameters())

    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                                 multi_precision=True)

    def loss_fn(net, ids, labels):
        loss, _ = net(ids, labels=labels)
        return loss

    step = paddle.jit.TrainStep(model, loss_fn, opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    # compile + warmup
    step(ids, ids)
    step(ids, ids)
    import jax.numpy as jnp

    jnp.zeros(()).block_until_ready()

    # best-of-N windows: the shared-tunnel TPU throttles unpredictably
    # (±15% run-to-run), so the max window is the least-noisy estimate of
    # what the program sustains
    best_dt = None
    for _ in range(windows):
        t0 = time.time()
        for _ in range(steps):
            loss = step(ids, ids)
        float(loss)  # sync
        dt = time.time() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)

    tokens_per_sec = batch * seq * steps / best_dt
    # 6*N FLOPs/token (fwd+bwd); attention FLOPs excluded (conservative)
    flops_per_tok = 6 * n_params
    peak = {"axon": 197e12, "tpu": 197e12}.get(backend, 1e12)  # v5e bf16 peak
    mfu = tokens_per_sec * flops_per_tok / peak
    print(json.dumps({
        "metric": f"tokens/sec/chip GPT-{n_params/1e6:.0f}M bf16 train (b{batch}xs{seq}, {backend})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.50, 4),
    }))


if __name__ == "__main__":
    main()
