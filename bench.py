#!/usr/bin/env python
"""Benchmark: flagship GPT (ERNIE/LLaMA-style) jitted train step on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
value = tokens/sec/chip; vs_baseline = achieved MFU / 0.50 (BASELINE.md's
derived A100-parity anchor — no published reference numbers exist, see
BASELINE.md provenance).
"""

import json
import os
import sys
import time


def kernel_smoke():
    """Tiny numerics check of the Pallas kernels ON THE REAL CHIP before any
    timing: a Mosaic-lowering regression (e.g. in the GQA index maps) must
    fail loudly here rather than silently corrupt the perf numbers
    (SURVEY.md §4 tolerance discipline; VERDICT r1 item 10)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    b, s, h, kv, d = 1, 256, 4, 2, 128
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, s, kv, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, s, kv, d), jnp.bfloat16)

    from paddle_tpu.ops.pallas.flash import flash_attention as pallas_flash
    from paddle_tpu.ops.flash_attention import _xla_flash
    for causal in (False, True):
        out = np.asarray(pallas_flash(q, k, v, causal=causal,
                                      interpret=False), np.float32)
        ref = np.asarray(_xla_flash(q, k, v, causal, None), np.float32)
        err = np.abs(out - ref).max()
        assert err < 0.1, f"flash kernel mismatch (causal={causal}): {err}"

    from paddle_tpu.ops.pallas.norms import layer_norm, rms_norm
    x = jnp.asarray(rng.randn(8, 512), jnp.float32)
    w = jnp.asarray(rng.randn(512), jnp.float32)
    bias = jnp.asarray(rng.randn(512), jnp.float32)
    ln = np.asarray(layer_norm(x, w, bias, interpret=False))
    mu = np.asarray(x, np.float64).mean(-1, keepdims=True)
    var = np.asarray(x, np.float64).var(-1, keepdims=True)
    ln_ref = (np.asarray(x) - mu) / np.sqrt(var + 1e-5) * np.asarray(w) + np.asarray(bias)
    assert np.abs(ln - ln_ref).max() < 1e-3, "layer_norm kernel mismatch"
    rn = np.asarray(rms_norm(x, w, interpret=False))
    rn_ref = np.asarray(x) / np.sqrt((np.asarray(x, np.float64) ** 2).mean(-1, keepdims=True) + 1e-6) * np.asarray(w)
    assert np.abs(rn - rn_ref).max() < 1e-3, "rms_norm kernel mismatch"

    from paddle_tpu.ops.pallas.norms import group_norm
    xg = jnp.asarray(rng.randn(2, 32, 16, 16), jnp.float32)
    wg = jnp.asarray(rng.randn(32), jnp.float32)
    bg = jnp.asarray(rng.randn(32), jnp.float32)
    gn = np.asarray(group_norm(xg, wg, bg, 8, 1e-5, interpret=False))
    x64 = np.asarray(xg, np.float64).reshape(2, 8, 4, 16, 16)
    mu = x64.mean(axis=(2, 3, 4), keepdims=True)
    var = x64.var(axis=(2, 3, 4), keepdims=True)
    gn_ref = ((x64 - mu) / np.sqrt(var + 1e-5)).reshape(2, 32, 16, 16) \
        * np.asarray(wg).reshape(1, 32, 1, 1) + np.asarray(bg).reshape(1, 32, 1, 1)
    assert np.abs(gn - gn_ref).max() < 1e-3, "group_norm kernel mismatch"


def main():
    import jax

    backend = jax.default_backend()
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    on_tpu = backend in ("tpu", "axon")
    if on_tpu:
        kernel_smoke()  # numerics gate before timing
        # ~0.5B-param config: big enough for meaningful MFU, fits 16G HBM;
        # fused chunked LM-head CE keeps the [B*S, 32k] f32 logits out of HBM
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536, intermediate_size=4096,
                        num_hidden_layers=12, num_attention_heads=12,
                        max_position_embeddings=2048, fused_lm_loss=True)
        batch, seq, steps, windows = 16, 1024, 10, 3
        batch = int(os.environ.get("BENCH_BATCH", batch))
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256, intermediate_size=688,
                        num_hidden_layers=4, num_attention_heads=8,
                        max_position_embeddings=512, fused_lm_loss=True)
        batch, seq, steps, windows = 2, 128, 3, 1

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")  # bf16 params + activations on the MXU
    n_params = sum(p.size for p in model.parameters())

    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                                 multi_precision=True)

    def loss_fn(net, ids, labels):
        loss, _ = net(ids, labels=labels)
        return loss

    step = paddle.jit.TrainStep(model, loss_fn, opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    # compile + warmup
    step(ids, ids)
    step(ids, ids)
    import jax.numpy as jnp

    jnp.zeros(()).block_until_ready()

    # best-of-N windows: the shared-tunnel TPU throttles unpredictably
    # (±15% run-to-run), so the max window is the least-noisy estimate of
    # what the program sustains
    best_dt = None
    for _ in range(windows):
        t0 = time.time()
        for _ in range(steps):
            loss = step(ids, ids)
        float(loss)  # sync
        dt = time.time() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)

    tokens_per_sec = batch * seq * steps / best_dt
    # 6*N FLOPs/token (fwd+bwd); attention FLOPs excluded (conservative)
    flops_per_tok = 6 * n_params
    peak = {"axon": 197e12, "tpu": 197e12}.get(backend, 1e12)  # v5e bf16 peak
    mfu = tokens_per_sec * flops_per_tok / peak
    print(json.dumps({
        "metric": f"tokens/sec/chip GPT-{n_params/1e6:.0f}M bf16 train (b{batch}xs{seq}, {backend})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.50, 4),
    }))


if __name__ == "__main__":
    main()
