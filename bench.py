#!/usr/bin/env python
"""Benchmark: flagship GPT (ERNIE/LLaMA-style) jitted train step on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
value = tokens/sec/chip; vs_baseline = achieved MFU / 0.50 (BASELINE.md's
derived A100-parity anchor — no published reference numbers exist, see
BASELINE.md provenance).
"""

import json
import os
import sys
import time


def main():
    import jax

    backend = jax.default_backend()
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    on_tpu = backend in ("tpu", "axon")
    if on_tpu:
        # ~0.5B-param config: big enough for meaningful MFU, fits 16G HBM
        cfg = GPTConfig(vocab_size=32000, hidden_size=1536, intermediate_size=4096,
                        num_hidden_layers=12, num_attention_heads=12,
                        max_position_embeddings=2048)
        batch, seq, steps, windows = 16, 1024, 10, 3
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256, intermediate_size=688,
                        num_hidden_layers=4, num_attention_heads=8,
                        max_position_embeddings=512)
        batch, seq, steps, windows = 2, 128, 3, 1

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")  # bf16 params + activations on the MXU
    n_params = sum(p.size for p in model.parameters())

    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                                 multi_precision=True)

    def loss_fn(net, ids, labels):
        loss, _ = net(ids, labels=labels)
        return loss

    step = paddle.jit.TrainStep(model, loss_fn, opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    # compile + warmup
    step(ids, ids)
    step(ids, ids)
    import jax.numpy as jnp

    jnp.zeros(()).block_until_ready()

    # best-of-N windows: the shared-tunnel TPU throttles unpredictably
    # (±15% run-to-run), so the max window is the least-noisy estimate of
    # what the program sustains
    best_dt = None
    for _ in range(windows):
        t0 = time.time()
        for _ in range(steps):
            loss = step(ids, ids)
        float(loss)  # sync
        dt = time.time() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)

    tokens_per_sec = batch * seq * steps / best_dt
    # 6*N FLOPs/token (fwd+bwd); attention FLOPs excluded (conservative)
    flops_per_tok = 6 * n_params
    peak = {"axon": 197e12, "tpu": 197e12}.get(backend, 1e12)  # v5e bf16 peak
    mfu = tokens_per_sec * flops_per_tok / peak
    print(json.dumps({
        "metric": f"tokens/sec/chip GPT-{n_params/1e6:.0f}M bf16 train (b{batch}xs{seq}, {backend})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.50, 4),
    }))


if __name__ == "__main__":
    main()
