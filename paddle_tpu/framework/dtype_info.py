"""paddle.iinfo / paddle.finfo (ref: pybind dtype-info bindings (U))."""

from __future__ import annotations

import numpy as np

from ..core.dtype import to_jax_dtype


class iinfo:
    def __init__(self, dtype):
        info = np.iinfo(np.dtype(to_jax_dtype(dtype)))
        self.min = int(info.min)
        self.max = int(info.max)
        self.bits = int(info.bits)
        self.dtype = str(info.dtype)

    def __repr__(self):
        return f"iinfo(min={self.min}, max={self.max}, bits={self.bits}, dtype={self.dtype})"


class finfo:
    def __init__(self, dtype):
        import jax.numpy as jnp
        import ml_dtypes

        jd = to_jax_dtype(dtype)
        if jd == jnp.bfloat16:
            info = ml_dtypes.finfo(ml_dtypes.bfloat16)
        else:
            info = np.finfo(np.dtype(jd))
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.tiny = float(getattr(info, "tiny", getattr(info, "smallest_normal", 0.0)))
        self.smallest_normal = self.tiny
        self.resolution = float(getattr(info, "resolution", self.eps))
        self.bits = int(info.bits)
        self.dtype = str(np.dtype(jd)) if jd != jnp.bfloat16 else "bfloat16"

    def __repr__(self):
        return (f"finfo(min={self.min}, max={self.max}, eps={self.eps}, "
                f"bits={self.bits}, dtype={self.dtype})")
