"""paddle.save / paddle.load parity (ref: python/paddle/framework/io.py (U)).

Format: a single pickle file whose tensor leaves are numpy arrays — same
"nested state_dict" user contract as the reference's .pdparams. The sharded /
distributed checkpoint path (tensorstore-style, reshard-on-load) lives in
paddle_tpu.distributed.checkpoint.
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data))
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


class _TensorPayload:
    __slots__ = ("array",)

    def __init__(self, array):
        self.array = array


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        return obj.array if return_numpy else Tensor(obj.array)
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saveable(obj, return_numpy=return_numpy)
