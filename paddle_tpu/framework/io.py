"""paddle.save / paddle.load parity (ref: python/paddle/framework/io.py (U)).

Two formats, one API:
  * small objects — a single pickle whose tensor leaves are numpy arrays
    (same "nested state_dict" user contract as the reference's .pdparams);
  * large checkpoints — the PTCKPT01 container: a pickled structure header
    followed by raw 64-byte-aligned tensor payloads, written/read through the
    native C++ parallel positional-IO path (paddle_tpu.native pwrite/pread —
    the TPU-era analog of the reference's C++ SaveCombine/LoadCombine ops,
    SURVEY.md §2.2 P27) and loaded zero-copy where possible.

The sharded/distributed checkpoint path (reshard-on-load) lives in
paddle_tpu.distributed.checkpoint on top of this.
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor

_MAGIC = b"PTCKPT01"
_ALIGN = 64
# below this many payload bytes the container's extra syscalls cost more
# than they save
_CONTAINER_THRESHOLD = 1 << 20


class _TensorPayload:
    __slots__ = ("array",)

    def __init__(self, array):
        self.array = array


class _PayloadRef:
    """Placeholder in the pickled header pointing into the payload region."""

    __slots__ = ("index",)

    def __init__(self, index):
        self.index = index


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data))
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        return obj.array if return_numpy else Tensor(obj.array)
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v, return_numpy) for v in obj)
    return obj


def _swap_payloads(obj, payloads):
    """_TensorPayload -> _PayloadRef, appending arrays to `payloads`."""
    if isinstance(obj, _TensorPayload):
        payloads.append(np.ascontiguousarray(obj.array))
        return _PayloadRef(len(payloads) - 1)
    if isinstance(obj, dict):
        return {k: _swap_payloads(v, payloads) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_swap_payloads(v, payloads) for v in obj)
    return obj


def _resolve_refs(obj, arrays, return_numpy):
    if isinstance(obj, _PayloadRef):
        a = arrays[obj.index]
        return a if return_numpy else Tensor(a)
    if isinstance(obj, dict):
        return {k: _resolve_refs(v, arrays, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_resolve_refs(v, arrays, return_numpy) for v in obj)
    return obj


def _save_container(saveable, path, protocol):
    payloads = []
    structure = _swap_payloads(saveable, payloads)
    metas = []
    offset = 0
    for a in payloads:
        offset = (offset + _ALIGN - 1) // _ALIGN * _ALIGN
        metas.append((str(a.dtype), a.shape, offset, a.nbytes))
        offset += a.nbytes
    header = pickle.dumps({"structure": structure, "metas": metas},
                          protocol=protocol)
    preamble = _MAGIC + len(header).to_bytes(8, "little") + header
    payload_start = (len(preamble) + _ALIGN - 1) // _ALIGN * _ALIGN
    total = payload_start + offset

    # write to a temp file and os.replace so an interrupted save can never
    # leave a structurally-valid-but-zero checkpoint for autoresume to load
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(preamble)
        f.truncate(total)

    from .. import native

    for a, (_, _, off, nbytes) in zip(payloads, metas):
        if nbytes == 0:
            continue
        if not native.pwrite(tmp, payload_start + off, a):
            with open(tmp, "r+b") as f:  # no native toolchain: plain IO
                f.seek(payload_start + off)
                f.write(a.tobytes())
    os.replace(tmp, path)


def _load_container(path, return_numpy):
    with open(path, "rb") as f:
        f.seek(len(_MAGIC))
        header_len = int.from_bytes(f.read(8), "little")
        header = pickle.loads(f.read(header_len))
        preamble_len = len(_MAGIC) + 8 + header_len
    payload_start = (preamble_len + _ALIGN - 1) // _ALIGN * _ALIGN

    from .. import native

    arrays = []
    use_native = native.available()
    mm = None
    if not use_native:
        mm = np.memmap(path, dtype=np.uint8, mode="r")
    for dtype_str, shape, off, nbytes in header["metas"]:
        out = np.empty(shape, dtype=np.dtype(dtype_str))
        if nbytes:
            if use_native:
                flat = out.reshape(-1).view(np.uint8)
                native.pread(path, payload_start + off, flat)
            else:
                raw = mm[payload_start + off: payload_start + off + nbytes]
                # copy into the writable buffer (frombuffer views are
                # read-only, unlike every other load path)
                out.reshape(-1).view(np.uint8)[:] = raw
        arrays.append(out)
    return _resolve_refs(header["structure"], arrays, return_numpy)


def _payload_bytes(obj):
    if isinstance(obj, _TensorPayload):
        return obj.array.nbytes
    if isinstance(obj, dict):
        return sum(_payload_bytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_payload_bytes(v) for v in obj)
    return 0


def save(obj, path, protocol=4, **configs):
    # checkpoint saves land on the observability timeline (begin/end pair
    # + a duration histogram), so "why did step time spike" is answerable
    # when the answer is "a checkpoint flushed"
    from ..observability.span import span as _obs_span

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    saveable = _to_saveable(obj)
    nbytes = _payload_bytes(saveable)
    with _obs_span("checkpoint.save", cat="io",
                   event_args={"path": str(path),
                               "payload_bytes": nbytes}):
        if nbytes >= _CONTAINER_THRESHOLD:
            _save_container(saveable, path, protocol)
            return
        with open(path, "wb") as f:
            pickle.dump(saveable, f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
    if magic == _MAGIC:
        return _load_container(path, return_numpy)
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saveable(obj, return_numpy=return_numpy)
