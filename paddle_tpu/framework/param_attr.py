"""ParamAttr (ref: python/paddle/base/param_attr.py (U))."""

from __future__ import annotations


class ParamAttr:
    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        do_model_average=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        from ..nn.initializer.initializer import Initializer

        if attr is None or attr is False:
            # False means "omit this parameter" — callers check identity first
            return None
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        raise TypeError(f"cannot convert {attr!r} to ParamAttr")
