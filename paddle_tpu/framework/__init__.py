from .param_attr import ParamAttr
from .io import save, load
from ..core import random_state


def seed(s):
    from ..core.random import seed as _seed

    _seed(s)


def get_default_dtype():
    from ..core.dtype import get_default_dtype as _g

    return _g()


def set_default_dtype(d):
    from ..core.dtype import set_default_dtype as _s

    return _s(d)
