"""paddle.batch reader compatibility (ref: python/paddle/reader (U) — the
pre-2.0 generator-based input pipeline that `paddle.batch` wraps)."""


def batch(reader, batch_size, drop_last=False):
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched
