from .dataset import (
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ZipDataset,
    ConcatDataset, ChainDataset, Subset, random_split,
)
from .sampler import (
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    SubsetRandomSampler, BatchSampler, DistributedBatchSampler,
)
from .dataloader import (DataLoader, WorkerInfo, default_collate_fn,
                         get_worker_info)
from .record import RecordWriter, RecordFile, RecordDataset
