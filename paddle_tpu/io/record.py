"""Native record-file sample store (SURVEY.md §2.2 P6 — the reference feeds
big runs via multiprocess DataLoader workers + pinned memory; the host-native
TPU analog is an indexed binary record file read by C++ threads with no GIL
between syscall and numpy view).

Format PTRECD01 (see native.cc): magic + [u64 len + payload]*. Use
`RecordWriter` to build a file, `RecordDataset` (a paddle.io.Dataset) to
consume it — compose with DataLoader like any dataset; `read_batch` gives
the packed parallel-read path the thread workers use.

A pure-Python fallback keeps everything working without the toolchain."""

from __future__ import annotations

import ctypes
import os
import struct

import numpy as np

_MAGIC = b"PTRECD01"


class RecordWriter:
    def __init__(self, path):
        self._f = open(path, "wb")
        self._f.write(_MAGIC)
        self._n = 0

    def write(self, payload):
        """Append one record (bytes / bytes-like / numpy array's buffer)."""
        if isinstance(payload, np.ndarray):
            payload = payload.tobytes()
        b = bytes(payload)
        self._f.write(struct.pack("<Q", len(b)))
        self._f.write(b)
        self._n += 1
        return self._n - 1

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordFile:
    """Indexed reader over a PTRECD01 file; native parallel reads when the
    C++ core is available."""

    def __init__(self, path, num_threads=0):
        self.path = path
        self._threads = num_threads
        from ..native import get_lib

        self._lib = get_lib()
        self._h = None
        if self._lib is not None:
            h = self._lib.prec_open(os.fsencode(path))
            if h > 0:
                self._h = h
        if self._h is None:
            self._index = self._scan(path)

    @staticmethod
    def _scan(path):
        idx = []
        with open(path, "rb") as f:
            if f.read(8) != _MAGIC:
                raise ValueError(f"{path!r} is not a PTRECD01 record file")
            off = 8
            end = os.fstat(f.fileno()).st_size
            while off + 8 <= end:
                f.seek(off)
                (ln,) = struct.unpack("<Q", f.read(8))
                off += 8
                if off + ln > end:
                    break
                idx.append((off, ln))
                off += ln
        return idx

    def __len__(self):
        if self._h is not None:
            return int(self._lib.prec_count(self._h))
        return len(self._index)

    def size(self, i):
        if self._h is not None:
            s = int(self._lib.prec_size(self._h, int(i)))
            if s < 0:
                raise IndexError(i)
            return s
        return self._index[i][1]

    def read(self, i):
        """One record as bytes."""
        if self._h is not None:
            n = self.size(i)
            buf = np.empty(n, np.uint8)
            rc = self._lib.prec_read(
                self._h, int(i), buf.ctypes.data_as(ctypes.c_void_p))
            if rc != 0:
                raise OSError(rc, f"prec_read failed for record {i}")
            return buf.tobytes()
        off, ln = self._index[i]
        with open(self.path, "rb") as f:
            f.seek(off)
            return f.read(ln)

    def read_batch(self, indices):
        """Parallel read of many records into ONE contiguous buffer;
        returns (buffer, offsets, sizes) — zero-copy views are
        buffer[offsets[k]:offsets[k]+sizes[k]]."""
        indices = [int(i) for i in indices]
        sizes = np.asarray([self.size(i) for i in indices], np.uint64)
        offsets = np.zeros(len(indices), np.uint64)
        if len(indices) > 1:
            offsets[1:] = np.cumsum(sizes[:-1])
        total = int(sizes.sum())
        buf = np.empty(total, np.uint8)
        if self._h is not None and indices:
            idx_arr = np.asarray(indices, np.int64)
            rc = self._lib.prec_read_many(
                self._h,
                idx_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(indices),
                buf.ctypes.data_as(ctypes.c_void_p),
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                self._threads)
            if rc != 0:
                raise OSError(rc, "prec_read_many failed")
        else:
            for k, i in enumerate(indices):
                o = int(offsets[k])
                buf[o:o + int(sizes[k])] = np.frombuffer(self.read(i),
                                                         np.uint8)
        return buf, offsets, sizes

    def close(self):
        if self._h is not None:
            self._lib.prec_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class RecordDataset:
    """paddle.io-style Dataset over a record file. `decode_fn(bytes) -> sample`
    defaults to identity; `ndarray_spec=(dtype, shape)` decodes fixed-shape
    tensors with zero copies."""

    def __init__(self, path, decode_fn=None, ndarray_spec=None,
                 num_threads=0):
        self._rf = RecordFile(path, num_threads=num_threads)
        self._decode = decode_fn
        self._spec = ndarray_spec

    def __len__(self):
        return len(self._rf)

    def __getitem__(self, i):
        raw = self._rf.read(i)
        if self._spec is not None:
            dtype, shape = self._spec
            return np.frombuffer(raw, dtype=dtype).reshape(shape)
        if self._decode is not None:
            return self._decode(raw)
        return raw

    def read_batch(self, indices):
        """Packed batch via the native parallel path: for fixed-shape
        ndarray records this returns one [n, *shape] array with a single
        allocation and no per-sample Python."""
        buf, offsets, sizes = self._rf.read_batch(indices)
        if self._spec is not None:
            dtype, shape = self._spec
            per = int(np.prod(shape)) * np.dtype(dtype).itemsize
            if not all(int(s) == per for s in sizes):
                raise ValueError("records do not match ndarray_spec")
            return buf.view(dtype).reshape((len(indices),) + tuple(shape))
        out = []
        for k in range(len(indices)):
            o = int(offsets[k])
            raw = buf[o:o + int(sizes[k])].tobytes()
            out.append(self._decode(raw) if self._decode else raw)
        return out
