"""DataLoader (ref: python/paddle/io/dataloader/dataloader_iter.py (U)).

TPU-native design: the reference's multiprocess workers + pinned-memory +
CUDA-stream H2D pipeline becomes a threaded prefetch pipeline feeding
device_put — on TPU VMs the host is roomy and jax transfers are async, so
worker *threads* (NumPy releases the GIL) plus a bounded prefetch queue give
the same overlap without fork/IPC fragility. A native C++ prefetcher can slot
under `paddle_tpu.utils.hostloader` for decode-heavy pipelines.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, (int, np.integer)):
        # int32, not the reference's int64: x64 is disabled jax-side, and
        # int32 indices are what TPU embedding/gather kernels want
        return Tensor(np.asarray(batch, np.int32))
    if isinstance(sample, float):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(items)) for items in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, str):
        return list(batch)
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.timeout = timeout
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_size = batch_size
            self.batch_sampler = None
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
                )

    def __len__(self):
        if self._iterable:
            raise TypeError("length of IterableDataset DataLoader is unknown")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # ---------------- iteration ----------------
    def _fetch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def _iter_sync(self):
        if self._iterable:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.collate_fn([self.dataset[i]])
            return
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_threaded(self):
        """num_workers>0: worker threads fetch+collate; a bounded queue keeps
        `num_workers * prefetch_factor` batches in flight, preserving order."""
        index_iter = iter(self.batch_sampler)
        max_inflight = self.num_workers * self.prefetch_factor
        results = {}
        results_lock = threading.Condition()
        task_q = queue.Queue()
        n_submitted = 0
        n_consumed = 0
        done_submitting = False

        def worker():
            while True:
                item = task_q.get()
                if item is None:
                    return
                seq, indices = item
                try:
                    out = self._fetch(indices)
                except Exception as e:  # propagate to consumer
                    out = e
                with results_lock:
                    results[seq] = out
                    results_lock.notify_all()

        threads = [threading.Thread(target=worker, daemon=True) for _ in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            # prime
            for _ in range(max_inflight):
                try:
                    task_q.put((n_submitted, next(index_iter)))
                    n_submitted += 1
                except StopIteration:
                    done_submitting = True
                    break
            while n_consumed < n_submitted or not done_submitting:
                with results_lock:
                    while n_consumed not in results:
                        got_notify = results_lock.wait(timeout=self.timeout or None)
                        # re-check the predicate before timing out: wait() can
                        # return False even though the batch landed just as the
                        # deadline elapsed
                        if not got_notify and self.timeout \
                                and n_consumed not in results:
                            raise RuntimeError(
                                f"DataLoader worker timed out after "
                                f"{self.timeout}s waiting for batch {n_consumed}")
                    out = results.pop(n_consumed)
                n_consumed += 1
                if isinstance(out, Exception):
                    raise out
                if not done_submitting:
                    try:
                        task_q.put((n_submitted, next(index_iter)))
                        n_submitted += 1
                    except StopIteration:
                        done_submitting = True
                yield out
        finally:
            for _ in threads:
                task_q.put(None)

    def __iter__(self):
        if self.num_workers and self.num_workers > 0 and not self._iterable and self.batch_sampler is not None:
            return self._iter_threaded()
        return self._iter_sync()


def get_worker_info():
    return None
