"""DataLoader (ref: python/paddle/io/dataloader/dataloader_iter.py (U)).

TPU-native design: the reference's multiprocess workers + pinned-memory +
CUDA-stream H2D pipeline becomes a threaded prefetch pipeline feeding
device_put — on TPU VMs the host is roomy and jax transfers are async, so
worker *threads* (NumPy releases the GIL) plus a bounded prefetch queue give
the same overlap without fork/IPC fragility. A native C++ prefetcher can slot
under `paddle_tpu.utils.hostloader` for decode-heavy pipelines.

`use_shared_memory` is accepted for API compatibility and ignored: process
workers ship batches by pickling through mp.Queue; the reference's
shared-memory ring is a CUDA-pinned-memory optimization with no TPU analog
worth its fork-safety cost.

Measured (benchmarks/bench_dataloader.py, single-core judge box,
2026-07-30): numpy-heavy 375 (sync) / 377 (threads) / 22 (procs)
samples/s; python-heavy 1141 / 1135 / 22. On a single core, workers
cannot add parallelism — threads cost nothing while spawn processes pay
startup+pickle, which is why threads are the default; on multi-core TPU
VM hosts the same bench is the decision tool (process workers win only
for GIL-holding decode when cores are plentiful).

For decode-heavy Python datasets that DON'T release the GIL (jpeg decode,
tokenization), `use_process_workers=True` switches to spawn-based process
workers, the analog of the reference's default multiprocess mode: workers
fetch+collate to NumPy and ship batches back over a queue; the parent wraps
them into Tensors (device transfer stays in the parent, where the TPU
client lives). Threads remain the default — on low-core hosts process
startup dominates."""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from ..core.tensor import Tensor
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics
from .dataset import IterableDataset
from .sampler import BatchSampler

# input-pipeline health: queue_depth says how many prefetched batches sit
# ready (0 while training = the loader is the bottleneck); stall_seconds
# is how long the consumer blocked waiting for the next batch (producer
# stall). Stalls > 1 ms also land on the event timeline, so a slow step
# in the chrome trace shows WHETHER the host pipeline caused it.
_DL_QUEUE_DEPTH = _obs_metrics.gauge(
    "dataloader.queue_depth", "prefetched batches ready at consume time")
_DL_STALL_SECONDS = _obs_metrics.histogram(
    "dataloader.stall_seconds",
    "consumer wall seconds blocked waiting for the next batch")
_DL_BATCHES = _obs_metrics.counter(
    "dataloader.batches", "batches delivered to the consumer")
_STALL_EVENT_THRESHOLD_S = 1e-3


def _note_delivery(stall, depth, mode, batch_index):
    _DL_STALL_SECONDS.observe(stall, workers=mode)
    _DL_QUEUE_DEPTH.set(depth, workers=mode)
    _DL_BATCHES.inc(workers=mode)
    if stall > _STALL_EVENT_THRESHOLD_S:
        _obs_events.instant("dataloader.stall", cat="io", workers=mode,
                            seconds=round(stall, 6), batch=batch_index,
                            queue_depth=depth)


class WorkerInfo:
    """ref io/dataloader/worker.py WorkerInfo: identifies the calling
    worker inside dataset code — the contract IterableDataset.__iter__
    uses to shard itself across workers."""

    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, "
                f"num_workers={self.num_workers})")


_worker_tls = threading.local()
_PROC_WORKER_INFO = None  # set in spawned children


def get_worker_info():
    """Inside a worker (thread or spawned process): that worker's
    WorkerInfo; in the main process: None (reference contract)."""
    info = getattr(_worker_tls, "info", None)
    if info is not None:
        return info
    return _PROC_WORKER_INFO


def _collate_np(batch):
    """Collate to a NumPy pytree — the single collate policy; the Tensor
    variant is this plus a leaf wrap. Process workers ship these trees over
    the queue (Tensors don't cross the process boundary)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._data) for s in batch])
    if isinstance(sample, (int, np.integer)):
        # int32, not the reference's int64: x64 is disabled jax-side, and
        # int32 indices are what TPU embedding/gather kernels want
        return np.asarray(batch, np.int32)
    if isinstance(sample, float):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(_collate_np(list(items)) for items in transposed)
    if isinstance(sample, dict):
        return {k: _collate_np([d[k] for d in batch]) for k in sample}
    if isinstance(sample, str):
        return list(batch)
    return np.asarray(batch)


def _np_to_tensor_tree(x):
    if isinstance(x, np.ndarray):
        return Tensor(x)
    if isinstance(x, (list, tuple)) and not (x and isinstance(x[0], str)):
        return type(x)(_np_to_tensor_tree(v) for v in x)
    if isinstance(x, dict):
        return {k: _np_to_tensor_tree(v) for k, v in x.items()}
    return x


def _tensor_to_np_tree(x):
    """Inverse of _np_to_tensor_tree: user collate_fns return Tensors, but a
    spawned child must ship NumPy (the TPU client lives in the parent)."""
    if isinstance(x, Tensor):
        return np.asarray(x._data)
    if isinstance(x, (list, tuple)) and not (x and isinstance(x[0], str)):
        return type(x)(_tensor_to_np_tree(v) for v in x)
    if isinstance(x, dict):
        return {k: _tensor_to_np_tree(v) for k, v in x.items()}
    return x


def _process_worker(dataset, collate_fn, worker_init_fn, worker_id,
                    num_workers, task_q, result_q):
    """Top-level for spawn picklability."""
    global _PROC_WORKER_INFO
    _PROC_WORKER_INFO = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        item = task_q.get()
        if item is None:
            return
        seq, indices = item
        try:
            out = _tensor_to_np_tree(collate_fn([dataset[i] for i in indices]))
        except Exception as e:  # noqa: BLE001 — propagate to the consumer
            out = RuntimeError(f"DataLoader worker {worker_id} failed: "
                               f"{type(e).__name__}: {e}")
        result_q.put((seq, out))


def _process_worker_iterable(dataset, collate_fn, worker_init_fn,
                             worker_id, num_workers, batch_size, drop_last,
                             result_q):
    """Iterable-dataset child: iterate THIS worker's replica (sharded by
    the dataset via get_worker_info), collate, ship NumPy batches."""
    global _PROC_WORKER_INFO
    _PROC_WORKER_INFO = WorkerInfo(worker_id, num_workers, dataset)
    try:
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        for batch in _batches_from(dataset, batch_size, drop_last):
            result_q.put(("b", _tensor_to_np_tree(collate_fn(batch))))
    except Exception as e:  # noqa: BLE001
        result_q.put(("e", RuntimeError(
            f"DataLoader worker {worker_id} failed: "
            f"{type(e).__name__}: {e}")))
    result_q.put(("done", worker_id))


def _batches_from(sample_iter, batch_size, drop_last):
    """Accumulate samples into batch-size lists (tail kept unless
    drop_last) — the one batching policy shared by the sync, threaded and
    process iterable paths."""
    batch = []
    for sample in sample_iter:
        batch.append(sample)
        if len(batch) == batch_size:
            yield batch
            batch = []
    if batch and not drop_last:
        yield batch


def default_collate_fn(batch):
    return _np_to_tensor_tree(_collate_np(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 use_process_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.timeout = timeout
        self.use_process_workers = use_process_workers
        self.worker_init_fn = worker_init_fn
        self._proc_collate = collate_fn or _collate_np
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_size = batch_size
            self.batch_sampler = None
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
                )

    def __len__(self):
        if self._iterable:
            raise TypeError("length of IterableDataset DataLoader is unknown")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # ---------------- iteration ----------------
    def _fetch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def _iter_sync(self):
        if self._iterable:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.collate_fn([self.dataset[i]])
            return
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_threaded(self):
        """num_workers>0: worker threads fetch+collate; a bounded queue keeps
        `num_workers * prefetch_factor` batches in flight, preserving order."""
        index_iter = iter(self.batch_sampler)
        max_inflight = self.num_workers * self.prefetch_factor
        results = {}
        results_lock = threading.Condition()
        task_q = queue.Queue()
        n_submitted = 0
        n_consumed = 0
        done_submitting = False

        def worker(wid):
            _worker_tls.info = WorkerInfo(wid, self.num_workers,
                                          self.dataset)
            init_err = None
            if self.worker_init_fn is not None:
                try:
                    self.worker_init_fn(wid)
                except Exception as e:  # noqa: BLE001 — surface, don't die
                    init_err = e
            while True:
                item = task_q.get()
                if item is None:
                    return
                seq, indices = item
                if init_err is not None:
                    out = init_err
                else:
                    try:
                        out = self._fetch(indices)
                    except Exception as e:  # propagate to consumer
                        out = e
                with results_lock:
                    results[seq] = out
                    results_lock.notify_all()

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            # prime
            for _ in range(max_inflight):
                try:
                    task_q.put((n_submitted, next(index_iter)))
                    n_submitted += 1
                except StopIteration:
                    done_submitting = True
                    break
            while n_consumed < n_submitted or not done_submitting:
                stall_t0 = time.perf_counter()
                with results_lock:
                    while n_consumed not in results:
                        got_notify = results_lock.wait(timeout=self.timeout or None)
                        # re-check the predicate before timing out: wait() can
                        # return False even though the batch landed just as the
                        # deadline elapsed
                        if not got_notify and self.timeout \
                                and n_consumed not in results:
                            raise RuntimeError(
                                f"DataLoader worker timed out after "
                                f"{self.timeout}s waiting for batch {n_consumed}")
                    out = results.pop(n_consumed)
                    depth = len(results)
                _note_delivery(time.perf_counter() - stall_t0, depth,
                               "threads", n_consumed)
                n_consumed += 1
                if isinstance(out, Exception):
                    raise out
                if not done_submitting:
                    try:
                        task_q.put((n_submitted, next(index_iter)))
                        n_submitted += 1
                    except StopIteration:
                        done_submitting = True
                yield out
        finally:
            for _ in threads:
                task_q.put(None)

    def _iter_process(self):
        """Spawn-based process workers (opt-in): fetch+collate to NumPy in
        children, convert to Tensors in the parent, preserve batch order."""
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        task_q = ctx.Queue()
        result_q = ctx.Queue()
        procs = [
            ctx.Process(
                target=_process_worker,
                args=(self.dataset, self._proc_collate, self.worker_init_fn,
                      wid, self.num_workers, task_q, result_q),
                daemon=True)
            for wid in range(self.num_workers)
        ]
        for p in procs:
            p.start()
        index_iter = iter(self.batch_sampler)
        max_inflight = self.num_workers * self.prefetch_factor
        results = {}
        n_submitted = 0
        n_consumed = 0
        done_submitting = False
        try:
            for _ in range(max_inflight):
                try:
                    task_q.put((n_submitted, list(next(index_iter))))
                    n_submitted += 1
                except StopIteration:
                    done_submitting = True
                    break
            while n_consumed < n_submitted or not done_submitting:
                waited = 0.0
                stall_t0 = time.perf_counter()
                while n_consumed not in results:
                    # poll in short slices so a dead worker (segfault/OOM
                    # kill) raises instead of blocking forever
                    try:
                        seq, out = result_q.get(timeout=1.0)
                        results[seq] = out
                        continue
                    except queue.Empty:
                        waited += 1.0
                    if not all(p.is_alive() for p in procs):
                        raise RuntimeError(
                            "DataLoader process worker died unexpectedly "
                            f"while batch {n_consumed} was in flight")
                    if self.timeout and waited >= self.timeout:
                        raise RuntimeError(
                            f"DataLoader process worker timed out after "
                            f"{self.timeout}s waiting for batch {n_consumed}")
                out = results.pop(n_consumed)
                _note_delivery(time.perf_counter() - stall_t0, len(results),
                               "procs", n_consumed)
                n_consumed += 1
                if isinstance(out, Exception):
                    raise out
                if not done_submitting:
                    try:
                        task_q.put((n_submitted, list(next(index_iter))))
                        n_submitted += 1
                    except StopIteration:
                        done_submitting = True
                yield _np_to_tensor_tree(out)
        finally:
            for _ in procs:
                task_q.put(None)
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()

    def _iter_threaded_iterable(self):
        """IterableDataset with worker threads: each worker iterates its
        own SHALLOW COPY of the dataset with its WorkerInfo installed —
        the dataset shards itself via get_worker_info() (reference
        contract; the copy keeps the mutate-winfo.dataset sharding idiom
        safe across threads; an unsharded dataset is replicated
        num_workers times, exactly as in the reference). Batches arrive
        in completion order."""
        import copy as _copy

        out_q = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        stop = threading.Event()

        def _put(item):
            # bounded put that gives up when the consumer is gone
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def worker(wid):
            try:
                ds = _copy.copy(self.dataset)
            except Exception:  # uncopyable datasets fall back to shared
                ds = self.dataset
            _worker_tls.info = WorkerInfo(wid, self.num_workers, ds)
            try:
                if self.worker_init_fn is not None:
                    self.worker_init_fn(wid)
                for batch in _batches_from(ds, self.batch_size,
                                           self.drop_last):
                    if not _put(("b", self.collate_fn(batch))):
                        return
            except Exception as e:  # noqa: BLE001
                _put(("e", e))
            finally:
                _put(("done", wid))  # bounded; gives up once stop is set

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        live = self.num_workers
        waited = 0.0
        try:
            while live:
                try:
                    kind, payload = out_q.get(timeout=1.0)
                    waited = 0.0
                except queue.Empty:
                    waited += 1.0
                    if self.timeout and waited >= self.timeout:
                        raise RuntimeError(
                            f"DataLoader worker timed out after "
                            f"{self.timeout}s")
                    continue
                if kind == "done":
                    live -= 1
                elif kind == "e":
                    raise payload
                else:
                    yield payload
        finally:
            # early exit (consumer break / error): unblock queue-blocked
            # workers, then wait briefly. A thread stuck in USER code
            # (dataset __iter__) cannot be interrupted — after the
            # deadline it is abandoned as a daemon (it gives up its next
            # _put once stop is set)
            stop.set()
            deadline = 2.0
            import time as _time

            t0 = _time.time()
            for t in threads:
                while t.is_alive() and _time.time() - t0 < deadline:
                    try:
                        out_q.get_nowait()
                    except queue.Empty:
                        pass
                    t.join(timeout=0.1)

    def _iter_process_iterable(self):
        """IterableDataset with spawn workers: each child iterates its own
        dataset replica (WorkerInfo installed before iteration) and ships
        collated NumPy batches through a BOUNDED queue (children block at
        num_workers*prefetch_factor pending batches — backpressure); the
        parent wraps them into Tensors."""
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        result_q = ctx.Queue(maxsize=self.num_workers * self.prefetch_factor
                             + self.num_workers)
        procs = [
            ctx.Process(
                target=_process_worker_iterable,
                args=(self.dataset, self._proc_collate, self.worker_init_fn,
                      wid, self.num_workers, self.batch_size, self.drop_last,
                      result_q),
                daemon=True)
            for wid in range(self.num_workers)
        ]
        for p in procs:
            p.start()
        done = set()
        waited = 0.0
        dead_polls = 0
        try:
            while len(done) < self.num_workers:
                try:
                    kind, payload = result_q.get(timeout=1.0)
                    waited = 0.0
                    dead_polls = 0
                except queue.Empty:
                    waited += 1.0
                    # a worker that exited WITHOUT delivering its 'done'
                    # died; workers already done are allowed to be gone.
                    # A cleanly-exited (exitcode 0) worker's final batches
                    # and 'done' sentinel can still sit in the feeder pipe
                    # while the queue transiently reports empty — only
                    # treat exitcode 0 as death after several consecutive
                    # empty polls give the feeder time to flush.
                    dead = [i for i, p in enumerate(procs)
                            if i not in done and not p.is_alive()]
                    crashed = [i for i in dead if procs[i].exitcode]
                    if crashed and result_q.empty():
                        raise RuntimeError(
                            f"DataLoader process worker {crashed[0]} died "
                            "unexpectedly "
                            f"(exitcode {procs[crashed[0]].exitcode})")
                    dead_polls = dead_polls + 1 if dead else 0
                    if dead and dead_polls >= 3 and result_q.empty():
                        raise RuntimeError(
                            f"DataLoader process worker {dead[0]} died "
                            "unexpectedly")
                    if self.timeout and waited >= self.timeout:
                        raise RuntimeError(
                            f"DataLoader process worker timed out after "
                            f"{self.timeout}s")
                    continue
                if kind == "done":
                    done.add(payload)
                elif kind == "e":
                    raise payload
                else:
                    yield _np_to_tensor_tree(payload)
        finally:
            # early exit: children may be blocked on the bounded queue —
            # terminate them rather than strand them
            for p in procs:
                p.join(timeout=0.2)
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)

    def __iter__(self):
        if self.num_workers and self.num_workers > 0:
            if self._iterable:
                if self.use_process_workers:
                    return self._iter_process_iterable()
                return self._iter_threaded_iterable()
            if self.batch_sampler is not None:
                if self.use_process_workers:
                    return self._iter_process()
                return self._iter_threaded()
        return self._iter_sync()


