"""DataLoader (ref: python/paddle/io/dataloader/dataloader_iter.py (U)).

TPU-native design: the reference's multiprocess workers + pinned-memory +
CUDA-stream H2D pipeline becomes a threaded prefetch pipeline feeding
device_put — on TPU VMs the host is roomy and jax transfers are async, so
worker *threads* (NumPy releases the GIL) plus a bounded prefetch queue give
the same overlap without fork/IPC fragility. A native C++ prefetcher can slot
under `paddle_tpu.utils.hostloader` for decode-heavy pipelines.

For decode-heavy Python datasets that DON'T release the GIL (jpeg decode,
tokenization), `use_process_workers=True` switches to spawn-based process
workers, the analog of the reference's default multiprocess mode: workers
fetch+collate to NumPy and ship batches back over a queue; the parent wraps
them into Tensors (device transfer stays in the parent, where the TPU
client lives). Threads remain the default — on low-core hosts process
startup dominates."""

from __future__ import annotations

import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler


def _collate_np(batch):
    """Collate to a NumPy pytree — the single collate policy; the Tensor
    variant is this plus a leaf wrap. Process workers ship these trees over
    the queue (Tensors don't cross the process boundary)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._data) for s in batch])
    if isinstance(sample, (int, np.integer)):
        # int32, not the reference's int64: x64 is disabled jax-side, and
        # int32 indices are what TPU embedding/gather kernels want
        return np.asarray(batch, np.int32)
    if isinstance(sample, float):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(_collate_np(list(items)) for items in transposed)
    if isinstance(sample, dict):
        return {k: _collate_np([d[k] for d in batch]) for k in sample}
    if isinstance(sample, str):
        return list(batch)
    return np.asarray(batch)


def _np_to_tensor_tree(x):
    if isinstance(x, np.ndarray):
        return Tensor(x)
    if isinstance(x, (list, tuple)) and not (x and isinstance(x[0], str)):
        return type(x)(_np_to_tensor_tree(v) for v in x)
    if isinstance(x, dict):
        return {k: _np_to_tensor_tree(v) for k, v in x.items()}
    return x


def _tensor_to_np_tree(x):
    """Inverse of _np_to_tensor_tree: user collate_fns return Tensors, but a
    spawned child must ship NumPy (the TPU client lives in the parent)."""
    if isinstance(x, Tensor):
        return np.asarray(x._data)
    if isinstance(x, (list, tuple)) and not (x and isinstance(x[0], str)):
        return type(x)(_tensor_to_np_tree(v) for v in x)
    if isinstance(x, dict):
        return {k: _tensor_to_np_tree(v) for k, v in x.items()}
    return x


def _process_worker(dataset, collate_fn, worker_init_fn, worker_id, task_q,
                    result_q):
    """Top-level for spawn picklability."""
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        item = task_q.get()
        if item is None:
            return
        seq, indices = item
        try:
            out = _tensor_to_np_tree(collate_fn([dataset[i] for i in indices]))
        except Exception as e:  # noqa: BLE001 — propagate to the consumer
            out = RuntimeError(f"DataLoader worker {worker_id} failed: "
                               f"{type(e).__name__}: {e}")
        result_q.put((seq, out))


def default_collate_fn(batch):
    return _np_to_tensor_tree(_collate_np(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 use_process_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.timeout = timeout
        self.use_process_workers = use_process_workers
        self.worker_init_fn = worker_init_fn
        self._proc_collate = collate_fn or _collate_np
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_size = batch_size
            self.batch_sampler = None
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
                )

    def __len__(self):
        if self._iterable:
            raise TypeError("length of IterableDataset DataLoader is unknown")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # ---------------- iteration ----------------
    def _fetch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def _iter_sync(self):
        if self._iterable:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.collate_fn([self.dataset[i]])
            return
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_threaded(self):
        """num_workers>0: worker threads fetch+collate; a bounded queue keeps
        `num_workers * prefetch_factor` batches in flight, preserving order."""
        index_iter = iter(self.batch_sampler)
        max_inflight = self.num_workers * self.prefetch_factor
        results = {}
        results_lock = threading.Condition()
        task_q = queue.Queue()
        n_submitted = 0
        n_consumed = 0
        done_submitting = False

        def worker():
            while True:
                item = task_q.get()
                if item is None:
                    return
                seq, indices = item
                try:
                    out = self._fetch(indices)
                except Exception as e:  # propagate to consumer
                    out = e
                with results_lock:
                    results[seq] = out
                    results_lock.notify_all()

        threads = [threading.Thread(target=worker, daemon=True) for _ in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            # prime
            for _ in range(max_inflight):
                try:
                    task_q.put((n_submitted, next(index_iter)))
                    n_submitted += 1
                except StopIteration:
                    done_submitting = True
                    break
            while n_consumed < n_submitted or not done_submitting:
                with results_lock:
                    while n_consumed not in results:
                        got_notify = results_lock.wait(timeout=self.timeout or None)
                        # re-check the predicate before timing out: wait() can
                        # return False even though the batch landed just as the
                        # deadline elapsed
                        if not got_notify and self.timeout \
                                and n_consumed not in results:
                            raise RuntimeError(
                                f"DataLoader worker timed out after "
                                f"{self.timeout}s waiting for batch {n_consumed}")
                    out = results.pop(n_consumed)
                n_consumed += 1
                if isinstance(out, Exception):
                    raise out
                if not done_submitting:
                    try:
                        task_q.put((n_submitted, next(index_iter)))
                        n_submitted += 1
                    except StopIteration:
                        done_submitting = True
                yield out
        finally:
            for _ in threads:
                task_q.put(None)

    def _iter_process(self):
        """Spawn-based process workers (opt-in): fetch+collate to NumPy in
        children, convert to Tensors in the parent, preserve batch order."""
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        task_q = ctx.Queue()
        result_q = ctx.Queue()
        procs = [
            ctx.Process(
                target=_process_worker,
                args=(self.dataset, self._proc_collate, self.worker_init_fn,
                      wid, task_q, result_q),
                daemon=True)
            for wid in range(self.num_workers)
        ]
        for p in procs:
            p.start()
        index_iter = iter(self.batch_sampler)
        max_inflight = self.num_workers * self.prefetch_factor
        results = {}
        n_submitted = 0
        n_consumed = 0
        done_submitting = False
        try:
            for _ in range(max_inflight):
                try:
                    task_q.put((n_submitted, list(next(index_iter))))
                    n_submitted += 1
                except StopIteration:
                    done_submitting = True
                    break
            while n_consumed < n_submitted or not done_submitting:
                waited = 0.0
                while n_consumed not in results:
                    # poll in short slices so a dead worker (segfault/OOM
                    # kill) raises instead of blocking forever
                    try:
                        seq, out = result_q.get(timeout=1.0)
                        results[seq] = out
                        continue
                    except queue.Empty:
                        waited += 1.0
                    if not all(p.is_alive() for p in procs):
                        raise RuntimeError(
                            "DataLoader process worker died unexpectedly "
                            f"while batch {n_consumed} was in flight")
                    if self.timeout and waited >= self.timeout:
                        raise RuntimeError(
                            f"DataLoader process worker timed out after "
                            f"{self.timeout}s waiting for batch {n_consumed}")
                out = results.pop(n_consumed)
                n_consumed += 1
                if isinstance(out, Exception):
                    raise out
                if not done_submitting:
                    try:
                        task_q.put((n_submitted, list(next(index_iter))))
                        n_submitted += 1
                    except StopIteration:
                        done_submitting = True
                yield _np_to_tensor_tree(out)
        finally:
            for _ in procs:
                task_q.put(None)
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()

    def __iter__(self):
        if self.num_workers and self.num_workers > 0 and not self._iterable and self.batch_sampler is not None:
            if self.use_process_workers:
                return self._iter_process()
            return self._iter_threaded()
        return self._iter_sync()


def get_worker_info():
    return None
