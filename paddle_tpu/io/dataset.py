"""Datasets (ref: python/paddle/io/dataloader/dataset.py (U))."""

from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        from ..core.tensor import Tensor

        self.tensors = tensors
        assert all(len(t) == len(tensors[0]) for t in tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert all(len(d) == len(self.datasets[0]) for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ZipDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        return tuple(d[idx] for d in self.datasets)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        start = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - start]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    import paddle_tpu as paddle

    if all(isinstance(l, float) for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6:
        n = len(dataset)
        lengths = [int(l * n) for l in lengths]
        lengths[-1] += n - sum(lengths)
    total = sum(lengths)
    if total != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    perm = paddle.randperm(total).numpy().tolist()
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l]))
        offset += l
    return out
