"""paddle.version parity (ref: generated python/paddle/version/__init__.py)."""

full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
cuda_version = "False"   # TPU build: no CUDA
cudnn_version = "False"
tensorrt_version = "None"
xpu_version = "False"
istaged = False
commit = "unknown"
with_pip_cuda_libraries = "OFF"


def show():
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"rc: {rc}")
    print(f"commit: {commit}")
    print("tpu: True (jax/XLA backend)")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version


def xpu():
    return xpu_version


def tpu():
    import jax

    try:
        devs = jax.devices()
        return devs[0].device_kind if devs else "none"
    except Exception:
        return "none"
