"""paddle.metric parity (ref: python/paddle/metric/metrics.py (U))."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = np.asarray(pred._data if isinstance(pred, Tensor) else pred)
        label_np = np.asarray(label._data if isinstance(label, Tensor) else label)
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        order = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = order == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(correct._data if isinstance(correct, Tensor) else correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum()
            tot = c.shape[0] if c.ndim > 1 else len(c)
            self.total[i] += float(num)
            self.count[i] += int(np.prod(c.shape[:-1]))
            accs.append(float(num) / max(int(np.prod(c.shape[:-1])), 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds).reshape(-1)
        l = np.asarray(labels._data if isinstance(labels, Tensor) else labels).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fp += int(np.sum(pred_pos & (l == 0)))

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds).reshape(-1)
        l = np.asarray(labels._data if isinstance(labels, Tensor) else labels).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fn += int(np.sum(~pred_pos & (l == 1)))

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._data if isinstance(labels, Tensor) else labels).reshape(-1)
        if p.ndim == 2:
            p = p[:, -1]
        bins = (p * self.num_thresholds).astype(int).clip(0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoidal over thresholds descending
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") else float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import paddle_tpu as paddle

    topk_vals, topk_idx = paddle.topk(input, k)
    lbl = label
    if lbl.ndim == 1:
        lbl = lbl.unsqueeze(-1)
    correct_mat = (topk_idx == lbl.astype(topk_idx.dtype)).astype("float32")
    return correct_mat.sum(axis=-1).mean()
